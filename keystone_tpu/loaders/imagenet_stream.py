"""ImageNet-scale streaming batch sources and the calibrated synthetic
corpus — the ingestion side of ``models/imagenet_sift_lcs_fv``.

The tar source serves each process its disjoint shard of the corpus
(reference ImageLoaderUtils.scala:177-216 per-executor streaming); the
synthetic source generates the SAME distribution lazily so 100k-image
runs never materialize ~80 GB of pixels on the host; ``label_noise``
gives the corpus a provable top-1 error floor of exactly q so the scale
eval can assert a nonzero band in both directions.
"""

from __future__ import annotations

import numpy as np


def synthetic_centers(k: int) -> np.ndarray:
    """The (k, 8, 8, 3) class centers every synthetic path shares (eager
    load, streaming source, and the calibration test in
    tests/test_streaming.py)."""
    return np.random.default_rng(42).normal(
        loc=128, scale=30, size=(k, 8, 8, 3)
    )


def render_classes(labels, k: int, q: float, rng) -> np.ndarray:
    """Class index each synthetic image is RENDERED from: with
    probability ``q`` a uniformly random OTHER class, while the label
    stays. Because a flip never lands back on the labeled class, the
    top-1 error floor is exactly ``q`` — the calibrated overlap behind
    ``label_noise``."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(
            f"label_noise={q} must be in [0, 1] — it IS the top-1 error "
            "floor the calibrated eval asserts against"
        )
    render = labels.copy()
    if q and k > 1:
        flip = rng.random(len(labels)) < q
        other = (labels + rng.integers(1, k, size=len(labels))) % k
        render[flip] = other[flip]
    return render


def synthetic_source(conf, which: str):
    """Serve the synthetic corpus through the streaming iterator contract.

    Batches are generated LAZILY and deterministically (per-batch rngs):
    at ImageNet scale the eager corpus would be ~80GB of host RAM
    for 100k 256² images — materializing it would defeat the bounded-
    memory property the streaming path exists to provide. Same
    distribution as the eager ``_load`` (shared class centers, per-batch
    noise), so small-scale tests that compare against the eager path
    stay valid. ``conf`` is any object with the ImageNetConfig synthetic
    fields (synthetic, synthetic_classes, image_size, stream_batch,
    label_noise).
    """
    k = conf.synthetic_classes
    n = conf.synthetic if which == "train" else max(conf.synthetic // 4, 1)
    seed = 0 if which == "train" else 1
    centers = synthetic_centers(k)
    up = conf.image_size // 8

    def source():
        for s in range(0, n, conf.stream_batch):
            b = min(conf.stream_batch, n - s)
            rng = np.random.default_rng((seed, s))
            labels = rng.integers(0, k, size=b).astype(np.int32)
            render = render_classes(labels, k, conf.label_noise, rng)
            imgs = np.kron(centers[render], np.ones((1, up, up, 1)))
            imgs += rng.normal(scale=20, size=imgs.shape)
            yield np.clip(imgs, 0, 255).astype(np.float32), labels

    return source


def tar_source(conf, which: str):
    """Re-streamable batch source over the tar corpus: each call returns a
    fresh iterator of (images, labels) host batches (this process's share
    of the tar files)."""
    import jax as _jax

    from keystone_tpu.loaders.image_loaders import (
        load_class_map,
        make_synset_label_of,
    )
    from keystone_tpu.loaders.streaming import iter_tar_image_batches

    label_of = make_synset_label_of(load_class_map(conf.label_map))
    location = conf.train_location if which == "train" else conf.test_location

    def source():
        for _, imgs, labels in iter_tar_image_batches(
            location,
            batch_size=conf.stream_batch,
            target_size=conf.image_size,
            label_of=label_of,
            process_index=_jax.process_index(),
            process_count=_jax.process_count(),
        ):
            yield imgs, labels

    return source


def assemble_global(features: np.ndarray, labels: np.ndarray):
    """Combine every process's local (n_p, D) features + labels into the
    global training set (each process streamed a disjoint tar shard).

    Features are small relative to images (the whole point of streaming),
    so an allgather-and-concatenate keeps the solver's simple
    prefix-validity contract — the same host footprint the eager path
    already pays for its feature matrix. Single-process: passthrough.
    """
    import jax as _jax

    if _jax.process_count() == 1:
        return features, labels
    from jax.experimental import multihost_utils

    # gather count AND width: a process whose tar shard was empty (or all
    # undecodable) holds a (0, 0) feature array, and allgather needs
    # identical shapes across processes
    meta = multihost_utils.process_allgather(
        np.asarray([len(features), features.shape[-1]], np.int64)
    ).reshape(-1, 2)
    counts, dims = meta[:, 0], meta[:, 1]
    n_max = int(counts.max())
    dim = int(dims.max())
    pad_f = np.zeros((n_max, dim), np.float32)
    pad_f[: len(features), : features.shape[-1]] = features
    pad_y = np.zeros((n_max,), np.int32)
    pad_y[: len(labels)] = labels
    all_f = multihost_utils.process_allgather(pad_f)  # (P, n_max, D)
    all_y = multihost_utils.process_allgather(pad_y)
    feats = np.concatenate(
        [all_f[p, : counts[p]] for p in range(len(counts))]
    )
    labs = np.concatenate(
        [all_y[p, : counts[p]] for p in range(len(counts))]
    )
    return feats, labs
