"""Tar/JPEG image ingestion (reference loaders/ImageLoaderUtils.scala,
VOCLoader.scala, ImageNetLoader.scala).

The reference streams tar archives on executors (one partition per tar) and
decodes JPEGs with ImageIO; here tars are streamed on the host with Python's
tarfile + PIL, in parallel across files via threads (JPEG decode releases
the GIL in PIL).

Static-shape policy (the SURVEY §7 "hard part #1"): XLA wants one shape, so
every image is resized to ``target_size`` at load (the reference keeps
variable sizes and pays per-image JNI calls instead — resizing is the
documented deviation; bucketing by aspect ratio is a later refinement).
"""

from __future__ import annotations

import concurrent.futures
import glob
import io
import os
import tarfile

import numpy as np

from keystone_tpu.utils.images import LabeledImages

VOC_NUM_CLASSES = 20


def decode_image(data: bytes, target_size: int | None) -> np.ndarray:
    """JPEG/PNG bytes → (H, W, 3) float32 0-255 (grayscale triplicated to 3
    channels like the reference, ImageConversions.scala)."""
    from PIL import Image as PILImage

    img = PILImage.open(io.BytesIO(data))
    if img.mode != "RGB":
        img = img.convert("RGB")
    if target_size is not None:
        img = img.resize((target_size, target_size), PILImage.BILINEAR)
    return np.asarray(img, np.float32)


def _iter_tar_images(tar_path: str, *, strict: bool = False):
    """Yield ``(name, bytes)`` image entries of one tar, resiliently.

    Transient open errors retry under ``IO_POLICY`` (and the
    ``tar.read`` fault site injects them); an archive that stays
    unreadable is SKIPPED with one warning + an
    ``ingest_archives_skipped`` counter — one corrupt shard must not
    abort a multi-tar ingest (the reference got this from Spark task
    re-execution; tf.data treats ingest skip/retry the same way). A
    read error mid-archive (truncated tar) yields the readable prefix
    and skips the rest, counted separately. ``strict=True`` restores
    raise-on-error for callers that want the abort."""
    from keystone_tpu.resilience import faults, retry

    def _open():
        faults.maybe_raise("tar.read", note=tar_path)
        return tarfile.open(tar_path)

    try:
        tf = retry.IO_POLICY.call(_open, label="tar.open")
    except (retry.RetryExhausted, OSError, tarfile.ReadError) as e:
        if strict:
            raise
        _count_archive_failure(tar_path, e, "unreadable")
        return
    with tf:
        try:
            for member in tf:
                if not member.isfile():
                    continue
                name = os.path.basename(member.name)
                if not name.lower().endswith((".jpg", ".jpeg", ".png")):
                    continue
                data = tf.extractfile(member).read()
                yield member.name, data
        except (OSError, EOFError, tarfile.ReadError) as e:
            if strict:
                raise
            _count_archive_failure(tar_path, e, "truncated")


def _count_archive_failure(tar_path: str, e: BaseException, reason: str) -> None:
    """One warning + counter + resilience event per skipped archive."""
    from keystone_tpu.resilience.emit import decision

    _logger().warning("skipping %s tar %s: %s", reason, tar_path, e)
    decision(
        "archive_skipped",
        counter="ingest_archives_skipped",
        counter_labels={"reason": reason},
        path=tar_path,
        reason=reason,
        error=repr(e),
    )


def load_tar_images(
    paths: list[str],
    target_size: int | None = 256,
    workers: int = 8,
    decode_batch: int = 512,
    name_prefix: str | None = None,
) -> tuple[list[str], np.ndarray]:
    """All images from the given tar files → (names, (N, S, S, 3) array).

    ``name_prefix`` drops entries outside a path prefix *before* decode
    (the reference's ``VOCDataPath.namePrefix`` filter). Decoding streams
    in ``decode_batch``-sized groups so raw compressed bytes are dropped as
    soon as each group is decoded (peak host memory is pixels + one group
    of bytes, not the whole corpus's bytes).

    This eager entry point is STRICT about archives: transient open
    errors still retry, but a corrupt/unreadable tar raises rather than
    silently shrinking the materialized dataset (a small eager load is
    usually one archive — an empty result would fail confusingly far
    downstream). The skip-and-continue contract belongs to the
    streaming path (:func:`keystone_tpu.loaders.streaming.
    iter_tar_image_batches`).
    """

    def try_decode(nd):
        # undecodable entries are skipped with a warning, like the
        # reference's ImageUtils.loadImage failure filter
        try:
            return decode_image(nd[1], target_size)
        except Exception as e:  # noqa: BLE001 — PIL raises various types
            _logger().warning("failed to decode %s: %s", nd[0], e)
            _count_decode_failure("image_loaders")
            return None

    names: list[str] = []
    imgs: list[np.ndarray] = []
    with concurrent.futures.ThreadPoolExecutor(workers) as ex:
        batch: list[tuple[str, bytes]] = []

        def flush():
            nonlocal batch
            decoded = list(ex.map(try_decode, batch))
            for (n, _), img in zip(batch, decoded):
                if img is not None:
                    names.append(n)
                    imgs.append(img)
            batch = []

        for p in paths:
            for item in _iter_tar_images(p, strict=True):
                if name_prefix is not None and not item[0].startswith(
                    name_prefix
                ):
                    continue
                batch.append(item)
                if len(batch) >= decode_batch:
                    flush()
        if batch:
            flush()
    return names, np.stack(imgs) if imgs else np.zeros((0, 0, 0, 3), np.float32)


def _count_decode_failure(loader: str) -> None:
    from keystone_tpu.observe import metrics

    metrics.get_registry().counter(
        "ingest_decode_failures", loader=loader
    ).inc()


def _logger():
    from keystone_tpu.core.logging import get_logger

    return get_logger("keystone_tpu.loaders.image_loaders")


def _expand(path: str, suffix: str) -> list[str]:
    if os.path.isdir(path):
        return sorted(glob.glob(os.path.join(path, f"*{suffix}")))
    return sorted(glob.glob(path)) or [path]


def load_voc(
    tar_path: str,
    label_csv_path: str,
    *,
    target_size: int | None = 256,
    name_prefix: str | None = None,
) -> LabeledImages:
    """VOC2007 tar(s) + multi-label CSV → images with per-image label lists
    (reference VOCLoader.scala:41-63).

    Two CSV layouts are accepted: the VOC2007 annotation export the
    reference parses — header row then
    ``id,class,classname,traintesteval,filename`` with 1-indexed class and
    quoted paths (columns 1 and 4, VOCLoader.scala:50-53) — and the
    simplified ``filename,label_index`` (also 1-indexed). ``name_prefix``
    keeps only tar entries under a path prefix (the reference's
    ``VOCDataPath.namePrefix``, e.g. "VOCdevkit/VOC2007/JPEGImages/").

    ``labels`` is an (N, k) int array padded with −1 (ragged multi-labels),
    feeding ClassLabelIndicators' padded path.
    """
    label_map: dict[str, list[int]] = {}
    with open(label_csv_path) as f:
        lines = [ln.strip() for ln in f if ln.strip()]
    reference_format = bool(lines) and lines[0].replace('"', "").lower().startswith("id,")
    for line in lines[1 if reference_format else 0 :]:
        if line.startswith("#"):
            continue
        parts = [p.strip().strip('"') for p in line.split(",")]
        if reference_format:
            fname, label = os.path.basename(parts[4]), int(parts[1]) - 1
        else:
            fname, label = parts[0], int(parts[1]) - 1
        label_map.setdefault(fname, []).append(label)

    names, images = load_tar_images(
        _expand(tar_path, ".tar"), target_size, name_prefix=name_prefix
    )
    labels_ragged = [
        sorted(set(label_map.get(os.path.basename(n), []))) for n in names
    ]
    k = max((len(l) for l in labels_ragged), default=1)
    labels = -np.ones((len(names), max(k, 1)), np.int32)
    for i, ls in enumerate(labels_ragged):
        labels[i, : len(ls)] = ls
    return LabeledImages(labels=labels, images=images)


def load_class_map(class_map_path: str) -> dict[str, int]:
    """Parse a "synset class_index" map file (reference ImageNetLoader)."""
    class_map: dict[str, int] = {}
    with open(class_map_path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                class_map[parts[0]] = int(parts[1])
    return class_map


def make_synset_label_of(class_map: dict[str, int]):
    """name → class index: synset prefix of the basename, falling back to
    the parent directory name; −1 when unmapped."""

    def label_of(name: str) -> int:
        base = os.path.basename(name)
        synset = base.split("_")[0]
        if synset in class_map:
            return class_map[synset]
        parent = os.path.basename(os.path.dirname(name))
        return class_map.get(parent, -1)

    return label_of


def load_imagenet(
    tar_path: str, class_map_path: str, *, target_size: int | None = 256
) -> LabeledImages:
    """ImageNet tar(s) + "dirname class_index" map file → labeled images
    (reference ImageNetLoader: label from the synset prefix of the entry
    name via the map file)."""
    label_of = make_synset_label_of(load_class_map(class_map_path))
    names, images = load_tar_images(_expand(tar_path, ".tar"), target_size)
    labels = np.asarray([label_of(n) for n in names], np.int32)
    unmapped = labels < 0
    if unmapped.any():
        # keep unmapped images out of training entirely — a -1 label would
        # otherwise wrap to the last class in the indicator scatter
        _logger().warning(
            "dropping %d images with no class-map entry", int(unmapped.sum())
        )
        labels, images = labels[~unmapped], images[~unmapped]
    return LabeledImages(labels=labels, images=images)
