"""CSV loading (reference loaders/CsvDataLoader.scala): rows of
comma-separated numbers → one matrix; optional first-column labels."""

from __future__ import annotations

import glob
import os

import numpy as np

from keystone_tpu.loaders.labeled import LabeledData


def _paths(path: str) -> list[str]:
    """A file, a directory of part files, or a glob — like sc.textFile."""
    if os.path.isdir(path):
        found = sorted(
            p
            for p in glob.glob(os.path.join(path, "*"))
            if os.path.isfile(p) and not os.path.basename(p).startswith(("_", "."))
        )
    else:
        found = sorted(glob.glob(path)) or [path]
    if not found or not all(os.path.exists(p) for p in found):
        raise FileNotFoundError(path)
    return found


def load_csv(path: str, dtype=np.float32) -> np.ndarray:
    """All rows from file/dir/glob ``path`` as an (N, d) array.

    Uses the native mmap/OpenMP parser (``keystone_tpu.native``) when the
    library is available (~3x numpy's parser on MNIST-sized files), else
    ``np.loadtxt``.
    """
    from keystone_tpu.native import native_load_csv

    parts = []
    for p in _paths(path):
        mat = native_load_csv(p)
        if mat is None:
            mat = np.loadtxt(p, delimiter=",", dtype=np.float32, ndmin=2)
        parts.append(mat.astype(dtype, copy=False))
    return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)


def load_labeled_csv(
    path: str, label_offset: int = 0, dtype=np.float32
) -> LabeledData:
    """First column = integer label (minus ``label_offset``), rest = features.

    MNIST csvs in the reference workload are 1-indexed → ``label_offset=1``
    (the reference subtracts 1 inline, MnistRandomFFT.scala ``x(0).toInt - 1``).
    """
    mat = load_csv(path, dtype=dtype)
    labels = mat[:, 0].astype(np.int32) - label_offset
    return LabeledData(labels=labels, data=np.ascontiguousarray(mat[:, 1:]))
