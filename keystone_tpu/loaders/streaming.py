"""Streaming, multi-host-ready image ingestion.

The reference streams tar archives per executor — one Spark partition per
tar file, images decoded and featurized without ever materializing the
corpus on one machine (``loaders/ImageLoaderUtils.scala:177-216``). The
TPU-native equivalent here:

- :func:`iter_tar_image_batches` — incremental tar decode yielding
  fixed-size host batches; peak host memory is one batch of pixels plus
  one group of compressed bytes. ``process_index/process_count`` shard
  the tar FILES round-robin per process (the one-partition-per-tar
  analog), so every host of a multi-process run ingests a disjoint slice
  and assembles global arrays via
  :func:`keystone_tpu.parallel.multihost.global_batch_from_local`.
- :class:`ColumnReservoir` — bounded-memory uniform sample of descriptor
  columns across a stream (the streaming successor of the reference's
  collect-to-driver ColumnSampler, ``nodes/stats/Sampling.scala:245-261``).
- :func:`featurize_stream` — push each host batch through a jitted
  featurizer (padded to one static chunk shape → a single compiled
  executable) and keep only the small feature output on the host.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Iterable, Iterator

import numpy as np

from keystone_tpu.loaders.image_loaders import (
    _count_decode_failure,
    _expand,
    _iter_tar_images,
    decode_image,
)

ENV_INGEST_WORKERS = "KEYSTONE_INGEST_WORKERS"
# the frontier's thread-pool ceiling; the LIVE worker count (≤ this)
# bounds how many decodes are actually in flight
_INGEST_POOL_MAX = 16


def default_ingest_workers() -> int:
    """Decode parallelism when no autotuner drives it:
    ``KEYSTONE_INGEST_WORKERS``, else 8 (the historical tar-decode pool
    width)."""
    raw = os.environ.get(ENV_INGEST_WORKERS, "").strip()
    if raw:
        try:
            return max(int(raw), 1)
        except ValueError:
            pass
    return 8


def _live_workers() -> int:
    """The ingest-frontier worker count: the autotuner's
    ``ingest_workers`` knob when one is active (the wait_host ⇒ more
    ingest parallelism feedback loop), else the env/static default."""
    from keystone_tpu.core.staging import tune_active

    tuner = tune_active()
    if tuner is not None:
        v = tuner.value("ingest_workers")
        if v:
            return int(v)
    return default_ingest_workers()


def ingest_frontier(
    items: Iterable,
    fn: Callable,
    *,
    workers: int | Callable[[], int] | None = None,
    pool: int = _INGEST_POOL_MAX,
    span_name: str | None = "ingest.wait_host",
) -> Iterator[Any]:
    """Map ``fn`` over ``items`` with a bounded multi-worker decode pool
    running AHEAD of the consumer, yielding results in input order —
    bit-exact vs ``(fn(i) for i in items)``, exceptions re-raised at the
    consumer in order.

    This is the async ingest frontier of the self-tuning runtime: up to
    the *current* worker count of decodes are in flight ahead of the
    consumer (``workers`` — an int, a callable polled at each refill, or
    None for the live autotuner knob / ``KEYSTONE_INGEST_WORKERS``), so
    host-side tar-read + decode stops gating accelerator feed. The time
    the consumer actually blocks waiting for the next decoded item is
    the wait_host stall: it feeds the active autotuner (which raises the
    worker count when that stall dominates) and — when a span log is
    active and the wait is non-trivial — one ``ingest.wait_host`` span,
    so goodput reports attribute ingest-bound time correctly.
    """
    import concurrent.futures
    import time as _time
    from collections import deque

    from keystone_tpu.core.staging import tune_active
    from keystone_tpu.observe import spans as _spans

    if workers is None:
        workers_fn: Callable[[], int] = _live_workers
    elif callable(workers):
        workers_fn = workers
    else:
        fixed = max(int(workers), 1)
        workers_fn = lambda: fixed  # noqa: E731

    tuner = tune_active()
    span_log = _spans.active_span_log() if span_name else None
    parent_ctx = _spans.current() if span_log is not None else None

    def gen() -> Iterator[Any]:
        ex = concurrent.futures.ThreadPoolExecutor(max_workers=max(pool, 1))
        it = iter(items)
        pending: deque = deque()
        exhausted = [False]

        def refill() -> None:
            # the knob is polled HERE, so a retuned worker count takes
            # effect at the next refill — no pool rebuild, no drain
            target = max(1, min(int(workers_fn()), pool))
            while not exhausted[0] and len(pending) < target:
                try:
                    item = next(it)
                except StopIteration:
                    exhausted[0] = True
                    return
                pending.append(ex.submit(fn, item))

        try:
            refill()
            while pending:
                fut = pending.popleft()
                t0 = _time.perf_counter()
                result = fut.result()  # re-raises fn's exception in order
                waited = _time.perf_counter() - t0
                if tuner is not None:
                    tuner.observe(
                        bucket="wait_host", wall_s=waited, rows=1
                    )
                if span_log is not None and waited > 1e-3:
                    span_log.record_span(
                        span_name,
                        wall_s=waited,
                        bucket="wait_host",
                        parent=parent_ctx,
                    )
                refill()
                yield result
        finally:
            for fut in pending:
                fut.cancel()
            ex.shutdown(wait=False)

    return gen()


def iter_tar_image_batches(
    paths: list[str] | str,
    *,
    batch_size: int = 512,
    target_size: int | None = 256,
    workers: int | None = None,
    name_prefix: str | None = None,
    process_index: int = 0,
    process_count: int = 1,
    label_of: Callable[[str], int] | None = None,
) -> Iterator[tuple[list[str], np.ndarray, np.ndarray | None]]:
    """Yield ``(names, images (B, S, S, 3), labels | None)`` batches.

    Bounded host memory: only ``batch_size`` compressed entries + decoded
    pixels are alive at once. ``label_of`` maps an entry name to an int
    label (entries mapping to a negative label are skipped, matching the
    eager loaders' unmapped-image drop).

    Corrupt/unreadable archives do not abort the stream: transient open
    errors retry, a dead archive is skipped with one warning and an
    ``ingest_archives_skipped`` counter, and per-image decode failures
    count under ``ingest_decode_failures`` (see
    :mod:`keystone_tpu.resilience`).

    Decode runs through the async ingest frontier
    (:func:`ingest_frontier`): up to the live worker count of images are
    decoded AHEAD of batch assembly (across batch boundaries), and the
    count is retunable mid-stream — ``workers=None`` follows the
    autotuner's ``ingest_workers`` knob / ``KEYSTONE_INGEST_WORKERS``.
    Batch boundaries are drawn every ``batch_size`` tar ENTRIES (decode
    failures then dropped), matching the historical grouping exactly.
    """
    if isinstance(paths, str):
        paths = _expand(paths, ".tar")
    paths = list(paths)[process_index::process_count]

    def entries() -> Iterator[tuple[str, bytes, int]]:
        for p in paths:
            for name, data in _iter_tar_images(p):
                if name_prefix is not None and not name.startswith(
                    name_prefix
                ):
                    continue
                lab = label_of(name) if label_of else 0
                if label_of and lab < 0:
                    continue
                yield (name, data, lab)

    def decode_one(entry):
        name, data, lab = entry
        try:
            return name, decode_image(data, target_size), lab
        except Exception as e:  # noqa: BLE001 — PIL raises various types
            _logger().warning("failed to decode %s: %s", name, e)
            _count_decode_failure("streaming")
            return name, None, lab

    names: list[str] = []
    imgs: list[np.ndarray] = []
    labels: list[int] = []
    seen = 0

    def assemble():
        out = (
            list(names),
            np.stack(imgs),
            np.asarray(labels, np.int32) if label_of else None,
        )
        names.clear()
        imgs.clear()
        labels.clear()
        return out

    decoded = ingest_frontier(
        entries(), decode_one, workers=workers, span_name=None
    )
    try:
        for name, img, lab in decoded:
            seen += 1
            if img is not None:
                names.append(name)
                imgs.append(img)
                labels.append(lab)
            if seen >= batch_size:
                seen = 0
                if imgs:
                    yield assemble()
        if imgs:
            yield assemble()
    finally:
        close = getattr(decoded, "close", None)
        if close is not None:
            close()


class ColumnReservoir:
    """Uniform reservoir sample of up to ``capacity`` rows from a stream.

    Vectorized per-batch acceptance (classic reservoir with batched index
    draws; within-batch collisions make it approximately uniform, which
    is all the PCA/GMM sampling needs — the reference's ColumnSampler is
    seeded-random too)."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self.rng = np.random.default_rng(seed)
        self.buf: np.ndarray | None = None
        self.seen = 0
        self.filled = 0

    def add(self, rows: np.ndarray) -> None:
        rows = np.asarray(rows)
        if rows.ndim != 2 or len(rows) == 0:
            return
        if self.buf is None:
            self.buf = np.empty(
                (self.capacity, rows.shape[1]), rows.dtype
            )
        take = min(self.capacity - self.filled, len(rows))
        if take > 0:
            self.buf[self.filled : self.filled + take] = rows[:take]
            self.filled += take
            self.seen += take
            rows = rows[take:]
        if len(rows) == 0:
            return
        idx = self.rng.integers(
            0, self.seen + np.arange(1, len(rows) + 1)
        )
        keep = idx < self.capacity
        self.buf[idx[keep]] = rows[keep]
        self.seen += len(rows)

    def sample(self) -> np.ndarray:
        if self.buf is None:
            return np.zeros((0, 0), np.float32)
        return self.buf[: self.filled]


def prefetch_batches(batches: Iterable, depth: int = 2) -> Iterator:
    """Run a batch producer on a background thread with a bounded queue.

    Tar/JPEG decode (or synthetic rendering) is pure host work; putting
    the producer one thread over lets it decode batch k+1 while the
    device featurizes batch k (the decode path releases the GIL inside
    PIL/numpy). ``depth`` bounds host memory to that many batches in
    flight. Exceptions from the producer re-raise at the consumer."""
    import queue
    import threading

    q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
    end = object()
    stop = threading.Event()  # consumer gone — unblock + retire producer

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for b in batches:
                if not put(b):
                    return
            put(end)
        except BaseException as e:  # noqa: BLE001 — re-raised at consumer
            put(e)

    threading.Thread(target=worker, daemon=True).start()

    def gen():
        # the finally runs on close()/GC of an abandoned generator (e.g.
        # the featurizer raised mid-stream), so the producer never stays
        # parked in q.put holding decoded batches + the source handle
        try:
            while True:
                item = q.get()
                if item is end:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    return gen()


def featurize_stream(
    batches: Iterable[np.ndarray],
    fn: Callable,
    *,
    chunk_size: int,
    mesh=None,
    prefetch: int = 2,
    stage_depth: int | None = None,
) -> np.ndarray:
    """Apply a jitted featurizer to a stream of host batches.

    Every chunk is zero-padded to one static row count (``chunk_size``,
    rounded up to a mesh-divisible shape when sharded; pad rows dropped
    from the output) so ONE compiled executable serves the whole stream
    regardless of ragged batch sizes; with ``mesh`` each padded chunk is
    placed data-sharded across the mesh so the featurizer runs as one
    SPMD program per chunk. Only the (small) feature output accumulates
    on the host — peak memory is a bounded handful of chunks (staged +
    in flight, see below) plus the features, never the corpus.

    Execution routes through the shared staging engine
    (:func:`keystone_tpu.core.staging.run_staged`): chunk k+1's
    host→device transfer is double-buffered behind chunk k's compute
    (``stage_depth`` / ``KEYSTONE_STAGE_DEPTH`` bounds the staged
    depth), and ``prefetch`` bounds un-forced device results — it is the
    ``np.asarray`` force that blocks, so the host moves on to
    decoding/padding the next chunk while the device computes. The
    producer side overlaps too when the caller wraps its iterator in
    :func:`prefetch_batches`. Peak device residency is ``stage_depth``
    staged chunks + ``prefetch`` un-forced results; ``prefetch=0``
    forces each result before the next dispatch, and adding
    ``stage_depth=0`` restores the fully synchronous one-chunk-at-a-time
    reference behavior (no staging thread)."""
    from keystone_tpu.core.batching import pad_to_chunk
    from keystone_tpu.core.staging import run_staged

    target = chunk_size
    sharding = None
    if mesh is not None:
        from keystone_tpu.parallel.mesh import (
            data_sharding_fn,
            shard_chunk_size,
        )

        target = shard_chunk_size(chunk_size, mesh)  # static + mesh-divisible
        sharding = data_sharding_fn(mesh)

    def chunks():
        # step by the (mesh-rounded) target: fewer, fuller chunks than
        # stepping by chunk_size and padding each up to target
        for batch in batches:
            for start in range(0, len(batch), target):
                yield pad_to_chunk(
                    np.asarray(batch[start : start + target]), target
                )

    outs = list(
        run_staged(
            chunks(),
            fn,
            sharding=sharding,
            stage_depth=stage_depth,
            inflight=prefetch,
            to_host=True,
        )
    )
    if not outs:
        return np.zeros((0, 0), np.float32)
    return np.concatenate(outs, axis=0)


def _logger():
    from keystone_tpu.core.logging import get_logger

    return get_logger("keystone_tpu.loaders.streaming")
