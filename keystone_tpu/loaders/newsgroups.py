"""20-Newsgroups loader (reference loaders/NewsgroupsDataLoader.scala):
one directory per class (hardcoded class list), one text file per post."""

from __future__ import annotations

import dataclasses
import glob
import os

import numpy as np

# Reference class order (NewsgroupsDataLoader.classes) — label ids depend on it.
CLASSES = (
    "comp.graphics",
    "comp.os.ms-windows.misc",
    "comp.sys.ibm.pc.hardware",
    "comp.sys.mac.hardware",
    "comp.windows.x",
    "rec.autos",
    "rec.motorcycles",
    "rec.sport.baseball",
    "rec.sport.hockey",
    "sci.crypt",
    "sci.electronics",
    "sci.med",
    "sci.space",
    "misc.forsale",
    "talk.politics.misc",
    "talk.politics.guns",
    "talk.politics.mideast",
    "talk.religion.misc",
    "alt.atheism",
    "soc.religion.christian",
)


@dataclasses.dataclass
class TextData:
    labels: np.ndarray  # (N,) int32
    data: list  # list of document strings

    def __len__(self):
        return len(self.data)


def load_newsgroups(path: str) -> TextData:
    """``path`` contains one subdirectory per class name."""
    docs: list[str] = []
    labels: list[int] = []
    for idx, cls in enumerate(CLASSES):
        cls_dir = os.path.join(path, cls)
        if not os.path.isdir(cls_dir):
            continue
        for f in sorted(glob.glob(os.path.join(cls_dir, "*"))):
            if not os.path.isfile(f):
                continue
            with open(f, errors="replace") as fh:
                docs.append(fh.read())
            labels.append(idx)
    if not docs:
        raise FileNotFoundError(f"no newsgroup class directories under {path}")
    return TextData(labels=np.asarray(labels, np.int32), data=docs)
