"""Host-side data ingestion (reference ``src/main/scala/loaders/``, SURVEY.md §2.7).

Loaders parse on the host (CSV/binary/tar/JPEG) into numpy, then feed the
mesh via ``parallel.mesh.shard_batch`` — the successor of one-partition-per-
file RDD ingestion.
"""

from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.loaders.csv_loader import load_csv, load_labeled_csv

__all__ = ["LabeledData", "load_csv", "load_labeled_csv"]
