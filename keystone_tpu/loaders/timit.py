"""TIMIT pre-featurized data loader (reference loaders/TimitFeaturesDataLoader.scala):
feature CSVs (440-dim rows) + sparse label files of "row# label" lines
(both 1-indexed).

DELIBERATE FIX of a reference bug (SURVEY.md §7 known quirks): the reference
reads *train* labels from ``testLabelsLocation``; here train labels come
from the train label file.
"""

from __future__ import annotations

import numpy as np

from keystone_tpu.loaders.csv_loader import load_csv
from keystone_tpu.loaders.labeled import LabeledData

TIMIT_DIMENSION = 440
NUM_CLASSES = 147


def _parse_sparse_labels(path: str, n_rows: int) -> np.ndarray:
    labels = np.full(n_rows, -1, np.int32)
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                row = int(parts[0]) - 1
                if 0 <= row < n_rows:
                    labels[row] = int(parts[1]) - 1
    if (labels < 0).any():
        missing = int((labels < 0).sum())
        raise ValueError(f"{missing} rows have no label in {path}")
    return labels


def load_timit_split(data_path: str, labels_path: str) -> LabeledData:
    data = load_csv(data_path)
    labels = _parse_sparse_labels(labels_path, data.shape[0])
    return LabeledData(labels=labels, data=data)


def load_timit(
    train_data: str, train_labels: str, test_data: str, test_labels: str
) -> tuple[LabeledData, LabeledData]:
    return (
        load_timit_split(train_data, train_labels),
        load_timit_split(test_data, test_labels),
    )
