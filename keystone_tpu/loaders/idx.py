"""IDX (MNIST ubyte) loader — the distribution format of the real MNIST
corpus (train-images-idx3-ubyte / train-labels-idx1-ubyte, optionally
gzipped).

The reference's MNIST workload reads a CSV conversion
(MnistRandomFFT.scala expects label-first CSV rows); this loader accepts
the UPSTREAM format directly so a staged real corpus works without a
conversion step (VERDICT r2 missing #4: no real-corpus parity point —
if the driver stages MNIST in either format, the pipeline runs on it).

Format (http-era de facto standard): big-endian header
``[0, 0, dtype_code, ndim] + ndim * int32 dims``, then row-major data.
Only dtype code 0x08 (uint8) is needed for MNIST.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from keystone_tpu.loaders.labeled import LabeledData

_DTYPES = {
    0x08: np.uint8, 0x09: np.int8, 0x0B: np.int16,
    0x0C: np.int32, 0x0D: np.float32, 0x0E: np.float64,
}


def _open(path: str):
    if path.endswith(".gz"):
        return gzip.open(path, "rb")
    return open(path, "rb")


def load_idx(path: str) -> np.ndarray:
    """One IDX file → ndarray with the header's shape and dtype.

    Transient read errors (flaky NFS/tunnel, the ``idx.read`` fault
    site) retry under ``IO_POLICY``; a malformed file (bad magic, short
    payload) is a ValueError that passes straight through — corruption
    is not transient."""
    from keystone_tpu.resilience import faults
    from keystone_tpu.resilience.retry import IO_POLICY

    def _read() -> np.ndarray:
        faults.maybe_raise("idx.read", note=path)
        with _open(path) as f:
            zero, code, ndim = struct.unpack(">HBB", f.read(4))
            if zero != 0 or code not in _DTYPES:
                raise ValueError(
                    f"{path}: not an IDX file (magic {zero:#x}/{code:#x})"
                )
            dims = struct.unpack(f">{ndim}i", f.read(4 * ndim))
            data = np.frombuffer(
                f.read(), dtype=np.dtype(_DTYPES[code]).newbyteorder(">")
            )
        if data.size != int(np.prod(dims)):
            raise ValueError(
                f"{path}: payload {data.size} != header {dims}"
            )
        return data.reshape(dims).astype(_DTYPES[code])

    return IO_POLICY.call(_read, label="idx.read")


def is_idx_path(path: str) -> bool:
    """Heuristic: the conventional ubyte naming, or a valid IDX magic."""
    name = os.path.basename(path)
    if "ubyte" in name or name.endswith(".idx") or name.endswith(".idx.gz"):
        return True
    try:
        with _open(path) as f:
            zero, code, _ = struct.unpack(">HBB", f.read(4))
        return zero == 0 and code in _DTYPES
    except Exception:  # noqa: BLE001 — unreadable/short: not IDX
        return False


def load_labeled_idx(images_path: str, labels_path: str) -> LabeledData:
    """(images idx3, labels idx1) → flattened float rows in [0, 255] +
    int labels, matching the CSV loader's LabeledData contract."""
    imgs = load_idx(images_path)
    labels = load_idx(labels_path)
    if imgs.shape[0] != labels.shape[0]:
        raise ValueError(
            f"image/label count mismatch: {imgs.shape[0]} vs "
            f"{labels.shape[0]}"
        )
    return LabeledData(
        labels=labels.astype(np.int32).reshape(-1),
        data=imgs.reshape(imgs.shape[0], -1).astype(np.float32),
    )


def guess_labels_path(images_path: str) -> str | None:
    """The conventional sibling name: ...images-idx3... → ...labels-idx1...
    Substitutes on the basename only — a directory component containing
    "images" must not be rewritten."""
    head, name = os.path.split(images_path)
    cand = name.replace("images", "labels").replace("idx3", "idx1")
    if cand == name:
        return None
    path = os.path.join(head, cand)
    return path if os.path.exists(path) else None
