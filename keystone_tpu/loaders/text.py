"""Byte-level text corpora for the LM stack.

The reference's loaders each turn one corpus format into arrays
(``loaders/*.scala``); this is the same role for free-form text: a file
(or directory of files) becomes one contiguous uint8 token stream —
byte-level tokenization (vocab 256) needs no vocabulary artifact, makes
every file valid input, and is the standard baseline for char-level LM
benchmarks (enwik8-style bits-per-byte). Deterministic train/validation
splitting happens on the stream, not the files, so a single-file corpus
still yields a held-out tail.
"""

from __future__ import annotations

import pathlib

import numpy as np

BYTE_VOCAB = 256


def load_bytes(
    path: str | pathlib.Path, pattern: str = "*.txt"
) -> np.ndarray:
    """One file, or every ``pattern``-matching file under a directory
    (sorted, concatenated) → uint8 token array. The default pattern keeps
    checkpoints/archives that happen to live beside a corpus directory
    out of the token stream."""
    p = pathlib.Path(path)
    if p.is_dir():
        files = sorted(f for f in p.rglob(pattern) if f.is_file())
        if not files:
            raise FileNotFoundError(f"no {pattern} files under {p}")
        data = b"".join(f.read_bytes() for f in files)
    else:
        data = p.read_bytes()
    if not data:
        raise ValueError(f"{p} is empty")
    return np.frombuffer(data, dtype=np.uint8)


def train_valid_split(
    tokens: np.ndarray, valid_frac: float = 0.1
) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic head/tail split of the token stream. The tail is the
    held-out set (no shuffling: adjacent bytes are the dependency being
    modeled, so a shuffled split would leak)."""
    if not 0.0 < valid_frac < 1.0:
        raise ValueError(f"valid_frac={valid_frac}: need 0 < f < 1")
    cut = max(1, int(len(tokens) * (1.0 - valid_frac)))
    if cut >= len(tokens):
        raise ValueError(
            f"corpus of {len(tokens)} tokens leaves no validation tail"
        )
    return tokens[:cut], tokens[cut:]


def load_text_corpus(
    path: str | pathlib.Path, valid_frac: float = 0.1
) -> tuple[np.ndarray, np.ndarray]:
    """(train, valid) int32 byte-token streams for
    :func:`keystone_tpu.models.lm_transformer.train`."""
    toks = load_bytes(path).astype(np.int32)
    return train_valid_split(toks, valid_frac)
