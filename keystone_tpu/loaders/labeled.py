"""LabeledData — (labels, data) bundle (reference loaders/LabeledData.scala)."""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class LabeledData:
    """Bundle of a data batch with its labels, with ``.data`` / ``.labels``
    projections. Batches stay aligned by construction (same leading axis) —
    the 'zip of co-partitioned RDDs' invariant is structural here."""

    labels: Any
    data: Any

    def __post_init__(self):
        n_l = len(self.labels)
        n_d = self.data.shape[0] if hasattr(self.data, "shape") else len(self.data)
        if n_l != n_d:
            raise ValueError(f"labels ({n_l}) and data ({n_d}) row counts differ")

    def __len__(self) -> int:
        return len(self.labels)
