"""CIFAR-10 binary loader (reference loaders/CifarLoader.scala).

Record format: 1 label byte + 3072 pixel bytes (1024 R, 1024 G, 1024 B
planes, row-major). Parsed on the host in one vectorized pass → (N, 32, 32,
3) float batch with values 0-255 (apply PixelScaler for [0,1]).
"""

from __future__ import annotations

import glob
import os

import numpy as np

from keystone_tpu.utils.images import LabeledImages

NROW, NCOL, NCHAN = 32, 32, 3
RECORD = 1 + NROW * NCOL * NCHAN


def load_cifar(path: str, dtype=np.float32) -> LabeledImages:
    """Load all records from a CIFAR-10 binary file, directory, or glob."""
    if os.path.isdir(path):
        files = sorted(glob.glob(os.path.join(path, "*.bin")))
    else:
        files = sorted(glob.glob(path)) or [path]
    from keystone_tpu.native import native_load_cifar

    all_labels, all_images = [], []
    for f in files:
        native = native_load_cifar(f)
        if native is not None:
            labels, images = native
        else:
            raw = np.fromfile(f, dtype=np.uint8)
            if raw.size % RECORD:
                raise ValueError(
                    f"{f}: size {raw.size} is not a multiple of the "
                    f"{RECORD}-byte CIFAR-10 record"
                )
            recs = raw.reshape(-1, RECORD)
            labels = recs[:, 0].astype(np.int32)
            planes = recs[:, 1:].reshape(-1, NCHAN, NROW, NCOL)  # (N, C, H, W)
            images = np.transpose(planes, (0, 2, 3, 1)).astype(np.float32)
        all_labels.append(labels)
        all_images.append(images.astype(dtype, copy=False))
    return LabeledImages(
        labels=np.concatenate(all_labels), images=np.concatenate(all_images)
    )
