"""Dense linear-algebra estimators: PCA, ZCA whitening, LDA
(reference ``nodes/learning/PCA.scala``, ``ZCAWhitener.scala``,
``LinearDiscriminantAnalysis.scala``).

The reference collects samples to the driver and calls LAPACK directly; on
TPU these are small replicated computations (``jnp.linalg`` lowers to XLA)
— the "driver" disappears (SURVEY.md §2.11 gather-to-driver row).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import Estimator, LabelEstimator, Transformer
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.ops.linear import LinearMapper


@treenode
class PCATransformer(Transformer):
    """Project feature vectors: (N, d) @ pca_mat → (N, dims)
    (reference PCATransformer ``pcaMat.t * in`` per vector)."""

    pca_mat: jnp.ndarray  # (d, dims)

    def __call__(self, batch):
        return batch @ self.pca_mat


@treenode
class BatchPCATransformer(Transformer):
    """Project feature-major descriptor matrices: (N, d, m) → (N, dims, m)
    (reference BatchPCATransformer ``pcaMat.t * in``)."""

    pca_mat: jnp.ndarray

    def __call__(self, batch):
        return jnp.einsum("dk,ndm->nkm", self.pca_mat.astype(batch.dtype), batch)


def compute_pca(data, dims: int) -> jnp.ndarray:
    """PCA matrix via SVD of the mean-centered sample, with the MATLAB sign
    convention (largest-|coeff| element of each column positive) — matching
    the reference's PCAEstimator.computePCA."""
    data = jnp.asarray(data)
    centered = data - jnp.mean(data, axis=0)
    _, _, vt = jnp.linalg.svd(centered, full_matrices=False)
    pca = vt.T  # (d, min(n, d)) columns = principal directions
    col_max = jnp.max(pca, axis=0)
    col_abs_max = jnp.max(jnp.abs(pca), axis=0)
    signs = jnp.where(col_max == col_abs_max, 1.0, -1.0).astype(pca.dtype)
    return (pca * signs)[:, :dims]


@treenode
class PCAEstimator(Estimator):
    """Fit PCA on a (sampled) batch (reference PCAEstimator).

    Columns-sampled descriptor sets should be pre-flattened to (N, d) rows
    (ColumnSampler output).
    """

    dims: int = static_field(default=64)

    def fit(self, data) -> PCATransformer:
        return PCATransformer(pca_mat=compute_pca(data, self.dims))

    def fit_batch(self, data) -> BatchPCATransformer:
        return BatchPCATransformer(pca_mat=compute_pca(data, self.dims))


@treenode
class ZCAWhitener(Transformer):
    """(x − mean) @ W (reference nodes/learning/ZCAWhitener.scala)."""

    whitener: jnp.ndarray  # (d, d)
    means: jnp.ndarray  # (d,)

    def __call__(self, batch):
        return (batch - self.means) @ self.whitener


@treenode
class ZCAWhitenerEstimator(Estimator):
    """ZCA whitening matrix from the SVD of one centered sample matrix:
    ``W = V diag((s²/(n−1) + 0.1)^-½) Vᵀ`` (reference ZCAWhitenerEstimator
    — note the 0.1 variance floor is hardcoded there too; its ``eps``
    constructor param is unused)."""

    eps: float = static_field(default=0.1)

    def fit(self, data) -> ZCAWhitener:
        data = jnp.asarray(data)
        means = jnp.mean(data, axis=0)
        centered = data - means
        n = data.shape[0]
        _, s, vt = jnp.linalg.svd(centered, full_matrices=False)
        scale = jax.lax.rsqrt(s * s / (n - 1.0) + self.eps)
        whitener = (vt.T * scale) @ vt
        return ZCAWhitener(whitener=whitener, means=means)


@treenode
class LinearDiscriminantAnalysis(LabelEstimator):
    """Multi-class LDA (reference nodes/learning/LinearDiscriminantAnalysis.scala).

    The reference eigendecomposes ``inv(S_W)·S_B`` (nonsymmetric); TPUs have
    no nonsymmetric eig, so the equivalent symmetric generalized problem is
    solved instead: Cholesky-whiten S_W, then ``eigh`` — same subspace.
    """

    num_dimensions: int = static_field(default=2)

    def fit(self, data, labels) -> LinearMapper:
        x = jnp.asarray(data)
        y = np.asarray(labels)
        classes = np.unique(y)
        d = x.shape[1]
        mean_all = jnp.mean(x, axis=0)
        s_w = jnp.zeros((d, d), x.dtype)
        s_b = jnp.zeros((d, d), x.dtype)
        for c in classes:
            xc = x[np.flatnonzero(y == c)]
            mu = jnp.mean(xc, axis=0)
            dev = xc - mu
            s_w = s_w + dev.T @ dev
            dm = (mu - mean_all)[:, None]
            s_b = s_b + xc.shape[0] * (dm @ dm.T)
        # regularize S_W slightly for Cholesky robustness
        s_w = s_w + 1e-6 * jnp.trace(s_w) / d * jnp.eye(d, dtype=x.dtype)
        l = jnp.linalg.cholesky(s_w)
        li = jax.scipy.linalg.solve_triangular(l, jnp.eye(d, dtype=x.dtype), lower=True)
        m = li @ s_b @ li.T
        vals, vecs = jnp.linalg.eigh(m)
        order = jnp.argsort(-vals)[: self.num_dimensions]
        w = li.T @ vecs[:, order]
        return LinearMapper(x=w)
