"""Multinomial naive Bayes (reference nodes/learning/NaiveBayesModel.scala,
which delegates training to Spark MLlib ``NaiveBayes.train``).

Same model family and λ-smoothing as MLlib's multinomial NB, fitted with two
one-hot matmuls over the (sharded) feature batch — per-class feature sums
and class counts are psum-shaped contractions on TPU.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from keystone_tpu.core.pipeline import LabelEstimator, Transformer
from keystone_tpu.core.treenode import static_field, treenode


@treenode
class NaiveBayesModel(Transformer):
    """``log π + θ·x`` dense log-posteriors (reference NaiveBayesModel)."""

    log_pi: jnp.ndarray  # (C,)
    log_theta: jnp.ndarray  # (C, D)

    def __call__(self, batch):
        return batch @ self.log_theta.T + self.log_pi


@treenode
class NaiveBayesEstimator(LabelEstimator):
    """Fit multinomial NB with λ smoothing (MLlib parity: λ=1.0 default).

    ``data``: (N, D) non-negative counts; ``labels``: (N,) int classes.
    """

    num_classes: int = static_field(default=2)
    lam: float = static_field(default=1.0)

    def fit(self, data, labels, n_valid: int | None = None) -> NaiveBayesModel:
        log_pi, log_theta = _nb_fit(
            data, jnp.asarray(labels), n_valid, self.num_classes, self.lam
        )
        return NaiveBayesModel(log_pi=log_pi, log_theta=log_theta)


@partial(jax.jit, static_argnames=("num_classes", "lam"))
def _nb_fit(data, labels, n_valid, num_classes: int, lam: float):
    n = data.shape[0]
    mask = (
        jnp.ones((n,), data.dtype)
        if n_valid is None
        else (jnp.arange(n) < n_valid).astype(data.dtype)
    )
    onehot = jax.nn.one_hot(labels, num_classes, dtype=data.dtype) * mask[:, None]
    class_counts = jnp.sum(onehot, axis=0)  # (C,)
    feature_sums = onehot.T @ data  # (C, D) — sharded contraction
    total = jnp.sum(class_counts)
    log_pi = jnp.log(class_counts + lam) - jnp.log(total + lam * num_classes)
    log_theta = jnp.log(feature_sums + lam) - jnp.log(
        jnp.sum(feature_sums, axis=1, keepdims=True) + lam * data.shape[1]
    )
    return log_pi, log_theta
