"""Attention with sequence/context parallelism over the mesh.

The reference has no sequence models (SURVEY.md §5), but long-context is
first-class here: two standard distributed-attention strategies scale the
sequence axis across chips, with collectives riding ICI:

- :func:`ring_attention` — blockwise attention with K/V blocks rotating
  around the mesh axis via ``ppermute`` while each chip keeps its query
  shard; a numerically-stable online softmax (flash-style running max/sum)
  accumulates across ring steps. Memory per chip is O(S/n · S/n) per step
  instead of O(S²).
- :func:`ulysses_attention` — all-to-all resharding: swap sequence-sharding
  for head-sharding (``lax.all_to_all``), run dense local attention over
  full sequences on 1/n of the heads, swap back.

Both are exact (== dense attention) and composable under jit; tests verify
equality on an 8-device mesh. ``dense_attention`` is the single-chip
reference implementation.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _flash_default() -> bool:
    """Fused Pallas kernels by default on real TPU hardware only."""
    from keystone_tpu.ops.flash_attention import on_tpu

    return on_tpu()


def dense_attention(q, k, v, *, causal: bool = False):
    """Reference multi-head attention. q,k,v: (B, H, S, D)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _ring_attention_shard(
    q, k, v, *, axis_name: str, causal: bool, use_flash: bool
):
    """Per-shard ring attention body (runs under shard_map).

    q, k, v: (B, H, S_local, D) — this chip's sequence shard. With
    ``use_flash`` the per-hop blockwise update runs as the fused Pallas
    kernel (:func:`keystone_tpu.ops.flash_attention.flash_attention_step`);
    the K/V rotation stays an XLA ``ppermute`` over ICI either way.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    scale = 1.0 / math.sqrt(d)

    q_pos = idx * s_local + jnp.arange(s_local)  # global query positions

    if use_flash:
        from keystone_tpu.ops.flash_attention import (
            _LANE,
            flash_attention_step,
        )

        # m/l carried in the kernel's native (…, LANE) tile across hops —
        # only column 0 is meaningful; avoids a 128x broadcast/slice of
        # the softmax state in and out of HBM on every ring step
        m = jnp.full((b, h, s_local, _LANE), -1e30, jnp.float32)
        l = jnp.zeros((b, h, s_local, _LANE), jnp.float32)
        acc = jnp.zeros((b, h, s_local, d), jnp.float32)
        k_blk, v_blk = k, v
        for step in range(n):
            owner = (idx - step) % n
            m, l, acc = flash_attention_step(
                q,
                k_blk,
                v_blk,
                m,
                l,
                acc,
                q_offset=idx * s_local,
                k_offset=owner * s_local,
                causal=causal,
                padded_state=True,
            )
            if step + 1 < n:
                perm = [(j, (j + 1) % n) for j in range(n)]
                k_blk = lax.ppermute(k_blk, axis_name, perm)
                v_blk = lax.ppermute(v_blk, axis_name, perm)
        out = acc / jnp.maximum(l[..., :1], 1e-30)
        return out.astype(q.dtype)

    m = jnp.full((b, h, s_local, 1), -jnp.inf, q.dtype)
    l = jnp.zeros((b, h, s_local, 1), q.dtype)
    acc = jnp.zeros_like(q)

    k_blk, v_blk = k, v
    for step in range(n):
        owner = (idx - step) % n  # which chip's K/V block we hold now
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        if causal:
            k_pos = owner * s_local + jnp.arange(s_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask, scores, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        # guard fully-masked rows (m_new = -inf): exp(-inf - -inf) → nan
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - m_safe)
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        alpha = jnp.where(
            jnp.isfinite(m), jnp.exp(m - m_safe), jnp.zeros_like(m)
        )
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
        m = m_new
        if step + 1 < n:
            perm = [(j, (j + 1) % n) for j in range(n)]
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
    return acc / jnp.maximum(l, 1e-30)


def ring_attention(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    seq_axis: str = "data",
    causal: bool = False,
    use_flash: bool | None = None,
):
    """Exact attention with the sequence axis sharded over ``seq_axis``.

    q, k, v: (B, H, S, D) global arrays (S divisible by the axis size).
    ``use_flash`` selects the fused Pallas per-hop kernel (default: on
    when running on TPU).
    """
    if use_flash is None:
        use_flash = _flash_default()
    spec = P(None, None, seq_axis, None)
    fn = jax.shard_map(
        partial(
            _ring_attention_shard,
            axis_name=seq_axis,
            causal=causal,
            use_flash=use_flash,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # pallas_call outputs carry no varying-mesh-axis metadata; skip the
        # vma consistency check on the flash path
        check_vma=not use_flash,
    )
    return fn(q, k, v)


def _ulysses_shard(q, k, v, *, axis_name: str, causal: bool, use_flash: bool):
    """All-to-all sequence↔head resharding (DeepSpeed-Ulysses style).

    In: (B, H, S_local, D) sequence-sharded → all_to_all → (B, H/n, S, D)
    head-sharded → local attention over the full sequence (fused Pallas
    flash kernel on TPU, dense jnp otherwise) → all_to_all back.
    """

    def seq_to_heads(x):
        # split heads across the axis, gather sequence
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def heads_to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if use_flash:
        from keystone_tpu.ops.flash_attention import flash_attention

        out = flash_attention(qh, kh, vh, causal=causal)
    else:
        out = dense_attention(qh, kh, vh, causal=causal)
    return heads_to_seq(out)


def ulysses_attention(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    seq_axis: str = "data",
    causal: bool = False,
    use_flash: bool | None = None,
):
    """Exact attention via all-to-all head/sequence resharding.

    Requires H divisible by the axis size. Prefers ICI bandwidth over ring
    latency — the usual pick when heads are plentiful.
    """
    if use_flash is None:
        use_flash = _flash_default()
    n = mesh.shape[seq_axis]
    if q.shape[1] % n:
        raise ValueError(f"heads ({q.shape[1]}) not divisible by axis ({n})")
    spec = P(None, None, seq_axis, None)
    fn = jax.shard_map(
        partial(
            _ulysses_shard,
            axis_name=seq_axis,
            causal=causal,
            use_flash=use_flash,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=not use_flash,
    )
    return fn(q, k, v)
