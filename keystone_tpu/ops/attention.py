"""Attention with sequence/context parallelism over the mesh.

The reference has no sequence models (SURVEY.md §5), but long-context is
first-class here: two standard distributed-attention strategies scale the
sequence axis across chips, with collectives riding ICI:

- :func:`ring_attention` — blockwise attention with K/V blocks rotating
  around the mesh axis via ``ppermute`` while each chip keeps its query
  shard; a numerically-stable online softmax (flash-style running max/sum)
  accumulates across ring steps. Memory per chip is O(S/n · S/n) per step
  instead of O(S²).
- :func:`ulysses_attention` — all-to-all resharding: swap sequence-sharding
  for head-sharding (``lax.all_to_all``), run dense local attention over
  full sequences on 1/n of the heads, swap back.

Both are exact (== dense attention) and composable under jit; tests verify
equality on an 8-device mesh. ``dense_attention`` is the single-chip
reference implementation.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _check_seq_divisible(q, mesh, seq_axis: str) -> None:
    """Loud precondition shared by ring/Ulysses — shard_map's own error
    for a non-divisible spec is opaque."""
    n = mesh.shape[seq_axis]
    if q.shape[2] % n:
        raise ValueError(
            f"sequence length {q.shape[2]} not divisible by the "
            f"{seq_axis!r} axis ({n} devices)"
        )


def _flash_default() -> bool:
    """Fused Pallas kernels by default on real TPU hardware only."""
    from keystone_tpu.ops.flash_attention import on_tpu

    return on_tpu()


def dense_attention(q, k, v, *, causal: bool = False):
    """Reference multi-head attention. q,k,v: (B, H, S, D)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _ring_fwd_state(q, k, v, *, axis_name: str, causal: bool,
                    use_flash: bool):
    """Ring forward returning (out, lse). lse is the per-row logsumexp of
    the full (all-hops) masked score matrix, (B, H, S_local) f32 — the
    O(S) residual the ring backward consumes; fully masked rows carry
    -1e30."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    scale = 1.0 / math.sqrt(d)

    q_pos = idx * s_local + jnp.arange(s_local)  # global query positions

    if use_flash:
        from keystone_tpu.ops.flash_attention import (
            _LANE,
            flash_attention_step,
        )

        # m/l carried in the kernel's native (…, LANE) tile across hops —
        # only column 0 is meaningful; avoids a 128x broadcast/slice of
        # the softmax state in and out of HBM on every ring step
        m = jnp.full((b, h, s_local, _LANE), -1e30, jnp.float32)
        l = jnp.zeros((b, h, s_local, _LANE), jnp.float32)
        acc = jnp.zeros((b, h, s_local, d), jnp.float32)
        k_blk, v_blk = k, v
        for step in range(n):
            owner = (idx - step) % n
            m, l, acc = flash_attention_step(
                q,
                k_blk,
                v_blk,
                m,
                l,
                acc,
                q_offset=idx * s_local,
                k_offset=owner * s_local,
                causal=causal,
                padded_state=True,
            )
            if step + 1 < n:
                perm = [(j, (j + 1) % n) for j in range(n)]
                k_blk = lax.ppermute(k_blk, axis_name, perm)
                v_blk = lax.ppermute(v_blk, axis_name, perm)
        out = (acc / jnp.maximum(l[..., :1], 1e-30)).astype(q.dtype)
        lse = m[..., 0] + jnp.log(jnp.maximum(l[..., 0], 1e-30))
        return out, lse

    # softmax state in f32 regardless of q.dtype: lse is load-bearing for
    # the trainable backward, and a bf16 lse (abs err ~0.04 at lse≈10)
    # would denormalize every recomputed probability row
    m = jnp.full((b, h, s_local, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, s_local, 1), jnp.float32)
    acc = jnp.zeros((b, h, s_local, d), jnp.float32)

    k_blk, v_blk = k, v
    for step in range(n):
        owner = (idx - step) % n  # which chip's K/V block we hold now
        scores = (
            jnp.einsum(
                "bhqd,bhkd->bhqk", q, k_blk,
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if causal:
            k_pos = owner * s_local + jnp.arange(s_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask, scores, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        # guard fully-masked rows (m_new = -inf): exp(-inf - -inf) → nan
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - m_safe)
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        alpha = jnp.where(
            jnp.isfinite(m), jnp.exp(m - m_safe), jnp.zeros_like(m)
        )
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        m = m_new
        if step + 1 < n:
            perm = [(j, (j + 1) % n) for j in range(n)]
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
    out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    lse = jnp.where(
        jnp.isfinite(m[..., 0]),
        m[..., 0] + jnp.log(jnp.maximum(l[..., 0], 1e-30)),
        -1e30,
    )
    return out, lse


def _ring_attention_shard(
    q, k, v, *, axis_name: str, causal: bool, use_flash: bool
):
    """Per-shard ring attention body (runs under shard_map).

    q, k, v: (B, H, S_local, D) — this chip's sequence shard. With
    ``use_flash`` the per-hop blockwise update runs as the fused Pallas
    kernel (:func:`keystone_tpu.ops.flash_attention.flash_attention_step`);
    the K/V rotation stays an XLA ``ppermute`` over ICI either way.
    """
    return _ring_fwd_state(
        q, k, v, axis_name=axis_name, causal=causal, use_flash=use_flash
    )[0]


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_shard_trainable(q, k, v, axis_name, causal, use_flash):
    """Differentiable per-shard ring attention: flash-rate forward, ring
    backward. The backward circulates each K/V shard around the ring a
    second time together with its grad accumulators — per hop it
    recomputes that rectangle's probabilities from (q, k, lse) with the
    blockwise machinery (never an (S, S) tensor), adds dq locally and
    dk/dv into the traveling accumulators, then one final ppermute brings
    every accumulator home. Exactly n extra ppermutes over ICI; memory
    O(S_local·d)."""
    return _ring_fwd_state(
        q, k, v, axis_name=axis_name, causal=causal, use_flash=use_flash
    )[0]


def _ring_trainable_fwd(q, k, v, axis_name, causal, use_flash):
    out, lse = _ring_fwd_state(
        q, k, v, axis_name=axis_name, causal=causal, use_flash=use_flash
    )
    return out, (q, k, v, out, lse)


def _ring_trainable_bwd(axis_name, causal, use_flash, res, g):
    from keystone_tpu.ops.flash_attention import _bwd_block, _grads_rect

    q, k, v, out, lse = res
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    q_off = idx * s_local

    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)

    bwd_block = _bwd_block()
    blk = bwd_block if s_local > bwd_block else -(-s_local // 8) * 8
    pad = -(-s_local // blk) * blk - s_local

    dq = jnp.zeros((b, h, s_local, d), jnp.float32)
    k_blk, v_blk = k, v
    dk_blk = jnp.zeros((b, h, s_local, d), jnp.float32)
    dv_blk = jnp.zeros_like(dk_blk)
    perm = [(j, (j + 1) % n) for j in range(n)]
    for step in range(n):
        owner = (idx - step) % n
        k_off = owner * s_local

        def hop_grads(k_blk, v_blk, k_off):
            kp = jnp.pad(
                k_blk.astype(jnp.float32),
                ((0, 0), (0, 0), (0, pad), (0, 0)),
            )
            vp = jnp.pad(
                v_blk.astype(jnp.float32),
                ((0, 0), (0, 0), (0, pad), (0, 0)),
            )
            return _grads_rect(
                qf, kp, vp, gf, delta, lse, q_off, k_off + s_local,
                causal, blk, k_off=k_off,
            )

        if causal:
            # hops whose K/V shard is entirely in this chip's future are
            # fully masked — skip their three dead gemm sweeps (the
            # ppermutes below stay unconditional: the ring must rotate)
            dq_c, dk_c, dv_c = lax.cond(
                owner <= idx,
                hop_grads,
                lambda k_, v_, o_: (
                    jnp.zeros_like(dq),
                    jnp.zeros((b, h, pad + s_local, d), jnp.float32),
                    jnp.zeros((b, h, pad + s_local, d), jnp.float32),
                ),
                k_blk, v_blk, k_off,
            )
        else:
            dq_c, dk_c, dv_c = hop_grads(k_blk, v_blk, k_off)
        dq = dq + dq_c
        dk_blk = dk_blk + dk_c[:, :, :s_local]
        dv_blk = dv_blk + dv_c[:, :, :s_local]
        if step + 1 < n:
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
            dk_blk = lax.ppermute(dk_blk, axis_name, perm)
            dv_blk = lax.ppermute(dv_blk, axis_name, perm)
    # after n-1 rotations shard s (and its accumulated grads) sits on chip
    # s-1; one final hop sends every accumulator home
    dk_blk = lax.ppermute(dk_blk, axis_name, perm)
    dv_blk = lax.ppermute(dv_blk, axis_name, perm)
    return dq.astype(q.dtype), dk_blk.astype(k.dtype), dv_blk.astype(v.dtype)


_ring_shard_trainable.defvjp(_ring_trainable_fwd, _ring_trainable_bwd)


def ring_attention(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    seq_axis: str = "data",
    causal: bool = False,
    use_flash: bool | None = None,
    trainable: bool = False,
):
    """Exact attention with the sequence axis sharded over ``seq_axis``.

    q, k, v: (B, H, S, D) global arrays (S divisible by the axis size).
    ``use_flash`` selects the fused Pallas per-hop kernel (default: on
    when running on TPU). ``trainable`` swaps in the custom-VJP shard
    body (ring backward with traveling dk/dv accumulators) — required to
    differentiate the flash path (its kernels are forward-only), and
    blockwise-memory-bounded for the jnp path too.
    """
    if use_flash is None:
        use_flash = _flash_default()
    _check_seq_divisible(q, mesh, seq_axis)
    spec = P(None, None, seq_axis, None)
    if trainable:
        body = lambda q_, k_, v_: _ring_shard_trainable(  # noqa: E731
            q_, k_, v_, seq_axis, causal, use_flash
        )
    else:
        body = partial(
            _ring_attention_shard,
            axis_name=seq_axis,
            causal=causal,
            use_flash=use_flash,
        )
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # pallas_call outputs carry no varying-mesh-axis metadata, and the
        # trainable backward's zero-initialized scan carries start
        # device-invariant before accumulating device-varying grads —
        # both trip the vma consistency check spuriously
        check_vma=not (use_flash or trainable),
    )
    return fn(q, k, v)


def _ulysses_shard(q, k, v, *, axis_name: str, causal: bool,
                   use_flash: bool, trainable: bool = False):
    """All-to-all sequence↔head resharding (DeepSpeed-Ulysses style).

    In: (B, H, S_local, D) sequence-sharded → all_to_all → (B, H/n, S, D)
    head-sharded → local attention over the full sequence (fused Pallas
    flash kernel on TPU, dense jnp otherwise) → all_to_all back.
    ``trainable`` uses the flash trainable wrapper for the local part —
    ``all_to_all`` is linear, so JAX transposes it in the backward on its
    own; only the attention kernel needs the custom VJP.
    """

    def seq_to_heads(x):
        # split heads across the axis, gather sequence
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    def heads_to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if use_flash and trainable:
        from keystone_tpu.ops.flash_attention import (
            flash_attention_trainable,
        )

        out = flash_attention_trainable(qh, kh, vh, causal)
    elif use_flash:
        from keystone_tpu.ops.flash_attention import flash_attention

        out = flash_attention(qh, kh, vh, causal=causal)
    else:
        out = dense_attention(qh, kh, vh, causal=causal)
    return heads_to_seq(out)


def ulysses_attention(
    q,
    k,
    v,
    mesh: Mesh,
    *,
    seq_axis: str = "data",
    causal: bool = False,
    use_flash: bool | None = None,
    trainable: bool = False,
):
    """Exact attention via all-to-all head/sequence resharding.

    Requires H divisible by the axis size. Prefers ICI bandwidth over ring
    latency — the usual pick when heads are plentiful. ``trainable``
    makes the flash path differentiable (blockwise recompute backward).
    """
    if use_flash is None:
        use_flash = _flash_default()
    n = mesh.shape[seq_axis]
    if q.shape[1] % n:
        raise ValueError(f"heads ({q.shape[1]}) not divisible by axis ({n})")
    _check_seq_divisible(q, mesh, seq_axis)
    spec = P(None, None, seq_axis, None)
    fn = jax.shard_map(
        partial(
            _ulysses_shard,
            axis_name=seq_axis,
            causal=causal,
            use_flash=use_flash,
            trainable=trainable,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=not use_flash,
    )
    return fn(q, k, v)
