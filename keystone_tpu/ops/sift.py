"""Dense multi-scale SIFT on-device — vl_dsift flat-window semantics.

TPU-native replacement for the reference's native VLFeat JNI component
(``src/main/cpp/VLFeat.cxx`` over vl_dsift; SURVEY.md §2.10). The shim
runs vl_dsift with the FLAT-window fast path (``useFlatWindow=VL_TRUE``,
``windowSize=1.5``, ``VLFeat.cxx:98-104``); this module reproduces that
algorithm exactly, stage by stage:

- scales: bin sizes ``bin + 2·s``; per scale the ORIGINAL image is
  gaussian-smoothed with ``sigma = bin_s / magnif`` (magnif 6,
  ``VLFeat.cxx:85-91``), kernel radius ``ceil(4σ)``, edge ("continuity")
  padding — vl_imsmooth behavior;
- gradients by central differences, one-sided (not halved) at borders —
  vl_dsift_process;
- soft angular binning of the magnitude into 8 orientation planes;
- bilinear spatial binning as a unit-integral triangular convolution of
  each plane (vl_imconvcoltri, edge padding), POINT-SAMPLED at bin
  corners ``frame + bin_index · bin_s`` — the flat-window trick: the
  per-descriptor gaussian window is replaced by per-bin constant weights
  ``w(i)·w(j)·bin_s²`` (``_vl_dsift_get_bin_window_mean`` with
  windowSize 1.5);
- keypoint grid: ``off = (1 + 2·num_scales) − 3·s`` clamped to 0
  (``VLFeat.cxx:93-96``), frames up to ``dim − 3·bin_s − 1``, step
  ``step + s·scale_step``;
- descriptors L2-normalized, clamped at 0.2, renormalized; descriptors
  whose PRE-normalization norm < 0.005 zeroed (the shim's
  contrast-threshold copy-suppression, ``VLFeat.cxx:143-152``);
- quantized ``min(trunc(512·v), 255)`` (``VLFeat.cxx:260-263``).

Axis convention: the shim feeds vlfeat the transposed image (xDim=height,
``SIFTExtractor.scala:82``, ``Image.scala:89-103``) and transposes each
descriptor back (``vl_dsift_transpose_descriptor``). The net layout
reproduced here: descriptor entries ordered (row-bin, col-bin,
orientation) with orientation angle ``atan2(−gx, gy)``, keypoints
ordered column-outer / row-inner, scales concatenated (the shim's
``groupByPixels=false`` branch).

Everything is one jitted program of convolutions and gathers — no host
round-trip per image, unlike the JNI-per-image reference path. Gated
against an independent direct-summation golden (tests/goldens) with the
reference tolerance: ≥99.5% of entries within ±1
(``VLFeatSuite.scala:46-51``).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.utils.images import conv2d_separable

NUM_ORIENTATIONS = 8
NUM_SPATIAL_BINS = 4
DESC_DIM = NUM_ORIENTATIONS * NUM_SPATIAL_BINS * NUM_SPATIAL_BINS  # 128
CONTRAST_THRESHOLD = 0.005
WINDOW_SIZE = 1.5  # vl window size (VLFeat.cxx:103)
MAGNIF = 6.0


def gaussian_kernel(sigma: float) -> np.ndarray:
    radius = max(int(math.ceil(4.0 * sigma)), 1)
    x = np.arange(-radius, radius + 1, dtype=np.float32)
    k = np.exp(-0.5 * (x / max(sigma, 1e-8)) ** 2)
    return (k / k.sum()).astype(np.float32)


def triangular_kernel(bin_size: int) -> np.ndarray:
    """vl_imconvcoltri: unit-INTEGRAL triangle over (−bin, bin)."""
    u = np.arange(-bin_size + 1, bin_size, dtype=np.float32)
    return (bin_size - np.abs(u)) / (bin_size * bin_size)


def bin_window_mean(bin_size: int, bin_index: int) -> float:
    """_vl_dsift_get_bin_window_mean: mean of the flat-window gaussian
    (sigma = bin_size · windowSize) over one bin's triangle support."""
    delta = bin_size * (bin_index - 0.5 * (NUM_SPATIAL_BINS - 1))
    sigma = bin_size * WINDOW_SIZE
    x = np.arange(-bin_size + 1, bin_size, dtype=np.float64)
    z = (x - delta) / sigma
    return float(np.mean(np.exp(-0.5 * z * z)))


def _conv_edge_padded(img, k: np.ndarray):
    """Separable convolution with edge replication (VL_PAD_BY_CONTINUITY)."""
    r = (len(k) - 1) // 2
    pad = ((0, 0), (r, r), (r, r)) + ((0, 0),) * (img.ndim - 3)
    padded = jnp.pad(img, pad, mode="edge")
    if img.ndim == 3:
        out = conv2d_separable(padded[..., None], k, k)[..., 0]
    else:
        out = conv2d_separable(padded, k, k)
    return out[:, r:-r, r:-r] if r else out


def _gradients(img):
    """vl_dsift gradients: central differences, one-sided at borders."""
    gr = jnp.concatenate(
        [
            (img[:, 1:2, :] - img[:, 0:1, :]),
            0.5 * (img[:, 2:, :] - img[:, :-2, :]),
            (img[:, -1:, :] - img[:, -2:-1, :]),
        ],
        axis=1,
    )  # d/d(row)
    gc = jnp.concatenate(
        [
            (img[:, :, 1:2] - img[:, :, 0:1]),
            0.5 * (img[:, :, 2:] - img[:, :, :-2]),
            (img[:, :, -1:] - img[:, :, -2:-1]),
        ],
        axis=2,
    )  # d/d(col)
    return gr, gc


def _orientation_planes(img):
    """(N, H, W) → (N, H, W, 8) soft-binned gradient magnitude planes.

    Angle convention matches the shim's net transpose: θ = atan2(−gx, gy)
    where gx is the column derivative and gy the row derivative.
    """
    gy, gx = _gradients(img)
    mag = jnp.sqrt(gx * gx + gy * gy)
    angle = jnp.arctan2(-gx, gy)
    t = angle * (NUM_ORIENTATIONS / (2 * jnp.pi))
    t = jnp.mod(t, NUM_ORIENTATIONS)
    lo = jnp.floor(t)
    frac = t - lo
    lo = lo.astype(jnp.int32) % NUM_ORIENTATIONS
    hi = (lo + 1) % NUM_ORIENTATIONS
    onehot_lo = jax.nn.one_hot(lo, NUM_ORIENTATIONS, dtype=img.dtype)
    onehot_hi = jax.nn.one_hot(hi, NUM_ORIENTATIONS, dtype=img.dtype)
    return (
        onehot_lo * (mag * (1 - frac))[..., None]
        + onehot_hi * (mag * frac)[..., None]
    )


def _scale_descriptors(img, bin_size: int, step: int, offset: int):
    """Flat-window descriptors for one scale. img: (N, H, W) smoothed.

    Returns (N, num_kp, 128) unnormalized histograms in (row-bin,
    col-bin, orientation) order, keypoints column-outer / row-inner.
    """
    n, h, w = img.shape
    planes = _orientation_planes(img)  # (N, H, W, 8)
    tri = triangular_kernel(bin_size)
    acc = _conv_edge_padded(planes, tri)  # (N, H, W, 8)

    frame_size = (NUM_SPATIAL_BINS - 1) * bin_size + 1
    rs = np.arange(offset, h - frame_size + 1, step, dtype=np.int32)
    cs = np.arange(offset, w - frame_size + 1, step, dtype=np.int32)
    if len(rs) == 0 or len(cs) == 0:
        return jnp.zeros((n, 0, DESC_DIM), img.dtype)

    bin_off = np.arange(NUM_SPATIAL_BINS, dtype=np.int32) * bin_size
    row_idx = (rs[:, None] + bin_off[None, :]).reshape(-1)  # (kr·4,)
    col_idx = (cs[:, None] + bin_off[None, :]).reshape(-1)  # (kc·4,)
    g = jnp.take(acc, jnp.asarray(row_idx), axis=1)
    g = jnp.take(g, jnp.asarray(col_idx), axis=2)
    # (N, kr, 4, kc, 4, 8) → keypoints column-outer: (N, kc, kr, 4, 4, 8)
    g = g.reshape(
        n, len(rs), NUM_SPATIAL_BINS, len(cs), NUM_SPATIAL_BINS,
        NUM_ORIENTATIONS,
    )
    g = jnp.transpose(g, (0, 3, 1, 2, 4, 5))
    # flat-window bin weights: w(i)·w(j)·bin² (triangle conv is
    # unit-integral; SIFT wants unit height → ×bin per axis)
    wmean = np.array(
        [bin_window_mean(bin_size, i) for i in range(NUM_SPATIAL_BINS)],
        np.float32,
    ) * bin_size
    g = g * (wmean[:, None, None] * wmean[None, :, None])
    return g.reshape(n, len(rs) * len(cs), DESC_DIM)


def _finalize(desc):
    """vl_dsift + shim post-processing: L2 → clamp 0.2 → re-L2 →
    quantize min(trunc(512v), 255); zero descriptors whose
    pre-normalization norm < 0.005 (the shim's contrast threshold)."""
    norm = jnp.linalg.norm(desc, axis=-1, keepdims=True)
    d = desc / jnp.maximum(norm, 1e-10)
    d = jnp.minimum(d, 0.2)
    d = d / jnp.maximum(jnp.linalg.norm(d, axis=-1, keepdims=True), 1e-10)
    d = jnp.minimum(jnp.floor(512.0 * d), 255.0)
    return jnp.where(norm >= CONTRAST_THRESHOLD, d, 0.0)


@treenode
class SIFTExtractor(Transformer):
    """Multi-scale dense SIFT (reference external.SIFTExtractor; the VOC
    pipeline uses step 3, bin 4, 5 scales, scale_step 0).

    Input: (N, H, W) or (N, H, W, 1) grayscale in [0, 1].
    Output: (N, 128, M) quantized descriptors, scales concatenated in
    order (the shim's groupByPixels=false concat path).
    """

    step: int = static_field(default=3)
    bin_size: int = static_field(default=4)
    num_scales: int = static_field(default=5)
    scale_step: int = static_field(default=0)
    # "device": one jitted XLA program (default). "native": the C++ host
    # kernel (native/dsift.cpp via ctypes) — the VLFeat-shim parity
    # fallback, same algorithm and layout, for hosts without a usable
    # accelerator; falls back to device if the library won't build.
    backend: str = static_field(default="device")

    def __call__(self, batch):
        if batch.ndim == 4:
            batch = batch[..., 0]
        if self.backend == "native":
            if isinstance(batch, jax.core.Tracer):
                raise TypeError(
                    "SIFTExtractor(backend='native') is a host-only path "
                    "and cannot run under jit; use the default device "
                    "backend inside jitted pipelines"
                )
            from keystone_tpu.native import native_dsift

            out = native_dsift(
                np.asarray(batch),
                step=self.step,
                bin_size=self.bin_size,
                num_scales=self.num_scales,
                scale_step=self.scale_step,
            )
            if out is not None:
                return jnp.asarray(out)
        elif self.backend != "device":
            raise ValueError(
                f"SIFTExtractor backend={self.backend!r}; "
                "expected device|native"
            )
        return _sift_multiscale(
            batch, self.step, self.bin_size, self.num_scales, self.scale_step
        )


@partial(
    jax.jit, static_argnames=("step", "bin_size", "num_scales", "scale_step")
)
def _sift_multiscale(
    img, step: int, bin_size: int, num_scales: int, scale_step: int
):
    outs = []
    for s in range(num_scales):
        bin_s = bin_size + 2 * s
        sigma = bin_s / MAGNIF
        k = gaussian_kernel(sigma)
        smoothed = _conv_edge_padded(img, k)
        offset = max((1 + 2 * num_scales) - 3 * s, 0)
        desc = _scale_descriptors(
            smoothed, bin_s, step + s * scale_step, offset
        )
        outs.append(_finalize(desc))
    all_desc = jnp.concatenate(outs, axis=1)  # (N, M, 128)
    return jnp.transpose(all_desc, (0, 2, 1))  # (N, 128, M)
