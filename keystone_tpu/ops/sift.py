"""Dense multi-scale SIFT on-device.

TPU-native replacement for the reference's native VLFeat JNI component
(``src/main/cpp/VLFeat.cxx`` over vl_dsift; SURVEY.md §2.10). Shim-parity
structure:

- scales: bin sizes ``bin + 2·s`` for s = 0..num_scales−1,
- per scale the image is gaussian-smoothed with ``sigma = bin_s / 6``
  (magnif 6), gradients → 8 soft-binned orientation planes, 4×4 spatial
  bins of size ``bin_s``,
- keypoint grid starts at ``off = (1 + 2·num_scales) − 3·s`` with the given
  step (the shim's bounding-box trick),
- descriptors L2-normalized, clamped at 0.2, renormalized (standard SIFT),
- low-contrast descriptors (pre-normalization norm < 0.005) zeroed — the
  shim's contrast-threshold zeroing,
- quantized ``min(512·v, 255)`` like the shim's short output.

Everything is one jitted program of convolutions and gathers — no host
round-trip per image, unlike the JNI-per-image reference path. The spatial
weighting uses bilinear (triangular) binning, vl_dsift's exact-SIFT mode
(the shim enables the flat-window *approximation* for speed; bit-exact
parity with vl_phow goldens is a known gap tracked for a later round).

Output layout matches ``SIFTExtractor.scala``: per image a feature-major
(128, num_descriptors) matrix, batched to (N, 128, M).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.utils.images import conv2d_separable

NUM_ORIENTATIONS = 8
NUM_SPATIAL_BINS = 4
DESC_DIM = NUM_ORIENTATIONS * NUM_SPATIAL_BINS * NUM_SPATIAL_BINS  # 128
CONTRAST_THRESHOLD = 0.005


def gaussian_kernel(sigma: float) -> np.ndarray:
    radius = max(int(math.ceil(4.0 * sigma)), 1)
    x = np.arange(-radius, radius + 1, dtype=np.float32)
    k = np.exp(-0.5 * (x / max(sigma, 1e-8)) ** 2)
    return k / k.sum()


def _smooth_edge_padded(img, k: np.ndarray):
    """Gaussian smooth with edge replication (vl_imsmooth behavior) — plain
    zero padding would manufacture gradients at the borders."""
    r = (len(k) - 1) // 2
    padded = jnp.pad(img, ((0, 0), (r, r), (r, r)), mode="edge")
    out = conv2d_separable(padded[..., None], k, k)[..., 0]
    return out[:, r:-r, r:-r] if r else out


def _orientation_planes(img):
    """(N, H, W) → (N, H, W, 8) soft-binned gradient magnitude planes."""
    gy = jnp.pad(img[:, 2:, :] - img[:, :-2, :], ((0, 0), (1, 1), (0, 0))) * 0.5
    gx = jnp.pad(img[:, :, 2:] - img[:, :, :-2], ((0, 0), (0, 0), (1, 1))) * 0.5
    mag = jnp.sqrt(gx * gx + gy * gy)
    angle = jnp.arctan2(gy, gx)  # [-pi, pi]
    t = angle / (2 * jnp.pi / NUM_ORIENTATIONS)  # in bins
    t = jnp.mod(t, NUM_ORIENTATIONS)
    lo = jnp.floor(t)
    frac = t - lo
    lo = lo.astype(jnp.int32) % NUM_ORIENTATIONS
    hi = (lo + 1) % NUM_ORIENTATIONS
    onehot_lo = jax.nn.one_hot(lo, NUM_ORIENTATIONS, dtype=img.dtype)
    onehot_hi = jax.nn.one_hot(hi, NUM_ORIENTATIONS, dtype=img.dtype)
    return (
        onehot_lo * (mag * (1 - frac))[..., None]
        + onehot_hi * (mag * frac)[..., None]
    )


def _scale_descriptors(img, bin_size: int, step: int, offset: int):
    """Descriptors for one scale. img: (N, H, W) already smoothed.

    Returns (N, num_kp, 128) unnormalized histograms.
    """
    n, h, w = img.shape
    planes = _orientation_planes(img)  # (N, H, W, 8)
    # triangular spatial window of half-width bin_size (exact-SIFT mode)
    tri = np.maximum(
        0.0, 1.0 - np.abs(np.arange(-bin_size + 1, bin_size)) / bin_size
    ).astype(np.float32)
    acc = conv2d_separable(planes, tri, tri)  # (N, H, W, 8)

    support = NUM_SPATIAL_BINS * bin_size
    # bin centers relative to descriptor corner (rounded to pixels)
    centers = (np.arange(NUM_SPATIAL_BINS) * bin_size + (bin_size - 1) / 2.0)
    centers = np.round(centers).astype(np.int32)
    max_corner_y = h - support
    max_corner_x = w - support
    ys0 = np.arange(offset, max_corner_y + 1, step, dtype=np.int32)
    xs0 = np.arange(offset, max_corner_x + 1, step, dtype=np.int32)
    if len(ys0) == 0 or len(xs0) == 0:
        return jnp.zeros((n, 0, DESC_DIM), img.dtype)

    row_idx = (ys0[:, None] + centers[None, :]).reshape(-1)  # (ky*4,)
    col_idx = (xs0[:, None] + centers[None, :]).reshape(-1)  # (kx*4,)
    g = jnp.take(acc, jnp.asarray(row_idx), axis=1)
    g = jnp.take(g, jnp.asarray(col_idx), axis=2)
    # (N, ky, 4, kx, 4, 8) → (N, ky, kx, 4, 4, 8)
    g = g.reshape(n, len(ys0), NUM_SPATIAL_BINS, len(xs0), NUM_SPATIAL_BINS, NUM_ORIENTATIONS)
    g = jnp.transpose(g, (0, 1, 3, 2, 4, 5))
    return g.reshape(n, len(ys0) * len(xs0), DESC_DIM)


def _finalize(desc):
    """SIFT normalization: L2 → clamp 0.2 → re-L2 → quantize min(512v, 255);
    zero out low-contrast descriptors (pre-norm norm < 0.005)."""
    norm = jnp.linalg.norm(desc, axis=-1, keepdims=True)
    d = desc / jnp.maximum(norm, 1e-10)
    d = jnp.minimum(d, 0.2)
    d = d / jnp.maximum(jnp.linalg.norm(d, axis=-1, keepdims=True), 1e-10)
    d = jnp.minimum(jnp.floor(512.0 * d), 255.0)
    return jnp.where(norm >= CONTRAST_THRESHOLD, d, 0.0)


@treenode
class SIFTExtractor(Transformer):
    """Multi-scale dense SIFT (reference external.SIFTExtractor defaults:
    step 3, bin 4, 5 scales, scale_step 0).

    Input: (N, H, W) or (N, H, W, 1) grayscale in [0, 1].
    Output: (N, 128, M) quantized descriptors, scales concatenated in order
    (the shim's no-grouping concat path).
    """

    step: int = static_field(default=3)
    bin_size: int = static_field(default=4)
    num_scales: int = static_field(default=5)
    scale_step: int = static_field(default=0)

    def __call__(self, batch):
        if batch.ndim == 4:
            batch = batch[..., 0]
        return _sift_multiscale(
            batch, self.step, self.bin_size, self.num_scales, self.scale_step
        )


@partial(
    jax.jit, static_argnames=("step", "bin_size", "num_scales", "scale_step")
)
def _sift_multiscale(
    img, step: int, bin_size: int, num_scales: int, scale_step: int
):
    outs = []
    for s in range(num_scales):
        bin_s = bin_size + 2 * s
        sigma = bin_s / 6.0
        k = gaussian_kernel(sigma)
        smoothed = _smooth_edge_padded(img, k)
        offset = max((1 + 2 * num_scales) - 3 * s, 0)
        desc = _scale_descriptors(
            smoothed, bin_s, step + s * scale_step, offset
        )
        outs.append(_finalize(desc))
    all_desc = jnp.concatenate(outs, axis=1)  # (N, M, 128)
    return jnp.transpose(all_desc, (0, 2, 1))  # (N, 128, M)
