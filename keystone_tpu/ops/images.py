"""Image nodes (reference ``nodes/images/``, SURVEY.md §2.3).

All nodes operate on (N, H, W, C) float batches. Patch/feature layouts
flatten as (dy, dx, c) with channel fastest — the reference's patch index
``c + x·C + y·C·k`` (Convolver.makePatches), so fitted filters/whiteners are
layout-compatible across the whole stack.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import FunctionNode, Transformer
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.utils.images import rgb_to_gray


@treenode
class GrayScaler(Transformer):
    """MATLAB rgb2gray weights (reference ImageUtils.toGrayScale)."""

    def __call__(self, batch):
        return rgb_to_gray(batch)


@treenode
class PixelScaler(Transformer):
    """Scale byte pixels to [0,1] (reference nodes/images/PixelScaler.scala)."""

    def __call__(self, batch):
        return batch / 255.0


@treenode
class ImageVectorizer(Transformer):
    """(N, H, W, C) → (N, H·W·C), channel fastest
    (reference nodes/images/ImageVectorizer.scala)."""

    def __call__(self, batch):
        return batch.reshape(batch.shape[0], -1)


def extract_patches(batch, patch_size: int, stride: int = 1):
    """All patch_size×patch_size windows at the given stride.

    Returns (N, oh, ow, patch_size·patch_size·C) with (dy, dx, c) flattening,
    channel fastest — matching the reference patch layout.

    Pure strided slicing — exact data movement, no arithmetic. (The
    previous ``conv_general_dilated_patches`` formulation lowers to a
    real convolution, which at XLA's default precision rounds the patch
    VALUES through bf16 passes — ~0.2% error on pixels, measured on both
    CPU and TPU backends.)
    """
    n, h, w, c = batch.shape
    k = patch_size
    oh = (h - k) // stride + 1
    ow = (w - k) // stride + 1
    slabs = [
        batch[
            :,
            dy : dy + (oh - 1) * stride + 1 : stride,
            dx : dx + (ow - 1) * stride + 1 : stride,
            :,
        ]
        for dy in range(k)
        for dx in range(k)
    ]  # k² slabs of (N, oh, ow, C), ordered (dy, dx) — channel fastest
    return jnp.concatenate(slabs, axis=-1).reshape(n, oh, ow, k * k * c)


@treenode
class Windower(FunctionNode):
    """FlatMap each image into all stride-spaced square windows
    (reference nodes/images/Windower.scala).

    (N, H, W, C) → (N·n_windows, w, w, C).
    """

    stride: int = static_field(default=1)
    window_size: int = static_field(default=6)

    def __call__(self, batch):
        n, _, _, c = batch.shape
        w = self.window_size
        p = extract_patches(batch, w, self.stride)
        return p.reshape(n * p.shape[1] * p.shape[2], w, w, c)


def normalize_patch_rows(mat, var_constant: float = 10.0):
    """Per-row mean-center and divide by sqrt(var + alpha)
    (reference utils/Stats.scala normalizeRows; var uses d-1 denominator)."""
    d = mat.shape[-1]
    mean = jnp.mean(mat, axis=-1, keepdims=True)
    var = jnp.sum((mat - mean) ** 2, axis=-1, keepdims=True) / max(d - 1, 1)
    return (mat - mean) / jnp.sqrt(var + var_constant)


def conv_convolver(
    batch,
    filters,
    *,
    patch_size: int,
    normalize_patches: bool,
    var_constant: float,
    whitener_means=None,
    precision=None,
):
    """Convolver forward as ONE dense convolution plus box-filter algebra.

    The reference's per-patch normalization (``Stats.normalizeRows``) is
    affine in the patch: with per-patch mean mu and sigma = sqrt(var+vc),

        ((p - mu)/sigma - m) . F_f = (p.F_f - mu * sum(F_f)) / sigma - m.F_f

    so the whole im2col pipeline factors into a plain MXU convolution
    (``p.F_f``) plus per-patch scalars from two box-filter reductions —
    no (N, oh, ow, k^2 C) patch tensor ever exists. HBM traffic drops from
    ~k^2 x image bytes to image-in/featuremap-out; this is the TPU-first
    design the fused Pallas kernel approximated, measured faster than
    both it and the XLA im2col path on a real v5e (TPU_VALIDATION.json).

    The box sums run through ``lax.reduce_window`` (exact f32 VPU adds),
    not the MXU, so mu/sigma carry no bf16-pass rounding.
    """
    n, h, w, c = batch.shape
    k = patch_size
    f = filters.shape[0]
    d = k * k * c
    batch = batch.astype(jnp.float32)
    filters = filters.astype(jnp.float32)
    # (F, d) rows are (dy, dx, c) flattened, channel fastest -> HWIO
    wts = jnp.transpose(filters.reshape(f, k, k, c), (1, 2, 3, 0))
    dn = jax.lax.conv_dimension_numbers(
        batch.shape, wts.shape, ("NHWC", "HWIO", "NHWC")
    )
    out = jax.lax.conv_general_dilated(
        batch, wts, (1, 1), "VALID", dimension_numbers=dn,
        precision=precision,
    )  # (N, oh, ow, F)
    if normalize_patches:
        csum = jnp.sum(batch, axis=-1)  # (N, H, W)
        csq = jnp.sum(batch * batch, axis=-1)
        box = lambda x: jax.lax.reduce_window(  # noqa: E731
            x, 0.0, jax.lax.add, (1, k, k), (1, 1, 1), "VALID"
        )
        s1 = box(csum)  # (N, oh, ow) patch sums
        s2 = box(csq)
        mu = s1 / d
        # clamp: one-pass variance can round slightly negative for flat
        # patches, which would NaN the sqrt at var_constant=0
        var = jnp.maximum(s2 - s1 * mu, 0.0) / max(d - 1, 1)
        sigma = jnp.sqrt(var + var_constant)
        colsum = jnp.sum(filters, axis=1)  # (F,)
        out = (out - mu[..., None] * colsum) / sigma[..., None]
    if whitener_means is not None:
        out = out - jnp.einsum(
            "fd,d->f",
            filters,
            jnp.asarray(whitener_means, jnp.float32),
            precision=precision,
        )
    return out


@treenode
class Convolver(Transformer):
    """Filter-bank convolution (reference nodes/images/Convolver.scala).

    The reference packs every patch into a row, optionally normalizes each
    patch (``Stats.normalizeRows`` with ``varConstant``), optionally
    subtracts the whitener means, then does one gemm with the filter bank.
    Implementations:

    - ``conv`` (default via auto): :func:`conv_convolver` — the
      normalization algebra folded around one dense MXU convolution.
    - ``xla``: im2col — materialize patches, normalize, gemm (the
      reference's schedule; the parity baseline the others are tested
      against).

    A Pallas im2col kernel (``impl="fused"``) existed through round 2 and
    was retired: per-image im2col with C=3 writes 3-of-128 lanes per
    store — structurally lane-hostile — and it measured 0.28× the im2col
    path on v5e while the conv-algebra path won (ROOFLINE.md §5). Folding
    the normalization *algebraically* around XLA's native conv lowering
    is the TPU-first answer here, not a hand-written kernel.

    ``filters``: (num_filters, patch_size²·C), rows in (dy, dx, c) layout —
    exactly what :class:`Windower`+:class:`ImageVectorizer` sampling or
    ``RandomPatchCifar``-style whitened filter construction produces.
    """

    filters: jnp.ndarray
    whitener_means: jnp.ndarray | None = None
    patch_size: int = static_field(default=6)
    normalize_patches: bool = static_field(default=True)
    var_constant: float = static_field(default=10.0)
    impl: str = static_field(default="auto")
    # gemm/conv precision: None = backend default (bf16 MXU passes on
    # TPU, ~0.2% relative); "highest" = full f32 (reference-BLAS class)
    precision: str | None = static_field(default=None)

    def __call__(self, batch):
        if self.impl not in ("auto", "conv", "xla"):
            raise ValueError(
                f"Convolver impl={self.impl!r}; expected auto|conv|xla"
            )
        # every impl computes and emits float32; keeps auto-path output
        # independent of which impl runs
        batch = batch.astype(jnp.float32)
        if self.impl in ("auto", "conv"):
            return conv_convolver(
                batch,
                self.filters,
                patch_size=self.patch_size,
                normalize_patches=self.normalize_patches,
                var_constant=self.var_constant,
                whitener_means=self.whitener_means,
                precision=self.precision,
            )
        p = extract_patches(batch, self.patch_size)  # (N, oh, ow, k²C)
        if self.normalize_patches:
            p = normalize_patch_rows(p, self.var_constant)
        if self.whitener_means is not None:
            p = p - self.whitener_means
        return jnp.einsum(
            "nhwp,fp->nhwf",
            p,
            self.filters.astype(p.dtype),
            precision=self.precision,
        )


@treenode
class SymmetricRectifier(Transformer):
    """x → [max(maxVal, x−α), max(maxVal, −x−α)] stacked on the channel axis
    (reference nodes/images/SymmetricRectifier.scala): C → 2C channels."""

    max_val: float = static_field(default=0.0)
    alpha: float = static_field(default=0.0)

    def __call__(self, batch):
        pos = jnp.maximum(self.max_val, batch - self.alpha)
        neg = jnp.maximum(self.max_val, -batch - self.alpha)
        return jnp.concatenate([pos, neg], axis=-1)


@treenode
class Pooler(Transformer):
    """Strided pooling with the reference's exact window geometry
    (reference nodes/images/Pooler.scala):

    - pool centers start at ``strideStart = pool_size // 2``,
    - each window spans ``[x − pool_size/2, min(x + pool_size/2, dim))`` —
      i.e. windows start at 0, stride apart, edge windows truncated,
    - ``num_pools = ceil((dim − strideStart) / stride)``.

    Implemented as pixel_fn → zero-pad right → ``lax.reduce_window``.
    Zero padding reproduces the truncated edge windows for sum/max pooling
    (the reference's pool buffer is likewise zero-filled). NOTE (reference
    quirk, SURVEY.md §7): a mean pool would divide by the wrong count at
    edges — replicated faithfully by dividing by pool_size².
    """

    stride: int = static_field(default=13)
    pool_size: int = static_field(default=14)
    pixel_fn: Callable | None = static_field(default=None)
    pool_fn: str = static_field(default="sum")  # sum | max | mean

    def __call__(self, batch):
        if self.pixel_fn is not None:
            batch = self.pixel_fn(batch)
        n, h, w, c = batch.shape
        ph = self._num_pools(h)
        pw = self._num_pools(w)
        pad_h = (ph - 1) * self.stride + self.pool_size - h
        pad_w = (pw - 1) * self.stride + self.pool_size - w
        if self.pool_fn == "max":
            init, op = -jnp.inf, jax.lax.max
            pad_val = -jnp.inf
        else:
            init, op = 0.0, jax.lax.add
            pad_val = 0.0
        if pad_h > 0 or pad_w > 0:
            batch = jnp.pad(
                batch,
                ((0, 0), (0, max(pad_h, 0)), (0, max(pad_w, 0)), (0, 0)),
                constant_values=pad_val,
            )
        out = jax.lax.reduce_window(
            batch,
            jnp.asarray(init, batch.dtype),
            op,
            window_dimensions=(1, self.pool_size, self.pool_size, 1),
            window_strides=(1, self.stride, self.stride, 1),
            padding="VALID",
        )
        if self.pool_fn == "mean":
            out = out / float(self.pool_size * self.pool_size)
        return out

    def _num_pools(self, dim: int) -> int:
        stride_start = self.pool_size // 2
        return -(-(dim - stride_start) // self.stride)


@treenode
class FusedConvRectifyPool(Transformer):
    """``Convolver >> SymmetricRectifier >> Pooler`` as one node.

    Produced by :func:`keystone_tpu.core.fusion.optimize`; carries the
    union of the three nodes' parameters. Implementations:

    - ``auto`` (default): conv-algebra convolution, then each rectifier
      half is pooled *before* the channel concat. The unfused chain's
      ``concatenate`` forces XLA to materialize the (N, oh, ow, 2F) map
      in HBM between the rectifier and the pooler; pooling each half
      first keeps the rectifier fused into ``reduce_window``'s operand
      and the concat runs on the tiny pooled map (measured ~12% e2e on
      v5e at the CIFAR random-patch shape, and the 2F map never exists).
    - ``unfused``: the literal three-node chain (parity baseline).

    A single fused VMEM Pallas kernel (``impl="pallas"``) existed through
    round 2 and was retired with the Convolver's kernel — the per-image
    im2col made it slower than ``auto`` on v5e (ROOFLINE.md §5).

    Output is identical in shape/layout to the chain: (N, ph, pw, 2F),
    channels ``[pos | neg]``.
    """

    filters: jnp.ndarray
    whitener_means: jnp.ndarray | None = None
    patch_size: int = static_field(default=6)
    normalize_patches: bool = static_field(default=True)
    var_constant: float = static_field(default=10.0)
    alpha: float = static_field(default=0.0)
    max_val: float = static_field(default=0.0)
    pool_stride: int = static_field(default=13)
    pool_size: int = static_field(default=14)
    pool_fn: str = static_field(default="sum")
    impl: str = static_field(default="auto")  # auto | unfused

    def _unfused(self) -> Transformer:
        from keystone_tpu.core.pipeline import Pipeline

        return Pipeline.of(
            Convolver(
                filters=self.filters,
                whitener_means=self.whitener_means,
                patch_size=self.patch_size,
                normalize_patches=self.normalize_patches,
                var_constant=self.var_constant,
            ),
            SymmetricRectifier(max_val=self.max_val, alpha=self.alpha),
            Pooler(
                stride=self.pool_stride,
                pool_size=self.pool_size,
                pool_fn=self.pool_fn,
            ),
        )

    def __call__(self, batch):
        if self.impl not in ("auto", "unfused"):
            raise ValueError(
                f"FusedConvRectifyPool impl={self.impl!r}; "
                "expected auto|unfused"
            )
        if self.impl == "unfused":
            return self._unfused()(batch)
        conv = conv_convolver(
            batch,
            self.filters,
            patch_size=self.patch_size,
            normalize_patches=self.normalize_patches,
            var_constant=self.var_constant,
            whitener_means=self.whitener_means,
        )
        pool = Pooler(
            stride=self.pool_stride,
            pool_size=self.pool_size,
            pool_fn=self.pool_fn,
        )
        pos = pool(jnp.maximum(self.max_val, conv - self.alpha))
        neg = pool(jnp.maximum(self.max_val, -conv - self.alpha))
        return jnp.concatenate([pos, neg], axis=-1)


@treenode
class LabelExtractor(Transformer):
    """Project labels out of a LabeledImages batch
    (reference nodes/images/LabeledImageExtractors.scala)."""

    def __call__(self, batch):
        return batch.labels


@treenode
class ImageExtractor(Transformer):
    """Project images out of a LabeledImages batch."""

    def __call__(self, batch):
        return batch.images


# Multi-label variants are the same projections; provided for parity.
MultiLabelExtractor = LabelExtractor
MultiLabeledImageExtractor = ImageExtractor
