"""Image nodes (reference ``nodes/images/``, SURVEY.md §2.3).

All nodes operate on (N, H, W, C) float batches. Patch/feature layouts
flatten as (dy, dx, c) with channel fastest — the reference's patch index
``c + x·C + y·C·k`` (Convolver.makePatches), so fitted filters/whiteners are
layout-compatible across the whole stack.
"""

from __future__ import annotations

import logging
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import FunctionNode, Transformer
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.utils.images import rgb_to_gray


@treenode
class GrayScaler(Transformer):
    """MATLAB rgb2gray weights (reference ImageUtils.toGrayScale)."""

    def __call__(self, batch):
        return rgb_to_gray(batch)


@treenode
class PixelScaler(Transformer):
    """Scale byte pixels to [0,1] (reference nodes/images/PixelScaler.scala)."""

    def __call__(self, batch):
        return batch / 255.0


@treenode
class ImageVectorizer(Transformer):
    """(N, H, W, C) → (N, H·W·C), channel fastest
    (reference nodes/images/ImageVectorizer.scala)."""

    def __call__(self, batch):
        return batch.reshape(batch.shape[0], -1)


def extract_patches(batch, patch_size: int, stride: int = 1):
    """All patch_size×patch_size windows at the given stride.

    Returns (N, oh, ow, patch_size·patch_size·C) with (dy, dx, c) flattening,
    channel fastest — matching the reference patch layout.
    """
    n, h, w, c = batch.shape
    patches = jax.lax.conv_general_dilated_patches(
        jnp.transpose(batch, (0, 3, 1, 2)),  # NCHW
        filter_shape=(patch_size, patch_size),
        window_strides=(stride, stride),
        padding="VALID",
    )  # (N, C*ph*pw, oh, ow), feature dim ordered (c, dy, dx)
    oh, ow = patches.shape[2], patches.shape[3]
    patches = patches.reshape(n, c, patch_size, patch_size, oh, ow)
    # → (N, oh, ow, dy, dx, c): channel fastest in the flattened patch
    patches = jnp.transpose(patches, (0, 4, 5, 2, 3, 1))
    return patches.reshape(n, oh, ow, patch_size * patch_size * c)


@treenode
class Windower(FunctionNode):
    """FlatMap each image into all stride-spaced square windows
    (reference nodes/images/Windower.scala).

    (N, H, W, C) → (N·n_windows, w, w, C).
    """

    stride: int = static_field(default=1)
    window_size: int = static_field(default=6)

    def __call__(self, batch):
        n, _, _, c = batch.shape
        w = self.window_size
        p = extract_patches(batch, w, self.stride)
        return p.reshape(n * p.shape[1] * p.shape[2], w, w, c)


def normalize_patch_rows(mat, var_constant: float = 10.0):
    """Per-row mean-center and divide by sqrt(var + alpha)
    (reference utils/Stats.scala normalizeRows; var uses d-1 denominator)."""
    d = mat.shape[-1]
    mean = jnp.mean(mat, axis=-1, keepdims=True)
    var = jnp.sum((mat - mean) ** 2, axis=-1, keepdims=True) / max(d - 1, 1)
    return (mat - mean) / jnp.sqrt(var + var_constant)


@treenode
class Convolver(Transformer):
    """Filter-bank convolution by im2col (reference nodes/images/Convolver.scala).

    The reference packs every patch into a row, optionally normalizes each
    patch (``Stats.normalizeRows`` with ``varConstant``), optionally
    subtracts the whitener means, then does one gemm with the filter bank.
    Per-patch normalization makes this NOT a plain convolution, so the
    im2col design is kept: patches → normalize → subtract mean → MXU gemm.
    Without normalization/whitening this lowers to the same FLOPs XLA would
    emit for ``lax.conv``.

    ``filters``: (num_filters, patch_size²·C), rows in (dy, dx, c) layout —
    exactly what :class:`Windower`+:class:`ImageVectorizer` sampling or
    ``RandomPatchCifar``-style whitened filter construction produces.
    """

    filters: jnp.ndarray
    whitener_means: jnp.ndarray | None = None
    patch_size: int = static_field(default=6)
    normalize_patches: bool = static_field(default=True)
    var_constant: float = static_field(default=10.0)
    # "auto": fused Pallas im2col kernel on TPU when the per-image working
    # set fits VMEM (keystone_tpu/ops/conv_kernel.py), XLA im2col otherwise
    impl: str = static_field(default="auto")

    def __call__(self, batch):
        if self.impl not in ("auto", "fused", "xla"):
            raise ValueError(
                f"Convolver impl={self.impl!r}; expected auto|fused|xla"
            )
        # both impls compute and emit float32 (the fused kernel always
        # does); keeps auto-path output independent of which impl runs
        batch = batch.astype(jnp.float32)
        if self.impl in ("auto", "fused"):
            from keystone_tpu.ops import conv_kernel
            from keystone_tpu.ops.flash_attention import on_tpu

            n, h, w, c = batch.shape
            fits = conv_kernel.fused_convolver_fits(
                h, w, c, self.patch_size, self.filters.shape[0]
            )
            # auto only on a single chip: pallas_call is not GSPMD-auto-
            # partitionable, so sharded multi-device batches keep the XLA
            # im2col path (mesh users can call impl="fused" inside their
            # own shard_map)
            auto_ok = on_tpu() and fits and jax.device_count() == 1
            if self.impl == "fused" or auto_ok:
                try:
                    return conv_kernel.fused_convolver(
                        batch,
                        self.filters,
                        patch_size=self.patch_size,
                        normalize_patches=self.normalize_patches,
                        var_constant=self.var_constant,
                        whitener_means=self.whitener_means,
                    )
                except Exception as e:  # noqa: BLE001
                    if self.impl == "fused":
                        raise
                    # auto: trace-time kernel failure falls back to XLA
                    logging.getLogger("keystone_tpu").warning(
                        "fused Convolver kernel failed (%s: %s); "
                        "falling back to XLA im2col",
                        type(e).__name__,
                        e,
                    )
        p = extract_patches(batch, self.patch_size)  # (N, oh, ow, k²C)
        if self.normalize_patches:
            p = normalize_patch_rows(p, self.var_constant)
        if self.whitener_means is not None:
            p = p - self.whitener_means
        return jnp.einsum(
            "nhwp,fp->nhwf", p, self.filters.astype(p.dtype)
        )


@treenode
class SymmetricRectifier(Transformer):
    """x → [max(maxVal, x−α), max(maxVal, −x−α)] stacked on the channel axis
    (reference nodes/images/SymmetricRectifier.scala): C → 2C channels."""

    max_val: float = static_field(default=0.0)
    alpha: float = static_field(default=0.0)

    def __call__(self, batch):
        pos = jnp.maximum(self.max_val, batch - self.alpha)
        neg = jnp.maximum(self.max_val, -batch - self.alpha)
        return jnp.concatenate([pos, neg], axis=-1)


@treenode
class Pooler(Transformer):
    """Strided pooling with the reference's exact window geometry
    (reference nodes/images/Pooler.scala):

    - pool centers start at ``strideStart = pool_size // 2``,
    - each window spans ``[x − pool_size/2, min(x + pool_size/2, dim))`` —
      i.e. windows start at 0, stride apart, edge windows truncated,
    - ``num_pools = ceil((dim − strideStart) / stride)``.

    Implemented as pixel_fn → zero-pad right → ``lax.reduce_window``.
    Zero padding reproduces the truncated edge windows for sum/max pooling
    (the reference's pool buffer is likewise zero-filled). NOTE (reference
    quirk, SURVEY.md §7): a mean pool would divide by the wrong count at
    edges — replicated faithfully by dividing by pool_size².
    """

    stride: int = static_field(default=13)
    pool_size: int = static_field(default=14)
    pixel_fn: Callable | None = static_field(default=None)
    pool_fn: str = static_field(default="sum")  # sum | max | mean

    def __call__(self, batch):
        if self.pixel_fn is not None:
            batch = self.pixel_fn(batch)
        n, h, w, c = batch.shape
        ph = self._num_pools(h)
        pw = self._num_pools(w)
        pad_h = (ph - 1) * self.stride + self.pool_size - h
        pad_w = (pw - 1) * self.stride + self.pool_size - w
        if self.pool_fn == "max":
            init, op = -jnp.inf, jax.lax.max
            pad_val = -jnp.inf
        else:
            init, op = 0.0, jax.lax.add
            pad_val = 0.0
        if pad_h > 0 or pad_w > 0:
            batch = jnp.pad(
                batch,
                ((0, 0), (0, max(pad_h, 0)), (0, max(pad_w, 0)), (0, 0)),
                constant_values=pad_val,
            )
        out = jax.lax.reduce_window(
            batch,
            jnp.asarray(init, batch.dtype),
            op,
            window_dimensions=(1, self.pool_size, self.pool_size, 1),
            window_strides=(1, self.stride, self.stride, 1),
            padding="VALID",
        )
        if self.pool_fn == "mean":
            out = out / float(self.pool_size * self.pool_size)
        return out

    def _num_pools(self, dim: int) -> int:
        stride_start = self.pool_size // 2
        return -(-(dim - stride_start) // self.stride)


@treenode
class LabelExtractor(Transformer):
    """Project labels out of a LabeledImages batch
    (reference nodes/images/LabeledImageExtractors.scala)."""

    def __call__(self, batch):
        return batch.labels


@treenode
class ImageExtractor(Transformer):
    """Project images out of a LabeledImages batch."""

    def __call__(self, batch):
        return batch.images


# Multi-label variants are the same projections; provided for parity.
MultiLabelExtractor = LabelExtractor
MultiLabeledImageExtractor = ImageExtractor
