"""ViT-style patch-embedding featurizer.

The BASELINE stretch config: a transformer-encoder featurizer in the
pipeline DSL ("stretch the Transformer API") feeding the ridge solver — the
random-features philosophy of the reference (random FFTs, random conv
patches) applied to a modern architecture: a frozen randomly-initialized
ViT encoder as the featurizer, linear model on top.

Everything is a pytree; attention can run dense (single chip) or
sequence-parallel via :mod:`keystone_tpu.ops.attention` on a mesh.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.ops.attention import (
    _flash_default,
    dense_attention,
    ring_attention,
)
from keystone_tpu.ops.images import extract_patches


def _layer_norm(x, eps=1e-6):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps)


@treenode
class ViTBlock:
    wq: jnp.ndarray
    wk: jnp.ndarray
    wv: jnp.ndarray
    wo: jnp.ndarray
    w1: jnp.ndarray
    w2: jnp.ndarray
    num_heads: int = static_field(default=4)


@treenode
class ViTFeaturizer(Transformer):
    """(N, H, W, C) images → (N, dim) pooled encoder features."""

    patch_embed: jnp.ndarray  # (P²·C, dim)
    pos_embed: jnp.ndarray  # (num_patches, dim)
    blocks: tuple  # of ViTBlock
    patch_size: int = static_field(default=8)
    mesh: object = static_field(default=None)  # sequence-parallel when set
    seq_axis: str = static_field(default="data")

    def __call__(self, batch):
        n = batch.shape[0]
        p = extract_patches(batch, self.patch_size, self.patch_size)
        x = p.reshape(n, -1, p.shape[-1]) @ self.patch_embed  # (N, S, dim)
        x = x + self.pos_embed
        for blk in self.blocks:
            x = x + self._attention(_layer_norm(x), blk)
            h = _layer_norm(x) @ blk.w1
            x = x + jax.nn.gelu(h) @ blk.w2
        return jnp.mean(_layer_norm(x), axis=1)  # (N, dim)

    def _attention(self, x, blk: ViTBlock):
        n, s, d = x.shape
        heads = blk.num_heads
        hd = d // heads

        def split(w):
            return (x @ w).reshape(n, s, heads, hd).transpose(0, 2, 1, 3)

        q, k, v = split(blk.wq), split(blk.wk), split(blk.wv)
        if self.mesh is not None:
            out = ring_attention(q, k, v, self.mesh, seq_axis=self.seq_axis)
        elif _flash_default():
            from keystone_tpu.ops.flash_attention import flash_attention

            out = flash_attention(q, k, v)
        else:
            out = dense_attention(q, k, v)
        out = out.transpose(0, 2, 1, 3).reshape(n, s, d)
        return out @ blk.wo

    @staticmethod
    def create(
        key,
        image_size: int = 32,
        patch_size: int = 8,
        dim: int = 128,
        depth: int = 4,
        num_heads: int = 4,
        channels: int = 3,
        mesh=None,
        seq_axis: str = "data",
    ) -> "ViTFeaturizer":
        num_patches = (image_size // patch_size) ** 2
        keys = jax.random.split(key, 2 + 6 * depth)
        pd = patch_size * patch_size * channels

        def init(k, shape, fan_in):
            return jax.random.normal(k, shape, jnp.float32) / math.sqrt(fan_in)

        blocks = []
        for i in range(depth):
            ks = keys[2 + 6 * i : 8 + 6 * i]
            blocks.append(
                ViTBlock(
                    wq=init(ks[0], (dim, dim), dim),
                    wk=init(ks[1], (dim, dim), dim),
                    wv=init(ks[2], (dim, dim), dim),
                    wo=init(ks[3], (dim, dim), dim),
                    w1=init(ks[4], (dim, 4 * dim), dim),
                    w2=init(ks[5], (4 * dim, dim), 4 * dim),
                    num_heads=num_heads,
                )
            )
        return ViTFeaturizer(
            patch_embed=init(keys[0], (pd, dim), pd),
            pos_embed=0.02 * jax.random.normal(keys[1], (num_patches, dim)),
            blocks=tuple(blocks),
            patch_size=patch_size,
            mesh=mesh,
            seq_axis=seq_axis,
        )
