"""The node library — Transformers/Estimators over batched arrays.

TPU-native successor of the reference's ``src/main/scala/nodes/`` tree
(SURVEY.md §2.2-§2.6): every node is a pytree, operates on whole (possibly
mesh-sharded) batches, and is jit-composable. Submodules:

- ``stats``   scalers, random features, FFT, rectifiers, normalizers
- ``util``    label indicators, classifiers, casts, block split/zip
- ``linear``  linear models and the distributed least-squares solver layer
- ``linalg``  PCA / ZCA / LDA
- ``images``  convolution / pooling / windowing / rectification / descriptors
- ``gmm``     Gaussian mixture EM + Fisher vectors
- ``nlp``     tokenization, n-grams, language models (host+device split)
- ``sparse``  sparse-feature capping and dense-ification
"""
