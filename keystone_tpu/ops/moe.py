"""Mixture-of-experts FFN with expert parallelism (EP).

The reference has no MoE (its EP-shaped pattern is the weighted solver's
one-class-per-partition solves, ``BlockWeightedLeastSquares.scala:228-263``
— covered by ``ops/weighted_linear.py``). This layer makes EP first-class
for the sequence-model stack: a GShard-style top-2 routed expert FFN
where the *sharding layout is the parallelism* —

- routing, dispatch, and combine are einsums over a dense one-hot
  dispatch tensor (no host-side scatter, no ragged shapes — the
  capacity-factor bound makes every shape static, which is what XLA
  needs to tile the expert gemms onto the MXU);
- the expert axis of ``w1``/``w2`` is sharded over the mesh ``model``
  axis (see :func:`keystone_tpu.models.lm_transformer.shard_params`), so
  XLA inserts the dispatch/combine ``all_to_all``s over ICI exactly
  where GShard's hand-written ones sit;
- tokens over capacity are *dropped* (contribute zero; the residual
  stream carries them unchanged) — the standard static-shape trade, and
  the load-balance auxiliary loss keeps drops rare.

Shapes follow the GShard/Switch convention: tokens route within
fixed-size groups (``group_size``; the last group is padded with
capacity-neutral dummies), E experts, C capacity slots per expert per
group — bounding the (group, E, C) dispatch/combine tensors to
O(tokens · group) total instead of O(tokens²).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from keystone_tpu.core.treenode import static_field, treenode


@treenode
class MoELayer:
    """Top-2 routed expert FFN: (B, S, d) → (B, S, d) plus an auxiliary
    load-balance loss (Shazeer et al.'s importance loss, GShard eq. 4)."""

    w_router: jnp.ndarray  # (d, E)
    w1: jnp.ndarray  # (E, d, ff)
    w2: jnp.ndarray  # (E, ff, d)
    capacity_factor: float = static_field(default=1.25)
    # routing group size (GShard's G axis): tokens route within fixed
    # groups so capacity — and with it the (group, E, C) dispatch/combine
    # tensors — is bounded per group. Without it C grows with B·S and the
    # dispatch tensors are O((B·S)²); with it they are O(B·S · group).
    group_size: int = static_field(default=4096)

    @property
    def num_experts(self) -> int:
        return self.w_router.shape[-1]

    @staticmethod
    def create(key, dim: int, ff: int, num_experts: int,
               capacity_factor: float = 1.25,
               group_size: int = 4096) -> "MoELayer":
        kr, k1, k2 = jax.random.split(key, 3)
        return MoELayer(
            w_router=0.02 * jax.random.normal(kr, (dim, num_experts)),
            w1=jax.random.normal(k1, (num_experts, dim, ff))
            / math.sqrt(dim),
            w2=jax.random.normal(k2, (num_experts, ff, dim))
            / math.sqrt(ff),
            capacity_factor=capacity_factor,
            group_size=group_size,
        )

    def _capacity(self, num_tokens: int) -> int:
        # top-2: every token wants two slots; round up to keep tiny test
        # shapes from degenerating to C=0
        cap = int(
            math.ceil(2 * num_tokens * self.capacity_factor
                      / self.num_experts)
        )
        return max(cap, 1)

    def __call__(self, x):
        """x: (B, S, d) → (out (B, S, d), aux_loss scalar f32)."""
        b, s, d = x.shape
        g_tot = b * s
        xf = x.reshape(g_tot, d)
        gs = min(self.group_size, g_tot)
        ng = -(-g_tot // gs)
        pad = ng * gs - g_tot
        xp = jnp.pad(xf, ((0, pad), (0, 0)))
        valid = (jnp.arange(ng * gs) < g_tot).reshape(ng, gs)
        c = self._capacity(gs)

        outs, auxs, counts = jax.vmap(
            lambda xi, vi: self._route_group(xi, vi, c)
        )(xp.reshape(ng, gs, d), valid)
        out = outs.reshape(ng * gs, d)[:g_tot]
        # per-group aux weighted by real token count (padding excluded)
        aux = jnp.sum(auxs * counts) / jnp.maximum(jnp.sum(counts), 1.0)
        return out.reshape(b, s, d), aux

    def _route_group(self, xf, valid, c: int):
        """Route one group. xf: (gs, d); valid: (gs,) bool marks real
        tokens (padding claims no capacity and emits zero). Returns
        (out (gs, d), aux scalar, valid count)."""
        e = self.num_experts

        # --- routing (f32: softmax + cumsum bookkeeping is cheap and
        # precision-sensitive; the expert gemms below run in xf.dtype) ---
        logits = (
            xf.astype(jnp.float32) @ self.w_router.astype(jnp.float32)
        )  # (gs, E)
        probs = jax.nn.softmax(logits, axis=-1)
        vmask = valid.astype(jnp.float32)[:, None]

        idx1 = jnp.argmax(probs, axis=-1)  # (gs,)
        mask1 = jax.nn.one_hot(idx1, e, dtype=jnp.float32) * vmask
        probs2 = probs * (1.0 - mask1)
        idx2 = jnp.argmax(probs2, axis=-1)
        mask2 = jax.nn.one_hot(idx2, e, dtype=jnp.float32) * vmask

        # load-balance aux: mean one-hot fraction × mean prob over REAL
        # tokens, scaled E² (GShard) — minimized at uniform routing
        # where it equals 1
        count = jnp.maximum(jnp.sum(vmask), 1.0)
        aux = jnp.mean(
            (jnp.sum(mask1, axis=0) / count)
            * (jnp.sum(probs * vmask, axis=0) / count)
        ) * (e * e)

        # capacity slots: position of each token within its expert's
        # queue, top-1 claims first, top-2 queues behind all top-1s
        pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1  # (gs, E)
        count1 = jnp.sum(mask1, axis=0, keepdims=True)  # (1, E)
        pos2 = (jnp.cumsum(mask2, axis=0) - mask2 + count1) * mask2
        keep1 = mask1 * (pos1 < c)
        keep2 = mask2 * (pos2 < c)

        gate1 = jnp.sum(probs * keep1, axis=-1)  # (gs,)
        gate2 = jnp.sum(probs * keep2, axis=-1)
        denom = jnp.maximum(gate1 + gate2, 1e-9)
        gate1, gate2 = gate1 / denom, gate2 / denom

        slot1 = jax.nn.one_hot(
            jnp.sum(pos1, axis=-1).astype(jnp.int32), c, dtype=jnp.float32
        )  # (gs, C)
        slot2 = jax.nn.one_hot(
            jnp.sum(pos2, axis=-1).astype(jnp.int32), c, dtype=jnp.float32
        )
        # (gs, E, C) combine weights; dispatch is its 0/1 support
        combine = (
            gate1[:, None, None] * keep1[:, :, None] * slot1[:, None, :]
            + gate2[:, None, None] * keep2[:, :, None] * slot2[:, None, :]
        )
        dispatch = (combine > 0.0).astype(xf.dtype)

        # --- dispatch → expert gemms → combine (the EP einsums; with the
        # expert axis of w1/w2 sharded over `model`, XLA places
        # all_to_alls here) ---
        expert_in = jnp.einsum("gec,gd->ecd", dispatch, xf)  # (E, C, d)
        h = jax.nn.gelu(
            jnp.einsum("ecd,edf->ecf", expert_in, self.w1.astype(xf.dtype))
        )
        expert_out = jnp.einsum(
            "ecf,efd->ecd", h, self.w2.astype(xf.dtype)
        )
        out = jnp.einsum(
            "gec,ecd->gd", combine.astype(xf.dtype), expert_out
        )
        return out, aux, jnp.sum(vmask)
