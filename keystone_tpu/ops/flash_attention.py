"""Pallas TPU flash attention — fused blockwise attention kernels.

The jnp attention in :mod:`keystone_tpu.ops.attention` materializes the
(S_q, S_k) score matrix in HBM; on TPU the arithmetic intensity of
attention is set by how much of that traffic can stay in VMEM. These
kernels fuse the score gemm, online softmax, and value gemm into one
VMEM-resident pass (flash-attention schedule):

- :func:`flash_attention` — full attention, grid over (batch*heads,
  query blocks), K/V streamed through VMEM block by block with a running
  (max, sum, accumulator) online softmax.
- :func:`flash_attention_step` — one K/V block's contribution with the
  online-softmax state (m, l, acc) carried in and out. This is the fused
  inner step of ring attention: the ring loop keeps K/V rotating via
  ``ppermute`` (XLA collectives over ICI) and calls this kernel per hop.

Both run compiled on TPU and in Pallas interpret mode elsewhere (the
8-device CPU test mesh), selected automatically. Numerics: scores and the
online-softmax state are always float32; masked positions use a large
negative finite constant so no ±inf arithmetic appears in the kernel.

Reference: the reference framework has no attention (SURVEY.md §5 — out of
scope for parity); this is part of the beyond-parity long-context stack.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30  # masked-score value: exp(_NEG - m) underflows to exactly 0
_LANE = 128


def on_tpu() -> bool:
    """True on real TPU hardware (the axon platform is a TPU behind a
    tunnel) — selects compiled Pallas vs interpret mode and the
    flash-by-default policy in :mod:`keystone_tpu.ops.attention`."""
    return jax.default_backend() in ("tpu", "axon")


_on_tpu = on_tpu  # internal alias


@functools.cache
def _vmem_limit_bytes() -> int | None:
    """Mosaic scoped-VMEM limit to request, by TPU generation.

    The compiler default is 16MB; v5e/v5p/v6 chips have far more physical
    VMEM (validated on real v5e up to ≥96MB scoped allocations). Raising
    the limit lets the K/V-resident flash variant keep whole heads in
    VMEM at long context. Unknown/older generations keep the default.
    """
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — no backend yet (e.g. docs build)
        return None
    if any(g in kind for g in ("v5", "v6")):
        return 96 * 1024 * 1024
    return None


def _kv_vmem_budget() -> int:
    """K+V bytes above which K/V is streamed instead of held resident.

    Mosaic double-buffers every windowed input, so residency costs
    2x(K+V) + q/out double-buffers + softmax temporaries against the
    scoped limit (measured on v5e: K+V of 8MB OOMs a 16MB limit at
    16.25MB — exactly the 2x plus overhead)."""
    limit = _vmem_limit_bytes()
    if limit is None:
        return 6 * 1024 * 1024  # 2x6 + overhead < 16MB default
    return limit // 3  # 2x budget + overhead comfortably under limit


def _pad_to(x, axis: int, mult: int, value=0.0):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _flash_kernel_fori(
    scalars_ref,  # (3,) int32: [s_k_valid, q_offset, k_offset]
    q_ref,  # (1, block_q, d)
    k_ref,  # (1, s_k_pad, d) — K/V resident in VMEM for this head
    v_ref,
    o_ref,  # (1, block_q, d)
    *maybe_lse,  # (1, block_q, LANE) lse output when with_lse
    scale: float,
    block_k: int,
    causal: bool,
    with_lse: bool = False,
):
    """K/V-resident variant: one program per q block, fori over K blocks.

    Faster than grid-streaming K when K/V fit VMEM (no per-step grid
    overhead, no scratch churn); selected automatically by size.
    """
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    num_k = k_ref.shape[1] // block_k

    s_k_valid = scalars_ref[0]
    q_start = scalars_ref[1] + pl.program_id(1) * block_q
    q_pos = q_start + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    if causal:
        # skip K blocks entirely above the diagonal (dense attention pays
        # compute for the full rectangle)
        num_k_live = jnp.clip(
            (q_start + block_q - scalars_ref[2] + block_k - 1) // block_k,
            0,
            num_k,
        )
    else:
        num_k_live = num_k

    q = q_ref[0] * jnp.asarray(scale, q_ref.dtype)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        k_pos = (
            scalars_ref[2]
            + j * block_k
            + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        )
        valid = k_pos < s_k_valid
        if causal:
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # explicit zero on masked lanes: when a row is fully masked m_new
        # stays at the _NEG init and exp(s - m_new) alone would be 1
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(
            p.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = lax.fori_loop(0, num_k_live, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    if with_lse:
        # row logsumexp of the masked scaled scores — the O(S) residual a
        # blockwise backward needs (fully masked rows stay at _NEG)
        lse = m + jnp.log(jnp.maximum(l, 1e-30))
        maybe_lse[0][0] = jnp.broadcast_to(lse, maybe_lse[0].shape[1:])


def _flash_kernel_stream(
    scalars_ref,  # (3,) int32: [s_k_valid, q_offset, k_offset]
    q_ref,  # (1, block_q, d)
    k_ref,  # (1, block_k, d) — streamed via the sequential grid dim
    v_ref,
    o_ref,  # (1, block_q, d)
    *rest,  # [(1, block_q, LANE) lse out when with_lse], then the three
    # scratch refs: m (block_q, LANE), l (block_q, LANE), acc (block_q, d)
    scale: float,
    causal: bool,
    with_lse: bool = False,
):
    if with_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        m_scr, l_scr, acc_scr = rest
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]
    kk = pl.program_id(2)
    num_k = pl.num_programs(2)

    s_k_valid = scalars_ref[0]
    q_start = scalars_ref[1] + pl.program_id(1) * block_q
    k_start = scalars_ref[2] + kk * block_k

    @pl.when(kk == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # K blocks entirely above the causal diagonal contribute nothing; the
    # pipeline still streams them but the MXU work is skipped (dense
    # attention pays compute for the full rectangle)
    live = k_start < q_start + block_q if causal else True

    @pl.when(live)
    def _step():
        q = q_ref[0] * jnp.asarray(scale, q_ref.dtype)
        k_blk, v_blk = k_ref[0], v_ref[0]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        q_pos = q_start + lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0
        )
        k_pos = k_start + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        valid = k_pos < s_k_valid
        if causal:
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG)
        m = m_scr[:, :1]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            p.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l, l_scr.shape)

    @pl.when(kk == num_k - 1)
    def _finalize():
        l = l_scr[:, :1]
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        if with_lse:
            lse = m_scr[:, :1] + jnp.log(jnp.maximum(l, 1e-30))
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    block_q: int | None = None,
    block_k: int | None = None,
    q_offset=0,
    k_offset=0,
    mxu_dtype=None,
    kv_resident: bool | None = None,
    interpret: bool | None = None,
    return_lse: bool = False,
):
    """Fused attention. q: (B, H, S_q, D); k, v: (B, H, S_k, D).

    ``return_lse=True`` additionally returns the per-row logsumexp of the
    masked scaled scores, (B, H, S_q) float32 — the O(S) residual the
    blockwise training backward consumes (computed in-kernel from the
    online-softmax state; costs one extra lane-tile write, not a sweep).

    ``kv_resident`` forces the K/V-in-VMEM variant (True) or the
    streamed long-context variant (False); default None picks by the
    scoped-VMEM budget.

    ``mxu_dtype=jnp.bfloat16`` feeds the two gemms bf16 inputs (float32
    accumulation and softmax state) for ~2x MXU rate at ~1e-3 output
    error; default None keeps the gemms in the input precision.

    ``q_offset``/``k_offset`` give the global positions of the local q/k
    windows for causal masking (used when sequence shards carry different
    ranges, e.g. under Ulysses head-sharding the offsets stay 0 because
    each chip sees full sequences). Exact (== dense softmax attention).
    """
    if interpret is None:
        interpret = not _on_tpu()
    if block_q is None:
        block_q = _env_int("KST_FLASH_BLOCK_Q", 512)
    if block_k is None:
        block_k = _env_int("KST_FLASH_BLOCK_K", 512)
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    out_dtype = q.dtype

    # clamp to the sequence, rounded UP to a multiple of 8: Mosaic needs
    # 8-aligned f32 sublane tiles, and a short unaligned sequence (e.g.
    # ViT's 196 patches) would otherwise become the block shape itself
    block_q = -(-min(block_q, max(s_q, 8)) // 8) * 8
    block_k = -(-min(block_k, max(s_k, 8)) // 8) * 8

    if mxu_dtype is not None:
        # cast on the XLA side: halves the K/V HBM→VMEM stream for bf16
        q, k, v = (x.astype(mxu_dtype) for x in (q, k, v))
    qf = _pad_to(q.reshape(b * h, s_q, d), 1, block_q)
    kf = _pad_to(k.reshape(b * h, s_k, d), 1, block_k)
    vf = _pad_to(v.reshape(b * h, s_k, d), 1, block_k)
    # zero-padding D is free: extra K columns don't change scores, extra V
    # columns produce zero output columns that are sliced away
    qf = _pad_to(qf, 2, _LANE)
    kf = _pad_to(kf, 2, _LANE)
    vf = _pad_to(vf, 2, _LANE)
    s_q_pad, d_pad = qf.shape[1], qf.shape[2]
    s_k_pad = kf.shape[1]

    scalars = jnp.array([s_k + k_offset, q_offset, k_offset], jnp.int32)
    vmem_limit = None if interpret else _vmem_limit_bytes()
    kv_bytes = 2 * s_k_pad * d_pad * kf.dtype.itemsize
    if kv_resident is None:
        budget = 6 * 1024 * 1024 if interpret else _kv_vmem_budget()
        kv_resident = kv_bytes <= budget
    out_shape = jax.ShapeDtypeStruct((b * h, s_q_pad, d_pad), out_dtype)
    lse_shape = jax.ShapeDtypeStruct((b * h, s_q_pad, _LANE), jnp.float32)
    if kv_resident:
        # K/V resident in VMEM per program — lowest overhead
        out_spec = pl.BlockSpec(
            (1, block_q, d_pad), lambda i, j, *_: (i, j, 0)
        )
        lse_spec = pl.BlockSpec(
            (1, block_q, _LANE), lambda i, j, *_: (i, j, 0)
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, s_q_pad // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d_pad), lambda i, j, *_: (i, j, 0)),
                pl.BlockSpec((1, s_k_pad, d_pad), lambda i, j, *_: (i, 0, 0)),
                pl.BlockSpec((1, s_k_pad, d_pad), lambda i, j, *_: (i, 0, 0)),
            ],
            out_specs=(out_spec, lse_spec) if return_lse else out_spec,
        )
        kernel = functools.partial(
            _flash_kernel_fori, scale=scale, block_k=block_k, causal=causal,
            with_lse=return_lse,
        )
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=vmem_limit,
        )
    else:
        # long-context: stream K/V block-by-block through the pipelined
        # sequential grid dimension, state in VMEM scratch
        out_spec = pl.BlockSpec(
            (1, block_q, d_pad), lambda i, j, kk, *_: (i, j, 0)
        )
        lse_spec = pl.BlockSpec(
            (1, block_q, _LANE), lambda i, j, kk, *_: (i, j, 0)
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, s_q_pad // block_q, s_k_pad // block_k),
            in_specs=[
                pl.BlockSpec(
                    (1, block_q, d_pad), lambda i, j, kk, *_: (i, j, 0)
                ),
                pl.BlockSpec(
                    (1, block_k, d_pad), lambda i, j, kk, *_: (i, kk, 0)
                ),
                pl.BlockSpec(
                    (1, block_k, d_pad), lambda i, j, kk, *_: (i, kk, 0)
                ),
            ],
            out_specs=(out_spec, lse_spec) if return_lse else out_spec,
            scratch_shapes=[
                pltpu.VMEM((block_q, _LANE), jnp.float32),
                pltpu.VMEM((block_q, _LANE), jnp.float32),
                pltpu.VMEM((block_q, d_pad), jnp.float32),
            ],
        )
        kernel = functools.partial(
            _flash_kernel_stream, scale=scale, causal=causal,
            with_lse=return_lse,
        )
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=vmem_limit,
        )
    res = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(out_shape, lse_shape) if return_lse else out_shape,
        compiler_params=compiler_params,
        interpret=interpret,
    )(scalars, qf, kf, vf)
    if return_lse:
        out, lse = res
        return (
            out[:, :s_q, :d].reshape(b, h, s_q, d),
            lse[:, :s_q, 0].reshape(b, h, s_q),
        )
    return res[:, :s_q, :d].reshape(b, h, s_q, d)


def _flash_step_kernel(
    scalars_ref,  # (3,) int32: [q_offset, k_offset, valid-K end]
    q_ref,  # (1, block_q, d)
    k_ref,  # (1, s_k, d)
    v_ref,  # (1, s_k, d)
    m_ref,  # (1, block_q, LANE) broadcast state
    l_ref,
    acc_ref,  # (1, block_q, d)
    m_out,
    l_out,
    acc_out,
    *,
    scale: float,
    block_k: int,
    causal: bool,
):
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    num_k = k_ref.shape[1] // block_k

    q = q_ref[0].astype(jnp.float32) * scale
    q_start = scalars_ref[0] + pl.program_id(1) * block_q
    q_pos = q_start + lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    if causal:
        num_k_live = jnp.clip(
            (q_start + block_q - scalars_ref[1] + block_k - 1) // block_k,
            0,
            num_k,
        )
    else:
        num_k_live = num_k

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        k_pos = (
            scalars_ref[1]
            + j * block_k
            + lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        )
        valid = k_pos < scalars_ref[2]  # mask zero-padded K positions
        if causal:
            valid = jnp.logical_and(valid, q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # explicit zero on masked lanes: when a row is fully masked m_new
        # stays at the _NEG init and exp(s - m_new) alone would be 1
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l, acc

    m0 = m_ref[0, :, :1]
    l0 = l_ref[0, :, :1]
    m, l, acc = lax.fori_loop(0, num_k_live, body, (m0, l0, acc_ref[0]))
    m_out[0] = jnp.broadcast_to(m, (block_q, m_out.shape[2]))
    l_out[0] = jnp.broadcast_to(l, (block_q, l_out.shape[2]))
    acc_out[0] = acc


def flash_attention_step(
    q,
    k_blk,
    v_blk,
    m,
    l,
    acc,
    *,
    q_offset,
    k_offset,
    causal: bool = False,
    block_q: int = 512,
    block_k: int = 128,
    padded_state: bool = False,
    interpret: bool | None = None,
):
    """One fused online-softmax update: attend q over a single K/V block.

    State: m, l of shape (B, H, S_q) and acc of shape (B, H, S_q, D),
    always float32 (initialize m to a large negative value, l and acc to
    zeros). Returns updated (m, l, acc); finalize with ``acc / l``. The
    offsets are the *global* sequence positions of the q and k windows —
    traced values are fine (ring attention passes axis_index-derived
    offsets). Shards that don't tile evenly into blocks are zero-padded
    (padded K positions are masked; padded q rows are sliced away).

    With ``padded_state`` the m/l state is carried as (B, H, S_q, LANE)
    float32 — the kernel's native VMEM tile — so a multi-hop caller (ring
    attention) avoids re-broadcasting lane-1 state to 128 lanes and
    re-slicing it on every hop; only column 0 is meaningful.
    """
    if interpret is None:
        interpret = not _on_tpu()
    b, h, s_q, d = q.shape
    s_k = k_blk.shape[2]
    scale = 1.0 / math.sqrt(d)
    block_q = -(-min(block_q, max(s_q, 8)) // 8) * 8
    block_k = -(-min(block_k, max(s_k, 8)) // 8) * 8

    qf = _pad_to(q.reshape(b * h, s_q, d), 1, block_q)
    kf = _pad_to(k_blk.reshape(b * h, s_k, d), 1, block_k)
    vf = _pad_to(v_blk.reshape(b * h, s_k, d), 1, block_k)
    qf = _pad_to(qf, 2, _LANE)
    kf = _pad_to(kf, 2, _LANE)
    vf = _pad_to(vf, 2, _LANE)
    s_q_pad, d_pad = qf.shape[1], qf.shape[2]
    s_k_pad = kf.shape[1]
    # state rides as (BH, S_q, LANE)/(BH, S_q, d_pad) VMEM-tiled arrays
    if padded_state:
        mf = _pad_to(
            m.reshape(b * h, s_q, _LANE).astype(jnp.float32), 1, block_q
        )
        lf = _pad_to(
            l.reshape(b * h, s_q, _LANE).astype(jnp.float32), 1, block_q
        )
    else:
        mf = _pad_to(
            jnp.broadcast_to(
                m.reshape(b * h, s_q, 1), (b * h, s_q, _LANE)
            ).astype(jnp.float32),
            1,
            block_q,
        )
        lf = _pad_to(
            jnp.broadcast_to(
                l.reshape(b * h, s_q, 1), (b * h, s_q, _LANE)
            ).astype(jnp.float32),
            1,
            block_q,
        )
    accf = _pad_to(
        _pad_to(acc.reshape(b * h, s_q, d), 2, _LANE).astype(jnp.float32),
        1,
        block_q,
    )

    scalars = jnp.stack(
        [
            jnp.asarray(q_offset, jnp.int32),
            jnp.asarray(k_offset, jnp.int32),
            jnp.asarray(k_offset + s_k, jnp.int32),  # valid-K end
        ]
    )
    qspec = pl.BlockSpec((1, block_q, d_pad), lambda i, j, *_: (i, j, 0))
    kspec = pl.BlockSpec((1, s_k_pad, d_pad), lambda i, j, *_: (i, 0, 0))
    sspec = pl.BlockSpec((1, block_q, _LANE), lambda i, j, *_: (i, j, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * h, s_q_pad // block_q),
        in_specs=[qspec, kspec, kspec, sspec, sspec, qspec],
        out_specs=(sspec, sspec, qspec),
    )
    m2, l2, acc2 = pl.pallas_call(
        functools.partial(
            _flash_step_kernel, scale=scale, block_k=block_k, causal=causal
        ),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((b * h, s_q_pad, _LANE), jnp.float32),
            jax.ShapeDtypeStruct((b * h, s_q_pad, _LANE), jnp.float32),
            jax.ShapeDtypeStruct((b * h, s_q_pad, d_pad), jnp.float32),
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=None if interpret else _vmem_limit_bytes(),
        ),
        interpret=interpret,
    )(scalars, qf, kf, vf, mf, lf, accf)
    if padded_state:
        return (
            m2[:, :s_q, :].reshape(b, h, s_q, _LANE),
            l2[:, :s_q, :].reshape(b, h, s_q, _LANE),
            acc2[:, :s_q, :d].reshape(b, h, s_q, d),
        )
    return (
        m2[:, :s_q, 0].reshape(b, h, s_q),
        l2[:, :s_q, 0].reshape(b, h, s_q),
        acc2[:, :s_q, :d].reshape(b, h, s_q, d),
    )


def _env_int(name: str, default: int) -> int:
    """Tuning knob from the environment (the flash_sweep harness sets
    these per subprocess to map the block-size space on chip; normal use
    never sets them)."""
    import os

    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


# bytes budget for the dense-recompute backward's transient (S_q, S_k)
# tensors (~4 of them, f32, per (b, h)): above this the blockwise
# O(S·block) backward takes over
_DENSE_BWD_MAX_BYTES = 4 << 30


def _dense_bwd_max_bytes() -> int:
    # tunable per call like the other KST_FLASH_* knobs (0 forces the
    # blockwise backward everywhere — the dense-vs-blockwise A/B axis of
    # tools/lm_mfu_push.py); unset/malformed keeps the module default,
    # which tests monkeypatch directly (read at call time)
    return _env_int("KST_FLASH_DENSE_BWD_MAX", _DENSE_BWD_MAX_BYTES)


def _bwd_block() -> int:
    # read per call, like the forward block_q/block_k pair — setting
    # KST_FLASH_BWD_BLOCK after import must take effect (a tuner knob)
    return _env_int("KST_FLASH_BWD_BLOCK", 512)


def _dense_bwd_bytes(q, k) -> int:
    b, h, s_q, _ = q.shape
    return 4 * 4 * b * h * s_q * k.shape[2]


def _bwd_mask(q_pos, k_pos, s_k_valid, causal: bool):
    """(S_q, blk) validity mask for one KV block (padding + causality).

    Causal positions are BEGIN-aligned (q_pos = i, k_pos = j), matching
    the flash forward's offset convention at q_offset = k_offset = 0; the
    trainable wrapper rejects causal s_q != s_k, where begin- and
    end-aligned conventions diverge."""
    valid = (k_pos < s_k_valid)[None, :]
    if causal:
        valid = valid & (q_pos[:, None] >= k_pos[None, :])
    return valid


# causal backward q-chunking: each chunk sweeps only its live K prefix.
# More chunks → closer to the ideal 0.5·S² triangle (n chunks execute
# (n+1)/2n of the rectangle) at the cost of shorter scans; 8 is a good
# regular-pipelining compromise (0.5625·S²)
def _bwd_causal_chunks() -> int:
    return _env_int("KST_FLASH_BWD_CHUNKS", 8)


def _grads_rect(qf, kp, vp, gf, delta, lse, q_off, s_k_valid, causal, block,
                k_off=0):
    """Rectangle sweep of the blockwise backward over one q range: scan
    over the given (padded) K/V blocks, recomputing each score block from
    (q, k, lse). Positions are global begin-aligned (q_off / k_off = the
    global position of the first q / k row — nonzero k_off serves the
    ring backward's rotating K/V shards). Returns (dq, dk, dv) for this
    rectangle, dk/dv over kp's full padded length. Peak memory O(S·d)
    state + O(S_q·block) transient."""
    b, h, s_q, d = qf.shape
    scale = 1.0 / math.sqrt(d)
    nb = kp.shape[2] // block
    kb = jnp.moveaxis(kp.reshape(b, h, nb, block, d), 2, 0)
    vb = jnp.moveaxis(vp.reshape(b, h, nb, block, d), 2, 0)
    q_pos = q_off + jnp.arange(s_q)

    def step(dq, inp):
        kblk, vblk, j = inp
        kf = kblk.astype(jnp.float32)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
        k_pos = k_off + j * block + jnp.arange(block)
        mask = _bwd_mask(q_pos, k_pos, s_k_valid, causal)
        p = jnp.where(mask, jnp.exp(scores - lse[..., None]), 0.0)
        dv_j = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vblk.astype(jnp.float32))
        ds = p * (dp - delta[..., None])
        dq = dq + scale * jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
        dk_j = scale * jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        return dq, (dk_j, dv_j)

    dq0 = jnp.zeros((b, h, s_q, d), jnp.float32)
    dq, (dks, dvs) = jax.lax.scan(step, dq0, (kb, vb, jnp.arange(nb)))
    dk = jnp.moveaxis(dks, 0, 2).reshape(b, h, nb * block, d)
    dv = jnp.moveaxis(dvs, 0, 2).reshape(b, h, nb * block, d)
    return dq, dk, dv


def _blockwise_grads(q, k, v, g, out, lse, causal: bool, block: int):
    """FlashAttention-style backward. Non-causal: one rectangle sweep.
    Causal: q chunked into block-aligned prefixes, each sweeping only the
    K blocks at or below its diagonal — ~0.56·S² of score work instead of
    the full rectangle's 1.0 (the forward kernel's num_k_live analog)."""
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    nb = -(-s_k // block)
    pad = nb * block - s_k
    kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    # delta_i = Σ_d g·out — the softmax-jacobian diagonal term
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)  # (B, H, S_q)

    if not causal:
        dq, dk, dv = _grads_rect(
            qf, kp, vp, gf, delta, lse, 0, s_k, False, block
        )
        return (
            dq.astype(q.dtype),
            dk[:, :, :s_k].astype(k.dtype),
            dv[:, :, :s_k].astype(v.dtype),
        )

    # causal (s_q == s_k enforced by the trainable wrapper): chunk edges
    # in whole K blocks so each chunk's live prefix is block-aligned
    n_chunks = min(_bwd_causal_chunks(), nb)
    edges = sorted({round(nb * c / n_chunks) for c in range(n_chunks + 1)})
    dq_parts = []
    dk = jnp.zeros((b, h, nb * block, d), jnp.float32)
    dv = jnp.zeros_like(dk)
    for lo, hi in zip(edges[:-1], edges[1:]):
        q0, q1 = lo * block, min(hi * block, s_q)
        k_end = hi * block  # K blocks [0, hi) are the live prefix
        dq_c, dk_c, dv_c = _grads_rect(
            qf[:, :, q0:q1],
            kp[:, :, :k_end],
            vp[:, :, :k_end],
            gf[:, :, q0:q1],
            delta[:, :, q0:q1],
            lse[:, :, q0:q1],
            q0,
            s_k,
            True,
            block,
        )
        dq_parts.append(dq_c)
        dk = dk.at[:, :, :k_end].add(dk_c)
        dv = dv.at[:, :, :k_end].add(dv_c)
    dq = jnp.concatenate(dq_parts, axis=2)
    return (
        dq.astype(q.dtype),
        dk[:, :, :s_k].astype(k.dtype),
        dv[:, :, :s_k].astype(v.dtype),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention_trainable(q, k, v, causal: bool = False):
    """Differentiable fused attention: Pallas flash forward, recompute
    backward.

    The flash kernels above are forward-only (inference featurizers and
    the ring/Ulysses per-hop updates). Training needs a VJP: save ONLY
    (q, k, v) from the forward — nothing S²-sized persists between the
    forward and backward (with per-layer remat that's what bounds memory
    ACROSS the step). The backward recomputes attention two ways:

    - short context (transient bytes ≤ ``_DENSE_BWD_MAX_BYTES``, counting
      the B·H multiplier): differentiate the dense formulation — a few
      transient (S_q, S_k) tensors, fastest at sizes where they fit;
    - long context: FlashAttention-style blockwise backward — the
      forward kernel emits the row logsumexp (O(S), in-kernel, no extra
      sweep), and the backward accumulates dq/dk/dv block by block from
      (q, k, v, out, lse). Peak memory O(S·d + S_q·block), which is what
      makes 32k+ causal *training* fit a single chip (the forward kernel
      alone could stream 32k since round 2; the dense backward could
      not).
    """
    return flash_attention(q, k, v, causal=causal)


def _flash_trainable_fwd(q, k, v, causal: bool):
    if causal and q.shape[2] != k.shape[2]:
        # the flash forward masks begin-aligned (q_pos >= k_pos at offset
        # 0) while dense_attention's tril is end-aligned — the two only
        # agree at s_q == s_k, and the blockwise backward assumes the
        # forward's convention. Reject rather than return wrong grads.
        raise ValueError(
            f"flash_attention_trainable: causal cross-attention with "
            f"s_q={q.shape[2]} != s_k={k.shape[2]} is ambiguous"
        )
    if _dense_bwd_bytes(q, k) <= _dense_bwd_max_bytes():
        # short context: the dense backward needs only (q, k, v)
        return flash_attention(q, k, v, causal=causal), (q, k, v, None, None)
    out, lse = flash_attention(q, k, v, causal=causal, return_lse=True)
    return out, (q, k, v, out, lse)


def _flash_trainable_bwd(causal: bool, res, g):
    q, k, v, out, lse = res
    if out is None:
        from keystone_tpu.ops.attention import dense_attention

        _, vjp = jax.vjp(
            lambda q, k, v: dense_attention(q, k, v, causal=causal), q, k, v
        )
        return vjp(g)
    return _blockwise_grads(q, k, v, g, out, lse, causal, _bwd_block())


flash_attention_trainable.defvjp(_flash_trainable_fwd, _flash_trainable_bwd)
