"""Gaussian mixture models and Fisher vectors.

TPU-native replacement for the reference's native enceval components
(``src/main/cpp/EncEval.cxx`` shim over enceval-toolkit's
``gaussian_mixture``/``fisher``; SURVEY.md §2.10): diagonal-covariance GMM
fit by EM, and improved-Fisher-vector encoding of descriptor sets. The
reference runs EM in C++ on the driver with seed-42 random init; here EM is
a jitted ``lax.fori_loop`` whose E and M steps are batched MXU matmuls, and
fitting happens wherever the sample array lives (replicated or sharded).

Model container parity (``nodes/learning/GaussianMixtureModel.scala``):
``means``/``variances`` are (dim, k) matrices, ``weights`` (k,); CSV
save/load of the three files matches the reference's artifact format.
Deviation (documented): the reference's ``GaussianMixtureModel.apply`` is
unimplemented (``???``); here it returns the soft cluster assignments its
docstring promises.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import Estimator, Transformer
from keystone_tpu.core.treenode import static_field, treenode

VAR_FLOOR = 1e-5


@treenode
class GaussianMixtureModel(Transformer):
    """Diagonal-covariance GMM parameter container + soft assignment."""

    means: jnp.ndarray  # (dim, k)
    variances: jnp.ndarray  # (dim, k)
    weights: jnp.ndarray  # (k,)

    @property
    def k(self) -> int:
        return self.means.shape[1]

    @property
    def dim(self) -> int:
        return self.means.shape[0]

    def log_responsibilities(self, x):
        """(N, d) points → (N, k) log posteriors."""
        mu = self.means.T  # (k, d)
        var = self.variances.T  # (k, d)
        log_norm = -0.5 * (
            jnp.sum(jnp.log(2 * jnp.pi * var), axis=1)
        )  # (k,)
        # -(x-mu)^2 / 2var, expanded to use matmuls on the MXU
        x2 = (x * x) @ (0.5 / var).T  # (N, k)
        xm = x @ (mu / var).T  # (N, k)
        m2 = jnp.sum(mu * mu / (2 * var), axis=1)  # (k,)
        log_p = log_norm - x2 + xm - m2 + jnp.log(self.weights)
        return log_p - jax.scipy.special.logsumexp(log_p, axis=1, keepdims=True)

    def __call__(self, batch):
        """Soft cluster assignments (N, k)."""
        return jnp.exp(self.log_responsibilities(batch))

    def save_csv(self, mean_file: str, vars_file: str, weights_file: str):
        np.savetxt(mean_file, np.asarray(self.means), delimiter=",")
        np.savetxt(vars_file, np.asarray(self.variances), delimiter=",")
        np.savetxt(weights_file, np.asarray(self.weights)[None], delimiter=",")

    @staticmethod
    def load_csv(
        mean_file: str, vars_file: str, weights_file: str
    ) -> "GaussianMixtureModel":
        """Reference-parity artifact load (GaussianMixtureModel.load)."""
        means = np.loadtxt(mean_file, delimiter=",", ndmin=2)
        variances = np.loadtxt(vars_file, delimiter=",", ndmin=2)
        weights = np.loadtxt(weights_file, delimiter=",").ravel()
        return GaussianMixtureModel(
            means=jnp.asarray(means, jnp.float32),
            variances=jnp.asarray(variances, jnp.float32),
            weights=jnp.asarray(weights, jnp.float32),
        )


@treenode
class GaussianMixtureModelEstimator(Estimator):
    """Fit a diagonal GMM with EM (reference GaussianMixtureModelEstimator →
    EncEval.computeGMM, seed-42 random init)."""

    k: int = static_field(default=16)
    max_iter: int = static_field(default=100)
    seed: int = static_field(default=42)
    var_floor: float = static_field(default=VAR_FLOOR)
    # "device" = jitted jnp EM; "native" = C++ XLA FFI host kernel
    # (native/enceval_ffi.cpp) — the EncEval.cxx parity path
    backend: str = static_field(default="device")

    def fit(self, samples) -> GaussianMixtureModel:
        if self.backend == "native":
            from keystone_tpu.native import enceval

            means, variances, weights = enceval.gmm_em(
                np.asarray(samples), self.k, self.max_iter, self.seed,
                self.var_floor,
            )
            return GaussianMixtureModel(
                means=jnp.asarray(means),
                variances=jnp.asarray(variances),
                weights=jnp.asarray(weights),
            )
        x = jnp.asarray(samples, jnp.float32)
        means, variances, weights = _gmm_em(
            x, self.k, self.max_iter, self.seed, self.var_floor
        )
        return GaussianMixtureModel(
            means=means, variances=variances, weights=weights
        )


def gmm_init(x, k: int, seed: int, var_floor: float):
    """Deterministic EM init shared by the device and native backends:
    k distinct samples as means (the reference's random_init), global
    variance, uniform weights."""
    n = x.shape[0]
    idx = jax.random.choice(jax.random.key(seed), n, (k,), replace=False)
    mu0 = x[idx].T  # (d, k)
    global_var = jnp.maximum(jnp.var(x, axis=0), var_floor)
    var0 = jnp.tile(global_var[:, None], (1, k))
    w0 = jnp.full((k,), 1.0 / k, x.dtype)
    return mu0, var0, w0


@partial(jax.jit, static_argnames=("k", "max_iter", "seed", "var_floor"))
def _gmm_em(x, k: int, max_iter: int, seed: int, var_floor: float):
    n, d = x.shape
    mu0, var0, w0 = gmm_init(x, k, seed, var_floor)

    def em_step(_, state):
        mu, var, w = state
        model = GaussianMixtureModel(means=mu, variances=var, weights=w)
        gamma = jnp.exp(model.log_responsibilities(x))  # (N, k)
        nk = jnp.sum(gamma, axis=0) + 1e-10  # (k,)
        new_mu = (x.T @ gamma) / nk  # (d, k)
        ex2 = (x * x).T @ gamma / nk  # (d, k)
        new_var = jnp.maximum(ex2 - new_mu * new_mu, var_floor)
        new_w = nk / n
        return new_mu, new_var, new_w

    mu, var, w = jax.lax.fori_loop(0, max_iter, em_step, (mu0, var0, w0))
    return mu, var, w


@treenode
class FisherVector(Transformer):
    """Improved Fisher vector of a descriptor set wrt a GMM
    (reference nodes/images/external/FisherVector.scala → enceval
    ``fisher<float>`` with alpha=1, pnorm=0 — i.e. *no* internal power/L2
    normalization; the pipeline applies signed-sqrt + L2 as separate nodes).

    Input: (N, d, m) batch of feature-major descriptor matrices (the
    BatchPCATransformer output layout). Output: (N, d, 2k) — columns
    0..k-1 are the mean gradients, k..2k-1 the variance gradients.
    """

    gmm: GaussianMixtureModel
    backend: str = static_field(default="device")  # or "native" (FFI)

    def __call__(self, batch):
        if self.backend == "native":
            from keystone_tpu.native import enceval

            return jnp.asarray(
                enceval.fisher_vectors(
                    np.asarray(batch),
                    np.asarray(self.gmm.means),
                    np.asarray(self.gmm.variances),
                    np.asarray(self.gmm.weights),
                )
            )
        return _fisher_vectors(batch, self.gmm)


@jax.jit
def _fisher_vectors(batch, gmm: GaussianMixtureModel):
    n_imgs, d, m = batch.shape
    x = jnp.transpose(batch, (0, 2, 1)).reshape(n_imgs * m, d)  # (Nm, d)
    gamma = jnp.exp(gmm.log_responsibilities(x)).reshape(n_imgs, m, -1)
    x = x.reshape(n_imgs, m, d)

    mu = gmm.means.T  # (k, d)
    sigma = jnp.sqrt(gmm.variances.T)  # (k, d)
    w = gmm.weights  # (k,)

    s0 = jnp.sum(gamma, axis=1)  # (N, k)
    s1 = jnp.einsum("nmk,nmd->nkd", gamma, x)  # (N, k, d)
    s2 = jnp.einsum("nmk,nmd->nkd", gamma, x * x)  # (N, k, d)

    # mean gradient: (1/(m sqrt(w_k))) sum_i gamma (x - mu)/sigma
    fv_mu = (s1 - s0[..., None] * mu) / sigma
    fv_mu = fv_mu / (m * jnp.sqrt(w)[:, None])
    # var gradient: (1/(m sqrt(2 w_k))) sum_i gamma ((x-mu)^2/sigma^2 - 1)
    quad = s2 - 2 * s1 * mu + s0[..., None] * (mu * mu)
    fv_sig = quad / (sigma * sigma) - s0[..., None]
    fv_sig = fv_sig / (m * jnp.sqrt(2 * w)[:, None])

    out = jnp.concatenate([fv_mu, fv_sig], axis=1)  # (N, 2k, d)
    return jnp.transpose(out, (0, 2, 1))  # (N, d, 2k)
