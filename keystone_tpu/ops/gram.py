"""Quantized Gram operators for the streaming normal-equations fit.

The solver path's hottest contraction is ``AᵀA`` over streamed feature
chunks (:func:`keystone_tpu.ops.linear.normal_eq_update`). On TPU the
int8 MXU runs ~2× the bf16 rate, and the decode path already owns the
machinery (``quantization.py`` symmetric scales, the
``int8_matmul.mm_fused`` Pallas idiom) — this module generalizes it to
the Gram shape: per-column symmetric int8 codes, ``qᵀq`` accumulated in
f32 (int32 per k-tile — exact), the per-column scales applied as a
rank-1 outer product on the (D, D) result.

Selection is the PLANNER's call, not the caller's: the fused-fit pass
(:mod:`keystone_tpu.plan.fused_fit`) measures the quantization error on
its probe features (:func:`gram_quantization_error`, relative Frobenius
error of the probe Gram) and only picks int8 when the error is under
``KEYSTONE_GRAM_INT8_MAX_ERR`` AND the device's int8 rate beats fp32
(:func:`keystone_tpu.plan.costs.int8_gram_speedup`) — otherwise it
falls back to the exact fp32 Gram and records the decision. The
``KEYSTONE_GRAM_OP`` env knob (``auto`` | ``fp32`` | ``int8``)
overrides.

Like ``mm_fused``, the Pallas kernel runs compiled on TPU and falls
back to an XLA int8→int32 dot elsewhere (CPU tests, interpret mode is
opt-in) — same numerics either way.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from keystone_tpu.ops.quantization import symmetric_int8

# jax renamed TPUCompilerParams → CompilerParams across the versions
# this repo meets; resolve whichever this runtime has so the kernel
# (unlike the decode-only mm_fused) stays testable on both
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)

ENV_GRAM_OP = "KEYSTONE_GRAM_OP"
ENV_INT8_MAX_ERR = "KEYSTONE_GRAM_INT8_MAX_ERR"
_DEFAULT_INT8_MAX_ERR = 0.03


def gram_op_request() -> str:
    """The requested Gram operator: ``KEYSTONE_GRAM_OP`` env knob,
    normalized to ``auto`` | ``fp32`` | ``int8`` (unknown → auto)."""
    raw = os.environ.get(ENV_GRAM_OP, "").strip().lower()
    return raw if raw in ("fp32", "int8") else "auto"


def int8_error_threshold() -> float:
    """Max relative Gram quantization error the planner accepts before
    falling back to fp32 (``KEYSTONE_GRAM_INT8_MAX_ERR``)."""
    raw = os.environ.get(ENV_INT8_MAX_ERR, "").strip()
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return _DEFAULT_INT8_MAX_ERR


def ata_fp32(a) -> jnp.ndarray:
    """The exact default Gram operator: ``aᵀa`` in f32."""
    a = a.astype(jnp.float32)
    return a.T @ a


def _quantize_cols(a):
    """Per-COLUMN symmetric int8 (scales pool over rows): the Gram's
    (i, j) entry then reconstructs as ``s_i s_j · (qᵀq)_{ij}``. Masked
    (zero) pad rows quantize to zero codes and contribute nothing."""
    q, scale = symmetric_int8(a, reduce_axes=(0,))  # scale (1, D)
    return q, scale


def ata_int8_xla(a) -> jnp.ndarray:
    """XLA form of the quantized Gram: int8 codes contracted with an
    int32 accumulator (exact — |q| ≤ 127), scaled back to f32. The
    non-TPU half of :func:`ata_int8`; also the reference the kernel is
    tested against."""
    q, scale = _quantize_cols(a)
    qtq = jax.lax.dot_general(
        q,
        q,
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return qtq.astype(jnp.float32) * (scale[0][:, None] * scale[0][None, :])


def _ata_kernel(x1_ref, x2_ref, o_ref, acc_ref, *, n_k: int):
    """One (bm, bn) output tile of qᵀq; grid = (D/bm, D/bn, N/bk) with
    the row (contraction) dimension k sequential. The int8 codes stream
    from HBM as int8 (the economics — ¼ the f32 bytes) and contract on
    the row axis via ``dot_general``; each k-step's partial product is
    exact in int32 (≤ bk·127² < 2²⁴) and folds into the f32 VMEM
    accumulator."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    prod = jax.lax.dot_general(
        x1_ref[...],
        x2_ref[...],
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    acc_ref[...] += prod.astype(jnp.float32)

    @pl.when(k == n_k - 1)
    def _finalize():
        o_ref[...] = acc_ref[...]


def _pad_dim(x, axis: int, mult: int):
    pad = (-x.shape[axis]) % mult
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.jit, static_argnames=("block_d", "block_k", "interpret")
)
def ata_int8_pallas(
    a,
    *,
    block_d: int = 256,
    block_k: int = 512,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """``AᵀA`` with per-column int8 codes streamed through a Pallas
    kernel (f32 accumulation) — the Gram-shaped generalization of
    ``int8_matmul.mm_fused``. ``a``: (N, D) float; returns (D, D) f32.
    """
    if interpret is None:
        from keystone_tpu.ops.flash_attention import on_tpu

        interpret = not on_tpu()
    n, d = a.shape
    q, scale = _quantize_cols(a)
    # int8 tiles are (32, 128)-granular; rows pad to the k block (zero
    # codes contribute nothing), columns to the d block and trimmed back
    q = _pad_dim(_pad_dim(q, 0, block_k), 1, block_d)
    n_pad, d_pad = q.shape
    n_k = n_pad // block_k

    qtq = pl.pallas_call(
        functools.partial(_ata_kernel, n_k=n_k),
        grid=(d_pad // block_d, d_pad // block_d, n_k),
        in_specs=[
            pl.BlockSpec((block_k, block_d), lambda i, j, k: (k, i)),
            pl.BlockSpec((block_k, block_d), lambda i, j, k: (k, j)),
        ],
        out_specs=pl.BlockSpec((block_d, block_d), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((d_pad, d_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_d, block_d), jnp.float32)],
        # the two D-tile axes are independent; k is the sequential
        # accumulator dim — declaring it lets Mosaic pipeline the int8
        # HBM loads across steps (same contract as mm_fused)
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, q)
    qtq = qtq[:d, :d]
    return qtq * (scale[0][:, None] * scale[0][None, :])


def ata_int8(a) -> jnp.ndarray:
    """The planner-selectable int8 Gram operator: Pallas on TPU, the
    XLA int32 dot elsewhere — identical numerics, chosen at trace time
    (``gram_fn`` is jit-static, so each backend compiles its own
    form)."""
    from keystone_tpu.ops.flash_attention import on_tpu

    if on_tpu():
        return ata_int8_pallas(a)
    return ata_int8_xla(a)


def gram_quantization_error(a) -> float:
    """Worst per-column quantization error of int8 codes on a probe
    slice, relative to the column's TYPICAL magnitude:
    ``max_col (amax_col/127) / (√12 · median|col|_nonzero)`` — the RMS
    rounding noise of a column's codes over the scale of the mass that
    actually carries the normal equations' signal.

    Norm-relative metrics (Gram Frobenius ratio, whole-matrix RMS) are
    blind to exactly the failure int8 Grams have: one heavy-tailed row
    blows a column's scale so every other entry quantizes to zero, yet
    the outlier dominates the norms too, so the ratio stays tiny. The
    median-of-nonzeros denominator is what the outlier can't move, and
    the max over columns is deliberate — a single destroyed column
    poisons every weight the solve produces through it. ~0.01 on
    well-scaled gaussian or relu features; orders of magnitude past any
    threshold once a column's amax dwarfs its typical value. Host-side
    eager; probe-sized inputs only.
    """
    a = np.abs(np.asarray(a, np.float32))
    if a.size == 0:
        return 0.0
    amax = a.max(axis=0)
    step_rms = amax / 127.0 / np.sqrt(12.0)
    worst = 0.0
    for j in range(a.shape[1]):
        col = a[:, j]
        nz = col[col > 0]
        if nz.size == 0:
            continue
        worst = max(worst, float(step_rms[j] / np.median(nz)))
    return worst
