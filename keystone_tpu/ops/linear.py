"""Linear models and the distributed least-squares solver layer.

TPU-native rebuild of the reference's solver stack (SURVEY.md §2.2): the
``nodes/learning/LinearMapper.scala`` / ``BlockLinearMapper.scala`` nodes
*and* the external ``mlmatrix`` engine they call (RowPartitionedMatrix,
NormalEquations, BlockCoordinateDescent) — re-expressed as sharded jnp:

- the data matrix lives sharded over the mesh "data" axis (one shard per
  chip = one Spark partition's row block),
- every Gram/cross product ``A.T @ R`` contracts the sharded axis, which XLA
  compiles to per-shard partial gemms + an ICI ``psum`` — the successor of
  ``mlmatrix.Utils.treeReduce`` of per-partition ``(AᵀA, AᵀR)``,
- the small ``(d_block, d_block)`` solves are replicated (every chip solves;
  the "driver" disappears),
- block coordinate descent iterates model-column blocks exactly like the
  reference's ``BlockCoordinateDescent.solveLeastSquaresWithL2``, carrying
  the residual as loop state instead of a mutable cached RDD chain.

Padding: batches zero-padded for sharding (``parallel.mesh.pad_batch``) pass
``n_valid``; padded rows are masked out of means and Gram products.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import Estimator, LabelEstimator, Transformer
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.ops.stats import StandardScalerModel


def _row_mask(n_rows: int, n_valid, dtype) -> jnp.ndarray:
    """(n_rows, 1) mask of valid rows; all-ones when n_valid is None."""
    if n_valid is None:
        return jnp.ones((n_rows, 1), dtype)
    return (jnp.arange(n_rows) < n_valid)[:, None].astype(dtype)


def _cho_factor_escalating(
    m: jnp.ndarray, jitter: float, max_steps: int = 5
):
    """Cholesky with an escalating jitter floor: factor ``m + j·I``,
    multiplying ``j`` by 32 until the factor is NaN-free (rank-deficient
    Grams of large-scale features can be INDEFINITE at the f32 noise
    level — a fixed 1e-6 jitter then produces a NaN factor, which without
    this guard silently poisons the model into chance predictions).
    Returns the (factor, jitter_used) pair; traced, so the retry costs
    nothing when the first factorization is clean (the while_loop exits
    after one iteration)."""
    d = m.shape[0]
    eye = jnp.eye(d, dtype=m.dtype)

    def factor(j):
        return jax.scipy.linalg.cho_factor(m + j * eye)[0]

    def cond(state):
        j, c, steps = state
        return jnp.logical_and(
            jnp.any(jnp.isnan(c)), steps < max_steps
        )

    def body(state):
        j, _, steps = state
        j = j * 32.0
        return (j, factor(j), steps + 1)

    j0 = jnp.asarray(jitter, m.dtype)
    j, c, _ = jax.lax.while_loop(cond, body, (j0, factor(j0), 0))
    # cho_factor's default layout is upper (lower=False); cho_solve needs
    # the matching flag
    return (c, False), j


def ridge_factor(
    ata: jnp.ndarray, lam, jitter: float = 1e-6
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Equilibrated escalating-jitter Cholesky of ``AᵀA + λI`` as plain
    arrays ``(c, inv_s)`` — vmappable, hoistable out of solve loops (the
    TPU factorization is sequential-panel latency; BCD re-solves the same
    Gram every pass, so factoring once per fit instead of once per pass
    removes the dominant fixed cost of multi-pass fits)."""
    inv_s = jax.lax.rsqrt(jnp.clip(jnp.diagonal(ata), 1e-30, None))
    m = ata * (inv_s[:, None] * inv_s[None, :])
    m = m + jnp.diag(lam * inv_s * inv_s)
    cf, _ = _cho_factor_escalating(m, jitter)
    return cf[0], inv_s


def ridge_solve_prefactored(
    factor: tuple[jnp.ndarray, jnp.ndarray],
    ata: jnp.ndarray,
    atb: jnp.ndarray,
    lam,
    refine: int = 2,
) -> jnp.ndarray:
    """Solve with a :func:`ridge_factor` result; refinement targets the
    ORIGINAL system so the equilibrated/jittered factor's error is
    recovered exactly as in :func:`ridge_solve`."""
    c, inv_s = factor

    def solve_prec(rhs):
        return inv_s[:, None] * jax.scipy.linalg.cho_solve(
            (c, False), rhs * inv_s[:, None]
        )

    x = solve_prec(atb)
    for _ in range(refine):
        r = atb - (ata @ x + lam * x)
        x = x + solve_prec(r)
    return x


def ridge_solve(
    ata: jnp.ndarray,
    atb: jnp.ndarray,
    lam,
    refine: int = 2,
    jitter: float = 1e-6,
) -> jnp.ndarray:
    """Solve ``(AᵀA + λI) X = AᵀB`` — the NormalEquations primitive.

    The reference does this in f64 LAPACK where Gram conditioning is a
    non-issue; in f32 on TPU the normal equations square A's condition
    number, so a raw Cholesky NaNs out on realistic features (e.g. the
    random-FFT pipeline's O(700)-scale features). Stabilized while staying
    f32:

    - diagonal (Jacobi) equilibration of the Gram,
    - a relative ``jitter`` floor keeping the factorization positive even
      when λ is tiny vs the Gram scale, escalated ×32 until the factor is
      NaN-free (rank-deficient N<d Grams of 255-scale inputs need more
      than the base floor),
    - ``refine`` steps of iterative refinement against the *original*
      system, recovering the accuracy the equilibrated factor loses.

    Tiny replicated compute; runs identically on every chip.
    """
    return ridge_solve_prefactored(
        ridge_factor(ata, lam, jitter), ata, atb, lam, refine
    )


ENV_MATMUL_PRECISION = "KEYSTONE_MATMUL_PRECISION"


def _matmul_precision(precision: str | None):
    """Context for an estimator-level matmul-precision override.

    ``precision=None`` falls back to the ``KEYSTONE_MATMUL_PRECISION``
    env knob (e.g. ``highest`` forces full-f32 MXU accumulation for
    solver Grams on TPU, where the backend default runs bf16 passes on
    f32 inputs), and is a no-op when that too is unset. The jit cache
    keys on the config state, so fits at different precisions don't
    collide.
    """
    import contextlib

    if precision is None:
        precision = os.environ.get(ENV_MATMUL_PRECISION, "").strip() or None
    if precision is None:
        return contextlib.nullcontext()
    return jax.default_matmul_precision(precision)


def stabilized_cho_solve(mat: jnp.ndarray, jitter: float = 1e-6):
    """Factor a symmetric PSD ``mat`` once, return a multi-RHS solver.

    Same Jacobi-equilibration + relative-jitter stabilization as
    :func:`ridge_solve` (f32 Grams on TPU), but exposed as a reusable
    closure so callers that solve against ONE base matrix with many
    right-hand sides (e.g. the weighted solver's Woodbury path) pay the
    O(d³) factorization once and every solve is triangular-substitution
    gemms. The returned fn maps (d, k) → (d, k).
    """
    c, inv_s = ridge_factor(mat, 0.0, jitter)

    def solve(rhs):
        return inv_s[:, None] * jax.scipy.linalg.cho_solve(
            (c, False), rhs * inv_s[:, None]
        )

    return solve


@treenode
class LinearMapper(Transformer):
    """``in @ x + b`` with an optional feature scaler applied first
    (nodes/learning/LinearMapper.scala).

    One MXU gemm over the whole sharded batch — the reference's
    rows-to-matrix-per-partition batching is the default here.
    """

    x: jnp.ndarray  # (D, K)
    b: jnp.ndarray | None = None
    feature_scaler: StandardScalerModel | None = None

    def __call__(self, batch):
        if self.feature_scaler is not None:
            batch = self.feature_scaler(batch)
        out = batch @ self.x
        if self.b is not None:
            out = out + self.b
        return out


@treenode
class LinearMapEstimator(LabelEstimator):
    """Exact ridge/OLS via normal equations on mean-centered A and b
    (nodes/learning/LinearMapper.scala LinearMapEstimator).

    The reference calls ``mlmatrix NormalEquations.solveLeastSquares[WithL2]``
    (per-partition Gram blocks tree-reduced to the driver); here the centered
    Gram contraction sharded over "data" + replicated Cholesky is the whole
    story.
    """

    lam: float = static_field(default=0.0)

    def fit(self, data, labels, n_valid: int | None = None) -> LinearMapper:
        with _matmul_precision(None):
            x, b_mean, a_mean = _linear_map_fit(
                data, labels, n_valid, self.lam
            )
        scaler = StandardScalerModel(mean=a_mean, std=None)
        return LinearMapper(x=x, b=b_mean, feature_scaler=scaler)

    def fit_sweep(
        self, data, labels, lams, n_valid: int | None = None
    ) -> list[LinearMapper]:
        """One exact ridge model per λ: the (N·d²) Gram is computed once,
        the (d³) solves are vmapped over the sweep (mlmatrix's
        ``Array(lambda)`` capability — see
        ``BlockLeastSquaresEstimator.fit_sweep``)."""
        lams_arr = jnp.asarray(lams)
        with _matmul_precision(None):
            xs, b_mean, a_mean = _linear_map_fit_sweep(
                data, labels, n_valid, lams_arr
            )
        scaler = StandardScalerModel(mean=a_mean, std=None)
        return [
            LinearMapper(x=xs[i], b=b_mean, feature_scaler=scaler)
            for i in range(lams_arr.shape[0])
        ]

    # -- streaming normal-equations protocol (fit_stats_*) ------------
    # The chunk-accumulating form of the fit: running (AᵀA, AᵀB, Σa,
    # Σb, n) state updated per chunk, solved at finalize — the planner's
    # fused featurize→accumulate fit path drives this instead of
    # requiring the whole feature matrix resident.

    def fit_stats_init(self, d: int, k: int) -> "NormalEqState":
        return normal_eq_init(d, k)

    def fit_stats_update(
        self, state, data, labels, n_valid=None, gram_fn=None
    ) -> "NormalEqState":
        return normal_eq_update(state, data, labels, n_valid, gram_fn)

    def fit_stats_finalize(self, state, widths=None) -> LinearMapper:
        ata, atb, b_mean, a_mean, _ = normal_eq_finalize(state)
        with _matmul_precision(None):
            x = _ridge_from_stats(ata, atb, self.lam)
        scaler = StandardScalerModel(mean=a_mean, std=None)
        return LinearMapper(x=x, b=b_mean, feature_scaler=scaler)

    def fit_sweep_finalize(
        self, state, lams, widths=None
    ) -> list[LinearMapper]:
        """The λ-sweep off ONE accumulated state: the streamed Gram is
        the expensive part; the per-λ solves are vmapped exactly like
        :meth:`fit_sweep`."""
        ata, atb, b_mean, a_mean, _ = normal_eq_finalize(state)
        lams_arr = jnp.asarray(lams, jnp.float32)
        with _matmul_precision(None):
            xs = _ridge_sweep_from_stats(ata, atb, lams_arr)
        scaler = StandardScalerModel(mean=a_mean, std=None)
        return [
            LinearMapper(x=xs[i], b=b_mean, feature_scaler=scaler)
            for i in range(lams_arr.shape[0])
        ]

    @staticmethod
    def fit_stats_flops_per_row(d: int, k: int) -> float:
        """Modeled accumulation FLOPs per streamed row (Gram + AᵀB) —
        the planner's cost-model basis for the fused-fit sink."""
        return 2.0 * d * (d + k)

    @staticmethod
    def fit_stats_state_bytes(d: int, k: int) -> int:
        """Resident f32 state bytes — the planner refuses to stream a
        fit whose state alone would blow the memory budget."""
        return 4 * (d * d + d * k + 2 * d + 2 * k)


def _normal_eq_stats(data, labels, n_valid):
    """Shared preamble: masked means, centered Gram AᵀA and AᵀB."""
    dtype = data.dtype
    mask = _row_mask(data.shape[0], n_valid, dtype)
    n = jnp.sum(mask)
    a_mean = jnp.sum(data * mask, axis=0) / n
    b_mean = jnp.sum(labels * mask, axis=0) / n
    a_c = (data - a_mean) * mask
    b_c = (labels - b_mean) * mask
    return a_c.T @ a_c, a_c.T @ b_c, b_mean, a_mean


@partial(jax.jit, static_argnames=("lam",))
def _linear_map_fit(data, labels, n_valid, lam: float):
    ata, atb, b_mean, a_mean = _normal_eq_stats(data, labels, n_valid)
    x = ridge_solve(ata, atb, lam)
    return x, b_mean, a_mean


@jax.jit
def _linear_map_fit_sweep(data, labels, n_valid, lams):
    ata, atb, b_mean, a_mean = _normal_eq_stats(data, labels, n_valid)
    lams = lams.astype(data.dtype)
    xs = jax.vmap(lambda l: ridge_solve(ata, atb, l))(lams)
    return xs, b_mean, a_mean


# ---------------------------------------------------------------------------
# Streaming normal equations: chunk-accumulated (AᵀA, AᵀB, μa, μb, n)
# state in f32 — each chunk centered about its own mean, merged with a
# rank-1 mean-difference correction (Chan's pairwise update), so the
# centered Gram needs no finalize-time subtraction. This is the
# fit_stats_init/update/finalize protocol the planner's fused
# featurize→accumulate path drives (plan/fused_fit.py): the feature
# matrix is never resident — only the (D, D+K) state is.


@treenode
class NormalEqState:
    """Running f32 normal-equation statistics over streamed chunks.

    The Gram is kept CENTERED throughout (Chan's pairwise merge): each
    chunk is centered about its OWN masked mean before contracting, and
    the merge adds only a small rank-1 mean-difference correction,
    ``(n·m/(n+m)) · δδᵀ`` with ``δ = μ_chunk − μ_running``. Nothing
    large is ever subtracted — the finalize is a plain read — which is
    the difference between ~1e-3 and ~1e-6 relative error on realistic
    f32 feature scales.
    """

    ata: jnp.ndarray  # (D, D) centered Σ about the running mean
    atb: jnp.ndarray  # (D, K) centered cross product
    mean_a: jnp.ndarray  # (D,) running masked mean of the features
    mean_b: jnp.ndarray  # (K,) running masked mean of the labels
    n: jnp.ndarray  # () valid-row count


def normal_eq_init(d: int, k: int) -> NormalEqState:
    """Zero state for a (N, d) → (N, k) streamed fit."""
    f32 = jnp.float32
    return NormalEqState(
        ata=jnp.zeros((d, d), f32),
        atb=jnp.zeros((d, k), f32),
        mean_a=jnp.zeros((d,), f32),
        mean_b=jnp.zeros((k,), f32),
        n=jnp.zeros((), f32),
    )


def _concat_blocks(data):
    if isinstance(data, (list, tuple)):
        return jnp.concatenate([jnp.asarray(b) for b in data], axis=-1)
    return data


@partial(jax.jit, static_argnames=("gram_fn",))
def _normal_eq_update(state, data, labels, n_valid, gram_fn):
    data = _concat_blocks(data)
    f32 = jnp.float32
    mask = _row_mask(data.shape[0], n_valid, f32)
    m = jnp.sum(mask)
    m_safe = jnp.maximum(m, 1.0)
    a = data.astype(f32)
    b = labels.astype(f32)
    mu_a = jnp.sum(a * mask, 0) / m_safe
    mu_b = jnp.sum(b * mask, 0) / m_safe
    a_c = (a - mu_a) * mask
    b_c = (b - mu_b) * mask
    gram = gram_fn(a_c) if gram_fn is not None else a_c.T @ a_c
    # Chan merge: an all-pad chunk (m = 0) contributes nothing — the
    # rank-1 weight n·m/(n+m) and the mean step m/(n+m) both vanish
    n_new = jnp.maximum(state.n + m, 1.0)
    w = state.n * m / n_new
    da = mu_a - state.mean_a
    db = mu_b - state.mean_b
    return NormalEqState(
        ata=state.ata + gram + w * jnp.outer(da, da),
        atb=state.atb + a_c.T @ b_c + w * jnp.outer(da, db),
        mean_a=state.mean_a + (m / n_new) * da,
        mean_b=state.mean_b + (m / n_new) * db,
        n=state.n + m,
    )


def normal_eq_update(
    state: NormalEqState,
    data,
    labels,
    n_valid=None,
    gram_fn=None,
    precision: str | None = None,
) -> NormalEqState:
    """Fold one chunk into the state — ONE jitted step (featurize
    prefixes fuse in front of it when traced together). ``data`` may be
    a (rows, d) array or a list of feature blocks (concatenated);
    ``n_valid`` masks trailing pad rows out of every statistic;
    ``gram_fn`` swaps the AᵀA operator (e.g. the int8 quantized Gram,
    :func:`keystone_tpu.ops.gram.ata_int8`) — it must map a centered,
    masked (rows, d) chunk to a (d, d) f32 Gram; ``precision`` pins
    the matmul precision (falls back to ``KEYSTONE_MATMUL_PRECISION``),
    so an estimator's pinned precision reaches the streamed Grams the
    way it reaches the materialized ones."""
    with _matmul_precision(precision):
        return _normal_eq_update(state, data, labels, n_valid, gram_fn)


def normal_eq_finalize(state: NormalEqState):
    """Centered ``(AᵀA, AᵀB, b_mean, a_mean, n)`` — with the Chan-merge
    state this is a plain read (the Gram was never uncentered)."""
    n = jnp.maximum(state.n, 1.0)
    return state.ata, state.atb, state.mean_b, state.mean_a, n


@partial(jax.jit, static_argnames=("lam",))
def _ridge_from_stats(ata, atb, lam: float):
    return ridge_solve(ata, atb, lam)


@jax.jit
def _ridge_sweep_from_stats(ata, atb, lams):
    return jax.vmap(lambda l: ridge_solve(ata, atb, l))(lams)


def block_widths(d: int, block_size: int) -> tuple[int, ...]:
    """THE one home of feature-block boundaries: ``_split_blocks``,
    :class:`BlockLinearMapper`, and the streaming Gram-form BCD all
    derive block edges here, so block fits and streaming fits can't
    disagree on where a block (and its masking) starts."""
    return tuple(
        min(block_size, d - s) for s in range(0, max(d, 1), block_size)
    )


def split_by_widths(data, widths) -> list:
    """Slice the feature axis by explicit block widths."""
    blocks, start = [], 0
    for w in widths:
        blocks.append(data[..., start : start + w])
        start += w
    return blocks


def _split_blocks(data, block_size: int) -> list:
    if isinstance(data, (list, tuple)):
        return list(data)
    return split_by_widths(data, block_widths(data.shape[-1], block_size))


@treenode
class BlockLinearMapper(Transformer):
    """Linear model stored as column blocks of the feature axis
    (nodes/learning/BlockLinearMapper.scala).

    ``apply`` sums per-block partial products — the reference's
    feature-block ("tensor") parallelism. Accepts the full (N, D) array or a
    pre-split block list (VectorSplitter output).
    """

    xs: tuple  # per-block (d_i, K) weights
    b: jnp.ndarray | None = None
    means: tuple | None = None  # per-block feature means (centering)
    block_size: int = static_field(default=4096)

    def _blocks_of(self, batch) -> list:
        """Split by the fitted per-block widths (last block may be
        narrower) — the shared :func:`split_by_widths` boundary rule."""
        if isinstance(batch, (list, tuple)):
            return list(batch)
        return split_by_widths(batch, tuple(x.shape[0] for x in self.xs))

    def __call__(self, batch):
        return self._sum_blocks(tuple(self._blocks_of(batch)))

    def _partial(self, block, i):
        x = self.xs[i]
        if self.means is not None:
            block = block - self.means[i]
        return block @ x

    def _sum_blocks(self, blocks: tuple):
        out = self._partial(blocks[0], 0)
        for i in range(1, len(blocks)):
            out = out + self._partial(blocks[i], i)
        if self.b is not None:
            out = out + self.b
        return out

    def apply_and_evaluate(self, batch, evaluator: Callable[[jnp.ndarray], None]):
        """Stream per-block partial predictions to ``evaluator`` so test
        metrics can be monitored as blocks accumulate
        (BlockLinearMapper.applyAndEvaluate in the reference)."""
        blocks = self._blocks_of(batch)
        acc = None
        for i, blk in enumerate(blocks):
            p = self._partial(blk, i)
            acc = p if acc is None else acc + p
            out = acc if self.b is None else acc + self.b
            evaluator(out)


@treenode
class BlockLeastSquaresEstimator(LabelEstimator):
    """Block coordinate descent least squares with L2 regularization
    (nodes/learning/BlockLinearMapper.scala BlockLeastSquaresEstimator →
    mlmatrix ``BlockCoordinateDescent.solveLeastSquaresWithL2``).

    Semantics matched to the reference:
    - labels centered by their mean; each feature block mean-centered
      (per-block StandardScaler with ``normalizeStdDev=false``),
    - ``num_iter`` passes of BCD over the blocks with ridge ``lam``,
    - fitted model carries per-block means and the label-mean intercept.

    The BCD pass runs in one jitted program: per-block Grams are computed
    once and reused across passes (the reference's cached BlockStatistics);
    the residual is loop state.
    """

    block_size: int = static_field(default=4096)
    num_iter: int = static_field(default=1)
    lam: float = static_field(default=0.0)
    num_features: int | None = static_field(default=None)
    # Gram/solve matmul precision: None = backend default (bf16 MXU
    # passes on TPU; the equilibrated+refined ridge_solve is built for
    # this), "highest" = full f32 accumulation (reference-BLAS class) —
    # same contract as Convolver.precision
    precision: str | None = static_field(default=None)

    def fit(
        self,
        data,
        labels,
        n_valid: int | None = None,
        init: BlockLinearMapper | None = None,
    ) -> BlockLinearMapper:
        """``init`` warm-starts BCD from a previously fitted model's
        blocks — the fixed point is identical, and k passes from a model
        checkpointed after j passes equal one (j+k)-pass fit exactly (see
        :func:`keystone_tpu.core.checkpoint.resumable_fit`)."""
        blocks = _split_blocks(data, self.block_size)
        init_xs = None if init is None else tuple(init.xs)
        with _matmul_precision(self.precision):
            xs, means, intercept = _bcd_fit(
                tuple(blocks),
                labels,
                n_valid,
                init_xs,
                self.num_iter,
                self.lam,
            )
        return BlockLinearMapper(
            xs=xs, b=intercept, means=means, block_size=self.block_size
        )


    def fit_sweep(
        self,
        data,
        labels,
        lams,
        n_valid: int | None = None,
        sweep_chunk: int | None = None,
    ) -> list[BlockLinearMapper]:
        """Fit one model per ridge λ in ``lams`` at marginal cost.

        The reference's solver engine took an ARRAY of lambdas
        (mlmatrix ``solveLeastSquaresWithL2(A, b, Array(lambda), ...)``,
        BlockLinearMapper.scala:178-181) so hyperparameter sweeps could
        reuse the expensive normal-equation statistics; same here: the
        per-block Grams (the N·d² work) are computed once and the
        per-λ solves/residuals are batched (vmapped) over the sweep —
        an L-point sweep costs far less than L fits. Returns models in
        ``lams`` order.

        Memory: the sweep residual is (L, N, C) — L multiplies residual
        HBM, so at TIMIT scale (N~2M, C=147) even a 5-point sweep adds
        ~6GB/chip. ``sweep_chunk`` bounds this by running the sweep a few
        λs at a time (Grams are recomputed per chunk — the N·d² cost is
        re-paid once per chunk, still far cheaper than L separate fits).
        Default ``None`` auto-sizes chunks to keep the residual under
        ~2GiB/process.
        """
        blocks = _split_blocks(data, self.block_size)
        lams_arr = jnp.asarray(lams, jnp.float32)
        n_lam = int(lams_arr.shape[0])
        if sweep_chunk is None:
            itemsize = blocks[0].dtype.itemsize
            # per-λ liveness: the (N, C) residual slice PLUS the hoisted
            # per-(block, λ) Cholesky factors (Σ d_block² — resident for
            # the whole sweep since round 3's factor hoisting)
            per_lam = (
                blocks[0].shape[0] * labels.shape[-1]
                + sum(b.shape[-1] ** 2 for b in blocks)
            ) * itemsize
            sweep_chunk = max(1, min(n_lam, (2 << 30) // max(per_lam, 1)))
        # _bcd_fit_sweep is jitted: an uneven tail chunk (2,2,1) would
        # recompile the whole sweep program for the odd shape. Pad the
        # λ array to a chunk multiple (repeating the last λ — the extra
        # solves are marginal next to the shared Grams) so every chunk
        # compiles once; the padded models are dropped at the end.
        sweep_chunk = min(sweep_chunk, n_lam)
        n_pad = -(-n_lam // sweep_chunk) * sweep_chunk
        lams_pad = jnp.concatenate(
            [lams_arr, jnp.broadcast_to(lams_arr[-1:], (n_pad - n_lam,))]
        )
        models: list[BlockLinearMapper] = []
        with _matmul_precision(self.precision):
            for s in range(0, n_pad, sweep_chunk):
                chunk = lams_pad[s : s + sweep_chunk]
                xs_l, means, intercept = _bcd_fit_sweep(
                    tuple(blocks), labels, n_valid, chunk, self.num_iter
                )
                models.extend(
                    BlockLinearMapper(
                        xs=tuple(xb[i] for xb in xs_l),
                        b=intercept,
                        means=means,
                        block_size=self.block_size,
                    )
                    for i in range(chunk.shape[0])
                )
        return models[:n_lam]

    # -- streaming normal-equations protocol (fit_stats_*) ------------
    # Same accumulated (AᵀA, AᵀB, Σa, Σb, n) state as the exact solver —
    # the FULL (D, D) Gram carries every cross-block product BCD needs,
    # so finalize runs the Gram-form pass loop (:func:`_bcd_fit_gram`)
    # at D²·K per pass with the rows long gone. Memory: D² f32 state vs
    # the materialized N·D features — the planner prices the trade.

    def fit_stats_init(self, d: int, k: int) -> NormalEqState:
        return normal_eq_init(d, k)

    def fit_stats_update(
        self, state, data, labels, n_valid=None, gram_fn=None
    ) -> NormalEqState:
        return normal_eq_update(
            state, data, labels, n_valid, gram_fn, precision=self.precision
        )

    def _finalize_widths(self, state, widths) -> tuple[int, ...]:
        d = state.ata.shape[0]
        return tuple(widths) if widths else block_widths(d, self.block_size)

    def fit_stats_finalize(self, state, widths=None) -> BlockLinearMapper:
        """``widths`` pins the block boundaries to whatever the caller's
        feature blocks were (a bank's last block may be narrower than
        ``block_size``); default derives them from :func:`block_widths`
        — the same rule ``_split_blocks`` uses, so the streamed fit and
        the materialized fit can never disagree on block edges."""
        return self.fit_sweep_finalize(state, [self.lam], widths=widths)[0]

    def fit_sweep_finalize(
        self, state, lams, widths=None
    ) -> list[BlockLinearMapper]:
        widths = self._finalize_widths(state, widths)
        ata, atb, b_mean, a_mean, _ = normal_eq_finalize(state)
        lams_arr = jnp.asarray(lams, jnp.float32)
        with _matmul_precision(self.precision):
            xs_l = _bcd_fit_gram(ata, atb, lams_arr, widths, self.num_iter)
        means = tuple(split_by_widths(a_mean, widths))
        offs = np.concatenate([[0], np.cumsum(widths)]).astype(int)
        return [
            BlockLinearMapper(
                xs=tuple(
                    xs_l[i, offs[j] : offs[j + 1]]
                    for j in range(len(widths))
                ),
                b=b_mean,
                means=means,
                block_size=self.block_size,
            )
            for i in range(lams_arr.shape[0])
        ]

    @staticmethod
    def fit_stats_flops_per_row(d: int, k: int) -> float:
        return 2.0 * d * (d + k)

    @staticmethod
    def fit_stats_state_bytes(d: int, k: int) -> int:
        return 4 * (d * d + d * k + 2 * d + 2 * k)


def _block_stats(blocks: tuple, labels, n_valid):
    """Shared BCD preamble: row mask, label mean, per-block means,
    centered blocks, and Grams (the N·d² statistics both the single-λ fit
    and the λ-sweep reuse)."""
    dtype = blocks[0].dtype
    mask = _row_mask(blocks[0].shape[0], n_valid, dtype)
    n = jnp.sum(mask)
    b_mean = jnp.sum(labels * mask, axis=0) / n
    means, centered, grams = [], [], []
    for blk in blocks:
        m = jnp.sum(blk * mask, axis=0) / n
        a_c = (blk - m) * mask
        means.append(m)
        centered.append(a_c)
        grams.append(a_c.T @ a_c)  # contraction over sharded axis → psum
    return mask, b_mean, means, centered, grams


@partial(jax.jit, static_argnames=("num_iter",))
def _bcd_fit_sweep(blocks: tuple, labels, n_valid, lams, num_iter: int):
    """Multi-λ BCD: shared Grams, λ-batched solves. xs per block come back
    with a leading sweep axis (L, d_block, C)."""
    dtype = blocks[0].dtype
    lams = lams.astype(dtype)  # keep the fori_loop carry dtype-stable
    mask, b_mean, means, centered, grams = _block_stats(
        blocks, labels, n_valid
    )

    k = labels.shape[-1]
    n_lam = lams.shape[0]
    xs = tuple(
        jnp.zeros((n_lam, blk.shape[-1], k), dtype) for blk in blocks
    )
    resid = jnp.broadcast_to(
        (labels - b_mean) * mask, (n_lam,) + labels.shape
    ).astype(dtype)

    # batched per-(block, λ) factors, computed ONCE per sweep: factors
    # are pass-invariant, and the TPU factorization is the latency floor
    # (costs L·d_block² extra HBM per block — bounded by fit_sweep's
    # sweep chunking)
    factors = [
        jax.vmap(lambda l, g=g: ridge_factor(g, l))(lams) for g in grams
    ]

    def one_pass(_p, state):
        xs, resid = state
        xs = list(xs)
        for i, a_c in enumerate(centered):
            rhs = jnp.einsum("nd,lnc->ldc", a_c, resid) + jnp.einsum(
                "de,lec->ldc", grams[i], xs[i]
            )
            x_new = jax.vmap(
                lambda f, r, l, g=grams[i]: ridge_solve_prefactored(
                    f, g, r, l
                )
            )(factors[i], rhs, lams)
            resid = resid - jnp.einsum("nd,ldc->lnc", a_c, x_new - xs[i])
            xs[i] = x_new
        return tuple(xs), resid

    xs, resid = jax.lax.fori_loop(0, num_iter, one_pass, (xs, resid))
    return xs, tuple(means), b_mean


@partial(jax.jit, static_argnames=("num_iter", "lam"))
def _bcd_fit(
    blocks: tuple, labels, n_valid, init_xs, num_iter: int, lam: float
):
    dtype = blocks[0].dtype
    mask, b_mean, means, centered, grams = _block_stats(
        blocks, labels, n_valid
    )

    k = labels.shape[-1]
    if init_xs is None:
        xs = [jnp.zeros((blk.shape[-1], k), dtype) for blk in blocks]
    else:
        xs = [x.astype(dtype) for x in init_xs]
    # residual consistent with the (possibly warm-started) model:
    # R = b_c − Σ A_i x_i
    resid = (labels - b_mean) * mask
    for a_c, x in zip(centered, xs):
        resid = resid - a_c @ x

    # factor each block's Gram ONCE per fit — TPU factorizations are
    # sequential-panel latency, and every pass re-solves the same system
    factors = [ridge_factor(g, lam) for g in grams]
    for _ in range(num_iter):
        for i, a_c in enumerate(centered):
            rhs = a_c.T @ resid + grams[i] @ xs[i]
            x_new = ridge_solve_prefactored(factors[i], grams[i], rhs, lam)
            resid = resid - a_c @ (x_new - xs[i])
            xs[i] = x_new

    intercept = b_mean
    return tuple(xs), tuple(means), intercept


@partial(jax.jit, static_argnames=("widths", "num_iter"))
def _bcd_fit_gram(ata, atb, lams, widths: tuple, num_iter: int):
    """Gram-form BCD: the identical fixed point as :func:`_bcd_fit`,
    computed from the FULL centered normal-equation statistics instead
    of the data. The data-form block update is
    ``rhs_i = A_iᵀR + G_ii x_i`` with ``R = b_c − Σ_j A_j x_j``;
    substituting, ``A_iᵀR = (AᵀB)_i − Σ_j G_ij x_j`` — every quantity
    the pass loop needs lives in the (D, D) Gram, so a fit streamed
    through :func:`normal_eq_update` never touches the rows again.
    Returns (L, D, K) solutions, one per λ in ``lams``; per-pass work
    is D²·K gemms, independent of N."""
    f32 = ata.dtype
    lams = lams.astype(f32)
    offs = np.concatenate([[0], np.cumsum(widths)]).astype(int)
    diag = [
        ata[offs[i] : offs[i + 1], offs[i] : offs[i + 1]]
        for i in range(len(widths))
    ]

    def solve_one(lam):
        # factors are pass-invariant (same hoisting as _bcd_fit)
        factors = [ridge_factor(g, lam) for g in diag]
        x0 = jnp.zeros((ata.shape[0], atb.shape[-1]), f32)

        def one_pass(_p, x):
            for i in range(len(widths)):
                o, o2 = offs[i], offs[i + 1]
                # A_iᵀ R + G_ii x_i  ==  atb_i − G[i,:] x + G_ii x_i
                rhs = (
                    atb[o:o2]
                    - ata[o:o2] @ x
                    + diag[i] @ x[o:o2]
                )
                xi = ridge_solve_prefactored(factors[i], diag[i], rhs, lam)
                x = x.at[o:o2].set(xi)
            return x

        return jax.lax.fori_loop(0, num_iter, one_pass, x0)

    return jax.vmap(solve_one)(lams)


@treenode
class LeastSquaresEstimator(LabelEstimator):
    """Convenience: picks the single-solve or block path by feature count,
    mirroring how reference apps choose LinearMapEstimator vs
    BlockLeastSquaresEstimator by scale."""

    lam: float = static_field(default=0.0)
    block_size: int = static_field(default=4096)
    num_iter: int = static_field(default=1)

    def fit(self, data, labels, n_valid: int | None = None) -> Transformer:
        d = data.shape[-1] if not isinstance(data, (list, tuple)) else sum(
            b.shape[-1] for b in data
        )
        if isinstance(data, (list, tuple)) or d > self.block_size:
            est = BlockLeastSquaresEstimator(
                block_size=self.block_size,
                num_iter=self.num_iter,
                lam=self.lam,
            )
            return est.fit(data, labels, n_valid)
        return LinearMapEstimator(lam=self.lam).fit(data, labels, n_valid)
