"""Sparse featurization, dense-ified for TPU
(reference ``nodes/util/CommonSparseFeatures.scala``,
``AllSparseFeatures.scala``, ``SparseFeatureVectorizer.scala``).

The reference emits Breeze SparseVectors; TPUs want dense tiles, and the
reference itself caps the vocabulary (CommonSparseFeatures top-N) — so the
vectorizer here produces a dense (N, num_features) float array directly
(SURVEY.md §7 hard part #4: dense-ify top-K features).
"""

from __future__ import annotations

from collections import Counter

import numpy as np

from keystone_tpu.core.pipeline import Estimator, Transformer
from keystone_tpu.core.treenode import static_field, treenode


@treenode
class SparseFeatureVectorizer(Transformer):
    """{feature: value} dicts (or (feature, value) pair lists) → dense
    (N, |feature_space|) array; unseen features dropped."""

    feature_space: dict = static_field(default_factory=dict)

    def __call__(self, batch):
        out = np.zeros((len(batch), len(self.feature_space)), np.float32)
        space = self.feature_space
        for i, doc in enumerate(batch):
            items = doc.items() if isinstance(doc, dict) else doc
            for feat, val in items:
                j = space.get(feat)
                if j is not None:
                    out[i, j] = val
        return out


class CommonSparseFeatures(Estimator):
    """Keep the top-``num_features`` features by occurrence count
    (reference CommonSparseFeatures: each (feature, value) pair counts one
    occurrence; ties broken deterministically by feature repr)."""

    def __init__(self, num_features: int):
        self.num_features = num_features

    def fit(self, data) -> SparseFeatureVectorizer:
        counts: Counter = Counter()
        for doc in data:
            items = doc.keys() if isinstance(doc, dict) else (f for f, _ in doc)
            counts.update(items)
        top = sorted(counts.items(), key=lambda kv: (-kv[1], repr(kv[0])))
        space = {f: i for i, (f, _) in enumerate(top[: self.num_features])}
        return SparseFeatureVectorizer(feature_space=space)


class AllSparseFeatures(Estimator):
    """Keep every observed feature (reference AllSparseFeatures)."""

    def fit(self, data) -> SparseFeatureVectorizer:
        space: dict = {}
        for doc in data:
            items = doc.keys() if isinstance(doc, dict) else (f for f, _ in doc)
            for feat in items:
                if feat not in space:
                    space[feat] = len(space)
        return SparseFeatureVectorizer(feature_space=space)
