"""HOG descriptors, Felzenszwalb (FHOG) 31-dim variant
(reference nodes/images/HogExtractor.scala, a port of voc-release
``features.cc``).

Standard published algorithm, vectorized for TPU:
- per pixel, the channel with the largest gradient magnitude wins,
- orientation snapped to 18 signed bins (contrast-sensitive),
- bilinear spatial interpolation into cells of ``cell_size``,
- block energy from 9 contrast-insensitive sums; 4-way normalization with
  the 0.2 clamp; features = 18 sensitive + 9 insensitive + 4 texture-energy
  terms, scaled like the reference (0.2357 texture factor).

Output: (N, cells_h, cells_w, 31) — flatten with ImageVectorizer for the
pipeline, or keep spatial for visualization.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.core.treenode import static_field, treenode

NUM_SIGNED = 18
NUM_UNSIGNED = 9
EPS = 1e-4
TEXTURE_SCALE = 0.2357


@treenode
class HogExtractor(Transformer):
    """(N, H, W, C) → (N, cells_h, cells_w, 31)."""

    cell_size: int = static_field(default=8)

    def __call__(self, batch):
        return _hog(batch, self.cell_size)


@partial(jax.jit, static_argnames=("cell",))
def _hog(batch, cell: int):
    n, h, w, c = batch.shape
    # gradients (interior finite differences, zero at borders)
    gy = jnp.pad(batch[:, 2:, :] - batch[:, :-2, :], ((0, 0), (1, 1), (0, 0), (0, 0)))
    gx = jnp.pad(batch[:, :, 2:] - batch[:, :, :-2], ((0, 0), (0, 0), (1, 1), (0, 0)))
    mag2 = gx * gx + gy * gy  # (N, H, W, C)
    best = jnp.argmax(mag2, axis=-1, keepdims=True)
    gx1 = jnp.take_along_axis(gx, best, axis=-1)[..., 0]
    gy1 = jnp.take_along_axis(gy, best, axis=-1)[..., 0]
    mag = jnp.sqrt(jnp.take_along_axis(mag2, best, axis=-1)[..., 0])

    # snap to 18 signed orientations: argmax_k (ux_k·gx + uy_k·gy) over 9
    # unsigned directions, sign decides the other half (the reference's
    # snapping loop, vectorized)
    ks = np.arange(NUM_UNSIGNED)
    ux = np.cos(ks * math.pi / NUM_UNSIGNED).astype(np.float32)
    uy = np.sin(ks * math.pi / NUM_UNSIGNED).astype(np.float32)
    dots = gx1[..., None] * ux + gy1[..., None] * uy  # (N, H, W, 9)
    best_k = jnp.argmax(jnp.abs(dots), axis=-1)  # (N, H, W)
    sign_neg = jnp.take_along_axis(dots, best_k[..., None], axis=-1)[..., 0] < 0
    ori = best_k + NUM_UNSIGNED * sign_neg.astype(jnp.int32)  # 0..17

    cells_h = h // cell
    cells_w = w // cell
    # bilinear interpolation of each pixel into the 2x2 neighboring cells
    ys = (jnp.arange(h, dtype=jnp.float32) + 0.5) / cell - 0.5
    xs = (jnp.arange(w, dtype=jnp.float32) + 0.5) / cell - 0.5
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    wy1 = ys - y0
    wx1 = xs - x0

    onehot_o = jax.nn.one_hot(ori, NUM_SIGNED, dtype=batch.dtype)  # (N,H,W,18)
    weighted = onehot_o * mag[..., None]

    def cell_reduce(img, idx, weights, size, axis):
        """Scatter-add rows/cols into cells with the given weights."""
        idx_c = jnp.clip(idx, 0, size - 1)
        seg = jax.nn.one_hot(idx_c, size, dtype=img.dtype) * weights[:, None]
        # contract the pixel axis with the (pixels, cells) matrix
        return jnp.tensordot(img, seg, axes=[[axis], [0]])

    # rows → cells (two contributions: y0 with 1-wy1, y0+1 with wy1)
    rows = cell_reduce(weighted, y0, 1 - wy1, cells_h, 1) + cell_reduce(
        weighted, y0 + 1, wy1, cells_h, 1
    )  # (N, W, 18, cells_h)
    rows = jnp.moveaxis(rows, -1, 1)  # (N, cells_h, W, 18)
    hist = cell_reduce(rows, x0, 1 - wx1, cells_w, 2) + cell_reduce(
        rows, x0 + 1, wx1, cells_w, 2
    )  # (N, cells_h, 18, cells_w)
    hist = jnp.moveaxis(hist, -1, 2)  # (N, cells_h, cells_w, 18)

    # block energies from contrast-insensitive sums
    insens = hist[..., :NUM_UNSIGNED] + hist[..., NUM_UNSIGNED:]
    energy = jnp.sum(insens * insens, axis=-1)  # (N, ch, cw)
    # edge replication clamps out-of-range neighbor cells into the valid
    # range, like the reference's border handling (zero padding would
    # inflate boundary-cell normalization)
    pad_e = jnp.pad(energy, ((0, 0), (1, 1), (1, 1)), mode="edge")
    # 2x2 block sums at the four diagonal positions around each cell
    e = pad_e
    blocks = [
        e[:, :-2, :-2] + e[:, :-2, 1:-1] + e[:, 1:-1, :-2] + e[:, 1:-1, 1:-1],
        e[:, :-2, 1:-1] + e[:, :-2, 2:] + e[:, 1:-1, 1:-1] + e[:, 1:-1, 2:],
        e[:, 1:-1, :-2] + e[:, 1:-1, 1:-1] + e[:, 2:, :-2] + e[:, 2:, 1:-1],
        e[:, 1:-1, 1:-1] + e[:, 1:-1, 2:] + e[:, 2:, 1:-1] + e[:, 2:, 2:],
    ]
    norms = [jax.lax.rsqrt(b + EPS) for b in blocks]

    def norm_clip(v):
        parts = [jnp.minimum(v * nrm[..., None], 0.2) for nrm in norms]
        return parts

    sens_parts = norm_clip(hist)
    insens_parts = norm_clip(insens)
    f_sens = 0.5 * sum(sens_parts)
    f_insens = 0.5 * sum(insens_parts)
    f_texture = TEXTURE_SCALE * jnp.stack(
        [p.sum(axis=-1) for p in sens_parts], axis=-1
    )
    return jnp.concatenate([f_sens, f_insens, f_texture], axis=-1)
