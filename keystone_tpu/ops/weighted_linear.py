"""Class-weighted block-coordinate least squares
(reference ``nodes/learning/BlockWeightedLeastSquares.scala`` — the most
complex solver in the reference).

The model minimizes a per-class-weighted square loss: an example of class c
gets weight ``(1−w)/n`` on every output column plus ``w/n_c`` extra on its
own class column (``w`` = mixture_weight up-weights positives; the
reference test's ``computeGradient`` defines exactly this objective).

Reference mechanics → TPU mechanics:

- one-class-per-Spark-partition + reshuffle detection
  (``groupByClasses``, HashPartitioner(nClasses)) → a one-time row
  permutation into a class-sorted (C, L) grid inside the fit jit, after
  which every per-class statistic is a reshape and per-class Grams are
  batched gemms costing N·d² total — the same economics as the
  reference's per-partition local Grams. Input rows may arrive in any
  order (the permutation-invariance the shuffle protected is tested
  directly); when labels are traced (fit under an outer jit) a masked
  segment-reduction fallback covers correctness at C·N·d² cost.
- per-partition ``(AᵀA, AᵀR)`` + mlmatrix treeReduce → sharded einsum
  contractions (XLA psum over ICI).
- per-class local solves on executors, collected to the driver → batched
  (vmapped) replicated solves over class chunks (``lax.map`` over chunk
  groups keeps peak memory at ``chunk·d²``).
- mutable cached residual RDD chain + distributed System.gc() → residual is
  plain loop state inside one jitted program.

The per-class math matches the reference line for line (trainWithL2):
joint label mean, population/class covariance mixing, mean-difference outer
product, meanMixtureWt, and the final intercept from joint means.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import LabelEstimator
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.ops.linear import BlockLinearMapper, _row_mask, _split_blocks, ridge_solve


@treenode
class BlockWeightedLeastSquaresEstimator(LabelEstimator):
    """Weighted BCD (reference BlockWeightedLeastSquaresEstimator).

    ``labels``: (N, C) ±1 indicators, one positive class per row.
    ``class_chunk``: classes solved per inner step — peak memory is
    ``class_chunk · d_block²`` for the batched covariance/solve.
    """

    block_size: int = static_field(default=4096)
    num_iter: int = static_field(default=1)
    lam: float = static_field(default=0.0)
    mixture_weight: float = static_field(default=0.5)
    class_chunk: int = static_field(default=16)

    def fit(self, data, labels, n_valid: int | None = None) -> BlockLinearMapper:
        # The sorted fast path needs concrete, host-fetchable labels:
        # traced (fit under an outer jit) or multi-host non-addressable
        # arrays take the masked-segment path — correct anywhere, at
        # C·N·d² per-class-Gram cost.
        concrete = not (
            isinstance(data, jax.core.Tracer)
            or isinstance(labels, jax.core.Tracer)
        ) and getattr(labels, "is_fully_addressable", True)
        sort_idx, class_l = None, None
        if concrete:
            # fast path: permute rows ONCE into a class-sorted (C, L) grid
            # — the TPU analog of the reference's one-class-per-partition
            # reshuffle (BlockWeightedLeastSquares.scala:324-361). Every
            # per-class statistic then falls out of a reshape, and the
            # per-class Grams are batched gemms costing N·d² total like
            # the reference, not masked full-batch reductions (C·N·d²).
            # The gather itself runs inside the jit (one dispatch); only
            # the per-row argmax crosses to the host.
            n_val = data.shape[0] if n_valid is None else int(n_valid)
            class_idx = np.asarray(
                jnp.argmax(jnp.asarray(labels)[:n_val], axis=-1)
            )
            perm = _class_sorted_perm(
                class_idx, labels.shape[-1], data.shape[0]
            )
            if perm is not None:  # None: too imbalanced, grid would blow up
                sort_idx, class_l = perm.reshape(-1), perm.shape[1]
        xs, b = _weighted_bcd_fit(
            data,
            labels,
            sort_idx,
            n_valid,
            class_l,
            self.block_size,
            self.num_iter,
            self.lam,
            self.mixture_weight,
            min(self.class_chunk, labels.shape[-1]),
        )
        return BlockLinearMapper(
            xs=xs, b=b, means=None, block_size=self.block_size
        )


def _class_sorted_perm(
    class_idx: np.ndarray, c: int, n_rows: int
) -> np.ndarray | None:
    """(C, L) row-index grid: row c lists the batch rows of class c, padded
    with the sentinel ``n_rows`` (gathers hit an appended zero row).

    L is the max class count rounded up to 64 rows to bound retrace churn
    across fits of slightly different class balance. Returns None when the
    padded grid would exceed ~2x the batch (heavy class imbalance: L is
    sized to the LARGEST class, so a dominant class would inflate every
    gathered copy toward C·L ≫ N) — callers then use the masked path.
    """
    counts = np.bincount(class_idx, minlength=c)
    l_pad = max(-(-int(counts.max()) // 64) * 64, 64) if len(class_idx) else 64
    if c * l_pad > 2 * n_rows + 64 * c:
        return None
    perm = np.full((c, l_pad), n_rows, np.int64)
    order = np.argsort(class_idx, kind="stable")
    offsets = np.zeros(c + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    for ci in range(c):
        seg = order[offsets[ci] : offsets[ci + 1]]
        perm[ci, : len(seg)] = seg
    return perm


@partial(
    jax.jit,
    static_argnames=(
        "class_l",
        "block_size",
        "num_iter",
        "lam",
        "mixture_weight",
        "class_chunk",
    ),
)
def _weighted_bcd_fit(
    data,
    labels,
    sort_idx,
    n_valid,
    class_l: int | None,
    block_size: int,
    num_iter: int,
    lam: float,
    mixture_weight: float,
    class_chunk: int,
):
    """Weighted BCD body. ``class_l`` non-None means ``sort_idx`` lays the
    rows out as a class-sorted (C, class_l) grid (grid row r belongs to
    class r // class_l; sentinel indices point at an appended zero row),
    so per-class reductions are reshapes and per-class Grams are batched
    gemms; None falls back to one-hot masked reductions over the batch."""
    w = mixture_weight
    dtype = data.dtype
    c = labels.shape[-1]
    if class_l is not None:
        n_orig = data.shape[0]
        sort_idx = jnp.asarray(sort_idx)
        data = jnp.concatenate(
            [data, jnp.zeros((1, data.shape[-1]), dtype)]
        )[sort_idx]
        labels = jnp.concatenate(
            [labels.astype(dtype), jnp.zeros((1, c), dtype)]
        )[sort_idx]
        mask = (sort_idx < n_orig)[:, None].astype(dtype)
    else:
        mask = _row_mask(data.shape[0], n_valid, dtype)
    blocks = tuple(_split_blocks(data, block_size))
    n_rows = blocks[0].shape[0]
    n = jnp.sum(mask)

    # one-hot class membership (argmax of ±1 indicators), padded rows zeroed
    if class_l is not None:
        class_idx = jnp.arange(n_rows) // class_l  # layout-defined
    else:
        class_idx = jnp.argmax(labels, axis=-1)
    onehot = jax.nn.one_hot(class_idx, c, dtype=dtype) * mask  # (N, C)
    n_c = jnp.sum(onehot, axis=0)  # (C,)
    n_c_safe = jnp.maximum(n_c, 1.0)

    # jointLabelMean[c] = 2w + 2(1−w)·n_c/n − 1
    joint_label_mean = 2 * w + 2 * (1 - w) * n_c / n - 1

    resid = (labels - joint_label_mean) * mask  # (N, C)

    def residual_mean(r):
        # population column mean of the residual. DELIBERATE FIX of a
        # reference quirk: the reference averages per-class means uniformly
        # over classes (trainWithL2 residualMean), which equals the
        # population mean only for balanced classes — its own fixture. The
        # weighted objective's measure ((1−w)/n per row) requires the
        # population mean; with it the fixed point matches the exact
        # weighted-ridge optimum on imbalanced data too (see
        # test_weighted_matches_exact_optimum).
        return jnp.sum(r * mask, axis=0) / n  # (C,)

    res_mean = residual_mean(resid)

    def class_sum(x):
        """Per-class column sums of a row-major (N, ...) array → (C, ...)."""
        if class_l is not None:
            return x.reshape(c, class_l, *x.shape[1:]).sum(axis=1)
        return jnp.einsum("nc,n...->c...", onehot, x)

    # pass-0 cached per-block statistics (reference BlockStatistics)
    pop_means, pop_covs, joint_means = [], [], []
    for a in blocks:
        a_m = a * mask
        pop_mean = jnp.sum(a_m, axis=0) / n
        gram = a_m.T @ a_m  # sharded contraction → psum
        pop_cov = gram / n - jnp.outer(pop_mean, pop_mean)
        class_mean = class_sum(a_m) / n_c_safe[:, None]  # (C, d)
        joint_mean = w * class_mean + (1 - w) * pop_mean  # (C, d)
        pop_means.append(pop_mean)
        pop_covs.append(pop_cov)
        joint_means.append(joint_mean)

    n_chunks = -(-c // class_chunk)
    c_pad = n_chunks * class_chunk

    def pad_classes(x, axis):
        pad = [(0, 0)] * x.ndim
        pad[axis] = (0, c_pad - c)
        return jnp.pad(x, pad)

    xs = [jnp.zeros((a.shape[-1], c), dtype) for a in blocks]

    for _ in range(num_iter):
        for i, a in enumerate(blocks):
            a_m = a * mask
            pop_mean, pop_cov, joint_mean = pop_means[i], pop_covs[i], joint_means[i]
            pop_xtr = (a_m.T @ resid) / n  # (d, C)
            class_mean = class_sum(a_m) / n_c_safe[:, None]  # (C, d)
            # per-class residual stats restricted to own-class rows/column
            r_own = jnp.sum(resid * onehot, axis=-1, keepdims=True)  # (N, 1)
            class_xtr = class_sum(a_m * r_own) / n_c_safe[:, None]  # (C, d)
            r_own_mean = class_sum(r_own)[:, 0] / n_c_safe  # (C,)

            mean_mix = (1 - w) * res_mean + w * r_own_mean  # (C,)
            model = xs[i]

            # chunked per-class covariance + solve
            stats = {
                "class_mean": pad_classes(class_mean, 0).reshape(
                    n_chunks, class_chunk, -1
                ),
                "class_xtr": pad_classes(class_xtr, 0).reshape(
                    n_chunks, class_chunk, -1
                ),
                "joint_mean": pad_classes(joint_mean, 0).reshape(
                    n_chunks, class_chunk, -1
                ),
                "mean_mix": pad_classes(mean_mix, 0).reshape(
                    n_chunks, class_chunk
                ),
                "pop_xtr": pad_classes(pop_xtr.T, 0).reshape(
                    n_chunks, class_chunk, -1
                ),
                "model_col": pad_classes(model.T, 0).reshape(
                    n_chunks, class_chunk, -1
                ),
                "n_c": pad_classes(n_c_safe, 0).reshape(n_chunks, class_chunk),
            }
            if class_l is not None:
                # class-sorted rows: the chunk's own rows as (S, L, d) —
                # per-class Grams are batched gemms over L rows each
                stats["a_rows"] = pad_classes(
                    a_m.reshape(c, class_l, -1), 0
                ).reshape(n_chunks, class_chunk, class_l, -1)
            else:
                oh_chunks = pad_classes(onehot, 1).reshape(
                    n_rows, n_chunks, class_chunk
                )
                stats["onehot"] = jnp.moveaxis(oh_chunks, 1, 0)  # (K, N, S)

            def solve_chunk(s, a_m=a_m, pop_cov=pop_cov, pop_mean=pop_mean):
                if class_l is not None:
                    # (S, L, d) → (S, d, d): N·d² total across chunks
                    g = jnp.einsum("sld,sle->sde", s["a_rows"], s["a_rows"])
                else:
                    # masked full-batch reduction: C·N·d² (traced-label path)
                    g = jnp.einsum("nd,ns,ne->sde", a_m, s["onehot"], a_m)
                mu = s["class_mean"]  # (S, d)
                class_cov = g / s["n_c"][:, None, None] - jnp.einsum(
                    "sd,se->sde", mu, mu
                )
                md = mu - pop_mean  # (S, d)
                joint_xtx = (
                    (1 - w) * pop_cov[None]
                    + w * class_cov
                    + w * (1 - w) * jnp.einsum("sd,se->sde", md, md)
                )
                joint_xtr = (
                    (1 - w) * s["pop_xtr"]
                    + w * s["class_xtr"]
                    - s["joint_mean"] * s["mean_mix"][:, None]
                )
                rhs = joint_xtr - lam * s["model_col"]  # (S, d)
                delta = jax.vmap(
                    lambda m, r: ridge_solve(m, r[:, None], lam)[:, 0]
                )(joint_xtx, rhs)
                return delta  # (S, d)

            deltas = jax.lax.map(solve_chunk, stats)  # (K, S, d)
            delta = deltas.reshape(c_pad, -1)[:c].T  # (d, C)

            xs[i] = xs[i] + delta
            resid = resid - a_m @ delta
            res_mean = residual_mean(resid)

    # final intercept: b[c] = jointLabelMean[c] − Σ_blocks jointMean_c·x[:,c]
    b = joint_label_mean
    for jm, x in zip(joint_means, xs):
        b = b - jnp.einsum("cd,dc->c", jm, x)
    return tuple(xs), b
