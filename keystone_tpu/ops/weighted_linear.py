"""Class-weighted block-coordinate least squares
(reference ``nodes/learning/BlockWeightedLeastSquares.scala`` — the most
complex solver in the reference).

The model minimizes a per-class-weighted square loss: an example of class c
gets weight ``(1−w)/n`` on every output column plus ``w/n_c`` extra on its
own class column (``w`` = mixture_weight up-weights positives; the
reference test's ``computeGradient`` defines exactly this objective).

Reference mechanics → TPU mechanics:

- one-class-per-Spark-partition + reshuffle detection
  (``groupByClasses``, HashPartitioner(nClasses)) → a one-time row
  permutation into a class-sorted (C, L) grid inside the fit jit, after
  which every per-class statistic is a reshape and per-class Grams are
  batched gemms costing N·d² total — the same economics as the
  reference's per-partition local Grams. Input rows may arrive in any
  order (the permutation-invariance the shuffle protected is tested
  directly); when labels are traced (fit under an outer jit) a masked
  segment-reduction fallback covers correctness at C·N·d² cost.
- per-partition ``(AᵀA, AᵀR)`` + mlmatrix treeReduce → sharded einsum
  contractions (XLA psum over ICI).
- per-class local solves on executors, collected to the driver → batched
  (vmapped) replicated solves over class chunks (``lax.map`` over chunk
  groups keeps peak memory at ``chunk·d²``).
- mutable cached residual RDD chain + distributed System.gc() → residual is
  plain loop state inside one jitted program.

The per-class math matches the reference line for line (trainWithL2):
joint label mean, population/class covariance mixing, mean-difference outer
product, meanMixtureWt, and the final intercept from joint means.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import LabelEstimator
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.ops.linear import (
    BlockLinearMapper,
    _matmul_precision,
    _row_mask,
    _split_blocks,
    block_widths,
    ridge_factor,
    ridge_solve,
    ridge_solve_prefactored,
)

# per-block HBM budget (bytes) for hoisting the dense path's
# pass-invariant per-class systems + factors out of the BCD loop:
# 2 · C · d_block² · 4B must fit alongside the rest of the fit
_DENSE_HOIST_BUDGET = 2 << 30

# transient-HBM budget (bytes) for one Woodbury solve group. The Woodbury
# path never forms d² per-class matrices — its working set is the
# (S, d, L+1) v/y slices — so chunking it by ``class_chunk`` (sized for
# the dense path's chunk·d² solves) over-serializes the per-pass solves
# into tiny sequential lax.map steps whose launch/loop overhead dwarfs
# their gemms. Classes are instead grouped to fill this budget (v + y +
# ~4 same-sized transients per class), which solves TIMIT (C=147) in one
# batched step and ImageNet (C=1000) in two.
_WOODBURY_CHUNK_BUDGET = 4 << 30


@treenode
class BlockWeightedLeastSquaresEstimator(LabelEstimator):
    """Weighted BCD (reference BlockWeightedLeastSquaresEstimator).

    ``labels``: (N, C) ±1 indicators, one positive class per row.
    ``class_chunk``: classes solved per inner step — peak memory is
    ``class_chunk · d_block²`` for the batched covariance/solve.
    """

    block_size: int = static_field(default=4096)
    num_iter: int = static_field(default=1)
    lam: float = static_field(default=0.0)
    mixture_weight: float = static_field(default=0.5)
    class_chunk: int = static_field(default=16)
    # matmul precision for Grams/solves: None = backend default (bf16 MXU
    # passes), "highest" = full f32 (reference-BLAS class)
    precision: str | None = static_field(default=None)

    def fit(
        self,
        data,
        labels,
        n_valid: int | None = None,
        init: BlockLinearMapper | None = None,
    ) -> BlockLinearMapper:
        # The sorted fast path needs concrete, host-fetchable labels:
        # traced (fit under an outer jit) or multi-host non-addressable
        # arrays take the masked-segment path — correct anywhere, at
        # C·N·d² per-class-Gram cost.
        concrete = not (
            isinstance(data, jax.core.Tracer)
            or isinstance(labels, jax.core.Tracer)
        ) and getattr(labels, "is_fully_addressable", True)
        sort_idx, class_l = None, None
        if concrete:
            # fast path: permute rows ONCE into a class-sorted (C, L) grid
            # — the TPU analog of the reference's one-class-per-partition
            # reshuffle (BlockWeightedLeastSquares.scala:324-361). Every
            # per-class statistic then falls out of a reshape, and the
            # per-class Grams are batched gemms costing N·d² total like
            # the reference, not masked full-batch reductions (C·N·d²).
            # The gather itself runs inside the jit (one dispatch); only
            # the per-row argmax crosses to the host.
            n_val = data.shape[0] if n_valid is None else int(n_valid)
            class_idx = np.asarray(
                jnp.argmax(jnp.asarray(labels)[:n_val], axis=-1)
            )
            perm = _class_sorted_perm(
                class_idx, labels.shape[-1], data.shape[0]
            )
            if perm is not None:  # None: too imbalanced, grid would blow up
                sort_idx, class_l = perm.reshape(-1), perm.shape[1]
        with _matmul_precision(self.precision):
            xs, b = _weighted_bcd_fit(
                data,
                labels,
                sort_idx,
                n_valid,
                class_l,
                self.block_size,
                self.num_iter,
                self.lam,
                self.mixture_weight,
                min(self.class_chunk, labels.shape[-1]),
                init_xs=None if init is None else tuple(init.xs),
            )
        return BlockLinearMapper(
            xs=xs, b=b, means=None, block_size=self.block_size
        )

    # -- streaming per-class stats protocol (fit_stats_*) -------------
    # The weighted objective's sufficient statistics are the population
    # Gram PLUS per-class Grams/sums (every per-class covariance,
    # mean-difference outer product, and residual projection the BCD
    # passes consume reconstructs from them) — so the fit streams like
    # the plain solvers, at (C, D, D) state residency. The planner's
    # fused-fit pass prices that state against the memory budget and
    # falls back to the materialized fit when C·D² doesn't fit.

    def fit_stats_init(self, d: int, c: int) -> "WeightedEqState":
        return weighted_eq_init(d, c)

    def fit_stats_update(
        self, state, data, labels, n_valid=None, gram_fn=None
    ) -> "WeightedEqState":
        # gram_fn is accepted for protocol uniformity but unused: the
        # per-class Grams gate the solve's conditioning and stay exact
        return weighted_eq_update(
            state, data, labels, n_valid, precision=self.precision
        )

    def fit_stats_finalize(self, state, widths=None) -> BlockLinearMapper:
        d = state.ata.shape[0]
        widths = (
            tuple(widths) if widths else block_widths(d, self.block_size)
        )
        with _matmul_precision(self.precision):
            xs_full, b = _weighted_gram_fit(
                state,
                widths,
                self.num_iter,
                self.lam,
                self.mixture_weight,
            )
        offs = np.concatenate([[0], np.cumsum(widths)]).astype(int)
        xs = tuple(
            xs_full[offs[i] : offs[i + 1]] for i in range(len(widths))
        )
        return BlockLinearMapper(
            xs=xs, b=b, means=None, block_size=self.block_size
        )

    @staticmethod
    def fit_stats_flops_per_row(d: int, c: int) -> float:
        # population Gram + AᵀY + the masked per-class Gram contraction
        # (the C·d² einsum term dominates — the price of exact
        # per-class covariances without a class-sorted row gather)
        return 2.0 * d * (d + c) + 2.0 * c * d * d

    @staticmethod
    def fit_stats_state_bytes(d: int, c: int) -> int:
        return 4 * (c * d * d + d * d + 2 * d * c + d + 2 * c)


def _class_sorted_perm(
    class_idx: np.ndarray, c: int, n_rows: int
) -> np.ndarray | None:
    """(C, L) row-index grid: row c lists the batch rows of class c, padded
    with the sentinel ``n_rows`` (gathers hit an appended zero row).

    L is the max class count rounded up to 64 rows to bound retrace churn
    across fits of slightly different class balance. Returns None when the
    padded grid would exceed ~2x the batch (heavy class imbalance: L is
    sized to the LARGEST class, so a dominant class would inflate every
    gathered copy toward C·L ≫ N) — callers then use the masked path.
    """
    counts = np.bincount(class_idx, minlength=c)
    l_pad = max(-(-int(counts.max()) // 64) * 64, 64) if len(class_idx) else 64
    if c * l_pad > 2 * n_rows + 64 * c:
        return None
    perm = np.full((c, l_pad), n_rows, np.int64)
    order = np.argsort(class_idx, kind="stable")
    offsets = np.zeros(c + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    for ci in range(c):
        seg = order[offsets[ci] : offsets[ci + 1]]
        perm[ci, : len(seg)] = seg
    return perm


def _chunk_joint_xtx(s, a_m, pop_cov, pop_mean, class_l, dtype, w):
    """One chunk's per-class systems (S, d, d):
    (1−w)·pop_cov + w·class_cov + w(1−w)·md mdᵀ, with class_cov from
    CENTERED rows in grid mode (no g/n_c − μμᵀ cancellation; sentinel
    slots are zero rows that centering would turn into −μ, so they are
    masked out) and the onehot masked reduction in fallback mode."""
    mu = s["class_mean"]  # (S, d)
    if class_l is not None:
        valid = (
            jnp.arange(class_l)[None, :] < s["n_c"][:, None]
        ).astype(dtype)  # (S, L)
        rows_c = (s["a_rows"] - mu[:, None, :]) * valid[:, :, None]
        class_cov = (
            jnp.einsum("sld,sle->sde", rows_c, rows_c)
            / s["n_c"][:, None, None]
        )
    else:
        # masked full-batch reduction: C·N·d²; no row gather available,
        # so this keeps the subtraction form
        g = jnp.einsum("nd,ns,ne->sde", a_m, s["onehot"], a_m)
        class_cov = g / s["n_c"][:, None, None] - jnp.einsum(
            "sd,se->sde", mu, mu
        )
    md = mu - pop_mean  # (S, d)
    return (
        (1 - w) * pop_cov[None]
        + w * class_cov
        + w * (1 - w) * jnp.einsum("sd,se->sde", md, md)
    )


@partial(
    jax.jit,
    static_argnames=(
        "class_l",
        "block_size",
        "num_iter",
        "lam",
        "mixture_weight",
        "class_chunk",
    ),
)
def _weighted_bcd_fit(
    data,
    labels,
    sort_idx,
    n_valid,
    class_l: int | None,
    block_size: int,
    num_iter: int,
    lam: float,
    mixture_weight: float,
    class_chunk: int,
    init_xs=None,
):
    """Weighted BCD body. ``class_l`` non-None means ``sort_idx`` lays the
    rows out as a class-sorted (C, class_l) grid (grid row r belongs to
    class r // class_l; sentinel indices point at an appended zero row),
    so per-class reductions are reshapes and per-class Grams are batched
    gemms; None falls back to one-hot masked reductions over the batch."""
    w = mixture_weight
    dtype = data.dtype
    c = labels.shape[-1]
    if class_l is not None:
        n_orig = data.shape[0]
        sort_idx = jnp.asarray(sort_idx)
        data = jnp.concatenate(
            [data, jnp.zeros((1, data.shape[-1]), dtype)]
        )[sort_idx]
        labels = jnp.concatenate(
            [labels.astype(dtype), jnp.zeros((1, c), dtype)]
        )[sort_idx]
        mask = (sort_idx < n_orig)[:, None].astype(dtype)
    else:
        mask = _row_mask(data.shape[0], n_valid, dtype)
    blocks = tuple(_split_blocks(data, block_size))
    n_rows = blocks[0].shape[0]
    n = jnp.sum(mask)

    if class_l is not None:
        # grid mode: every gathered row is either a real (valid) row or
        # the appended all-zero sentinel, so ``a * mask`` is an identity —
        # skip it and save an N·d read+write per use per pass
        masked_rows = lambda a: a  # noqa: E731
    else:
        masked_rows = lambda a: a * mask  # noqa: E731

    # one-hot class membership (argmax of ±1 indicators), padded rows zeroed
    if class_l is not None:
        class_idx = jnp.arange(n_rows) // class_l  # layout-defined
    else:
        class_idx = jnp.argmax(labels, axis=-1)
    onehot = jax.nn.one_hot(class_idx, c, dtype=dtype) * mask  # (N, C)
    n_c = jnp.sum(onehot, axis=0)  # (C,)
    n_c_safe = jnp.maximum(n_c, 1.0)

    # jointLabelMean[c] = 2w + 2(1−w)·n_c/n − 1
    joint_label_mean = 2 * w + 2 * (1 - w) * n_c / n - 1

    resid = (labels - joint_label_mean) * mask  # (N, C)

    def residual_mean(r):
        # population column mean of the residual. DELIBERATE FIX of a
        # reference quirk: the reference averages per-class means uniformly
        # over classes (trainWithL2 residualMean), which equals the
        # population mean only for balanced classes — its own fixture. The
        # weighted objective's measure ((1−w)/n per row) requires the
        # population mean; with it the fixed point matches the exact
        # weighted-ridge optimum on imbalanced data too (see
        # test_weighted_matches_exact_optimum).
        r_m = r if class_l is not None else r * mask
        return jnp.sum(r_m, axis=0) / n  # (C,)

    res_mean = residual_mean(resid)

    def class_sum(x):
        """Per-class column sums of a row-major (N, ...) array → (C, ...)."""
        if class_l is not None:
            return x.reshape(c, class_l, *x.shape[1:]).sum(axis=1)
        return jnp.einsum("nc,n...->c...", onehot, x)

    # pass-0 cached per-block statistics (reference BlockStatistics), plus
    # — when the Woodbury path applies — the explicit stabilized inverse
    # of the pass-invariant base matrix B = (1−w)·pop_cov + λI (one
    # Cholesky per block per FIT, not per pass; see solve-path comment in
    # the block loop below)
    from keystone_tpu.ops.linear import stabilized_cho_solve

    pop_means, pop_covs, joint_means, b_invs = [], [], [], []
    for a in blocks:
        a_m = masked_rows(a)
        pop_mean = jnp.sum(a_m, axis=0) / n
        # covariance from CENTERED rows, not gram/n − μμᵀ: the
        # subtraction form loses |μ|²/|cov| digits to cancellation in
        # f32 (fatal when features have large means or rows are
        # near-duplicates — the noise lands on λ's scale and destabilizes
        # the BCD fixed point at small λ)
        a_cm = (a - pop_mean) * mask
        pop_cov = (a_cm.T @ a_cm) / n  # sharded contraction → psum
        class_mean = class_sum(a_m) / n_c_safe[:, None]  # (C, d)
        joint_mean = w * class_mean + (1 - w) * pop_mean  # (C, d)
        pop_means.append(pop_mean)
        pop_covs.append(pop_cov)
        joint_means.append(joint_mean)
        d_blk = a.shape[-1]
        if class_l is not None and class_l + 2 <= d_blk // 2:
            eye = jnp.eye(d_blk, dtype=dtype)
            b_invs.append(
                stabilized_cho_solve((1 - w) * pop_cov + lam * eye)(eye)
            )
        else:
            b_invs.append(None)

    n_chunks = -(-c // class_chunk)
    c_pad = n_chunks * class_chunk

    def chunk_grid(s_chunk):
        """(classes per chunk, number of chunks) with s_chunk clamped."""
        s_chunk = max(1, min(s_chunk, c))
        return s_chunk, -(-c // s_chunk)

    def pad_classes(x, axis, cp=None):
        pad = [(0, 0)] * x.ndim
        pad[axis] = (0, (c_pad if cp is None else cp) - c)
        return jnp.pad(x, pad)

    def map_chunks(f, xs, nch):
        """lax.map over the leading chunk axis; a single chunk calls the
        body directly (no one-trip loop standing between XLA and the
        batched gemms)."""
        if nch == 1:
            squeezed = jax.tree_util.tree_map(lambda a: a[0], xs)
            return jax.tree_util.tree_map(lambda a: a[None], f(squeezed))
        return jax.lax.map(f, xs)

    xs = tuple(jnp.zeros((a.shape[-1], c), dtype) for a in blocks)
    if init_xs is not None:
        # warm start (checkpoint resume): adopt the model and put the
        # residual in the consistent state R = (labels − mean) − Σ A_i x_i
        xs = tuple(x.astype(dtype) for x in init_xs)
        for blk_a, x in zip(blocks, xs):
            resid = resid - masked_rows(blk_a) @ x
        res_mean = residual_mean(resid)

    def chunk_rhs(s):
        joint_xtr = (
            (1 - w) * s["pop_xtr"]
            + w * s["class_xtr"]
            - s["joint_mean"] * s["mean_mix"][:, None]
        )
        return joint_xtr - lam * s["model_col"]  # (S, d)

    # Per-class systems are (joint_xtx_c + λI) δ_c = rhs_c with
    #   joint_xtx_c = (1−w)·pop_cov + w·class_cov_c + w(1−w)·md_c md_cᵀ ,
    # and class_cov_c built from only n_c ≈ N/C rows — LOW RANK when
    # classes are small relative to the block width. Dense per-class
    # Cholesky costs C·d³/3 and TPU factorizations run at a fixed
    # ~15-30 ms per 147-matrix batch on v5e REGARDLESS of size
    # (sequential panels), so when the grid layout is active and the
    # correction is low-rank (gated at L+2 ≤ d/2; the centered form's
    # actual rank is L+1, so the historical L+2 gate is one column
    # conservative) the solves go through Woodbury instead.
    # The correction is written as a SUM of positive rank-1 terms only:
    #   w·class_cov_c = (w/n_c)·Σᵢ (aᵢ−μ_c)(aᵢ−μ_c)ᵀ   (CENTERED rows)
    #   V = [√(w/n_c)·(A_c−μ_c)ᵀ, √(w(1−w))·md_c]   (L+1 columns)
    # so M = B + VVᵀ with shared SPD base B = (1−w)·pop_cov + λI and
    # Woodbury's SPD inner matrix G = I + VᵀB⁻¹V (eigs ≥ 1), inverted
    # exactly by a tiny equilibrated batched Cholesky once per fit.
    # (An earlier formulation used UNcentered rows plus a −qqᵀ
    # Sherman–Morrison downdate, q = √w·μ_c. That subtraction is
    # numerically fatal for degenerate classes: near-duplicate rows make
    # class_cov ≈ 0, the downdate nearly cancels a VVᵀ direction, and
    # the f32 denominator 1−qᵀM₁⁻¹q crosses zero — coefficients blew up
    # ~1e6× on the adversarial tests. Centering eliminates the
    # subtraction, so M's low-rank part is monotone in every direction.)
    # Per-pass solves are then pure gemms on the MXU — 5-40x faster than
    # batched dense Cholesky at TIMIT/ImageNet class counts. (The
    # reference solves each class densely on its own executor,
    # BlockWeightedLeastSquares.scala:228-263 — right on CPUs, wrong on
    # a systolic array.) Everything except the right-hand side is
    # pass-invariant, so v/y/ginv are built ONCE per fit here
    # (costs ~2·C·d·(L+1) floats of HBM — the same order as the grid
    # copy itself) and the per-pass work is rhs assembly + solves.
    use_woodbury = [
        class_l is not None and class_l + 2 <= a.shape[-1] // 2
        for a in blocks
    ]

    def class_static_stats(a_m, s_chunk=None, nch=None):
        """Chunked pass-invariant per-class stats shared by the Woodbury
        prep, the dense prep, and the in-loop fallback: class means,
        counts, and the class rows (grid) or one-hot columns (masked).
        Chunk geometry defaults to the dense path's (class_chunk-sized);
        the Woodbury path passes its own wider grouping."""
        if s_chunk is None:
            s_chunk, nch = class_chunk, n_chunks
        cp = s_chunk * nch
        static = {
            "class_mean": pad_classes(
                class_sum(a_m) / n_c_safe[:, None], 0, cp
            ).reshape(nch, s_chunk, -1),
            "n_c": pad_classes(n_c_safe, 0, cp).reshape(nch, s_chunk),
        }
        if class_l is not None:
            static["a_rows"] = pad_classes(
                a_m.reshape(c, class_l, -1), 0, cp
            ).reshape(nch, s_chunk, class_l, -1)
        else:
            oh_chunks = pad_classes(onehot, 1, cp).reshape(
                n_rows, nch, s_chunk
            )
            static["onehot"] = jnp.moveaxis(oh_chunks, 1, 0)
        return static

    # Woodbury chunk geometry per block: group classes to fill the
    # transient budget (v + y + ~4 like-sized transients per class of
    # d·(L+1) floats) instead of the dense path's class_chunk
    wood_chunks = [None] * len(blocks)
    for i, a in enumerate(blocks):
        if use_woodbury[i]:
            per_class = 6 * a.shape[-1] * (class_l + 1) * np.dtype(
                dtype
            ).itemsize
            wood_chunks[i] = chunk_grid(
                max(int(_WOODBURY_CHUNK_BUDGET // per_class), class_chunk)
            )

    wood_pre = []
    for i, a in enumerate(blocks):
        if not use_woodbury[i]:
            wood_pre.append(None)
            continue
        a_m = masked_rows(a)
        static = class_static_stats(a_m, *wood_chunks[i])
        lp1 = class_l + 1

        def prep_chunk(s, b_inv=b_invs[i], pop_mean=pop_means[i], lp1=lp1):
            mu = s["class_mean"]  # (S, d)
            md = mu - pop_mean
            scale = jnp.sqrt(w / jnp.maximum(s["n_c"], 1.0))
            # center the class rows about μ_c; sentinel (padding) slots
            # hold zero rows, which centering would turn into −μ_c and
            # corrupt class_cov — mask them back to zero
            valid = (
                jnp.arange(s["a_rows"].shape[1])[None, :]
                < s["n_c"][:, None]
            ).astype(dtype)  # (S, L)
            centered_rows = (s["a_rows"] - mu[:, None, :]) * valid[
                :, :, None
            ]  # (S, L, d)
            v = jnp.concatenate(
                [
                    centered_rows.transpose(0, 2, 1)
                    * scale[:, None, None],
                    (np.sqrt(w * (1 - w)) * md)[:, :, None],
                ],
                axis=2,
            )  # (S, d, L+1)
            y = jnp.einsum("de,sek->sdk", b_inv, v)  # B⁻¹V
            g = jnp.einsum("sdi,sdj->sij", v, y) + jnp.eye(lp1, dtype=dtype)
            # exact equilibrated-Cholesky inverse of the (L+1)² inner
            # matrix. G is SPD with eigs ≥ 1, but its spread tracks
            # ‖B⁻¹‖ — near-duplicate rows with tiny λ push it past 1e6,
            # where a fixed-depth Newton–Schulz iteration (the original
            # design) stalls on the unit eigenvalues and poisons every
            # downstream solve. The factorization is (L+1)³ per class
            # ONCE per fit — noise next to the N·d² Grams — so exactness
            # costs nothing that matters.
            def _inv_spd(gm):
                s = jax.lax.rsqrt(
                    jnp.clip(jnp.diagonal(gm), 1e-30, None)
                )
                me = gm * (s[:, None] * s[None, :]) + 1e-6 * jnp.eye(
                    lp1, dtype=dtype
                )
                cf = jax.scipy.linalg.cho_factor(me)
                inv = jax.scipy.linalg.cho_solve(
                    cf, jnp.eye(lp1, dtype=dtype)
                )
                return inv * (s[:, None] * s[None, :])

            ginv = jax.vmap(_inv_spd)(g)
            return {"v": v, "y": y, "ginv": ginv}

        wood_pre.append(map_chunks(prep_chunk, static, wood_chunks[i][1]))

    # DENSE-path hoisting: the per-class systems (class Grams + joint_xtx
    # + their factorizations) are pass-invariant too; for multi-pass fits
    # build them ONCE per fit when the 2·C·d² resident bytes fit the
    # budget (real TIMIT runs ~20 passes through this path — without
    # hoisting every pass repays the N·d² Grams AND the batched d³
    # factorizations)
    # the budget covers the AGGREGATE (every hoisted block's systems +
    # factors stay resident for the whole fit), so each eligible block
    # gets an equal share
    n_dense_candidates = sum(
        1 for u in use_woodbury if not u
    ) if num_iter > 1 else 0
    per_block_budget = _DENSE_HOIST_BUDGET // max(n_dense_candidates, 1)
    dense_pre = []
    for i, a in enumerate(blocks):
        d_blk = a.shape[-1]
        hoist = (
            not use_woodbury[i]
            and num_iter > 1
            and 2 * c_pad * d_blk * d_blk * np.dtype(dtype).itemsize
            <= per_block_budget
        )
        if not hoist:
            dense_pre.append(None)
            continue
        a_m = masked_rows(a)
        static = class_static_stats(a_m)

        def prep_dense(
            s, a_m=a_m, pop_cov=pop_covs[i], pop_mean=pop_means[i]
        ):
            jxtx = _chunk_joint_xtx(
                s, a_m, pop_cov, pop_mean, class_l, dtype, w
            )
            fc, fs = jax.vmap(lambda m_: ridge_factor(m_, lam))(jxtx)
            return {"jxtx": jxtx, "c": fc, "s": fs}

        dense_pre.append(map_chunks(prep_dense, static, n_chunks))

    # one full BCD sweep (every block) per fori_loop step: the program is
    # traced/compiled ONCE per block regardless of num_iter (an unrolled
    # pass loop made compile time scale linearly with passes)
    def one_pass(_p, state):
        xs, resid, res_mean = state
        xs = list(xs)
        for i, a in enumerate(blocks):
            a_m = masked_rows(a)
            pop_mean, pop_cov, joint_mean = (
                pop_means[i], pop_covs[i], joint_means[i],
            )
            pop_xtr = (a_m.T @ resid) / n  # (d, C)
            # per-class residual stats restricted to own-class rows/column
            if class_l is not None:
                # grid mode: row (c, l)'s own-class column IS column c —
                # a diagonal view of the (C, L, C) residual grid; skips
                # materializing + streaming the N·C onehot per pass
                r_own = jnp.take_along_axis(
                    resid.reshape(c, class_l, c),
                    jnp.arange(c)[:, None, None],
                    axis=2,
                ).reshape(-1, 1)  # (N, 1)
            else:
                r_own = jnp.sum(
                    resid * onehot, axis=-1, keepdims=True
                )  # (N, 1)
            class_xtr = class_sum(a_m * r_own) / n_c_safe[:, None]  # (C, d)
            r_own_mean = class_sum(r_own)[:, 0] / n_c_safe  # (C,)

            mean_mix = (1 - w) * res_mean + w * r_own_mean  # (C,)
            model = xs[i]

            # per-pass chunked stats: everything the rhs needs, laid out
            # in the block's solve-path chunk geometry
            s_chunk, nch = (
                wood_chunks[i] if use_woodbury[i] else (class_chunk, n_chunks)
            )
            cp_i = s_chunk * nch
            stats = {
                "class_xtr": pad_classes(class_xtr, 0, cp_i).reshape(
                    nch, s_chunk, -1
                ),
                "joint_mean": pad_classes(joint_mean, 0, cp_i).reshape(
                    nch, s_chunk, -1
                ),
                "mean_mix": pad_classes(mean_mix, 0, cp_i).reshape(
                    nch, s_chunk
                ),
                "pop_xtr": pad_classes(pop_xtr.T, 0, cp_i).reshape(
                    nch, s_chunk, -1
                ),
                "model_col": pad_classes(model.T, 0, cp_i).reshape(
                    nch, s_chunk, -1
                ),
            }

            if use_woodbury[i]:

                def solve_chunk(args, b_inv=b_invs[i], pop_cov=pop_cov):
                    pre, s = args
                    v, y, ginv = pre["v"], pre["y"], pre["ginv"]

                    def wsolve(r):  # M⁻¹r = (B + VVᵀ)⁻¹r, all gemms
                        z = jnp.einsum("de,se->sd", b_inv, r)
                        t = jnp.einsum(
                            "sij,sj->si",
                            ginv,
                            jnp.einsum("sdi,sd->si", v, z),
                        )
                        return z - jnp.einsum("sdi,si->sd", y, t)

                    def matvec(x):  # (joint_xtx + λI) x, never formed
                        bx = (1 - w) * jnp.einsum(
                            "de,se->sd", pop_cov, x
                        ) + lam * x
                        vx = jnp.einsum("sdi,sd->si", v, x)
                        return bx + jnp.einsum("sdi,si->sd", v, vx)

                    rhs = chunk_rhs(s)
                    x = wsolve(rhs)
                    # the Woodbury apply is algebraically exact but
                    # subtracts two large terms when B is
                    # ill-conditioned (z and the V-correction both scale
                    # with ‖B⁻¹‖): iterative refinement against the
                    # never-formed true operator recovers the cancelled
                    # f32 digits — one step more than ridge_solve's two,
                    # sized by the adversarial-conditioning tests
                    for _ in range(3):
                        x = x + wsolve(rhs - matvec(x))
                    return x  # (S, d)

                deltas = map_chunks(solve_chunk, (wood_pre[i], stats), nch)
            else:
                # dense per-class normal equations (big classes or the
                # traced-label masked fallback)
                if dense_pre[i] is not None:
                    # pass-invariant per-class systems hoisted: the
                    # per-pass work is rhs assembly + prefactored solves
                    def solve_chunk(args):
                        pre, s = args
                        return jax.vmap(
                            lambda fc, fs, m_, r_: ridge_solve_prefactored(
                                (fc, fs), m_, r_[:, None], lam
                            )[:, 0]
                        )(pre["c"], pre["s"], pre["jxtx"], chunk_rhs(s))

                    deltas = map_chunks(
                        solve_chunk, (dense_pre[i], stats), nch
                    )
                else:
                    stats.update(class_static_stats(a_m))

                    def solve_chunk(
                        s, a_m=a_m, pop_cov=pop_cov, pop_mean=pop_mean
                    ):
                        joint_xtx = _chunk_joint_xtx(
                            s, a_m, pop_cov, pop_mean, class_l, dtype, w
                        )
                        delta = jax.vmap(
                            lambda m, r: ridge_solve(m, r[:, None], lam)[
                                :, 0
                            ]
                        )(joint_xtx, chunk_rhs(s))
                        return delta  # (S, d)

                    deltas = map_chunks(solve_chunk, stats, nch)  # (K, S, d)

            delta = deltas.reshape(cp_i, -1)[:c].T  # (d, C)
            xs[i] = xs[i] + delta
            resid = resid - a_m @ delta
            res_mean = residual_mean(resid)
        return tuple(xs), resid, res_mean

    xs, resid, res_mean = jax.lax.fori_loop(
        0, num_iter, one_pass, (xs, resid, res_mean)
    )

    # final intercept: b[c] = jointLabelMean[c] − Σ_blocks jointMean_c·x[:,c]
    b = joint_label_mean
    for jm, x in zip(joint_means, xs):
        b = b - jnp.einsum("cd,dc->c", jm, x)
    return tuple(xs), b


# ---------------------------------------------------------------------------
# Streaming per-class statistics: the weighted fit's fit_stats protocol.
#
# Every quantity _weighted_bcd_fit derives from the rows — population
# mean/covariance, per-class means/covariances, residual projections,
# and their per-pass updates — is a function of the accumulated
# (AᵀA, AᵀY, per-class AᵀA, per-class Σa, Σa, n_c, n) statistics:
#
#   pop_cov   = AᵀA/n − μμᵀ
#   class_cov = AᵀA|_c /n_c − μ_c μ_cᵀ
#   pop_xtr   = AᵀR/n          with R = (Y − jlm)·mask  →  (AᵀY − Σa·jlmᵀ)/n
#   class_xtr = Σ_{j∈c} a_j r_own_j /n_c,  r_own init (1 − jlm_c)
#
# and a block-i BCD delta updates them in Gram form:
#   pop_xtr   −= AᵀA[:, i] δ / n
#   class_xtr −= AᵀA|_c[:, i] δ_c / n_c
#   r_own_mean−= Σa|_c[i]·δ_c / n_c
#   res_mean  −= Σa[i]·δ_c / n
#
# so the BCD pass loop runs entirely on statistics — the rows are gone.
# Centered quantities use the subtraction form (the streaming trade the
# dense path's comment warns about); the f32 state plus modest feature
# scales keeps the drift inside the fused-fit tolerance, and the dense
# path remains the reference for adversarial conditioning.


@treenode
class WeightedEqState:
    """Running f32 per-class normal-equation statistics."""

    ata: jnp.ndarray  # (D, D) Σ a aᵀ over valid rows
    at_labels: jnp.ndarray  # (D, C) Σ a yᵀ (±1 indicator labels)
    class_ata: jnp.ndarray  # (C, D, D) per-class Σ a aᵀ
    class_sum: jnp.ndarray  # (C, D) per-class Σ a
    sum_a: jnp.ndarray  # (D,)
    n_c: jnp.ndarray  # (C,)
    n: jnp.ndarray  # ()


def weighted_eq_init(d: int, c: int) -> WeightedEqState:
    f32 = jnp.float32
    return WeightedEqState(
        ata=jnp.zeros((d, d), f32),
        at_labels=jnp.zeros((d, c), f32),
        class_ata=jnp.zeros((c, d, d), f32),
        class_sum=jnp.zeros((c, d), f32),
        sum_a=jnp.zeros((d,), f32),
        n_c=jnp.zeros((c,), f32),
        n=jnp.zeros((), f32),
    )


@jax.jit
def _weighted_eq_update(state, data, labels, n_valid):
    from keystone_tpu.ops.linear import _concat_blocks

    data = _concat_blocks(data)
    f32 = jnp.float32
    mask = _row_mask(data.shape[0], n_valid, f32)
    a = data.astype(f32) * mask
    y = labels.astype(f32) * mask
    c = labels.shape[-1]
    onehot = jax.nn.one_hot(jnp.argmax(labels, axis=-1), c, dtype=f32) * mask
    return WeightedEqState(
        ata=state.ata + a.T @ a,
        at_labels=state.at_labels + a.T @ y,
        class_ata=state.class_ata + jnp.einsum("nc,nd,ne->cde", onehot, a, a),
        class_sum=state.class_sum + onehot.T @ a,
        sum_a=state.sum_a + jnp.sum(a, 0),
        n_c=state.n_c + jnp.sum(onehot, 0),
        n=state.n + jnp.sum(mask),
    )


def weighted_eq_update(
    state: WeightedEqState,
    data,
    labels,
    n_valid=None,
    precision: str | None = None,
) -> WeightedEqState:
    """Fold one (rows, D) chunk of ±1 indicator-labeled data into the
    per-class statistics; pad rows masked out of every accumulator.
    ``precision`` pins the matmul precision like the estimator's
    materialized fit does (env fallback when None)."""
    with _matmul_precision(precision):
        return _weighted_eq_update(state, data, labels, n_valid)


@partial(
    jax.jit,
    static_argnames=("widths", "num_iter", "lam", "mixture_weight"),
)
def _weighted_gram_fit(
    state: WeightedEqState,
    widths: tuple,
    num_iter: int,
    lam: float,
    mixture_weight: float,
):
    """Gram-form weighted BCD — the fixed point of
    :func:`_weighted_bcd_fit`, computed from streamed statistics.
    Per-class solves are dense batched (vmapped) ridge solves; per-pass
    work is C·d_block² gemms + C solves, independent of N."""
    w = mixture_weight
    f32 = jnp.float32
    d = state.ata.shape[0]
    c = state.n_c.shape[0]
    n = jnp.maximum(state.n, 1.0)
    n_c_safe = jnp.maximum(state.n_c, 1.0)
    offs = np.concatenate([[0], np.cumsum(widths)]).astype(int)

    pop_mean = state.sum_a / n  # (D,)
    class_mean = state.class_sum / n_c_safe[:, None]  # (C, D)
    pop_cov = state.ata / n - jnp.outer(pop_mean, pop_mean)
    class_cov = state.class_ata / n_c_safe[:, None, None] - jnp.einsum(
        "cd,ce->cde", class_mean, class_mean
    )
    joint_mean = w * class_mean + (1 - w) * pop_mean  # (C, D)
    md = class_mean - pop_mean  # (C, D)

    # jointLabelMean + the x=0 residual statistics (labels are ±1
    # indicators: Σ_j y_jc = 2n_c − n over valid rows; r_own = 1 − jlm)
    jlm = 2 * w + 2 * (1 - w) * state.n_c / n - 1  # (C,)
    pop_xtr = (state.at_labels - jnp.outer(state.sum_a, jlm)) / n  # (D, C)
    class_xtr = (1.0 - jlm)[:, None] * class_mean  # (C, D)
    r_own_mean = 1.0 - jlm  # (C,)
    res_mean = (2 * state.n_c / n - 1.0) - jlm  # (C,)

    # pass-invariant per-(block, class) systems + factors, built once
    sys_factors = []
    for i in range(len(widths)):
        o, o2 = offs[i], offs[i + 1]
        jxtx = (
            (1 - w) * pop_cov[o:o2, o:o2][None]
            + w * class_cov[:, o:o2, o:o2]
            + w * (1 - w) * jnp.einsum(
                "cd,ce->cde", md[:, o:o2], md[:, o:o2]
            )
        )  # (C, d_i, d_i)
        fc, fs = jax.vmap(lambda m_: ridge_factor(m_, lam))(jxtx)
        sys_factors.append((jxtx, fc, fs))

    x0 = jnp.zeros((d, c), f32)

    def one_pass(_p, carry):
        x, pop_xtr, class_xtr, r_own_mean, res_mean = carry
        for i in range(len(widths)):
            o, o2 = offs[i], offs[i + 1]
            jxtx, fc, fs = sys_factors[i]
            mean_mix = (1 - w) * res_mean + w * r_own_mean  # (C,)
            joint_xtr = (
                (1 - w) * pop_xtr[o:o2].T
                + w * class_xtr[:, o:o2]
                - joint_mean[:, o:o2] * mean_mix[:, None]
            )  # (C, d_i)
            rhs = joint_xtr - lam * x[o:o2].T  # (C, d_i)
            delta = jax.vmap(
                lambda f_c, f_s, m_, r_: ridge_solve_prefactored(
                    (f_c, f_s), m_, r_[:, None], lam
                )[:, 0]
            )(fc, fs, jxtx, rhs)  # (C, d_i)
            delta_dc = delta.T  # (d_i, C)
            x = x.at[o:o2].add(delta_dc)
            # Gram-form residual-statistic updates (see module comment)
            pop_xtr = pop_xtr - (state.ata[:, o:o2] @ delta_dc) / n
            class_xtr = class_xtr - jnp.einsum(
                "cDe,ec->cD", state.class_ata[:, :, o:o2], delta_dc
            ) / n_c_safe[:, None]
            r_own_mean = r_own_mean - jnp.einsum(
                "cd,dc->c", state.class_sum[:, o:o2], delta_dc
            ) / n_c_safe
            res_mean = res_mean - (state.sum_a[o:o2] @ delta_dc) / n
        return x, pop_xtr, class_xtr, r_own_mean, res_mean

    x, *_ = jax.lax.fori_loop(
        0,
        num_iter,
        one_pass,
        (x0, pop_xtr, class_xtr, r_own_mean, res_mean),
    )
    b = jlm - jnp.einsum("cd,dc->c", joint_mean, x)
    return x, b
