"""Statistical feature nodes.

TPU-native rebuild of the reference's ``nodes/stats/`` (SURVEY.md §2.4).
All nodes operate on ``(N, d)`` float batches with the leading axis sharded
over the mesh "data" axis; XLA turns the axis-0 reductions in the estimators
into ICI all-reduces (the successor of Spark ``treeAggregate``).
"""

from __future__ import annotations

from typing import Callable

import functools

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import Estimator, Transformer
from keystone_tpu.core.treenode import static_field, treenode

# Matlab eps — the reference's variance/norm floor (utils/Stats.scala).
EPS = 2.2e-16


@treenode
class StandardScalerModel(Transformer):
    """Subtract mean, optionally divide by std (nodes/stats/StandardScaler.scala).

    ``std`` is None when fitted with ``normalize_std_dev=False`` (the solver
    layer fits label/feature centering this way, e.g. the reference's
    ``BlockLeastSquaresEstimator`` per-block centering).
    """

    mean: jnp.ndarray
    std: jnp.ndarray | None = None

    def __call__(self, batch):
        out = batch - self.mean
        if self.std is not None:
            out = out / self.std
        return out


@treenode
class StandardScaler(Estimator):
    """Fit per-feature mean/std with a single sharded pass.

    The reference computes these with ``treeAggregate`` of a
    ``MultivariateOnlineSummarizer``; here ``jnp.mean``/``jnp.var`` over the
    sharded batch compile to per-shard partial sums + ICI ``psum``.

    ``n_valid``: number of real rows if the batch was zero-padded for
    sharding (see ``parallel.mesh.pad_batch``) — padding rows are masked out
    of the moments.
    """

    normalize_std_dev: bool = static_field(default=True)
    eps: float = static_field(default=EPS)

    def fit(self, data, n_valid: int | None = None) -> StandardScalerModel:
        mean, var = _masked_moments(data, n_valid)
        if not self.normalize_std_dev:
            return StandardScalerModel(mean=mean, std=None)
        n = data.shape[0] if n_valid is None else n_valid
        # unbiased (sample) std, matching the summarizer's variance
        var = var * (n / max(n - 1, 1))
        std = jnp.sqrt(var)
        std = jnp.where(std < self.eps, jnp.ones_like(std), std)
        return StandardScalerModel(mean=mean, std=std)


def _masked_moments(data, n_valid: int | None):
    """Population mean/var over valid rows of a possibly padded batch."""
    if n_valid is None or n_valid == data.shape[0]:
        return jnp.mean(data, axis=0), jnp.var(data, axis=0)
    mask = (jnp.arange(data.shape[0]) < n_valid)[:, None].astype(data.dtype)
    denom = jnp.asarray(n_valid, data.dtype)
    mean = jnp.sum(data * mask, axis=0) / denom
    var = jnp.sum(mask * (data - mean) ** 2, axis=0) / denom
    return mean, var


@treenode
class RandomSignNode(Transformer):
    """Elementwise multiply by a fixed ±1 mask (nodes/stats/RandomSignNode.scala)."""

    signs: jnp.ndarray

    def __call__(self, batch):
        return batch * self.signs

    @staticmethod
    def create(num_features: int, key: jax.Array) -> "RandomSignNode":
        signs = jax.random.rademacher(key, (num_features,), dtype=jnp.float32)
        return RandomSignNode(signs=signs)


@functools.lru_cache(maxsize=32)
def _cos_matrix_host(d: int, n: int):
    """Cached HOST (d, n/2) half-spectrum cosine matrix for PaddedFFT's
    matmul backend: real part of rfft of the zero-padded row — pad columns
    drop out of the sum, so only the d live rows exist. Cached as numpy so
    repeat eager calls skip the trig, without pinning device buffers."""
    k = np.arange(n // 2)[None, :]
    nn = np.arange(d)[:, None]
    return np.cos(2.0 * np.pi * k * nn / n)


def _cos_matrix(d: int, n: int, dtype: str):
    return jnp.asarray(_cos_matrix_host(d, n), dtype)


@treenode
class PaddedFFT(Transformer):
    """Zero-pad each row to the next power of two, FFT, return the real part
    of the first half (nodes/stats/PaddedFFT.scala).

    Output dim for input dim d: ``next_pow2(d) // 2``. Two backends:

    - ``fft``: ``Re(rfft)[:n/2]`` — best on CPU (O(n log n) butterflies).
    - ``matmul``: the same values as one cosine-matrix gemm,
      ``x @ cos(2π k n / N)`` — only the needed half-spectrum's real part
      is ever computed, the zero padding never materializes, and the work
      lands on the MXU where it fuses with neighboring ops. On v5e this
      is ~5x faster than XLA's FFT lowering at MNIST shapes (the
      featurize stage dominated the round-2 bench before this).
    - ``auto`` (default): matmul on TPU, fft elsewhere.
    """

    impl: str = static_field(default="auto")

    def __call__(self, batch):
        if self.impl not in ("auto", "fft", "matmul"):
            raise ValueError(
                f"PaddedFFT impl={self.impl!r}; expected auto|fft|matmul"
            )
        d = batch.shape[-1]
        n = 1 << max(int(np.ceil(np.log2(d))), 0) if d > 1 else 1
        impl = self.impl
        if impl == "auto":
            from keystone_tpu.ops.flash_attention import on_tpu

            impl = "matmul" if on_tpu() else "fft"
        if impl == "matmul":
            return batch @ _cos_matrix(d, n, str(batch.dtype))
        padded = jnp.pad(batch, [(0, 0)] * (batch.ndim - 1) + [(0, n - d)])
        return jnp.real(jnp.fft.rfft(padded, axis=-1))[..., : n // 2]


@treenode
class LinearRectifier(Transformer):
    """``max(max_val, x - alpha)`` (nodes/stats/LinearRectifier.scala)."""

    max_val: float = static_field(default=0.0)
    alpha: float = static_field(default=0.0)

    def __call__(self, batch):
        return jnp.maximum(self.max_val, batch - self.alpha)


@treenode
class CosineRandomFeatures(Transformer):
    """Random Fourier features ``cos(x W^T + b)``
    (nodes/stats/CosineRandomFeatures.scala).

    The reference batches each partition into one gemm; here the whole
    sharded batch is one MXU gemm. W: (num_features, input_dim), b:
    (num_features,). Gaussian W approximates an RBF kernel, Cauchy W a
    Laplacian kernel.
    """

    w: jnp.ndarray
    b: jnp.ndarray

    def __call__(self, batch):
        return jnp.cos(batch @ self.w.T + self.b)

    @staticmethod
    def create(
        input_dim: int,
        num_features: int,
        key: jax.Array,
        gamma: float = 1.0,
        distribution: str = "gaussian",
    ) -> "CosineRandomFeatures":
        kw, kb = jax.random.split(key)
        shape = (num_features, input_dim)
        if distribution == "gaussian":
            w = gamma * jax.random.normal(kw, shape, dtype=jnp.float32)
        elif distribution == "cauchy":
            w = gamma * jax.random.cauchy(kw, shape, dtype=jnp.float32)
        else:
            raise ValueError(f"unknown distribution {distribution!r}")
        b = jax.random.uniform(
            kb, (num_features,), minval=0.0, maxval=2 * np.pi, dtype=jnp.float32
        )
        return CosineRandomFeatures(w=w, b=b)


@treenode
class NormalizeRows(Transformer):
    """Row L2 normalization with eps floor (nodes/stats/NormalizeRows.scala)."""

    eps: float = static_field(default=EPS)

    def __call__(self, batch):
        norms = jnp.linalg.norm(batch, axis=-1, keepdims=True)
        return batch / jnp.maximum(norms, self.eps)


@treenode
class SignedHellingerMapper(Transformer):
    """``sign(x) * sqrt(|x|)`` (nodes/stats/SignedHellingerMapper.scala)."""

    def __call__(self, batch):
        return jnp.sign(batch) * jnp.sqrt(jnp.abs(batch))


@treenode
class Sampler:
    """Sample up to ``size`` rows from a batch (nodes/stats/Sampling.scala).

    The reference's ``takeSample``-backed FunctionNode; here a host-level
    helper used to feed driver-style fits (PCA/GMM/ZCA).
    """

    size: int = static_field(default=1000)
    seed: int = static_field(default=42)

    def __call__(self, batch):
        n = batch.shape[0]
        if n <= self.size:
            return batch
        idx = np.random.default_rng(self.seed).choice(n, self.size, replace=False)
        return jnp.take(batch, jnp.asarray(np.sort(idx)), axis=0)


def sample_columns(desc, num: int, seed: int) -> jnp.ndarray:
    """Sample up to ``num`` descriptor columns as (num, d) rows.

    ``desc``: an (N, d, m) batch of feature-major descriptor matrices, or a
    list of (d, n_i) matrices (ragged). The single implementation behind
    :class:`ColumnSampler` and the Fisher pipelines' PCA/GMM sampling.
    """
    if isinstance(desc, (list, tuple)):
        flat = jnp.concatenate(
            [jnp.asarray(m).T for m in desc], axis=0
        )  # (Σn_i, d)
    else:
        n, d, m = desc.shape
        flat = jnp.transpose(desc, (0, 2, 1)).reshape(n * m, d)
    total = flat.shape[0]
    if total > num:
        idx = np.sort(
            np.random.default_rng(seed).choice(total, num, replace=False)
        )
        if jax.default_backend() == "cpu" and getattr(
            flat, "is_fully_addressable", True
        ):
            # host-side gather: the index draw already lives on the host,
            # and jax 0.9's CPU gather flakily aborts when dispatched after
            # a multi-device shard_map run in the same process
            flat = jnp.asarray(np.asarray(flat)[idx])
        else:
            flat = jnp.take(flat, jnp.asarray(idx), axis=0)
    return flat


@treenode
class ColumnSampler:
    """Sample ``num_cols`` columns across descriptor matrices
    (nodes/stats/Sampling.scala ColumnSampler).

    Input: (N, d, m) array or list of per-item (d, n_i) feature-major
    matrices. Output: (num_cols, d) row batch suitable for PCA/GMM fits.
    """

    num_cols: int = static_field(default=100000)
    seed: int = static_field(default=42)

    def __call__(self, mats):
        return sample_columns(mats, self.num_cols, self.seed)


@treenode
class TermFrequency:
    """Per-item term counts re-weighted by ``fn`` (nodes/stats/TermFrequency.scala).

    Host-side: batch of token sequences → batch of {token: weight} dicts.
    """

    fn: Callable[[float], float] = static_field(default=lambda x: x)

    def __call__(self, batch):
        out = []
        for doc in batch:
            counts: dict = {}
            for tok in doc:
                counts[tok] = counts.get(tok, 0) + 1
            out.append({t: self.fn(c) for t, c in counts.items()})
        return out
