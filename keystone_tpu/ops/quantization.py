"""Weight-only int8 quantization for inference.

Decode is the HBM-bound regime (ROOFLINE.md §6, decode note: every step
re-reads all params), so the serving lever on TPU is weight bytes, not
FLOPs: int8 weights halve the bf16 stream. Symmetric per-output-channel
scales keep the matmul exact up to rounding, applied to the
activation-sized result (``(y @ q) * scale``).

The int8→compute-dtype convert is written as ``q.astype`` feeding the
dot; whether the weight stream actually halves rests on XLA fusing that
convert into the dot's operand load (the usual TPU lowering). That is a
compiler property, not a code guarantee — which is why the bench records
the measured int8-vs-float decode rates side by side
(``lm_decode[_int8]_tokens_per_s``) rather than asserting the ratio.

The reference has no quantization (it serves f64 BLAS models); this is a
beyond-reference serving capability in the spirit of the KV-cache
decode path.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.treenode import treenode


@treenode
class QTensor:
    """Symmetric int8 tensor: ``q * scale`` reconstructs the original.
    ``scale`` is broadcast-shaped against the reconstruction — (1, out)
    for (in, out) matmul weights, (V, 1) for row-quantized embeddings."""

    q: jnp.ndarray  # int8, original shape
    scale: jnp.ndarray  # f32, broadcastable to q's shape

    @property
    def shape(self):
        return self.q.shape

    def dequantize(self):
        return self.q.astype(jnp.float32) * self.scale


def symmetric_int8(w, reduce_axes):
    """The one symmetric-int8 recipe (amax/127 scales, round, clip ±127)
    shared by weight and KV-cache quantization — ``reduce_axes`` are the
    axes the scale pools over (keepdims). Returns (int8 codes, f32
    scale)."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale


def quantize_int8(w, *, channel_axis: int = -1) -> QTensor:
    """Per-channel symmetric quantization: scales are max|w|/127 along
    every axis EXCEPT ``channel_axis`` (the one that stays per-channel).
    channel_axis=-1 suits (in, out) weights; 0 suits (V, d) embeddings
    (per-row, so both the gather and the tied-logit transpose see a
    per-output scale)."""
    w = jnp.asarray(w)
    channel_axis = channel_axis % w.ndim
    reduce_axes = tuple(a for a in range(w.ndim) if a != channel_axis)
    q, scale = symmetric_int8(w, reduce_axes)
    return QTensor(q=q, scale=scale)


def mm(y, w, dt):
    """``y @ w`` where ``w`` is a plain array or a :class:`QTensor` with
    per-output-channel (1, out) scales. The int8 path scales the
    activation-sized result; the convert-into-dot is left to XLA fusion
    (see module docstring)."""
    if isinstance(w, QTensor):
        # scale stays f32: rounding it to bf16 first would add ~0.4%
        # relative error to every element of a channel on top of the int8
        # rounding; the single cast of the product is the cost of the
        # output dtype, not an avoidable one
        return ((y @ w.q.astype(dt)) * w.scale).astype(dt)
    return y @ w.astype(dt)


def quantization_error(w) -> float:
    """Max abs reconstruction error of quantizing ``w`` (diagnostics)."""
    qt = quantize_int8(np.asarray(w))
    return float(np.max(np.abs(np.asarray(qt.dequantize()) - w)))
