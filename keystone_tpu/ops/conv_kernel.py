"""Pallas TPU kernel: fused im2col + patch-normalize + filter gemm.

The :class:`keystone_tpu.ops.images.Convolver` (reference
``nodes/images/Convolver.scala``) is not a plain convolution — each patch
is mean/variance normalized and whitener-mean-subtracted before the filter
gemm — so XLA materializes the full (N, oh, ow, k²C) patch tensor in HBM
(~k² × the image bytes; 27x for 6x6 patches on CIFAR). This kernel keeps
the whole im2col pipeline in VMEM per image: build the patch matrix in
scratch with k² strided copies, normalize rows on the VPU, subtract the
whitener means, and run one MXU gemm against the filter bank — HBM sees
only the image in and the feature map out.

Selected explicitly via ``Convolver(impl="fused")``; the default
``conv`` impl (:func:`keystone_tpu.ops.images.conv_convolver`) measured
faster on real v5e, so this kernel is kept as the single-chip Pallas
exemplar rather than the auto path. Interpret mode covers the CPU test
mesh. Layout contract matches ``extract_patches``: patch rows flattened
(dy, dx, c), channel fastest.

:func:`fused_conv_rectify_pool` extends the kernel through the
SymmetricRectifier and Pooler stages (pooling as a 0/1-matrix gemm in
VMEM). Same verdict on real v5e: XLA's own convolution + the
pool-before-concat restructure (``FusedConvRectifyPool`` impl="auto")
wins — the per-image im2col with C=3 lane writes is the bottleneck —
so the full-chain kernel is likewise an explicitly-selected exemplar
(impl="pallas"), numerically gated against the chain in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from keystone_tpu.ops.flash_attention import (
    _pad_to,
    _vmem_limit_bytes,
    on_tpu,
)

_LANE = 128


def _conv_kernel(
    img_ref,  # (1, h, w, c)
    filt_ref,  # (P_pad, F_pad) — transposed filter bank
    mean_ref,  # (1, P_pad) whitener means (zeros when unused)
    o_ref,  # (1, oh*ow padded, F_pad)
    p_scr,  # (R_pad, P_pad) patch-matrix scratch
    *,
    patch_size: int,
    oh: int,
    ow: int,
    c: int,
    normalize: bool,
    var_constant: float,
    subtract_mean: bool,
):
    k = patch_size
    rows = oh * ow
    # im2col into scratch: one strided copy per (dy, dx) offset writes the
    # (oh, ow, c) window slab into columns [(dy*k+dx)*c, +c)
    img = img_ref[0]
    for dy in range(k):
        for dx in range(k):
            slab = img[dy : dy + oh, dx : dx + ow, :]  # (oh, ow, c)
            p_scr[:rows, (dy * k + dx) * c : (dy * k + dx + 1) * c] = (
                slab.reshape(rows, c)
            )

    d = k * k * c  # true patch length; scratch columns beyond d hold
    # garbage (never written) — mask them out of every statistic. The gemm
    # itself is safe either way: the padded filter rows are zero.
    p = p_scr[:rows, :]
    col = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
    p = jnp.where(col < d, p, 0.0)
    if normalize:
        mean = jnp.sum(p, axis=1, keepdims=True) / d
        centered = jnp.where(col < d, p - mean, 0.0)
        var = jnp.sum(centered * centered, axis=1, keepdims=True) / max(
            d - 1, 1
        )
        p = centered / jnp.sqrt(var + var_constant)
    if subtract_mean:
        p = jnp.where(col < d, p - mean_ref[0][None, :], 0.0)
    o_ref[0, :rows, :] = jnp.dot(
        p, filt_ref[:, :], preferred_element_type=jnp.float32
    ).astype(o_ref.dtype)


def _padded_dims(h: int, w: int, c: int, patch_size: int, num_filters: int):
    """Padded buffer dims shared by the kernel launch and the VMEM gate."""
    k = patch_size
    oh, ow = h - k + 1, w - k + 1
    rows = oh * ow
    rows_pad = -(-rows // 8) * 8
    p_pad = -(-(k * k * c) // _LANE) * _LANE
    f_pad = -(-num_filters // _LANE) * _LANE
    return oh, ow, rows, rows_pad, p_pad, f_pad


def fused_convolver(
    batch,
    filters,
    *,
    patch_size: int,
    normalize_patches: bool,
    var_constant: float,
    whitener_means=None,
    interpret: bool | None = None,
):
    """Fused Convolver forward. batch: (N, H, W, C); filters: (F, k²C).

    Returns (N, oh, ow, F), identical to the im2col jnp path.
    """
    if interpret is None:
        interpret = not on_tpu()
    n, h, w, c = batch.shape
    k = patch_size
    f = filters.shape[0]
    oh, ow, rows, rows_pad, p_pad, f_pad = _padded_dims(h, w, c, k, f)
    d = k * k * c

    ft = _pad_to(_pad_to(filters.T, 0, _LANE), 1, _LANE)  # (P_pad, F_pad)
    assert ft.shape == (p_pad, f_pad)
    means = (
        jnp.zeros((1, p_pad), jnp.float32)
        if whitener_means is None
        else _pad_to(
            jnp.asarray(whitener_means, jnp.float32).reshape(1, d), 1, _LANE
        )
    )

    out = pl.pallas_call(
        functools.partial(
            _conv_kernel,
            patch_size=k,
            oh=oh,
            ow=ow,
            c=c,
            normalize=normalize_patches,
            var_constant=var_constant,
            subtract_mean=whitener_means is not None,
        ),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((p_pad, f_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, p_pad), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, rows_pad, f_pad), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, rows_pad, f_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((rows_pad, p_pad), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
            vmem_limit_bytes=None if interpret else _vmem_limit_bytes(),
        ),
        interpret=interpret,
    )(batch.astype(jnp.float32), ft.astype(jnp.float32), means)
    return out[:, :rows, :f].reshape(n, oh, ow, f)


def _conv_rect_pool_kernel(
    img_ref,  # (1, h, w, c)
    filt_ref,  # (P_pad, F_pad) — transposed filter bank
    mean_ref,  # (1, P_pad) whitener means (zeros when unused)
    pool_ref,  # (NP_pad, R_pad) 0/1 pooling matrix
    o_ref,  # (1, NP_pad, 2*F_pad)
    p_scr,  # (R_pad, P_pad) patch-matrix scratch
    r_scr,  # (R_pad, 2*F_pad) rectified-map scratch
    *,
    patch_size: int,
    oh: int,
    ow: int,
    c: int,
    normalize: bool,
    var_constant: float,
    subtract_mean: bool,
    alpha: float,
    max_val: float,
    f_pad: int,
):
    k = patch_size
    rows = oh * ow
    img = img_ref[0]
    for dy in range(k):
        for dx in range(k):
            slab = img[dy : dy + oh, dx : dx + ow, :]
            p_scr[:rows, (dy * k + dx) * c : (dy * k + dx + 1) * c] = (
                slab.reshape(rows, c)
            )

    d = k * k * c
    p = p_scr[:rows, :]
    col = jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
    p = jnp.where(col < d, p, 0.0)
    if normalize:
        mean = jnp.sum(p, axis=1, keepdims=True) / d
        centered = jnp.where(col < d, p - mean, 0.0)
        var = jnp.sum(centered * centered, axis=1, keepdims=True) / max(
            d - 1, 1
        )
        p = centered / jnp.sqrt(var + var_constant)
    if subtract_mean:
        p = jnp.where(col < d, p - mean_ref[0][None, :], 0.0)
    conv = jnp.dot(p, filt_ref[:, :], preferred_element_type=jnp.float32)
    # SymmetricRectifier in VMEM: C → 2C channels, [pos | neg]
    r_scr[:rows, :f_pad] = jnp.maximum(max_val, conv - alpha)
    r_scr[:rows, f_pad:] = jnp.maximum(max_val, -conv - alpha)
    if rows < r_scr.shape[0]:
        # zero the padded rows: the pooling gemm touches every row and
        # scratch starts uninitialized
        r_scr[rows:, :] = jnp.zeros(
            (r_scr.shape[0] - rows, r_scr.shape[1]), jnp.float32
        )
    # Pooler as one small gemm: pooled[p, f] = Σ_r pool[p, r] · rect[r, f]
    o_ref[0] = jnp.dot(
        pool_ref[:, :], r_scr[:, :], preferred_element_type=jnp.float32
    ).astype(o_ref.dtype)


def _num_pools(dim: int, stride: int, pool_size: int) -> int:
    """Reference Pooler window count — delegates to the single source of
    truth (:meth:`keystone_tpu.ops.images.Pooler._num_pools`) so the fused
    kernel can never drift from the chain it must match."""
    from keystone_tpu.ops.images import Pooler

    return Pooler(stride=stride, pool_size=pool_size)._num_pools(dim)


def _pool_matrix(
    oh: int, ow: int, stride: int, pool_size: int
) -> "jnp.ndarray":
    """(ph·pw, oh·ow) 0/1 matrix summing each pool window's rows."""
    import numpy as np

    ph = _num_pools(oh, stride, pool_size)
    pw = _num_pools(ow, stride, pool_size)
    mat = np.zeros((ph * pw, oh * ow), np.float32)
    for py in range(ph):
        for px in range(pw):
            ys = slice(py * stride, min(py * stride + pool_size, oh))
            xs = slice(px * stride, min(px * stride + pool_size, ow))
            block = np.zeros((oh, ow), np.float32)
            block[ys, xs] = 1.0
            mat[py * pw + px] = block.ravel()
    return jnp.asarray(mat)


def fused_conv_rectify_pool(
    batch,
    filters,
    *,
    patch_size: int,
    normalize_patches: bool,
    var_constant: float,
    whitener_means=None,
    alpha: float = 0.0,
    max_val: float = 0.0,
    pool_stride: int = 13,
    pool_size: int = 14,
    pool_fn: str = "sum",
    interpret: bool | None = None,
):
    """Convolver → SymmetricRectifier → Pooler in ONE Pallas kernel.

    The unfused chain materializes the (N, oh, ow, F) feature map in HBM,
    re-reads it for the rectifier (doubling channels), and re-reads that
    for the pooler — ~2·oh·ow/(ph·pw) times more HBM traffic than the
    pooled result needs (≈360x on the CIFAR random-patch shape). Here the
    conv map lives only in VMEM: im2col + normalize + filter gemm
    (identical math to :func:`fused_convolver`), rectify on the VPU, and
    the reference's truncated-edge pool windows applied as one 0/1-matrix
    gemm. HBM sees the image in and the (N, ph, pw, 2F) pooled map out.

    ``pool_fn``: "sum" or "mean" (matmul pooling can't express max).
    Returns (N, ph, pw, 2F) float32, identical to the unfused chain
    (mean variant divides by pool_size² — the reference's edge-window
    quirk, nodes/images/Pooler.scala).
    """
    if pool_fn not in ("sum", "mean"):
        raise ValueError(f"pool_fn={pool_fn!r}: fused path is sum|mean only")
    if interpret is None:
        interpret = not on_tpu()
    n, h, w, c = batch.shape
    k = patch_size
    f = filters.shape[0]
    oh, ow, rows, rows_pad, p_pad, f_pad = _padded_dims(h, w, c, k, f)
    d = k * k * c
    ph = _num_pools(oh, pool_stride, pool_size)
    pw = _num_pools(ow, pool_stride, pool_size)
    np_pad = -(-(ph * pw) // 8) * 8

    ft = _pad_to(_pad_to(filters.T, 0, _LANE), 1, _LANE)
    means = (
        jnp.zeros((1, p_pad), jnp.float32)
        if whitener_means is None
        else _pad_to(
            jnp.asarray(whitener_means, jnp.float32).reshape(1, d), 1, _LANE
        )
    )
    pool_mat = _pad_to(
        _pad_to(_pool_matrix(oh, ow, pool_stride, pool_size), 0, 8),
        1,
        8,
    )
    assert pool_mat.shape == (np_pad, rows_pad)

    out = pl.pallas_call(
        functools.partial(
            _conv_rect_pool_kernel,
            patch_size=k,
            oh=oh,
            ow=ow,
            c=c,
            normalize=normalize_patches,
            var_constant=var_constant,
            subtract_mean=whitener_means is not None,
            alpha=alpha,
            max_val=max_val,
            f_pad=f_pad,
        ),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((p_pad, f_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, p_pad), lambda i: (0, 0)),
            pl.BlockSpec((np_pad, rows_pad), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, np_pad, 2 * f_pad), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, np_pad, 2 * f_pad), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((rows_pad, p_pad), jnp.float32),
            pltpu.VMEM((rows_pad, 2 * f_pad), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",),
            vmem_limit_bytes=None if interpret else _vmem_limit_bytes(),
        ),
        interpret=interpret,
    )(batch.astype(jnp.float32), ft.astype(jnp.float32), means, pool_mat)
    # channel layout: [pos f | neg f] — slice each half past the lane pad
    pos = out[:, : ph * pw, :f]
    neg = out[:, : ph * pw, f_pad : f_pad + f]
    res = jnp.concatenate([pos, neg], axis=-1).reshape(n, ph, pw, 2 * f)
    if pool_fn == "mean":
        res = res / float(pool_size * pool_size)
    return res


def fused_conv_rectify_pool_fits(
    h: int,
    w: int,
    c: int,
    patch_size: int,
    num_filters: int,
    pool_stride: int,
    pool_size: int,
) -> bool:
    """VMEM gate for :func:`fused_conv_rectify_pool` (same double-buffer
    accounting as :func:`fused_convolver_fits`, plus the rectified-map
    scratch and the pooling-matrix / pooled-output operands)."""
    oh, ow, _, rows_pad, p_pad, f_pad = _padded_dims(
        h, w, c, patch_size, num_filters
    )
    ph = _num_pools(oh, pool_stride, pool_size)
    pw = _num_pools(ow, pool_stride, pool_size)
    np_pad = -(-(ph * pw) // 8) * 8
    bytes_needed = 4 * (
        2 * (h * w * c + p_pad * f_pad + np_pad * rows_pad + np_pad * 2 * f_pad)
        + rows_pad * p_pad
        + rows_pad * 2 * f_pad
    )
    limit = _vmem_limit_bytes() or 16 * 1024 * 1024
    return bytes_needed <= (2 * limit) // 3


def fused_convolver_fits(h: int, w: int, c: int, patch_size: int,
                         num_filters: int) -> bool:
    """Whether the per-image working set fits the VMEM budget.

    Mosaic double-buffers every windowed input/output, so the image,
    filter, and output buffers count twice; only the scratch patch
    matrix is single-buffered. Gate against 2/3 of the scoped limit for
    the same safety margin the flash kernels use."""
    _, _, _, rows_pad, p_pad, f_pad = _padded_dims(
        h, w, c, patch_size, num_filters
    )
    bytes_needed = 4 * (
        2 * (h * w * c + p_pad * f_pad + rows_pad * f_pad)
        + rows_pad * p_pad
    )
    limit = _vmem_limit_bytes() or 16 * 1024 * 1024
    return bytes_needed <= (2 * limit) // 3
