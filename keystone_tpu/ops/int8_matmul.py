"""Fused int8-dequant matmul Pallas kernel for the decode path.

Weight-only int8 serving (``ops/quantization.py``) leans on XLA fusing
the ``q.astype(bf16)`` convert into the dot's operand load — a compiler
property, not a guarantee (ROOFLINE.md §6 decode note). This kernel
removes the bet: the int8 codes stream from HBM *as int8* (half the
bytes of bf16 — decode's entire economics) and are widened in VMEM right
before the MXU pass, with the per-output-channel f32 scale applied to
the accumulator.

Decode shapes are tall-K, tiny-M (B·1 activations against (K, N)
weights), so the kernel grids over N with K streamed sequentially per
tile and the f32 accumulator carried in VMEM scratch. Runs compiled on
TPU and in Pallas interpret mode elsewhere (CPU tests).

The serving entry point stays :func:`keystone_tpu.ops.quantization.mm`;
``mm_fused`` here is the measured alternative — ``tools/mfu_sweep.py``
A/Bs bf16 vs XLA-int8 vs this kernel at the decode shapes
(``decode_mm_*`` in MFU_SWEEP.json, weight-stream GB/s), and
``bench.py`` separately records the e2e float-vs-int8 generate rates —
so the fusion question is settled by numbers, not assumption
(VERDICT r3 #4, ROOFLINE.md §6 decode note).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from keystone_tpu.ops.quantization import QTensor, mm as _xla_mm


def _kernel(y_ref, q_ref, s_ref, o_ref, acc_ref, *, n_k: int):
    """One (M, N_blk) output tile; grid = (N tiles, K tiles) with K the
    minor (sequential) dimension. y (M, K_blk) in the caller's compute
    dtype; q (K_blk, N_blk) int8; s (1, N_blk) f32 scale applied once at
    the last K step."""
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # the widening happens HERE, after the int8 bytes landed in VMEM —
    # the HBM stream stays 1 byte/weight. Widen to y's dtype so the
    # kernel matches quantization.mm's compute semantics (bf16 policy →
    # bf16 MXU passes; f32 → f32 emulation), f32 accumulate either way
    acc_ref[...] += jnp.dot(
        y_ref[...],
        q_ref[...].astype(y_ref.dtype),
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == n_k - 1)
    def _finalize():
        o_ref[...] = acc_ref[...] * s_ref[...]


# Largest M the single-tile layout may carry: (M, block_n) f32 scratch +
# (M, block_k) activation tile stay well under ~1 MB of VMEM at the
# default 512 blocks. Decode uses M = batch ≤ 64; 256 leaves headroom.
_MAX_M = 256


def _pad_dim(x, axis: int, mult: int):
    pad = (-x.shape[axis]) % mult
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.jit, static_argnames=("block_n", "block_k", "interpret"))
def mm_fused(
    y,
    w: QTensor,
    *,
    block_n: int = 512,
    block_k: int = 512,
    interpret: bool | None = None,
):
    """``y @ w.q * w.scale`` with the dequant fused into the kernel.

    y: (..., K) float; w.q: (K, N) int8 with (1, N) f32 scales. Returns
    (..., N) in y's dtype (f32 accumulation, like ``mm``)."""
    if interpret is None:
        from keystone_tpu.ops.flash_attention import on_tpu

        interpret = not on_tpu()
    if w.scale.shape != (1, w.q.shape[1]):
        raise ValueError(
            f"mm_fused needs (1, N) per-output-channel scales; got "
            f"{w.scale.shape} for q {w.q.shape}"
        )
    lead = y.shape[:-1]
    k_dim = y.shape[-1]
    if k_dim != w.q.shape[0]:
        raise ValueError(f"contraction mismatch: {y.shape} @ {w.q.shape}")
    ym = y.reshape(-1, k_dim)
    m = ym.shape[0]
    # MXU-friendly tiles: M to the 16-sublane tile, K/N to blocks. The
    # whole M extent rides in one tile (plus an (M, block_n) scratch) —
    # this kernel is for decode's tiny-M regime, so the decode-only
    # contract is enforced here: past _MAX_M the full-M activation tile
    # + f32 scratch would blow VMEM, so fall back to the XLA path
    # rather than leave the guard to callers (models/lm/model.model_mm)
    if m > _MAX_M:
        return _xla_mm(y, w, y.dtype)
    ym = _pad_dim(_pad_dim(ym, 0, 16), 1, block_k)
    q = _pad_dim(_pad_dim(w.q, 0, block_k), 1, block_n)
    s = _pad_dim(w.scale.astype(jnp.float32), 1, block_n)
    m_pad, k_pad = ym.shape
    n_pad = q.shape[1]
    n_k = k_pad // block_k

    out = pl.pallas_call(
        functools.partial(_kernel, n_k=n_k),
        grid=(n_pad // block_n, n_k),
        in_specs=[
            pl.BlockSpec((m_pad, block_k), lambda n, k: (0, k)),
            pl.BlockSpec((block_k, block_n), lambda n, k: (k, n)),
            pl.BlockSpec((1, block_n), lambda n, k: (0, n)),
        ],
        out_specs=pl.BlockSpec((m_pad, block_n), lambda n, k: (0, n)),
        out_shape=jax.ShapeDtypeStruct((m_pad, n_pad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((m_pad, block_n), jnp.float32)],
        # N tiles are independent; K is the sequential accumulator dim —
        # telling Mosaic lets it pipeline the int8 HBM loads across steps
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(ym, q, s)
    out = out[:m, : w.q.shape[1]]
    return out.reshape(*lead, w.q.shape[1]).astype(y.dtype)
