"""Linguistic feature extraction
(reference nodes/nlp/CoreNLPFeatureExtractor.scala, which wraps the external
sista/CoreNLP ``FastNLPProcessor`` for tokenize → lemmatize → NER-replace →
n-grams).

That external JVM dependency has no TPU/Python analog in this image, so the
same pipeline shape is provided with lightweight, dependency-free stages
(documented deviation — swap in a real tagger by passing ``lemmatize``/
``ner_replace`` callables):

- rule-based English suffix lemmatizer (plural/verb/comparative stripping),
- capitalized-token NER replacement with an ``ENTITY`` placeholder,
- n-grams of the result.
"""

from __future__ import annotations

from typing import Callable

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.ops.nlp import NGramsFeaturizer, Tokenizer


def default_lemmatize(token: str) -> str:
    """Tiny rule-based lemmatizer (suffix stripping)."""
    for suffix, repl, min_len in (
        ("sses", "ss", 5),
        ("ies", "y", 4),
        ("ing", "", 5),
        ("edly", "", 6),
        ("ed", "", 4),
        ("s", "", 4),
    ):
        if token.endswith(suffix) and len(token) >= min_len:
            return token[: len(token) - len(suffix)] + repl
    return token


def default_ner_replace(token: str) -> str:
    """Replace capitalized (non-sentence-initial handling omitted) tokens."""
    if token[:1].isupper() and token[1:].islower() and len(token) > 1:
        return "ENTITY"
    return token


@treenode
class CoreNLPFeatureExtractor(Transformer):
    """Documents → n-grams of lemmatized, NER-replaced tokens."""

    orders: tuple = static_field(default=(1, 2))
    lemmatize: Callable[[str], str] = static_field(default=default_lemmatize)
    ner_replace: Callable[[str], str] = static_field(default=default_ner_replace)

    def __call__(self, batch):
        tokens = Tokenizer()(batch)
        processed = [
            [self.lemmatize(self.ner_replace(t)) for t in doc] for doc in tokens
        ]
        lowered = [[t.lower() for t in doc] for doc in processed]
        return NGramsFeaturizer(orders=self.orders)(lowered)
