"""Linguistic feature extraction
(reference ``nodes/nlp/CoreNLPFeatureExtractor.scala``, which wraps the
external sista/CoreNLP ``FastNLPProcessor`` for sentence-split → POS →
lemmatize → NER → n-grams).

That external JVM dependency has no TPU/Python analog in this image, so
the same pipeline is provided with self-contained host stages that mirror
the reference's observable behavior (CoreNLPFeatureExtractor.scala:21-45):

- sentence splitting with abbreviation guards (the reference's n-grams
  respect sentence boundaries),
- a WordNet-morphy-style lemmatizer: irregular-form exception tables,
  ordered suffix-detachment rules with orthographic repair (consonant
  undoubling, e-restoration), candidates validated against a built-in
  common-lemma lexicon — the same rules+exceptions+lexicon architecture
  as morphy, with a compact embedded lexicon instead of WordNet,
- gazetteer + cue NER over PERSON / LOCATION / ORGANIZATION / DATE /
  NUMBER: each entity token is replaced by its TYPE string, like the
  reference's ``s.entities.get(i) != "O"`` branch; deliberately
  precision-biased (only recognized entities are replaced, like the
  reference's NER — unrecognized capitalized tokens stay discriminative),
- non-entity tokens are lemmatized then normalized exactly like the
  reference's ``normalize`` (strip ``[^a-zA-Z0-9\\s+]``, lowercase),
- per-sentence n-grams joined with spaces, flattened across orders.
"""

from __future__ import annotations

import re

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.core.treenode import static_field, treenode

# ---------------------------------------------------------------------------
# Lemmatizer: exceptions + detachment rules + lexicon (morphy architecture)
# ---------------------------------------------------------------------------

_IRREGULAR = {
    # be / auxiliaries
    "am": "be", "is": "be", "are": "be", "was": "be", "were": "be",
    "been": "be", "being": "be",
    # common irregular verbs (past / participle → lemma)
    "went": "go", "gone": "go", "did": "do", "done": "do", "had": "have",
    "has": "have", "said": "say", "made": "make", "took": "take",
    "taken": "take", "came": "come", "saw": "see", "seen": "see",
    "got": "get", "gotten": "get", "gave": "give", "given": "give",
    "found": "find", "thought": "think", "told": "tell", "knew": "know",
    "known": "know", "became": "become", "left": "leave", "felt": "feel",
    "brought": "bring", "began": "begin", "begun": "begin", "kept": "keep",
    "held": "hold", "wrote": "write", "written": "write", "stood": "stand",
    "heard": "hear", "meant": "mean", "met": "meet", "ran": "run",
    "paid": "pay", "sat": "sit", "spoke": "speak", "spoken": "speak",
    "led": "lead", "grew": "grow", "grown": "grow", "lost": "lose",
    "fell": "fall", "fallen": "fall", "sent": "send", "built": "build",
    "understood": "understand", "drew": "draw", "drawn": "draw",
    "broke": "break", "broken": "break", "spent": "spend",
    "sent": "send", "rose": "rise",
    "risen": "rise", "drove": "drive", "driven": "drive", "bought": "buy",
    "wore": "wear", "worn": "wear", "chose": "choose", "chosen": "choose",
    "ate": "eat", "eaten": "eat", "flew": "fly", "flown": "fly",
    "caught": "catch", "taught": "teach", "fought": "fight",
    "sought": "seek", "slept": "sleep", "won": "win", "sold": "sell",
    "threw": "throw", "thrown": "throw", "shot": "shoot", "swam": "swim",
    "swum": "swim", "sang": "sing", "sung": "sing", "rang": "ring",
    "rung": "ring", "drank": "drink", "drunk": "drink", "spread": "spread",
    "struck": "strike", "hung": "hang", "dealt": "deal", "bent": "bend",
    "lent": "lend", "laid": "lay", "bore": "bear",
    "borne": "bear", "beat": "beat", "beaten": "beat", "bit": "bite",
    "bitten": "bite", "blew": "blow", "blown": "blow", "forgot": "forget",
    "forgotten": "forget", "froze": "freeze", "frozen": "freeze",
    "hid": "hide", "hidden": "hide", "lit": "light", "rode": "ride",
    "ridden": "ride", "shook": "shake", "shaken": "shake", "stole": "steal",
    "stolen": "steal", "tore": "tear", "torn": "tear", "woke": "wake",
    "woken": "wake", "wound": "wind", "spun": "spin", "dug": "dig",
    "stuck": "stick", "swore": "swear", "sworn": "swear",
    # irregular plurals
    "children": "child", "men": "man", "women": "woman",
    "people": "person", "feet": "foot", "teeth": "tooth", "mice": "mouse",
    "geese": "goose", "oxen": "ox", "criteria": "criterion",
    "phenomena": "phenomenon", "analyses": "analysis", "theses": "thesis",
    "crises": "crisis", "hypotheses": "hypothesis", "lives": "life",
    "wives": "wife", "knives": "knife", "leaves": "leaf", "halves": "half",
    "selves": "self", "shelves": "shelf", "wolves": "wolf",
    "indices": "index", "matrices": "matrix", "vertices": "vertex",
    "appendices": "appendix", "media": "medium", "bacteria": "bacterium",
    # comparatives / superlatives
    "better": "good", "best": "good", "worse": "bad", "worst": "bad",
    "further": "far", "farther": "far", "less": "little", "least": "little",
    "more": "much", "most": "much",
}

# compact common-lemma lexicon used to VALIDATE detachment candidates —
# the morphy pattern: a rule only fires if its output is a known word
_LEXICON = frozenset("""
be have do say get make go know take see come think look want give use
find tell ask work seem feel try leave call need become mean keep let
begin help talk turn start show hear play run move like live believe
hold bring happen write provide sit stand lose pay meet include continue
set learn change lead understand watch follow stop create speak read
allow add spend grow open walk win offer remember love consider appear
buy wait serve die send expect build stay fall cut reach kill remain
suggest raise pass sell require report decide pull return explain hope
develop carry break receive agree support hit produce eat cover catch
draw choose cause point listen realize place close involve increase wish
fly argue own pick study save share visit note state seek test fit issue
free judge drop plan drive teach check claim form fill act miss book fix
time year way day man thing woman life child world school family student
group country problem hand part place case week company system program
question government number night point home water room mother area money
story fact month lot right book eye job word business side kind head
house service friend father power hour game line end member law car city
community name president team minute idea body information back parent
face others level office door health person art war history party result
change morning reason research girl guy moment air teacher force
education foot boy age policy process music market sense nation plan
college interest death experience effect use class control care field
development role effort rate heart drug show leader light voice wife
machine image code type note test file user data value model text input
output image run state space list item table term base score post site
link page view news group net mail address message board topic thread
good new first last long great little own other old right big high
different small large next early young important few public bad same
able free true full special easy clear recent certain strong possible
late general human local sure real simple hard major better economic
current low common poor natural significant similar hot dead central
happy serious ready available likely short single medical dark various
entire close legal religious cold final main green nice huge popular
traditional cultural wide deep fast red white black blue wrong strange
safe rich fair weak direct open
ride smile dance hope bake race trade vote shine slide glide hide wave
save name tape note date rate hate gaze blame frame phrase praise raise
curse nurse argue value issue pursue rescue tie lie dye move prove love
solve serve curve carve merge urge charge change orange arrange manage
damage image voyage store score snore ignore explore restore bounce
pounce announce pronounce balance advance silence notice practice slice
price surface promise house mouse excuse refuse confuse amuse accuse
pause cause clause cease increase decrease release lease please tease
breathe bathe clothe scrape escape shape smoke poke joke stroke strike
like bike hike invite excite unite write quote vote devote promote
complete compete delete create relate debate locate rotate operate
separate update estimate generate iterate calculate populate simulate
hero potato tomato echo veto torpedo zero bus gas plus virus focus bonus
campus status circus genius radius chorus minus walrus octopus
wish push crash flash brush crush finish publish polish punish vanish
establish furnish banish cherish flourish nourish astonish diminish
accomplish distinguish extinguish
seed need feed speed breed greed deed weed bleed creed exceed proceed
succeed agree free flee tree knee degree guarantee shoe toe hoe canoe
cry dry fry spy marry bury copy empty apply reply supply imply comply
multiply occupy vary envy pity deny defy rely satisfy qualify classify
identify specify modify notify justify simplify clarify verify worry
hurry bully rally tally delay enjoy employ destroy annoy obey pray stray
jump swim grab hug ship shop chat clap jog nod pat rob rub skip slip snap
tap trap trim wrap swap scan scrub drag beg bet dim fan grin hop jam
knit map mop mug nap pad peg pin plug pop prop quit rip shrug sip skim
slam slap slot span spot stem stir strap strip tan tip tug whip zip
""".split())

# invariant forms that end in rule suffixes but must never be stemmed
# ("news" → "new" was a real regression caught by the held-out word list)
_INVARIANT = frozenset(
    "news species series means physics mathematics economics politics "
    "statistics athletics ethics headquarters measles diabetes "
    "sheep deer fish swine aircraft indeed".split()
)

# (suffix, replacement, fallback_ok) detachment rules, tried in order;
# the first rule whose candidate survives orthographic repair +
# lexicon/shape checks wins. The paired strip/+e forms are morphy's
# actual verb rule set — ("ed","e")/("ing","e") restore silent e without
# the CVC guesswork an orthographic-only repair needs ("created" →
# "creat"+CVC blocked, but rule "ed"→"e" proposes "create" directly).
# ``fallback_ok`` marks rules whose stem is a sane default for
# out-of-lexicon words: restoration rules for noun suffixes ("clues" →
# "clue", "puppies" → "puppy") and BARE strips for -ed/-ing (an
# unvalidated "+e" verb guess like "jumped" → "jumpe" is worse than the
# strip "jump").
_DETACH = (
    ("sses", "ss", True), ("ches", "ch", True), ("shes", "sh", True),
    ("xes", "x", True), ("zes", "z", True), ("ies", "y", True),
    ("ied", "y", True), ("ves", "f", True), ("oes", "o", True),
    # +e BEFORE bare strip: a CVC verb doubles its consonant before
    # -ed/-ing ("hopped"), so an undoubled stem ("hoped" → "hop") means
    # the lemma had a silent e — validation rejects "+e" when wrong
    # ("visited" → "visite" fails, falls through to "visit")
    ("ing", "e", False), ("ing", "", True), ("edly", "", True),
    ("ed", "e", False), ("ed", "", True),
    ("est", "", True), ("er", "", True),
    ("ly", "", True), ("es", "e", True), ("es", "", True),
    ("s", "", True),
)

_VOWELS = set("aeiou")


def _repair(stem: str) -> list[str]:
    """Orthographic candidates after a strip: as-is, undoubled, +e."""
    out = [stem]
    if (
        len(stem) >= 3
        and stem[-1] == stem[-2]
        and stem[-1] not in "lsz"
        and stem[-1] not in _VOWELS
    ):
        out.append(stem[:-1])  # running → runn → run
    if (
        len(stem) >= 3
        and stem[-1] not in _VOWELS
        and stem[-1] not in "wxy"
        and stem[-2] in _VOWELS
        and stem[-3] not in _VOWELS
    ):
        out.append(stem + "e")  # mak → make, writ → write
    return out


def default_lemmatize(token: str) -> str:
    """Morphy-style lemmatization: exceptions → detachment rules with
    orthographic repair, candidates validated against the lexicon; falls
    back to the plain strip when nothing validates."""
    t = token.lower()
    if t in _IRREGULAR:
        return _IRREGULAR[t]
    if t in _INVARIANT or t in _LEXICON or len(t) < 4 or not t.isalpha():
        return t
    fallback = None
    for suffix, repl, fallback_ok in _DETACH:
        if not t.endswith(suffix) or len(t) - len(suffix) < 2:
            continue
        stem = t[: len(t) - len(suffix)] + repl
        for cand in _repair(stem):
            if cand in _LEXICON or cand in _IRREGULAR:
                return _IRREGULAR.get(cand, cand)
        if fallback is None and len(stem) >= 3 and fallback_ok:
            fallback = stem
    return fallback if fallback is not None else t


# ---------------------------------------------------------------------------
# NER: gazetteers + cues (entity token → TYPE, like the reference)
# ---------------------------------------------------------------------------

_FIRST_NAMES = frozenset("""
james john robert michael william david richard joseph thomas charles
mary patricia jennifer linda elizabeth barbara susan jessica sarah karen
christopher daniel paul mark donald george kenneth steven edward brian
ronald anthony kevin jason matthew gary timothy jose larry jeffrey frank
scott eric stephen andrew raymond gregory joshua jerry dennis walter
nancy lisa margaret betty sandra ashley dorothy kimberly emily donna
michelle carol amanda melissa deborah stephanie rebecca laura sharon
cynthia kathleen amy shirley angela helen anna brenda pamela nicole
peter henry carl arthur ryan roger joe juan jack albert jonathan justin
terry gerald keith samuel willie ralph lawrence nicholas roy benjamin
bruce brandon adam harry fred billy steve louis jeremy aaron randy
emma olivia sophia isabella charlotte amelia harper evelyn abigail
alexander sebastian jacob ethan noah liam mason logan lucas
""".split())

_LOCATIONS = frozenset("""
america usa us uk england britain france germany italy spain russia
china japan india canada mexico brazil australia egypt israel iran iraq
turkey greece poland sweden norway denmark finland netherlands belgium
switzerland austria ireland scotland wales portugal ukraine korea
vietnam thailand indonesia philippines pakistan afghanistan syria
london paris berlin rome madrid moscow beijing tokyo delhi toronto
chicago boston seattle denver houston dallas atlanta miami detroit
philadelphia phoenix washington york angeles francisco vegas orleans
texas california florida virginia georgia ohio michigan arizona oregon
colorado nevada utah alaska hawaii kansas iowa maine montana idaho
europe asia africa antarctica earth
""".split())

_ORG_SUFFIXES = frozenset(
    "inc corp ltd co company corporation university institute college "
    "association committee department agency ministry bureau council "
    "bank group labs laboratories foundation society press times".split()
)

_MONTHS = frozenset(
    "january february march april may june july august september october "
    "november december jan feb mar apr jun jul aug sep sept oct nov "
    "dec".split()
)
_WEEKDAYS = frozenset(
    "monday tuesday wednesday thursday friday saturday sunday".split()
)
_HONORIFICS = frozenset(
    "mr mrs ms dr prof sir president senator judge captain general".split()
)
_NUMBER_WORDS = frozenset(
    "zero one two three four five six seven eight nine ten eleven twelve "
    "twenty thirty forty fifty sixty seventy eighty ninety hundred "
    "thousand million billion".split()
)

_ACRONYM_STOP = frozenset(
    "imho fyi faq asap btw aka diy lol irc ftp god ok yes no not and "
    "the you are was".split()
)

_YEAR_RE = re.compile(r"^[12]\d{3}$")
_NUM_RE = re.compile(r"^[+-]?\d+([.,]\d+)*(th|st|nd|rd)?$")


def _is_cap(tok: str) -> bool:
    return len(tok) > 1 and tok[0].isupper() and tok[1:].islower()


def tag_entities(tokens: list[str]) -> list[str]:
    """Per-token entity types ("O" for none) over one sentence — the shape
    of the reference's ``s.entities`` array."""
    tags = ["O"] * len(tokens)
    for i, tok in enumerate(tokens):
        low = tok.lower().strip(".")
        if _NUM_RE.match(tok) and not _YEAR_RE.match(tok):
            tags[i] = "NUMBER"
        elif low in _NUMBER_WORDS:
            tags[i] = "NUMBER"
        elif _YEAR_RE.match(tok) or low in _MONTHS or low in _WEEKDAYS:
            tags[i] = "DATE"
    for i, tok in enumerate(tokens):
        if tags[i] != "O" or not (_is_cap(tok) or tok.isupper()):
            continue
        low = tok.lower().strip(".,;:")
        prev = tokens[i - 1].lower().strip(".") if i else ""
        if low in _ORG_SUFFIXES and i and tags[i - 1] in (
            "O", "ORGANIZATION", "MISC", "PERSON",
        ):
            # suffix cue colors the preceding capitalized run (overriding
            # weaker MISC/PERSON guesses: "Acme Corp", "Smith Inc")
            tags[i] = "ORGANIZATION"
            j = i - 1
            while j >= 0 and (_is_cap(tokens[j]) or tokens[j].isupper()):
                tags[j] = "ORGANIZATION"
                j -= 1
        elif low in _LOCATIONS:
            tags[i] = "LOCATION"
        elif low in _FIRST_NAMES or prev in _HONORIFICS:
            tags[i] = "PERSON"
            # surname: following capitalized token
            if i + 1 < len(tokens) and _is_cap(tokens[i + 1]):
                tags[i + 1] = "PERSON"
        elif (
            tok.isupper()
            and 2 <= len(tok) <= 4
            and tok.isalpha()
            and low not in _ACRONYM_STOP
            and low not in _LEXICON
        ):
            # short unknown acronym → ORGANIZATION. Deliberately narrow:
            # shouted common words ("WINDOWS", "GOD") and discourse
            # acronyms must stay as ordinary, class-discriminative tokens
            # — the reference's NER only replaces recognized entities
            tags[i] = "ORGANIZATION"
    return tags


# ---------------------------------------------------------------------------
# Sentence splitting + the extractor
# ---------------------------------------------------------------------------

_ABBREV = frozenset(
    "mr mrs ms dr prof sr jr st vs etc inc corp ltd co eg ie al fig "
    "e.g i.e u.s u.k".split()
)
_TOKEN_RE = re.compile(r"[A-Za-z0-9][\w.'+-]*|[.!?]")


def split_sentences(text: str) -> list[list[str]]:
    """Tokenize into sentences: terminators split unless the previous
    token is a known abbreviation, a single initial, or a dotted form
    (e.g. "U.S.")."""
    sentences: list[list[str]] = []
    cur: list[str] = []
    toks: list[str] = []
    for tok in _TOKEN_RE.findall(text):
        # the word pattern absorbs a trailing period ("sat." is one
        # match): split it back out unless it marks an abbreviation
        body = tok.rstrip(".")
        if (
            tok.endswith(".")
            and body
            and "." not in body
            and len(body) > 1
            and body.lower() not in _ABBREV
        ):
            toks.extend([body, "."])
        else:
            toks.append(tok)
    for tok in toks:
        if tok in ".!?":
            if cur:
                sentences.append(cur)
                cur = []
        elif tok:
            cur.append(tok.rstrip("."))
    if cur:
        sentences.append(cur)
    return sentences


_NORMALIZE_RE = re.compile(r"[^a-zA-Z0-9\s+]")


def _normalize(s: str) -> str:
    """The reference's normalize: strip [^a-zA-Z0-9\\s+], lowercase."""
    return _NORMALIZE_RE.sub("", s).lower()


@treenode
class CoreNLPFeatureExtractor(Transformer):
    """Documents → per-sentence n-grams of lemmatized, entity-typed tokens
    (reference CoreNLPFeatureExtractor.scala:21-45: each entity token is
    replaced by its TYPE, other tokens by their normalized lemma; n-grams
    are space-joined and respect sentence boundaries)."""

    orders: tuple = static_field(default=(1, 2))

    def __call__(self, batch):
        docs = [batch] if isinstance(batch, str) else batch
        out = []
        for doc in docs:
            sentences = []
            for toks in split_sentences(doc):
                tags = tag_entities(toks)
                sentences.append(
                    [
                        tag if tag != "O" else _normalize(default_lemmatize(t))
                        for t, tag in zip(toks, tags)
                    ]
                )
            grams = []
            for n in self.orders:
                for s in sentences:
                    grams.extend(
                        " ".join(s[i : i + n])
                        for i in range(len(s) - n + 1)
                    )
            out.append(grams)
        return out[0] if isinstance(batch, str) else out
