"""Local Color Statistics extractor (reference nodes/images/LCSExtractor.scala).

Per keypoint on a regular grid: a 4×4 neighborhood of sub-patches, each
described by the mean and standard deviation of every color channel →
96-dim descriptors (4·4·3·2) for RGB. Mean/std maps come from one separable
box filter over the whole batch (the reference's conv2D with a ones
vector), then descriptors are pure gathers — all one jitted program.

Output layout parity: feature-major (N, 96, num_keypoints); feature order
(channel, nx, ny, {mean, std}) and column order row-major over the keypoint
grid, matching the reference's packing.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.utils.images import conv2d_separable


@treenode
class LCSExtractor(Transformer):
    """(N, H, W, C) → (N, C·4·4·2, num_keypoints).

    Reference defaults (ImageNetSiftLcsFV): stride 4, strideStart 16,
    subPatchSize 6.
    """

    stride: int = static_field(default=4)
    stride_start: int = static_field(default=16)
    sub_patch_size: int = static_field(default=6)

    def __call__(self, batch):
        return _lcs(
            batch, self.stride, self.stride_start, self.sub_patch_size
        )


@partial(jax.jit, static_argnames=("stride", "stride_start", "sps"))
def _lcs(batch, stride: int, stride_start: int, sps: int):
    n, h, w, c = batch.shape
    box = np.full(sps, 1.0 / sps, np.float32)
    means = conv2d_separable(batch, box, box)
    sq = conv2d_separable(batch * batch, box, box)
    stds = jnp.sqrt(jnp.maximum(sq - means * means, 0.0))

    # keypoint grid: strideStart until dim − strideStart by stride
    kp_rows = np.arange(stride_start, h - stride_start, stride)
    kp_cols = np.arange(stride_start, w - stride_start, stride)
    # neighborhood offsets: −2·sps + sps/2 − 1 .. sps + sps/2 − 1 by sps
    offs = np.arange(-2 * sps + sps // 2 - 1, sps + sps // 2, sps)

    row_idx = jnp.asarray((kp_rows[:, None] + offs[None, :]).reshape(-1))
    col_idx = jnp.asarray((kp_cols[:, None] + offs[None, :]).reshape(-1))

    def gather(img):
        g = jnp.take(img, row_idx, axis=1)
        g = jnp.take(g, col_idx, axis=2)
        return g.reshape(n, len(kp_rows), len(offs), len(kp_cols), len(offs), c)

    gm = gather(means)  # (N, kr, nx, kc, ny, C)
    gs = gather(stds)
    both = jnp.stack([gm, gs], axis=-1)  # (N, kr, nx, kc, ny, C, 2)
    # → features ordered (C, nx, ny, stat); columns row-major over (kr, kc)
    both = jnp.transpose(both, (0, 1, 3, 5, 2, 4, 6))
    n_kp = len(kp_rows) * len(kp_cols)
    feats = both.reshape(n, n_kp, c * len(offs) * len(offs) * 2)
    return jnp.transpose(feats, (0, 2, 1))
