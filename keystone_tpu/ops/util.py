"""Utility nodes (reference ``nodes/util/``, SURVEY.md §2.5)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.pipeline import FunctionNode, Transformer
from keystone_tpu.core.treenode import static_field, treenode


@treenode
class ClassLabelIndicators(Transformer):
    """Int label(s) → ±1 indicator vector (nodes/util/ClassLabelIndicators.scala).

    Accepts an (N,) int batch (one label per item) or an (N, k) / ragged list
    batch of multi-labels; output is (N, num_classes) with +1 at label
    positions and −1 elsewhere.
    """

    num_classes: int = static_field(default=2)

    def __call__(self, batch):
        if isinstance(batch, (list, tuple)):  # ragged multi-label
            out = -np.ones((len(batch), self.num_classes), np.float32)
            for i, labels in enumerate(batch):
                out[i, np.asarray(labels, np.int32)] = 1.0
            return jnp.asarray(out)
        batch = jnp.asarray(batch)
        if batch.ndim == 1:
            onehot = jnp.zeros(
                (batch.shape[0], self.num_classes), jnp.float32
            ).at[jnp.arange(batch.shape[0]), batch].set(1.0)
        else:  # (N, k) padded multi-label, negative entries = padding
            valid = batch >= 0
            clipped = jnp.clip(batch, 0, self.num_classes - 1)
            onehot = jnp.zeros((batch.shape[0], self.num_classes), jnp.float32)
            onehot = onehot.at[
                jnp.arange(batch.shape[0])[:, None], clipped
            ].max(valid.astype(jnp.float32))
        return 2.0 * onehot - 1.0


@treenode
class MaxClassifier(Transformer):
    """Argmax over the feature axis (nodes/util/MaxClassifier.scala)."""

    def __call__(self, batch):
        return jnp.argmax(batch, axis=-1)


@treenode
class TopKClassifier(Transformer):
    """Top-k indices, highest score first (nodes/util/TopKClassifier.scala)."""

    k: int = static_field(default=5)

    def __call__(self, batch):
        _, idx = jax.lax.top_k(batch, self.k)
        return idx


@treenode
class Cast(Transformer):
    """Dtype conversion. Covers the reference's ``FloatToDouble``; on TPU the
    useful casts are f32↔bf16 (nodes/util/FloatToDouble.scala)."""

    dtype: str = static_field(default="float32")

    def __call__(self, batch):
        return jnp.asarray(batch).astype(self.dtype)


def FloatToDouble() -> Cast:
    """Reference-parity alias. TPUs have no fast f64; the solver layer works
    in f32, so this is a no-op-ish cast kept for pipeline parity."""
    return Cast(dtype="float32")


@treenode
class MatrixVectorizer(Transformer):
    """Flatten per-item matrices to vectors (nodes/util/MatrixVectorizer.scala).

    Input (N, a, b) → output (N, a*b), column-major to match the reference's
    Breeze ``toDenseVector`` flattening.
    """

    def __call__(self, batch):
        n = batch.shape[0]
        return jnp.transpose(batch, (0, 2, 1)).reshape(n, -1)


@treenode
class VectorSplitter(FunctionNode):
    """Split (N, D) features into column blocks — the feature-blocking
    primitive feeding the block solvers (nodes/util/VectorSplitter.scala).

    The last block may be narrower, matching the reference. On a mesh this is
    pure slicing of the (replicated-feature-axis) array; the block solvers
    iterate blocks as the reference's BCD does.
    """

    block_size: int = static_field(default=4096)
    num_features: int | None = static_field(default=None)

    def __call__(self, data) -> list:
        d = self.num_features or data.shape[-1]
        return [
            data[..., start : min(start + self.block_size, d)]
            for start in range(0, d, self.block_size)
        ]


@treenode
class ZipVectors(FunctionNode):
    """Concatenate a list of (N, d_i) feature families along the feature axis
    (nodes/util/ZipVectors.scala). Identically data-sharded arrays concat
    shard-locally — the 'zip of co-partitioned RDDs' pattern is free here."""

    def __call__(self, datasets) -> jnp.ndarray:
        return jnp.concatenate(list(datasets), axis=-1)
