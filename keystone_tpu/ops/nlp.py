"""NLP nodes (reference ``nodes/nlp/``, SURVEY.md §2.6).

Tokenization, n-gram featurization/counting, backoff indexers, frequency
encoding, and the Stupid Backoff language model. These are host-side by
nature (string/dict work — the reference likewise runs them on the JVM heap,
not in BLAS); the TPU enters downstream, when counts become dense features
(``ops.sparse`` → solvers / NaiveBayes).
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Iterable, Sequence

from keystone_tpu.core.pipeline import Estimator, FunctionNode, Transformer
from keystone_tpu.core.treenode import static_field, treenode


@treenode
class Tokenizer(Transformer):
    """Split on a regex (reference StringUtils Tokenizer; default splits on
    punctuation + whitespace)."""

    sep: str = static_field(default=r"[^\w]+")

    def __call__(self, batch):
        pattern = re.compile(self.sep)
        return [[t for t in pattern.split(doc) if t] for doc in batch]


@treenode
class Trim(Transformer):
    def __call__(self, batch):
        return [doc.strip() for doc in batch]


@treenode
class LowerCase(Transformer):
    def __call__(self, batch):
        return [doc.lower() for doc in batch]


@treenode
class NGramsFeaturizer(Transformer):
    """All n-grams for consecutive orders (reference NGramsFeaturizer).

    batch of token sequences → batch of lists of n-gram tuples.
    """

    orders: tuple = static_field(default=(1, 2))

    def __post_init__(self):
        orders = sorted(self.orders)
        if orders[0] < 1:
            raise ValueError(f"minimum order must be >= 1, got {orders[0]}")
        for a, b in zip(orders, orders[1:]):
            if b != a + 1:
                raise ValueError(f"orders must be consecutive, got {orders}")

    def __call__(self, batch):
        lo, hi = min(self.orders), max(self.orders)
        out = []
        for tokens in batch:
            grams = []
            n = len(tokens)
            for i in range(n - lo + 1):
                for order in range(lo, hi + 1):
                    if i + order > n:
                        break
                    grams.append(tuple(tokens[i : i + order]))
            out.append(grams)
        return out


@treenode
class NGramsCounts(FunctionNode):
    """Count n-grams across the dataset (reference NGramsCounts).

    mode "default": aggregate counts globally, return list of
    ((ngram, count)) sorted by count descending. mode "noadd": per-document
    Counters without aggregation.
    """

    mode: str = static_field(default="default")

    def __call__(self, batch_of_grams):
        if self.mode == "noadd":
            return [Counter(grams) for grams in batch_of_grams]
        if self.mode != "default":
            raise ValueError("mode must be 'default' or 'noadd'")
        counts: Counter = Counter()
        for grams in batch_of_grams:
            counts.update(grams)
        return sorted(counts.items(), key=lambda kv: -kv[1])


class NGramIndexer:
    """Tuple-based indexer (reference NGramIndexerImpl): position 0 is the
    farthest context word, the last position is the current word."""

    min_order = 1
    max_order = 64

    @staticmethod
    def pack(words: Sequence) -> tuple:
        return tuple(words)

    @staticmethod
    def unpack(ngram: tuple, pos: int):
        return ngram[pos]

    @staticmethod
    def remove_farthest_word(ngram: tuple) -> tuple:
        return ngram[1:]

    @staticmethod
    def remove_current_word(ngram: tuple) -> tuple:
        return ngram[:-1]

    @staticmethod
    def ngram_order(ngram: tuple) -> int:
        return len(ngram)


class NaiveBitPackIndexer:
    """Pack up to a trigram of word ids < 2^20 into one int (reference
    NaiveBitPackIndexer bit layout: [4 control bits][farthest]...[current],
    left-aligned; control 00/01/10 = uni/bi/trigram)."""

    min_order = 1
    max_order = 3
    _MASK = (1 << 20) - 1

    @staticmethod
    def pack(ngram: Sequence[int]) -> int:
        for w in ngram:
            if w >= 1 << 20:
                raise ValueError(f"word id {w} >= 2^20")
        n = len(ngram)
        if n == 1:
            return ngram[0] << 40
        if n == 2:
            return (ngram[1] << 20) | (ngram[0] << 40) | (1 << 60)
        if n == 3:
            return ngram[2] | (ngram[1] << 20) | (ngram[0] << 40) | (1 << 61)
        raise ValueError("ngram order must be in {1, 2, 3}")

    @classmethod
    def unpack(cls, ngram: int, pos: int) -> int:
        if pos == 0:
            return (ngram >> 40) & cls._MASK
        if pos == 1:
            return (ngram >> 20) & cls._MASK
        if pos == 2:
            return ngram & cls._MASK
        raise ValueError("pos must be in {0, 1, 2}")

    @classmethod
    def ngram_order(cls, ngram: int) -> int:
        control = ngram >> 60
        if control == 0:
            return 1
        if control == 1:
            return 2
        if control == 2:
            return 3
        raise ValueError(f"bad control bits {control}")

    @classmethod
    def remove_farthest_word(cls, ngram: int) -> int:
        order = cls.ngram_order(ngram)
        if order == 3:
            w1, w2 = cls.unpack(ngram, 1), cls.unpack(ngram, 2)
            return cls.pack([w1, w2])
        if order == 2:
            return cls.pack([cls.unpack(ngram, 1)])
        raise ValueError("cannot remove from a unigram")

    @classmethod
    def remove_current_word(cls, ngram: int) -> int:
        order = cls.ngram_order(ngram)
        if order == 3:
            return cls.pack([cls.unpack(ngram, 0), cls.unpack(ngram, 1)])
        if order == 2:
            return cls.pack([cls.unpack(ngram, 0)])
        raise ValueError("cannot remove from a unigram")


def initial_bigram_shard(ngram, n_shards: int, indexer=NGramIndexer) -> int:
    """Shard id from the first two context words (reference
    InitialBigramPartitioner): co-locates every n-gram with its backoff
    context so scoring is shard-local."""
    if indexer.ngram_order(ngram) > 1:
        key = (indexer.unpack(ngram, 0), indexer.unpack(ngram, 1))
        return hash(key) % n_shards
    return 0


@treenode
class WordFrequencyTransformer(Transformer):
    """Token → frequency-ordered id; OOV → −1 (reference
    WordFrequencyTransformer)."""

    word_index: dict = static_field(default_factory=dict)
    unigram_counts: dict = static_field(default_factory=dict)

    OOV = -1

    def __call__(self, batch):
        idx = self.word_index
        return [[idx.get(w, self.OOV) for w in doc] for doc in batch]


class WordFrequencyEncoder(Estimator):
    """Fit the frequency-sorted vocabulary (reference WordFrequencyEncoder:
    ids respect descending count order; ties broken deterministically)."""

    def fit(self, data: Iterable[Sequence[str]]) -> WordFrequencyTransformer:
        counts: Counter = Counter()
        for doc in data:
            counts.update(doc)
        ordered = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        word_index = {w: i for i, (w, _) in enumerate(ordered)}
        unigrams = {word_index[w]: c for w, c in counts.items()}
        return WordFrequencyTransformer(
            word_index=word_index, unigram_counts=unigrams
        )


class StupidBackoffModel:
    """Brants et al. Stupid Backoff scorer (reference StupidBackoffModel).

    Scores are un-normalized:
    ``S(w|ctx) = freq(ctx·w)/freq(ctx)`` when seen, else ``α·S(w|shorter
    ctx)``; ``S(w) = freq(w)/N``.
    """

    def __init__(
        self,
        ngram_counts: dict,
        unigram_counts: dict,
        num_tokens: int,
        alpha: float = 0.4,
        indexer=NGramIndexer,
    ):
        self.ngram_counts = ngram_counts
        self.unigram_counts = unigram_counts
        self.num_tokens = num_tokens
        self.alpha = alpha
        self.indexer = indexer

    def score(self, ngram) -> float:
        return self._score(1.0, ngram, self.ngram_counts.get(ngram, 0))

    def _score(self, accum: float, ngram, freq: int) -> float:
        ix = self.indexer
        order = ix.ngram_order(ngram)
        if order == 1:
            count = (
                freq
                if freq
                else self.unigram_counts.get(ix.unpack(ngram, 0), 0)
            )
            return accum * count / self.num_tokens
        if freq != 0:
            context = ix.remove_current_word(ngram)
            if order != 2:
                context_freq = self.ngram_counts.get(context, 0)
            else:
                context_freq = self.unigram_counts.get(ix.unpack(context, 0), 0)
            return accum * freq / context_freq
        backoffed = ix.remove_farthest_word(ngram)
        return self._score(
            self.alpha * accum,
            backoffed,
            self.ngram_counts.get(backoffed, 0),
        )

    def scores_by_shard(self, n_shards: int) -> list[dict]:
        """Score every seen n-gram, grouped by its backoff-context shard —
        each shard's scoring touches only shard-local counts (the invariant
        the reference's InitialBigramPartitioner provides)."""
        shards: list[dict] = [dict() for _ in range(n_shards)]
        for ngram in self.ngram_counts:
            shards[initial_bigram_shard(ngram, n_shards, self.indexer)][
                ngram
            ] = self.score(ngram)
        return shards


class StupidBackoffEstimator(Estimator):
    """Fit from (ngram, count) pairs + unigram counts (reference
    StupidBackoffEstimator)."""

    def __init__(self, unigram_counts: dict, alpha: float = 0.4):
        self.unigram_counts = unigram_counts
        self.alpha = alpha

    def fit(self, ngram_counts) -> StupidBackoffModel:
        if not isinstance(ngram_counts, dict):
            ngram_counts = dict(ngram_counts)
        num_tokens = sum(self.unigram_counts.values())
        model = StupidBackoffModel(
            ngram_counts,
            self.unigram_counts,
            num_tokens,
            self.alpha,
        )
        for ngram, _ in ngram_counts.items():
            s = model.score(ngram)
            if not (0.0 <= s <= 1.0):
                raise ValueError(f"score {s} not in [0,1] for {ngram}")
        return model
