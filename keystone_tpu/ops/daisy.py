"""DAISY dense descriptors (reference nodes/images/DaisyExtractor.scala,
after Tola et al., "DAISY: An Efficient Dense Descriptor").

Reference-parity construction:
- gradients via separable [1,0,−1]/[1,2,1] convolutions,
- H rectified orientation maps ``max(0, cosθ·ix + sinθ·iy)``,
- Q cumulatively-blurred layers with the reference's un-normalized gaussian
  kernels (σ²_n = (R·n/2Q)², kernel weights exp(−n²/2Δ)/√(2πΔ)),
- per keypoint: center histogram from layer 0 + T ring histograms per layer
  at radius R(1+l)/Q, each L2-normalized (zeroed below 1e-8),
- feature layout identical to the reference's packing (center block first,
  then ring histograms indexed angle-major), keypoint-major output
  (N, num_keypoints, H·(T·Q+1)).

The whole extractor is separable convolutions + static gathers in one jit.
"""

from __future__ import annotations

import math
from functools import partial

import jax.numpy as jnp
import numpy as np

import jax

from keystone_tpu.core.pipeline import Transformer
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.utils.images import conv2d_separable

FEATURE_THRESHOLD = 1e-8
CONV_THRESHOLD = 1e-6


def _daisy_kernels(q: int, r: int) -> list[np.ndarray]:
    """The reference's per-layer gaussian kernels (unnormalized weights)."""
    sigma_sq = [(r * n / (2.0 * q)) ** 2 for n in range(q + 1)]
    diffs = [b - a for a, b in zip(sigma_sq, sigma_sq[1:])]
    kernels = []
    for delta in diffs:
        t = int(
            math.ceil(
                math.sqrt(
                    -2 * delta * math.log(CONV_THRESHOLD)
                    - delta * math.log(2 * math.pi * delta)
                )
            )
        )
        ns = np.arange(-t, t + 1, dtype=np.float64)
        k = np.exp(-(ns**2) / (2 * delta)) / math.sqrt(2 * math.pi * delta)
        kernels.append(k.astype(np.float32))
    return kernels


@treenode
class DaisyExtractor(Transformer):
    """(N, H, W) or (N, H, W, 1) grayscale → (N, num_kp, H·(T·Q+1))."""

    daisy_t: int = static_field(default=8)
    daisy_q: int = static_field(default=3)
    daisy_r: int = static_field(default=7)
    daisy_h: int = static_field(default=8)
    pixel_border: int = static_field(default=16)
    stride: int = static_field(default=4)

    @property
    def feature_size(self) -> int:
        return self.daisy_h * (self.daisy_t * self.daisy_q + 1)

    def __call__(self, batch):
        if batch.ndim == 4:
            batch = batch[..., 0]
        return _daisy(
            batch,
            self.daisy_t,
            self.daisy_q,
            self.daisy_r,
            self.daisy_h,
            self.pixel_border,
            self.stride,
        )


@partial(jax.jit, static_argnames=("t", "q", "r", "h_bins", "border", "stride"))
def _daisy(img, t: int, q: int, r: int, h_bins: int, border: int, stride: int):
    n, height, width = img.shape
    x4 = img[..., None]
    f1 = np.asarray([1.0, 0.0, -1.0], np.float32)
    f2 = np.asarray([1.0, 2.0, 1.0], np.float32)
    # reference: ix = conv2D(in, filter1, filter2); iy = conv2D(in, f2, f1)
    ix = conv2d_separable(x4, f1, f2)[..., 0]
    iy = conv2d_separable(x4, f2, f1)[..., 0]

    kernels = _daisy_kernels(q, r)

    # orientation maps → blurred layer stack (Q, H_bins) planes
    layers = []  # layers[l][a] : (N, H, W)
    maps0 = []
    for a in range(h_bins):
        theta = 2 * math.pi * a / h_bins
        m = jnp.maximum(math.cos(theta) * ix + math.sin(theta) * iy, 0.0)
        maps0.append(m)
    prev = [
        conv2d_separable(m[..., None], kernels[0], kernels[0])[..., 0]
        for m in maps0
    ]
    layers.append(prev)
    for l in range(1, q):
        prev = [
            conv2d_separable(m[..., None], kernels[l], kernels[l])[..., 0]
            for m in prev
        ]
        layers.append(prev)
    # stack: (Q, N, H, W, H_bins)
    stack = jnp.stack(
        [jnp.stack(layer, axis=-1) for layer in layers], axis=0
    )

    kp_rows = np.arange(border, height - border, stride)
    kp_cols = np.arange(border, width - border, stride)

    def normalize(h):
        norm = jnp.linalg.norm(h, axis=-1, keepdims=True)
        return jnp.where(
            norm > FEATURE_THRESHOLD, h / jnp.maximum(norm, 1e-30), 0.0
        )

    feats = []
    # center histogram: layer 0 at the keypoint
    center = stack[0][:, kp_rows][:, :, kp_cols]  # (N, kr, kc, H_bins)
    feats.append(normalize(center))
    # ring histograms: reference layout daisyH + angle·Q·H + l·H + off,
    # with ring angle 2π(a−1)/T and offsets (round(rad·sinθ), round(rad·cosθ))
    ring = [[None] * q for _ in range(t)]
    for a in range(t):
        theta = 2 * math.pi * (a - 1) / t
        for l in range(q):
            rad = r * (1.0 + l) / q
            dr = int(round(rad * math.sin(theta)))
            dc = int(round(rad * math.cos(theta)))
            rows = np.clip(kp_rows + dr, 0, height - 1)
            cols = np.clip(kp_cols + dc, 0, width - 1)
            hist = stack[l][:, rows][:, :, cols]
            ring[a][l] = normalize(hist)
    for a in range(t):
        for l in range(q):
            feats.append(ring[a][l])

    out = jnp.concatenate(feats, axis=-1)  # (N, kr, kc, H*(T*Q+1))
    return out.reshape(n, len(kp_rows) * len(kp_cols), -1)
