"""Live training telemetry — the per-step stream next to the event log.

The event log (:mod:`.events`) records *what ran*; this module records
*how fast it is running, right now*: one JSON line per training step (or
per planned chunk stream) in ``<run-dir>/steps.jsonl``, beside
``events.jsonl``. The LM train loop and the plan executor feed it; the
``observe top`` dashboard (:mod:`.top`) and :mod:`.report` consume it.

Activation mirrors the event log exactly: a :class:`StepLog` exists only
while an event sink is active, and :func:`active_step_log` is ONE global
read (``events.active()``) returning None on the disabled path — the
per-step hot path pays nothing when observability is off.

Step record schema (one JSON object per line; extra fields free-form):

==================  ====================================================
``ts``              unix time (float, seconds)
``run``             run id (same id as the run's events)
``source``          ``train`` (LM loop) | ``plan`` (chunked executor) |
                    ``solver`` (fused streaming fits) | ``serve``
``step``            step index (1-based, the completed step)
``loss``            host-read scalar loss
``wall_s``          wall-clock of the bracket the rates derive from
``tokens``          tokens this step → ``tokens_per_s``
``flops``           modeled FLOPs → ``tflops_per_s`` and ``mfu``
``mfu``             achieved / peak FLOPs, priced off
                    :data:`keystone_tpu.plan.costs.DEVICE_PEAKS`
``hbm_peak_bytes``  device-memory watermark (when the backend has stats)
==================  ====================================================
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any

from keystone_tpu.observe import events as _events
from keystone_tpu.observe import metrics as _metrics
from keystone_tpu.observe.metrics import percentiles  # noqa: F401 — the
# one home of the nearest-rank estimator; bench and tests reach it here

STEPS_FILE = "steps.jsonl"

# in-memory mirror cap — enough for percentile summaries and the
# dashboard's sparkline window without growing with run length
_MAX_MEMORY_STEPS = 4096

_bind_lock = threading.Lock()
_peak_cache: list = []  # [(device_kind, peak_total_flops | None)] memo


def _peak_flops_total() -> float | None:
    """Cluster-visible peak FLOP/s: per-device peak from the planner's
    roofline table × local device count. Memoized; None when the backend
    can't even be asked (MFU is then omitted, never wrong)."""
    if _peak_cache:
        return _peak_cache[0]
    try:
        import jax

        from keystone_tpu.plan.costs import device_peaks

        devs = jax.devices()
        peak = device_peaks(devs[0].device_kind)[0] * len(devs)
    except Exception:  # noqa: BLE001 — backend init failure
        peak = None
    _peak_cache.append(peak)
    return peak


class StepLog:
    """One run's per-step telemetry sink: ``steps.jsonl`` plus a bounded
    in-memory mirror (bench and the ``--once`` dashboard read it).

    ``run_dir=None`` gives a memory-only stream. Thread-safe; a failing
    disk write disables the file sink with one warning, same degrade
    rule as :class:`keystone_tpu.observe.events.EventLog`.
    """

    def __init__(self, run_dir: str | None = None, run_id: str | None = None):
        self.run_id = run_id
        self.records: collections.deque = collections.deque(
            maxlen=_MAX_MEMORY_STEPS
        )
        self._lock = threading.Lock()
        self._sink: _events.JsonlSink | None = None
        if run_dir:
            try:
                # size-rotated under KEYSTONE_OBSERVE_MAX_MB: a
                # million-step run must not grow steps.jsonl unbounded
                self._sink = _events.JsonlSink(
                    os.path.join(run_dir, STEPS_FILE), "step telemetry"
                )
            except OSError as e:
                from keystone_tpu.core.logging import get_logger

                get_logger("keystone_tpu.observe").warning(
                    "cannot open %s under %s (%r); step telemetry is "
                    "memory-only for this run",
                    STEPS_FILE,
                    run_dir,
                    e,
                )

    def record(self, source: str, **fields: Any) -> dict:
        rec: dict[str, Any] = {"ts": time.time(), "source": source}
        if self.run_id:
            rec["run"] = self.run_id
        rec.update(fields)
        with self._lock:
            self.records.append(rec)
            if self._sink is not None:
                self._sink.write(rec)
        return rec

    def step(
        self,
        *,
        step: int,
        loss: float | None = None,
        tokens: int | None = None,
        wall_s: float | None = None,
        flops: float | None = None,
        hbm_peak_bytes: int | None = None,
        source: str = "train",
        **extra: Any,
    ) -> dict:
        """Record one completed step, deriving the rate fields the
        dashboard renders: ``tokens_per_s`` from tokens/wall and ``mfu``
        as achieved-vs-peak FLOPs (roofline table in
        :mod:`keystone_tpu.plan.costs`)."""
        fields: dict[str, Any] = {"step": int(step), **extra}
        if loss is not None:
            fields["loss"] = float(loss)
        if wall_s is not None:
            fields["wall_s"] = round(float(wall_s), 6)
        if tokens is not None:
            fields["tokens"] = int(tokens)
            if wall_s:
                fields["tokens_per_s"] = round(tokens / wall_s, 3)
        if flops is not None and wall_s:
            fields["tflops_per_s"] = round(flops / wall_s / 1e12, 6)
            peak = _peak_flops_total()
            if peak:
                fields["mfu"] = round(flops / wall_s / peak, 6)
        if hbm_peak_bytes is not None:
            fields["hbm_peak_bytes"] = int(hbm_peak_bytes)
        reg = _metrics.get_registry()
        reg.gauge("telemetry_last_step", source=source).set(float(step))
        if "tokens_per_s" in fields:
            reg.gauge("telemetry_tokens_per_s", source=source).set(
                fields["tokens_per_s"]
            )
        if "mfu" in fields:
            reg.gauge("telemetry_mfu", source=source).set(fields["mfu"])
        if wall_s is not None:
            reg.timer("telemetry_step_seconds", source=source).observe(
                float(wall_s)
            )
        rec = self.record(source, **fields)
        if source == "train":
            # the anomaly monitor rides the live stream: NaN/spiked
            # loss, step-time drift, HBM growth → `alert` events. Only
            # reachable while a sink is active, so the telemetry-off
            # hot path still pays exactly one global read.
            from keystone_tpu.observe import health as _health

            _health.get_monitor().note_step(
                step=int(step),
                loss=loss,
                wall_s=wall_s,
                hbm_peak_bytes=hbm_peak_bytes,
            )
        return rec

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None


def active_step_log() -> StepLog | None:
    """The :class:`StepLog` riding the active event sink, or None.

    The ONLY check the per-step hot paths make: with no sink active this
    is exactly one global read (``events.active()``) and constructs
    nothing — the acceptance bar for telemetry-off overhead."""
    log = _events.active()
    if log is None:
        return None
    sl = log.__dict__.get("_steplog")
    if sl is None:
        with _bind_lock:
            sl = log.__dict__.get("_steplog")
            if sl is None:
                sl = StepLog(log.run_dir, log.run_id)
                log._steplog = sl
    return sl


def reset_peak_cache() -> None:
    """Drop the memoized device peak (tests that fake the backend)."""
    _peak_cache.clear()
