"""On-disk time-series store: the fleet collector's durable memory.

One process's telemetry lives in its run dir; a FLEET is N processes
(router + replicas + trainers) each writing its own. The collector
(:mod:`.collector`) merges their scraped ``/metrics`` snapshots and
tailed ``steps``/``events``/``spans`` streams into ONE of these stores,
and the SLO engine (:mod:`.slo`) and the live dashboard
(:mod:`.dashboard`) range-query it — the single pane the per-process
streams never gave the service tier.

Design: append-only JSONL *segments* (``ts-NNNNNN.jsonl``), rolled when
the active segment passes ``KEYSTONE_TS_SEGMENT_MB``, with retention +
compaction (:meth:`TimeSeriesStore.compact`) bounding total disk. The
format stays the repo's one substrate — tolerant JSONL via
:func:`keystone_tpu.observe.events.read_jsonl` — so a torn final line
from a killed collector costs one point, never a segment, and plain
``jq`` still works on the files.

Point schema (one JSON object per line; extra fields free-form)::

    ==========  =========================================================
    ``ts``      unix time (float, seconds)
    ``series``  series key — the :func:`..metrics._series_key` format
                (``name{label=value,...}``), so label escaping has one
                home across live registries and the store
    ``value``   float sample
    (extra)     free-form attributes; request points carry ``ok``,
                ``trace``/``rid`` (the exemplar an SLO alert links to)
    ==========  =========================================================

Crash contract: a write lands either as a complete line or as a torn
final line the readers skip; compaction writes every replacement
segment fully before deleting any source segment, so a reader never
sees a torn segment — at worst it sees a few points twice across the
replace window (the consumers tolerate duplicates; verdicts are
computed over rates, not exact counts).

The writer is LAZY: constructing a store opens nothing, so read-only
consumers (``observe slo``, the dashboard) can point one at a live
collector's directory without contending for the active segment.
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import Any, Callable, Iterable

from keystone_tpu.observe import events as _events

ENV_SEGMENT_MB = "KEYSTONE_TS_SEGMENT_MB"
ENV_RETENTION_S = "KEYSTONE_TS_RETENTION_S"

DEFAULT_SEGMENT_BYTES = 4 * 2**20  # 4 MiB per segment before roll
DEFAULT_RETENTION_S = 24 * 3600.0  # one day of points survives compact

_SEGMENT_RE = re.compile(r"^ts-(\d{6,})\.jsonl$")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if raw:
        try:
            v = float(raw)
            if v > 0:
                return v
        except ValueError:
            pass
    return default


def segment_bytes_from_env() -> int:
    return int(_env_float(ENV_SEGMENT_MB, DEFAULT_SEGMENT_BYTES / 2**20) * 2**20)


def retention_from_env() -> float:
    return _env_float(ENV_RETENTION_S, DEFAULT_RETENTION_S)


class TimeSeriesStore:
    """Append-only segmented point store under one directory.

    Thread-safe; all disk failures degrade (one warning, writes drop)
    rather than crash the collector — the same contract as the event
    log. ``clock`` is injectable so retention math is testable with
    zero sleeps.
    """

    def __init__(
        self,
        dir: str,
        *,
        segment_max_bytes: int | None = None,
        retention_s: float | None = None,
        clock: Callable[[], float] = time.time,
    ):
        self.dir = dir
        self.segment_max_bytes = (
            segment_bytes_from_env()
            if segment_max_bytes is None
            else int(segment_max_bytes)
        )
        self.retention_s = (
            retention_from_env() if retention_s is None else float(retention_s)
        )
        self.clock = clock
        self._lock = threading.Lock()
        self._fh = None  # lazy: opened on first append
        self._active: str | None = None
        self._size = 0
        self._degraded = False
        # (path, file size) → (min_ts, max_ts) — sealed segments are
        # immutable so the size key invalidates exactly when a segment
        # is still growing; lets range queries skip whole files
        self._meta: dict[str, tuple[int, float, float]] = {}
        # (path, file size) → series names — same invalidation rule;
        # keeps the dashboard's every-2s series listing from re-parsing
        # sealed segments
        self._names: dict[str, tuple[int, frozenset]] = {}

    # ------------------------------------------------------------ segments

    def segments(self) -> list[str]:
        """All segment file paths, oldest→newest (sequence order)."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        out = []
        for name in names:
            m = _SEGMENT_RE.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(self.dir, name)))
        return [path for _, path in sorted(out)]

    def _next_seq(self) -> int:
        seqs = [
            int(_SEGMENT_RE.match(os.path.basename(p)).group(1))
            for p in self.segments()
        ]
        return (max(seqs) + 1) if seqs else 1

    def _segment_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"ts-{seq:06d}.jsonl")

    def _open_active(self) -> None:
        """Open (or resume) the active segment — called under the lock."""
        os.makedirs(self.dir, exist_ok=True)
        segs = self.segments()
        path = None
        if segs:
            last = segs[-1]
            try:
                if os.path.getsize(last) < self.segment_max_bytes:
                    path = last
            except OSError:
                path = None
        if path is None:
            path = self._segment_path(self._next_seq())
        self._fh = open(path, "a", buffering=1)  # noqa: SIM115 — store-lifetime
        self._active = path
        self._size = self._fh.tell()

    def _roll(self) -> None:
        """Seal the active segment and start the next one (under lock)."""
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
        path = self._segment_path(self._next_seq())
        self._fh = open(path, "a", buffering=1)  # noqa: SIM115 — store-lifetime
        self._active = path
        self._size = 0

    def _degrade(self, err: Exception, what: str) -> None:
        if not self._degraded:
            self._degraded = True
            from keystone_tpu.core.logging import get_logger

            get_logger("keystone_tpu.observe").warning(
                "time-series store %s: %s failed (%r); writes disabled",
                self.dir,
                what,
                err,
            )
        self._fh = None

    # -------------------------------------------------------------- writes

    def append(
        self, series: str, value: float, *, ts: float | None = None, **attrs: Any
    ) -> dict:
        """Append one point; returns the record (written or not)."""
        rec: dict[str, Any] = {
            "ts": float(self.clock() if ts is None else ts),
            "series": str(series),
            "value": float(value),
        }
        for k, v in attrs.items():
            if v is not None:
                rec[k] = v
        line = _events._encode(rec)
        if line is None:
            return rec
        nbytes = len(line.encode("utf-8")) + 1
        with self._lock:
            if self._degraded:
                return rec
            try:
                if self._fh is None:
                    self._open_active()
                if self._size and self._size + nbytes > self.segment_max_bytes:
                    self._roll()
                self._fh.write(line + "\n")
                self._size += nbytes
            except OSError as e:
                self._degrade(e, "append")
        return rec

    def append_many(self, points: Iterable[tuple[str, float, dict]]) -> int:
        """Bulk form: ``(series, value, attrs)`` tuples; returns count."""
        n = 0
        for series, value, attrs in points:
            self.append(series, value, **attrs)
            n += 1
        return n

    def seal(self) -> None:
        """Close the active segment handle. The next append re-resolves
        the newest on-disk segment, so a compaction that ran in between
        (same process or another) is picked up instead of resurrecting
        a deleted file."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    # -------------------------------------------------------------- reads

    @staticmethod
    def _read_segment(path: str) -> list[dict]:
        """One segment's records; [] when the file vanished underneath
        us — a CONCURRENT compaction (another process's collector)
        deletes sources after writing survivors, and a reader that
        listed the old name must degrade to the survivors it can see,
        never crash (the compact docstring's contract)."""
        try:
            return _events.read_jsonl(path)
        except OSError:
            return []

    def _segment_span(self, path: str) -> tuple[float, float] | None:
        """Cached (min_ts, max_ts) of one segment, keyed by file size
        (sealed segments never change; the active one grows, which
        changes its size and refreshes the entry). None = unreadable or
        empty — the caller must scan it."""
        try:
            size = os.path.getsize(path)
        except OSError:
            return None
        hit = self._meta.get(path)
        if hit is not None and hit[0] == size:
            return hit[1], hit[2]
        lo = hi = None
        for rec in self._read_segment(path):
            ts = rec.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            lo = ts if lo is None else min(lo, ts)
            hi = ts if hi is None else max(hi, ts)
        if lo is None:
            return None
        self._meta[path] = (size, lo, hi)
        return lo, hi

    def query(
        self,
        series: str | None = None,
        *,
        start: float | None = None,
        end: float | None = None,
        prefix: str | None = None,
        limit: int | None = None,
    ) -> list[dict]:
        """Range query: points with ``start <= ts <= end`` (either bound
        optional), filtered to an exact ``series`` key or a ``prefix``
        (series name family, e.g. ``"serve_request_seconds"`` matching
        every labeled instance). Returned oldest→newest; ``limit`` keeps
        the NEWEST N (``limit=0`` = none). Reads from disk, so any
        process can query a live collector's store; segments whose
        cached time span falls outside the range are skipped unread —
        the dashboard's every-2s recent-window refresh must not re-parse
        a day of retention."""
        if limit is not None and limit <= 0:
            return []
        out: list[dict] = []
        for path in self.segments():
            if start is not None or end is not None:
                span = self._segment_span(path)
                if span is not None and (
                    (start is not None and span[1] < start)
                    or (end is not None and span[0] > end)
                ):
                    continue
            for rec in self._read_segment(path):
                ts = rec.get("ts")
                if not isinstance(ts, (int, float)):
                    continue
                if start is not None and ts < start:
                    continue
                if end is not None and ts > end:
                    continue
                key = rec.get("series")
                if series is not None and key != series:
                    continue
                if prefix is not None and not str(key).startswith(prefix):
                    continue
                out.append(rec)
        out.sort(key=lambda r: r["ts"])
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def series_names(self) -> list[str]:
        """Every distinct series key present in the store, sorted.
        Cached per sealed segment (size-keyed, like the span index) so
        the dashboard's refresh loop doesn't re-parse a day of
        retention to list names."""
        names: set[str] = set()
        for path in self.segments():
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            hit = self._names.get(path)
            if hit is None or hit[0] != size:
                found = frozenset(
                    str(rec["series"])
                    for rec in self._read_segment(path)
                    if rec.get("series")
                )
                hit = (size, found)
                self._names[path] = hit
            names |= hit[1]
        return sorted(names)

    def latest(self, series: str) -> dict | None:
        """The newest point of one series (None when absent)."""
        best: dict | None = None
        for path in self.segments():
            for rec in self._read_segment(path):
                if rec.get("series") != series:
                    continue
                if best is None or (rec.get("ts") or 0) >= (best.get("ts") or 0):
                    best = rec
        return best

    # --------------------------------------------------------- compaction

    def compact(self, now: float | None = None) -> dict:
        """Merge every segment into fresh ones, dropping points older
        than ``retention_s`` — the disk bound for a long-lived collector.

        Crash-safe by ordering: survivors are fully written to NEW
        segment files (higher sequence numbers) before any source
        segment is deleted, so a reader — or a crash at any instant —
        never sees a torn segment; the worst case is a short window of
        duplicated points, which every consumer tolerates.
        """
        now = self.clock() if now is None else float(now)
        horizon = now - self.retention_s
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
            old = self.segments()
            kept = 0
            dropped = 0
            written: list[str] = []
            seq = self._next_seq()
            out_fh = None
            out_size = 0
            try:
                for path in old:
                    for rec in self._read_segment(path):
                        ts = rec.get("ts")
                        if not isinstance(ts, (int, float)) or ts < horizon:
                            dropped += 1
                            continue
                        line = _events._encode(rec)
                        if line is None:
                            dropped += 1
                            continue
                        nbytes = len(line.encode("utf-8")) + 1
                        if out_fh is None or (
                            out_size and out_size + nbytes > self.segment_max_bytes
                        ):
                            if out_fh is not None:
                                out_fh.close()
                            new_path = self._segment_path(seq)
                            seq += 1
                            out_fh = open(  # noqa: SIM115 — closed below
                                new_path, "w", buffering=1
                            )
                            written.append(new_path)
                            out_size = 0
                        out_fh.write(line + "\n")
                        out_size += nbytes
                        kept += 1
                if out_fh is not None:
                    out_fh.close()
                    out_fh = None
                # every survivor is durable in a complete new segment:
                # NOW the sources can go
                for path in old:
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                    self._meta.pop(path, None)
                    self._names.pop(path, None)
            except OSError as e:
                if out_fh is not None:
                    try:
                        out_fh.close()
                    except OSError:
                        pass
                self._degrade(e, "compact")
        return {
            "segments_before": len(old),
            "segments_after": len(written),
            "points_kept": kept,
            "points_dropped": dropped,
        }
