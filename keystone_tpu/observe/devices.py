"""Device-memory telemetry: per-device HBM watermarks.

``jax`` exposes allocator statistics per device (``Device.memory_stats()``
— ``bytes_in_use``, ``peak_bytes_in_use``, ``bytes_limit`` on TPU/GPU;
``None`` on the CPU backend). This module samples them into the metrics
registry (``hbm_bytes_in_use{device=...}`` / ``hbm_peak_bytes{device=...}``
gauges), tracks the run-wide peak watermark per device, and emits
rate-limited ``device_memory`` events so :mod:`.report` and the
``observe top`` dashboard can render where the HBM high-water mark sits
against the device limit.

Degrade rule: a backend without memory stats (CPU) yields an empty
sample — no gauges, no events, no errors — so every caller can sample
unconditionally.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

from keystone_tpu.observe import events as _events
from keystone_tpu.observe import metrics as _metrics

#: min seconds between samples taken via :meth:`DeviceMemoryMonitor.maybe_sample`
#: and the background sampler's default period.
ENV_INTERVAL = "KEYSTONE_DEVMEM_INTERVAL_S"
_DEFAULT_INTERVAL_S = 5.0


def _device_stats(dev: Any) -> dict | None:
    """One device's allocator stats dict, or None when the backend has
    none (CPU) — split out so tests can fake accelerator stats."""
    try:
        return dev.memory_stats()
    except Exception:  # noqa: BLE001 — older jaxlib without the method
        return None


def sample_device_memory() -> list[dict]:
    """One point-in-time sample: a dict per device that reports stats
    (``[]`` on backends without allocator stats)."""
    try:
        import jax

        devs = jax.devices()
    except Exception:  # noqa: BLE001 — backend init failure
        return []
    out: list[dict] = []
    for d in devs:
        stats = _device_stats(d)
        if not stats:
            continue
        in_use = int(stats.get("bytes_in_use", 0))
        out.append(
            {
                "device": f"{getattr(d, 'platform', '?')}:{getattr(d, 'id', len(out))}",
                "kind": getattr(d, "device_kind", "unknown"),
                "bytes_in_use": in_use,
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use", in_use)
                ),
                "bytes_limit": int(stats.get("bytes_limit", 0)),
            }
        )
    return out


def interval_s() -> float:
    try:
        return float(
            os.environ.get(ENV_INTERVAL, "") or _DEFAULT_INTERVAL_S
        )
    except ValueError:
        return _DEFAULT_INTERVAL_S


class DeviceMemoryMonitor:
    """Watermark tracker over repeated samples.

    ``sample()`` takes a sample NOW: updates the per-device gauges, the
    run-peak watermarks, and (rate-limited) emits a ``device_memory``
    event into the active sink. ``maybe_sample()`` is the per-step form:
    it samples at most once per interval and returns the current overall
    peak watermark either way (None when the backend has no stats) — the
    train loop attaches that to its step records.
    """

    def __init__(self, emit_events: bool = True):
        self.watermarks: dict[str, int] = {}
        self.limits: dict[str, int] = {}
        self.emit_events = emit_events
        self._lock = threading.Lock()
        self._last_sample = 0.0
        self._last_event = 0.0

    def sample(self) -> list[dict]:
        samples = sample_device_memory()
        now = time.monotonic()
        reg = _metrics.get_registry()
        with self._lock:
            self._last_sample = now
            for s in samples:
                dev = s["device"]
                peak = max(
                    self.watermarks.get(dev, 0),
                    s["peak_bytes_in_use"],
                    s["bytes_in_use"],
                )
                self.watermarks[dev] = peak
                if s["bytes_limit"]:
                    self.limits[dev] = s["bytes_limit"]
                reg.gauge("hbm_bytes_in_use", device=dev).set(
                    float(s["bytes_in_use"])
                )
                reg.gauge("hbm_peak_bytes", device=dev).set(float(peak))
            emit = (
                self.emit_events
                and samples
                and now - self._last_event >= interval_s()
            )
            if emit:
                self._last_event = now
        if emit:
            log = _events.active()
            if log is not None:
                log.emit(
                    "device_memory",
                    devices=samples,
                    peak_bytes=self.peak_bytes(),
                )
        return samples

    def maybe_sample(self) -> int | None:
        """Rate-limited sample (at most once per ``interval_s()``);
        returns the overall peak watermark in bytes, or None when no
        device reports stats."""
        with self._lock:
            due = (
                time.monotonic() - self._last_sample >= interval_s()
                or not self._last_sample
            )
        if due:
            self.sample()
        return self.peak_bytes()

    def peak_bytes(self) -> int | None:
        """Highest HBM watermark across devices so far (None: no stats)."""
        with self._lock:
            return max(self.watermarks.values()) if self.watermarks else None
