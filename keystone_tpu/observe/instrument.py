"""Explicit pipeline instrumentation: ``instrument(pipeline)``.

The pipeline DSL already emits per-node events through lightweight hooks
in :mod:`keystone_tpu.core.pipeline` whenever an event sink is active.
:func:`instrument` is the stronger, opt-in form: it wraps every node so

- each call is recorded to the metrics registry (call counter + timer
  per node) regardless of whether an event sink is active,
- ``sync=True`` blocks on each node's output before stopping the clock,
  so per-node wall time attributes device work to the node that launched
  it instead of to whichever later node forces the value (JAX dispatch
  is async; see ROOFLINE.md §0),
- outputs are bit-exact: the wrapper calls the node and returns its
  result untouched (``block_until_ready`` does not change values).

Wrapped nodes are still treenodes, so an instrumented pipeline remains a
jittable pytree — under tracing each wrapper records once with
``phase="compile"``.
"""

from __future__ import annotations

import dataclasses
import time

import jax

from keystone_tpu.core.pipeline import Pipeline, Transformer, is_tracing
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.observe import events as _events
from keystone_tpu.observe import metrics as _metrics


@treenode
class InstrumentedNode(Transformer):
    """One wrapped pipeline node; see module docstring."""

    inner: Transformer
    label: str = static_field(default="")
    sync: bool = static_field(default=False)

    # core.pipeline's per-node hook skips nodes carrying this marker so
    # an instrumented pipeline under an active sink records once, not twice
    _observe_instrumented = True

    def __call__(self, batch):
        reg = _metrics.get_registry()
        log = _events.active()
        tracing = is_tracing(batch)
        phase = "compile" if tracing else "apply"
        t0 = time.perf_counter()
        try:
            out = self.inner(batch)
            if self.sync and not tracing:
                jax.block_until_ready(out)
        except BaseException as e:
            wall = time.perf_counter() - t0
            reg.counter("node_errors", node=self.label).inc()
            if log is not None:
                log.emit(
                    "node",
                    node=self.label,
                    phase=phase,
                    wall_s=wall,
                    status="failed",
                    error=repr(e),
                )
            raise
        wall = time.perf_counter() - t0
        if tracing:
            # trace time is not apply time: a 100x-slower compile sample
            # would dominate the timer's mean/max — keep it in its own
            # series so the apply metrics stay honest
            reg.counter("node_traces", node=self.label).inc()
            reg.timer("node_trace_seconds", node=self.label).observe(wall)
        else:
            reg.counter("node_calls", node=self.label).inc()
            reg.timer("node_seconds", node=self.label).observe(wall)
        if log is not None:
            log.emit(
                "node", node=self.label, phase=phase, wall_s=wall, status="ok"
            )
        return out

    def __repr__(self):
        return f"InstrumentedNode({self.label})"


def _wrap(node: Transformer, label: str, sync: bool) -> InstrumentedNode:
    if isinstance(node, InstrumentedNode):
        # no double wrapping, but honor a CHANGED sync request — silently
        # keeping the old setting would mis-attribute async device work
        # the caller just asked to pin down
        if node.sync == sync:
            return node
        return dataclasses.replace(node, sync=sync)
    return InstrumentedNode(inner=node, label=label, sync=sync)


def instrument(pipe: Transformer, sync: bool = False) -> Transformer:
    """Wrap every node of ``pipe`` (or a single transformer) so calls are
    recorded per node. Idempotent: already-wrapped nodes are not wrapped
    again (their ``sync`` is updated if the request differs)."""
    if isinstance(pipe, Pipeline):
        return Pipeline(
            nodes=tuple(
                _wrap(node, _events.node_label(node, i), sync)
                for i, node in enumerate(pipe.nodes)
            )
        )
    return _wrap(pipe, _events.node_label(pipe), sync)
