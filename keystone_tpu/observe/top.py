"""``observe top`` — a curses-free terminal dashboard for a live run.

Tails a run directory's ``steps.jsonl`` (:mod:`.telemetry`) and
``events.jsonl`` (:mod:`.events`) and refreshes a one-screen summary:
step rate / tokens-per-sec / MFU, a loss sparkline, per-device HBM
watermarks, and the resilience / planner decision counters. Pure file
tailing — it attaches to any live or finished run, local or on a shared
filesystem, with no jax import and no code running in the trained
process.

Usage::

    python -m keystone_tpu observe top <dir> [--once] [--interval S]

``--once`` renders one snapshot and exits (tests, CI artifacts, piping
to a file); otherwise the screen refreshes in place until Ctrl-C.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from typing import Any

from keystone_tpu.observe import events as _events
from keystone_tpu.observe import telemetry as _telemetry

SPARK = "▁▂▃▄▅▆▇█"
_RATE_WINDOW = 32  # steps the instantaneous rate is averaged over
_LOSS_WINDOW = 60  # sparkline width


class Tail:
    """Incremental JSONL reader: repeated :meth:`poll` calls parse only
    bytes appended since the last call, never re-reading the file.
    Complete lines only — a torn final line is left for the next poll.
    A truncated/rotated file restarts from the top."""

    def __init__(self, path: str, keep: int = 4096):
        self.path = path
        self.offset = 0
        self.records: list[dict] = []
        self.keep = keep

    def poll(self) -> list[dict]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return self.records
        if size < self.offset:  # truncated underneath us: start over
            self.offset, self.records = 0, []
        if size == self.offset:
            return self.records
        with open(self.path, "rb") as f:
            f.seek(self.offset)
            chunk = f.read()
        end = chunk.rfind(b"\n")
        if end < 0:
            return self.records
        self.offset += end + 1
        for raw in chunk[: end + 1].splitlines():
            raw = raw.strip()
            if not raw:
                continue
            try:
                self.records.append(json.loads(raw))
            except ValueError:
                continue
        if len(self.records) > self.keep:
            del self.records[: len(self.records) - self.keep]
        return self.records


#: fleet mode tails run dirs whose streams moved within this window —
#: a base dir accumulating months of finished runs must not pour every
#: dead run's alerts and losses into the live view
_FLEET_FRESH_S = 3600.0


class FleetTails:
    """Tails EVERY *live* run dir under a base observe directory,
    rediscovering on each poll. A router and its replicas each write
    their own run dir; a replica relaunched by a rolling restart writes
    a NEW one, and the operator staring at the dashboard must see it
    appear live, not restart ``observe top``. Run dirs whose files
    haven't moved for :data:`_FLEET_FRESH_S` are skipped at discovery
    (when nothing is fresh, the newest stale run is tailed so the
    command still shows something). Files are read through the
    collector's rotation-safe cursor — a size-capped ``steps.jsonl``
    rolling to ``.1`` mid-watch must not wipe the dashboard's history —
    with bounded in-memory accumulation, merged and ts-sorted so
    :func:`summarize` treats the fleet as one stream."""

    _KEEP = 4096  # records kept per file, the Tail bound

    def __init__(self, base: str):
        self.base = base
        self._tails: dict[str, tuple[Any, Any, list[dict], list[dict]]] = {}

    def _fresh(self, run_dir: str) -> float | None:
        """Newest stream mtime under ``run_dir`` (None = no streams)."""
        newest = None
        for f in (_telemetry.STEPS_FILE, _events.EVENTS_FILE):
            try:
                mtime = os.path.getmtime(os.path.join(run_dir, f))
            except OSError:
                continue
            newest = mtime if newest is None else max(newest, mtime)
        return newest

    def _discover(self) -> None:
        from keystone_tpu.observe.collector import _Cursor

        try:
            names = os.listdir(self.base)
        except OSError:
            return
        candidates: dict[str, float] = {}
        for name in sorted(names):
            run_dir = os.path.join(self.base, name)
            if run_dir in self._tails or not os.path.isdir(run_dir):
                continue
            mtime = self._fresh(run_dir)
            if mtime is not None:
                candidates[run_dir] = mtime
        now = time.time()
        live = {
            d for d, m in candidates.items() if now - m <= _FLEET_FRESH_S
        }
        if not live and candidates and not self._tails:
            # nothing fresh anywhere: show the newest finished run
            live = {max(candidates, key=candidates.get)}
        for run_dir in sorted(live):
            self._tails[run_dir] = (
                _Cursor(os.path.join(run_dir, _telemetry.STEPS_FILE)),
                _Cursor(os.path.join(run_dir, _events.EVENTS_FILE)),
                [],
                [],
            )

    def poll(self) -> tuple[list[dict], list[dict]]:
        self._discover()
        steps: list[dict] = []
        events: list[dict] = []
        for step_cur, event_cur, step_kept, event_kept in self._tails.values():
            for cur, kept in ((step_cur, step_kept), (event_cur, event_kept)):
                kept.extend(cur.poll())
                if len(kept) > self._KEEP:
                    del kept[: len(kept) - self._KEEP]
            steps.extend(step_kept)
            events.extend(event_kept)
        key = lambda r: float(r.get("ts") or 0.0)  # noqa: E731
        steps.sort(key=key)
        events.sort(key=key)
        return steps, events

    @property
    def run_count(self) -> int:
        return len(self._tails)


def sparkline(values: list[float], width: int = _LOSS_WINDOW) -> str:
    # non-finite values (a NaN'd loss — exactly when someone is staring
    # at the dashboard) render as the full bar instead of crashing the
    # watch loop mid-incident
    numeric = [v for v in values[-width:] if isinstance(v, (int, float))]
    if not numeric:
        return ""
    vals = [v for v in numeric if math.isfinite(v)]
    # an ALL-non-finite window (divergence that stuck) still renders —
    # a vanished loss line mid-incident would be worse than any scale
    lo, hi = (min(vals), max(vals)) if vals else (0.0, 0.0)
    span = (hi - lo) or 1.0
    out = []
    for v in numeric:
        if not math.isfinite(v):
            out.append(SPARK[-1])
            continue
        out.append(SPARK[int((v - lo) / span * (len(SPARK) - 1))])
    return "".join(out)


def _fmt_bytes(n: float | None) -> str:
    if not n:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return "-"


def _fmt_rate(v: float | None, unit: str = "") -> str:
    if v is None:
        return "-"
    for scale, suffix in ((1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= scale:
            return f"{v / scale:.2f} {suffix}{unit}"
    return f"{v:.2f} {unit}".rstrip()


def summarize(steps: list[dict], events: list[dict]) -> dict[str, Any]:
    """Aggregate the tailed records into the render model (split from
    rendering so tests and other frontends can assert on it)."""
    out: dict[str, Any] = {
        "run": None,
        "status": "running",
        "n_events": len(events),
        "n_steps": 0,
        "last_step": None,
        "steps_per_s": None,
        "tokens_per_s": None,
        "mfu": None,
        "loss": None,
        "losses": [],
        "devices": [],
        "hbm_peak_bytes": None,
        "resilience": {},
        "cluster": {},
        "alerts": {},
        "last_alert": None,
        "plan_decisions": 0,
        "plan_streams": 0,
        "trace_windows": 0,
        "serve": {},
        "fleet": {},
        "tune": {},
        "last_ts": None,
    }
    # the stream mixes sources: train steps (source="train") carry the
    # loss/step-rate the header renders; plan chunk streams
    # (source="plan") ride a process-lifetime sequence, not a step
    # index, and must not pollute the step-rate math
    train = [
        r
        for r in steps
        if "step" in r and r.get("source", "train") == "train"
    ]
    plan_rows = [r for r in steps if r.get("source") == "plan"]
    out["plan_streams"] = len(plan_rows)
    if plan_rows:
        out["last_ts"] = plan_rows[-1].get("ts")
    # serving stream rows: micro-batch dispatches (rows/bucket/batch_fill
    # per batch) and finished generations (kind="decode", tokens per
    # request) — the serving panel's live numbers
    serve_rows = [r for r in steps if r.get("source") == "serve"]
    if serve_rows:
        sv: dict[str, Any] = out["serve"]
        batches = [r for r in serve_rows if "bucket" in r]
        decodes = [r for r in serve_rows if r.get("kind") == "decode"]
        if batches:
            sv["batches"] = len(batches)
            sv["rows"] = int(
                sum(
                    r["rows"]
                    for r in batches
                    if isinstance(r.get("rows"), (int, float))
                )
            )
            fills = [
                r["batch_fill"]
                for r in batches
                if isinstance(r.get("batch_fill"), (int, float))
            ]
            if fills:
                sv["batch_fill"] = sum(fills) / len(fills)
        if decodes:
            sv["generations"] = len(decodes)
            sv["tokens"] = int(
                sum(
                    r["tokens"]
                    for r in decodes
                    if isinstance(r.get("tokens"), (int, float))
                )
            )
        out["last_ts"] = max(
            out["last_ts"] or 0, serve_rows[-1].get("ts") or 0
        ) or None
    out["n_steps"] = len(train)
    if train:
        last = train[-1]
        out["run"] = last.get("run")
        out["last_step"] = last.get("step")
        out["loss"] = last.get("loss")
        out["tokens_per_s"] = last.get("tokens_per_s")
        out["mfu"] = last.get("mfu")
        out["losses"] = [
            r["loss"] for r in train if isinstance(r.get("loss"), (int, float))
        ]
        out["last_ts"] = max(out["last_ts"] or 0, last.get("ts") or 0) or None
        window = train[-_RATE_WINDOW:]
        if len(window) >= 2:
            dt = window[-1].get("ts", 0) - window[0].get("ts", 0)
            if dt > 0:
                out["steps_per_s"] = (len(window) - 1) / dt
        elif last.get("wall_s"):
            out["steps_per_s"] = 1.0 / last["wall_s"]
        peaks = [
            r["hbm_peak_bytes"]
            for r in train
            if isinstance(r.get("hbm_peak_bytes"), (int, float))
        ]
        if peaks:
            out["hbm_peak_bytes"] = max(peaks)
    for ev in events:
        kind = ev.get("event")
        if out["run"] is None and ev.get("run"):
            out["run"] = ev["run"]
        if ev.get("ts"):
            out["last_ts"] = max(out["last_ts"] or 0, ev["ts"])
        if kind == "run_end":
            out["status"] = ev.get("status") or "done"
        elif kind == "resilience":
            action = str(ev.get("action", "?"))
            if action.startswith("fleet_"):
                # fleet routing/failover/lifecycle decisions render in
                # their own panel (per-replica state + counters), not
                # the generic resilience counter line
                fl = out["fleet"]
                if action == "fleet_replica_state":
                    fl.setdefault("replicas", {})[
                        str(ev.get("replica"))
                    ] = {
                        "state": ev.get("state"),
                        "port": ev.get("port"),
                        "restarts": ev.get("restarts", 0),
                    }
                elif action == "fleet_stats":
                    for key in ("routed", "shed", "failover", "hedges"):
                        if ev.get(key) is not None:
                            fl[key] = ev[key]
                    for rid, state in (ev.get("replicas") or {}).items():
                        fl.setdefault("replicas", {}).setdefault(
                            str(rid), {}
                        )["state"] = state
                else:
                    fl.setdefault("events", {})
                    fl["events"][action] = fl["events"].get(action, 0) + 1
                continue
            out["resilience"][action] = out["resilience"].get(action, 0) + 1
        elif kind == "cluster":
            action = str(ev.get("action", "?"))
            out["cluster"][action] = out["cluster"].get(action, 0) + 1
        elif kind == "alert":
            action = str(ev.get("action", "?"))
            out["alerts"][action] = out["alerts"].get(action, 0) + 1
            out["last_alert"] = ev
        elif kind == "serve":
            sv = out["serve"]
            action = str(ev.get("action", "?"))
            if action == "start":
                sv["model"] = ev.get("model")
                sv["port"] = ev.get("port")
                sv["cold_start_s"] = ev.get("cold_start_s")
                sv["status"] = "serving"
            elif action == "stop":
                sv["status"] = "stopped"
        elif kind == "model_swap":
            # the online-learning lifecycle: committed swaps advance the
            # served version; rollbacks count separately (the panel must
            # show a failed candidate never took over)
            sv = out["serve"]
            action = str(ev.get("action", "?"))
            if action == "swap":
                sv["version"] = ev.get("new_version")
                sv["swaps"] = sv.get("swaps", 0) + 1
            elif action == "rollback":
                sv["rollbacks"] = sv.get("rollbacks", 0) + 1
        elif kind == "tune":
            # the autotuner panel: current knob snapshot (each event
            # carries it) + the last non-hold decision
            tn = out["tune"]
            tn["decisions"] = tn.get("decisions", 0) + 1
            action = str(ev.get("action", "?"))
            tn[action] = tn.get(action, 0) + 1
            if isinstance(ev.get("knobs"), dict):
                tn["knobs"] = ev["knobs"]
            if action != "hold":
                tn["last"] = ev
        elif kind == "optimize":
            out["plan_decisions"] += len(ev.get("decisions") or []) or 1
        elif kind == "trace_window":
            if ev.get("status") == "started":
                out["trace_windows"] += 1
        elif kind == "device_memory":
            out["devices"] = ev.get("devices") or out["devices"]
            if ev.get("peak_bytes"):
                out["hbm_peak_bytes"] = max(
                    out["hbm_peak_bytes"] or 0, ev["peak_bytes"]
                )
    return out


def render(state: dict[str, Any], run_dir: str) -> str:
    lines: list[str] = []
    age = ""
    if state["last_ts"]:
        age = f"  last update {max(time.time() - state['last_ts'], 0.0):.1f}s ago"
    lines.append(
        f"run {state['run'] or '?'}  [{run_dir}]  "
        f"status={state['status']}  events={state['n_events']}{age}"
    )
    lines.append("")
    if state["n_steps"]:
        loss = state["loss"]
        lines.append(
            f"steps {state['last_step']}"
            + (f"  {state['steps_per_s']:.2f} steps/s"
               if state["steps_per_s"] else "")
            + (f"  {_fmt_rate(state['tokens_per_s'], 'tok/s')}"
               if state["tokens_per_s"] else "")
            + (f"  mfu {state['mfu']:.3f}" if state["mfu"] is not None else "")
            + (f"  loss {loss:.4f}" if isinstance(loss, (int, float)) else "")
        )
        spark = sparkline(state["losses"])
        if spark:
            finite = [
                v
                for v in state["losses"][-_LOSS_WINDOW:]
                if isinstance(v, (int, float)) and math.isfinite(v)
            ] or [0.0]
            lines.append(
                f"loss  {spark}  [{min(finite):.3f} .. {max(finite):.3f}]"
            )
    else:
        lines.append("steps (no step telemetry yet)")
    lines.append("")
    if state["devices"] or state["hbm_peak_bytes"]:
        lines.append("hbm watermarks:")
        for d in state["devices"]:
            # .get throughout: device_memory events are free-form (any
            # writer version, or hand-emitted) and the dashboard must
            # not die mid-watch on a missing field
            limit = d.get("bytes_limit") or 0
            peak = d.get("peak_bytes_in_use") or 0
            pct = (
                f"  ({100.0 * peak / limit:.0f}% of {_fmt_bytes(limit)})"
                if limit
                else ""
            )
            lines.append(
                f"  {d.get('device', '?'):12} "
                f"in-use {_fmt_bytes(d.get('bytes_in_use')):>10}"
                f"  peak {_fmt_bytes(peak):>10}{pct}"
            )
        if not state["devices"]:
            lines.append(f"  peak {_fmt_bytes(state['hbm_peak_bytes'])}")
        lines.append("")
    if state.get("alerts"):
        pairs = "  ".join(
            f"{k}={v}" for k, v in sorted(state["alerts"].items())
        )
        lines.append(f"ALERTS: {pairs}")
        last = state.get("last_alert") or {}
        detail = "  ".join(
            f"{k}={v}"
            for k, v in last.items()
            if k not in ("event", "ts", "run", "phase", "action")
            and v is not None
        )
        if detail:
            lines.append(f"  last: {last.get('action', '?')}  {detail}")
    if state["resilience"]:
        pairs = "  ".join(
            f"{k}={v}" for k, v in sorted(state["resilience"].items())
        )
        lines.append(f"resilience: {pairs}")
    if state.get("cluster"):
        pairs = "  ".join(
            f"{k}={v}" for k, v in sorted(state["cluster"].items())
        )
        lines.append(f"cluster: {pairs}")
    sv = state.get("serve") or {}
    if sv:
        head = "serving:"
        if sv.get("model"):
            head += f" {sv['model']}"
        if sv.get("port"):
            head += f" @ :{sv['port']}"
        if sv.get("version"):
            head += f"  model={sv['version']}"
        if sv.get("status"):
            head += f"  [{sv['status']}]"
        if isinstance(sv.get("cold_start_s"), (int, float)):
            head += f"  cold start {sv['cold_start_s']:.2f}s"
        if sv.get("swaps") or sv.get("rollbacks"):
            head += (
                f"  swaps={sv.get('swaps', 0)}"
                + (
                    f" rollbacks={sv['rollbacks']}"
                    if sv.get("rollbacks")
                    else ""
                )
            )
        lines.append(head)
        parts = []
        if sv.get("batches"):
            parts.append(
                f"{sv['batches']} batch(es)  {sv.get('rows', 0)} row(s)"
            )
            if isinstance(sv.get("batch_fill"), (int, float)):
                parts.append(f"fill {sv['batch_fill']:.2f}")
        if sv.get("generations"):
            parts.append(
                f"{sv['generations']} generation(s)  "
                f"{sv.get('tokens', 0)} tok"
            )
        if parts:
            lines.append("  " + "  ".join(parts))
    fl = state.get("fleet") or {}
    if fl:
        head = "fleet:"
        reps = fl.get("replicas") or {}
        if reps:
            up = sum(
                1 for r in reps.values() if r.get("state") == "up"
            )
            head += f" {up}/{len(reps)} up"
        counters = "  ".join(
            f"{k}={fl[k]}"
            for k in ("routed", "shed", "failover", "hedges")
            if fl.get(k) is not None
        )
        if counters:
            head += "  " + counters
        lines.append(head)
        for rid in sorted(reps, key=str):
            r = reps[rid]
            port = f" :{r['port']}" if r.get("port") else ""
            restarts = (
                f"  restarts={r['restarts']}" if r.get("restarts") else ""
            )
            lines.append(
                f"  r{rid}{port}  {r.get('state', '?')}{restarts}"
            )
        if fl.get("events"):
            lines.append(
                "  "
                + "  ".join(
                    f"{k.removeprefix('fleet_')}={v}"
                    for k, v in sorted(fl["events"].items())
                )
            )
    tn = state.get("tune") or {}
    if tn:
        head = "autotuner:"
        for k, v in sorted((tn.get("knobs") or {}).items()):
            head += f" {k}={v}"
        head += (
            f"  decisions={tn.get('decisions', 0)}"
            + (f" adjusts={tn['adjust']}" if tn.get("adjust") else "")
            + (f" reverts={tn['revert']}" if tn.get("revert") else "")
        )
        lines.append(head)
        last = tn.get("last")
        if last:
            detail = "  ".join(
                f"{k}={v}"
                for k, v in last.items()
                if k not in ("event", "ts", "run", "action", "knobs")
                and v is not None
            )
            lines.append(f"  last: {last.get('action', '?')}  {detail}")
    if state["plan_decisions"] or state.get("plan_streams"):
        parts = []
        if state["plan_decisions"]:
            parts.append(f"{state['plan_decisions']} decision(s)")
        if state.get("plan_streams"):
            parts.append(f"{state['plan_streams']} chunk stream(s)")
        lines.append("plan: " + "  ".join(parts))
    if state["trace_windows"]:
        lines.append(f"profiler: {state['trace_windows']} trace window(s)")
    return "\n".join(lines)


def resolve_run_dir(path: str) -> str:
    """Like :func:`events.resolve_run_dir` but also accepts a run that
    (so far) only has ``steps.jsonl`` — a crashed writer's run must
    still be inspectable."""
    try:
        return _events.resolve_run_dir(path)
    except (FileNotFoundError, NotADirectoryError):
        if os.path.isfile(os.path.join(path, _telemetry.STEPS_FILE)):
            return path
        candidates = [
            os.path.join(path, d)
            for d in (os.listdir(path) if os.path.isdir(path) else ())
            if os.path.isfile(os.path.join(path, d, _telemetry.STEPS_FILE))
        ]
        if not candidates:
            raise
        return max(candidates, key=os.path.getmtime)


def main(argv: list[str] | None = None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    once = "--once" in argv
    if once:
        argv.remove("--once")
    interval = 2.0
    if "--interval" in argv:
        i = argv.index("--interval")
        if i + 1 >= len(argv):
            raise SystemExit("--interval needs a seconds argument")
        try:
            interval = float(argv[i + 1])
        except ValueError:
            raise SystemExit(
                f"--interval: bad seconds value {argv[i + 1]!r}"
            ) from None
        del argv[i : i + 2]
    if not argv or argv[0] in ("-h", "--help"):
        raise SystemExit(
            "usage: python -m keystone_tpu observe top <run-dir> "
            "[--once] [--interval S]\n"
            "<run-dir> is a directory containing steps.jsonl/events.jsonl,"
            "\nor a base KEYSTONE_OBSERVE_DIR — fleet mode: every LIVE\n"
            "run dir under it is tailed as one merged stream, and new\n"
            "ones (replica relaunches, rolling restarts) appear without\n"
            "a restart; with no live run, the newest finished one shows"
        )
    path = argv[0]
    fleet: FleetTails | None = None
    if os.path.isdir(path) and not any(
        os.path.isfile(os.path.join(path, f))
        for f in (_telemetry.STEPS_FILE, _events.EVENTS_FILE)
    ):
        # a BASE observe dir: fleet mode — tail every run dir under it
        # and keep rediscovering, so replicas relaunched mid-watch (a
        # rolling restart mints fresh run dirs) appear live
        fleet = FleetTails(path)
        fleet._discover()
        if not fleet._tails:
            try:
                resolve_run_dir(path)  # raise the canonical error
            except OSError as e:
                raise SystemExit(str(e)) from None
    if fleet is None:
        try:
            run_dir = resolve_run_dir(path)
        except OSError as e:
            raise SystemExit(str(e)) from None
        steps = Tail(os.path.join(run_dir, _telemetry.STEPS_FILE))
        events = Tail(os.path.join(run_dir, _events.EVENTS_FILE))
    while True:
        if fleet is not None:
            step_recs, event_recs = fleet.poll()
            label = f"{path} [{fleet.run_count} run dir(s)]"
        else:
            step_recs, event_recs = steps.poll(), events.poll()
            label = run_dir
        state = summarize(step_recs, event_recs)
        screen = render(state, label)
        if once:
            print(screen)
            return
        # ANSI clear + home: refresh in place without curses
        sys.stdout.write("\x1b[2J\x1b[H" + screen + "\n")
        sys.stdout.flush()
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return
