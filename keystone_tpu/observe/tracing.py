"""Programmatic XLA profiler trace windows for training loops.

``core.profiling.trace`` brackets a whole code block; a multi-hour train
loop needs the opposite — profile *10 steps starting at step 120*
without restarting the run. Two triggers:

- ``KEYSTONE_PROFILE_STEPS="120:10"`` — capture 10 steps starting at
  step 120. Comma-separate multiple windows (``"120:10,5000:5"``).
- ``SIGUSR2`` — arm an on-demand window at the next step boundary
  (default :data:`DEFAULT_SIGNAL_STEPS` steps), for the "why is it slow
  *right now*" case.

Traces land under ``<base>/step_<start>/`` where ``<base>`` is, in
order: an explicit ``log_dir``, ``KEYSTONE_TRACE_DIR``, or a ``traces/``
subdirectory of the active observe run. The ``KEYSTONE_TRACE_DIR`` kill
switch (``0``/``off``/empty — see :mod:`keystone_tpu.core.profiling`)
disables every window. All profiler failures degrade to one warning and
an unprofiled run, PR 1's ``trace()`` invariant.
"""

from __future__ import annotations

import os
import threading
from typing import Any

from keystone_tpu.core.logging import get_logger
from keystone_tpu.core.profiling import ENV_TRACE_DIR, _DISABLED_VALUES
from keystone_tpu.observe import events as _events
from keystone_tpu.observe import metrics as _metrics

logger = get_logger("keystone_tpu.observe.tracing")

ENV_PROFILE_STEPS = "KEYSTONE_PROFILE_STEPS"
DEFAULT_SIGNAL_STEPS = 10


def parse_windows(spec: str) -> list[tuple[int, int]]:
    """Parse ``"start:steps[,start:steps...]"`` → ``[(start, steps)]``.

    Raises ``ValueError`` on malformed specs (non-integer, non-positive
    step count, negative start) so a typo is reported, not ignored."""
    out: list[tuple[int, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        head, sep, tail = part.partition(":")
        if not sep:
            raise ValueError(
                f"bad {ENV_PROFILE_STEPS} window {part!r}: expected "
                "'start:steps' (e.g. '120:10')"
            )
        try:
            start, steps = int(head), int(tail)
        except ValueError:
            raise ValueError(
                f"bad {ENV_PROFILE_STEPS} window {part!r}: start and "
                "steps must be integers"
            ) from None
        if start < 0 or steps <= 0:
            raise ValueError(
                f"bad {ENV_PROFILE_STEPS} window {part!r}: start must be "
                ">= 0 and steps > 0"
            )
        out.append((start, steps))
    return sorted(out)


class StepTracer:
    """Starts/stops ``jax.profiler`` traces around step windows.

    Call :meth:`step` with the upcoming step index at the TOP of every
    loop iteration — a window ``(s, n)`` then brackets the dispatch of
    steps ``[s, s+n)``. The idle cost per step is one flag check plus a
    scan of the (tiny) un-fired window list; with no windows configured
    and no signal installed, :meth:`from_env` returns None and the loop
    skips even that.
    """

    def __init__(
        self,
        windows: list[tuple[int, int]] | None = None,
        log_dir: str | None = None,
        signal_steps: int = DEFAULT_SIGNAL_STEPS,
        label: str = "train",
    ):
        self._windows = [
            {"start": s, "steps": n, "fired": False}
            for s, n in (windows or [])
        ]
        self.log_dir = log_dir
        self.signal_steps = signal_steps
        self.label = label
        self._requested = False  # SIGUSR2 arms this; next step() fires
        self._active_dir: str | None = None
        self._active_start = 0
        self._stop_at = 0
        self._prev_handler: Any = None
        self._signum: int | None = None

    # ----------------------------------------------------------- set-up

    @classmethod
    def from_env(
        cls,
        log_dir: str | None = None,
        install_signal: bool = False,
        label: str = "train",
    ) -> "StepTracer | None":
        """Build a tracer from ``KEYSTONE_PROFILE_STEPS``; installs the
        ``SIGUSR2`` handler when asked (main thread only — the caller
        checks). Returns None when there is nothing to do, so the train
        loop pays zero per-step cost. A malformed spec warns and is
        dropped — observability must not abort the run it watches."""
        spec = os.environ.get(ENV_PROFILE_STEPS, "")
        windows: list[tuple[int, int]] = []
        if spec:
            try:
                windows = parse_windows(spec)
            except ValueError as e:
                logger.warning("%s; profiling windows disabled", e)
        tracer = cls(windows, log_dir=log_dir, label=label)
        if install_signal:
            tracer.install_signal()
        if not windows and tracer._signum is None:
            return None
        return tracer

    def install_signal(self) -> None:
        """Arm ``SIGUSR2`` → on-demand window (no-op where the platform
        or thread context has no SIGUSR2)."""
        import signal as _signal

        if not hasattr(_signal, "SIGUSR2"):
            return
        if threading.current_thread() is not threading.main_thread():
            return
        def _on_usr2(signum, frame):  # noqa: ARG001
            self.request()

        try:
            self._prev_handler = _signal.signal(_signal.SIGUSR2, _on_usr2)
            self._signum = _signal.SIGUSR2
        except (ValueError, OSError):  # non-main thread raced us
            self._prev_handler = None
            self._signum = None

    def request(self, steps: int | None = None) -> None:
        """Arm an on-demand window starting at the next step boundary
        (what the SIGUSR2 handler calls; async-signal-safe: one flag)."""
        if steps is not None:
            self.signal_steps = steps
        self._requested = True

    # --------------------------------------------------------- per step

    def step(self, step: int) -> None:
        """Advance to ``step`` (about to dispatch): stop an expired
        window, then start a due one."""
        if self._active_dir is not None and step >= self._stop_at:
            self._stop_trace(step)
        if self._active_dir is not None:
            # mid-window: leave a pending SIGUSR2 request armed (it
            # fires at the first free step boundary) and env windows
            # un-fired rather than consuming them unstartable
            return
        want: tuple[int, int, str] | None = None
        if self._requested:
            self._requested = False
            want = (step, self.signal_steps, "sigusr2")
        else:
            for w in self._windows:
                if not w["fired"] and step >= w["start"]:
                    w["fired"] = True
                    # resume past the window's tail: nothing left to grab
                    if step < w["start"] + w["steps"]:
                        want = (step, w["start"] + w["steps"] - step, "env")
                    break
        if want is not None:
            self._start_trace(*want)

    def close(self) -> None:
        """Stop any in-flight window and restore the signal handler."""
        if self._active_dir is not None:
            self._stop_trace(self._stop_at)
        if self._signum is not None:
            import signal as _signal

            try:
                _signal.signal(self._signum, self._prev_handler)
            except (ValueError, OSError):
                pass
            self._signum = None

    # ---------------------------------------------------------- plumbing

    def _base_dir(self) -> str | None:
        env = os.environ.get(ENV_TRACE_DIR)
        if env is not None and env.lower() in _DISABLED_VALUES:
            return None  # the production kill switch beats everything
        if self.log_dir:
            return self.log_dir
        if env:
            return env
        log = _events.active()
        if log is not None and log.run_dir:
            return os.path.join(log.run_dir, "traces")
        return None

    def _start_trace(self, step: int, n_steps: int, reason: str) -> None:
        base = self._base_dir()
        if base is None:
            logger.warning(
                "profile window at step %d requested but no trace "
                "directory is configured (set %s or run under an "
                "observe sink); skipping",
                step,
                ENV_TRACE_DIR,
            )
            return
        trace_dir = os.path.join(base, f"step_{step}")
        try:
            import jax

            jax.profiler.start_trace(trace_dir)
        except Exception as e:  # noqa: BLE001 — degrade, don't abort
            logger.warning(
                "profiler trace to %s unavailable (%r); running "
                "unprofiled",
                trace_dir,
                e,
            )
            return
        self._active_dir = trace_dir
        self._active_start = step
        self._stop_at = step + n_steps
        _metrics.get_registry().counter(
            "trace_windows", reason=reason
        ).inc()
        log = _events.active()
        if log is not None:
            log.emit(
                "trace_window",
                status="started",
                step=step,
                steps=n_steps,
                reason=reason,
                dir=trace_dir,
                label=self.label,
            )

    def _stop_trace(self, step: int) -> None:
        trace_dir, start = self._active_dir, self._active_start
        self._active_dir = None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            logger.warning("profiler stop_trace failed: %r", e)
            status = "failed"
        else:
            logger.info(
                "profile of steps %d-%d written to %s",
                start,
                step - 1,
                trace_dir,
            )
            status = "ok"
        log = _events.active()
        if log is not None:
            log.emit(
                "trace_window",
                status=status,
                step=start,
                steps=step - start,
                dir=trace_dir,
                label=self.label,
            )
