"""Per-node cost-profile registry — the KeystoneML operator profile,
TPU-native.

KeystoneML's optimizer samples each operator's time, memory, and output
size at runtime to drive caching and materialization decisions. On TPU
the compiler already knows most of that statically: lowering a jitted
node and asking the compiled executable for ``cost_analysis()`` (FLOPs,
bytes accessed) and ``memory_analysis()`` (argument/output/temp bytes)
yields the operator profile without running anything. This module
collects those profiles per pipeline node into a process-wide registry
and persists them next to the event log (``cost_profiles.json``) so
:mod:`.report` can join wall-time events against modeled FLOPs — the
substrate any principled fusion/caching decision in ``core/fusion.py``
needs.

Profile schema per node label::

    {"flops": float, "bytes_accessed": float, "argument_bytes": int,
     "output_bytes": int, "temp_bytes": int, "peak_bytes": int,
     "input_shapes": [...], "error": str (only when analysis failed)}
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable

import jax

from keystone_tpu.observe import events as _events

COST_FILE = "cost_profiles.json"


def _shapes(tree: Any) -> list[str]:
    out = []
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        out.append(
            f"{dtype}{list(shape)}" if shape is not None else type(leaf).__name__
        )
    return out


def analyze(fn: Callable, *args: Any, **kwargs: Any) -> dict:
    """Lower+compile ``fn(*args, **kwargs)`` and extract its cost profile.

    ``fn`` is jitted here (wrapping an already-jitted callable is fine —
    ``jax.jit`` of a jitted function reuses the inner trace). Analysis
    failures are captured as an ``{"error": ...}`` profile rather than
    raised: a node the compiler can't cost (host callbacks, non-jax
    python) should not abort profile collection for the rest.
    """
    try:
        compiled = jax.jit(fn).lower(*args, **kwargs).compile()
        cost = compiled.cost_analysis()
        # jax returns one dict per computation on some versions, a bare
        # dict on others; the entry computation comes first
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        cost = cost or {}
        profile: dict[str, Any] = {
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        }
        try:
            mem = compiled.memory_analysis()
        except Exception:  # noqa: BLE001 — backend without memory stats
            mem = None
        if mem is not None:
            arg_b = int(getattr(mem, "argument_size_in_bytes", 0))
            out_b = int(getattr(mem, "output_size_in_bytes", 0))
            tmp_b = int(getattr(mem, "temp_size_in_bytes", 0))
            profile.update(
                argument_bytes=arg_b,
                output_bytes=out_b,
                temp_bytes=tmp_b,
                peak_bytes=arg_b + out_b + tmp_b,
            )
        return profile
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {str(e)[:200]}"}


class CostProfileRegistry:
    """Thread-safe map of node label → cost profile for one process."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._profiles: dict[str, dict] = {}
        self.device_kind: str | None = None
        self.num_devices: int | None = None

    def record(self, label: str, profile: dict) -> dict:
        with self._lock:
            self._profiles[label] = profile
        return profile

    def get(self, label: str) -> dict | None:
        """One node's recorded profile (the planner's join point — the
        cost source of choice before it falls back to a sampled pass)."""
        with self._lock:
            return self._profiles.get(label)

    def profile_node(self, node: Callable, batch: Any, label: str | None = None) -> dict:
        """Cost-profile one node applied to ``batch``. The node travels
        as a jit argument (pytree), matching how fitted nodes execute."""
        label = label or _events.node_label(node)
        profile = analyze(lambda n, b: n(b), node, batch)
        profile["input_shapes"] = _shapes(batch)
        return self.record(label, profile)

    def profile_pipeline(self, pipe, batch: Any) -> dict[str, dict]:
        """Profile each node of a fitted pipeline in sequence, feeding
        each node's (eagerly computed) output to the next so every
        profile reflects the shapes the node actually sees."""
        nodes = getattr(pipe, "nodes", None)
        if nodes is None:
            nodes = (pipe,)
        self._note_devices()
        from keystone_tpu.observe.instrument import InstrumentedNode

        out: dict[str, dict] = {}
        for i, node in enumerate(nodes):
            inner = node.inner if isinstance(node, InstrumentedNode) else node
            label = _events.node_label(inner, i)
            out[label] = self.profile_node(inner, batch, label=label)
            try:
                batch = inner(batch)
            except Exception as e:  # noqa: BLE001 — can't feed further nodes
                out[label].setdefault(
                    "error", f"apply failed: {type(e).__name__}"
                )
                break
        return out

    def _note_devices(self) -> None:
        try:
            devs = jax.devices()
            self.device_kind = devs[0].device_kind
            self.num_devices = len(devs)
        except Exception:  # noqa: BLE001 — backend init failure
            pass

    def snapshot(self) -> dict:
        with self._lock:
            profiles = dict(self._profiles)
        return {
            "device_kind": self.device_kind,
            "num_devices": self.num_devices,
            "profiles": profiles,
        }

    def save(self, run_dir: str) -> str:
        """Persist to ``<run_dir>/cost_profiles.json`` (atomic rename)."""
        path = os.path.join(run_dir, COST_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.snapshot(), f, indent=1)
        os.replace(tmp, path)
        return path

    def reset(self) -> None:
        with self._lock:
            self._profiles.clear()


_registry = CostProfileRegistry()


def get_cost_registry() -> CostProfileRegistry:
    return _registry


def record_pipeline_profile(
    pipe,
    probe: Any,
    registry: CostProfileRegistry | None = None,
    save_dir: str | None = None,
    sync: bool = True,
) -> dict[str, dict]:
    """One-call operator-profile sample for a fitted pipeline: an
    instrumented apply of ``probe`` (per-node wall-time events into the
    active sink + metrics) followed by per-node compiler cost profiles,
    optionally persisted to ``save_dir``. Uses a FRESH registry by
    default so one run's ``cost_profiles.json`` can't carry stale nodes
    from earlier runs in the same process. The probe passes through the
    pipeline twice (timed apply, then the profile feed-forward) — keep
    it bounded."""
    from keystone_tpu.observe.instrument import instrument

    registry = registry or CostProfileRegistry()
    instrument(pipe, sync=sync)(probe)
    profiles = registry.profile_pipeline(pipe, probe)
    if save_dir is not None:
        registry.save(save_dir)
    return profiles


def load_profiles(run_dir: str) -> dict:
    """Read a persisted ``cost_profiles.json``; empty snapshot shape when
    the run recorded none."""
    try:
        with open(os.path.join(run_dir, COST_FILE)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"device_kind": None, "num_devices": None, "profiles": {}}
