"""Declarative SLOs with multi-window, multi-burn-rate alerting.

The fleet's health question is not "did a request fail" (the anomaly
monitor answers that per process) but "is the error budget burning fast
enough that a human must act before it is gone" — the SRE burn-rate
formulation. This module evaluates it over the collector's time-series
store (:mod:`.timeseries`):

- an **objective** declares what fraction of outcomes must be good
  (availability: requests that didn't fail; latency: requests under a
  threshold; goodput: throughput samples above a floor),
- a **burn rate** is the window's bad fraction divided by the error
  budget (``1 - target``) — burn 1.0 spends the budget exactly at its
  sustainable rate,
- an alert **fires** only when BOTH a short and a long window exceed
  the speed's factor (fast: 5m-over-1h at 14.4x, slow: 1h-over-6h at
  6x by default) — the long window keeps a blip from paging, the short
  window makes recovery reset the alert promptly,
- every firing alert carries a **trace exemplar** — the trace/request
  id of a concrete offending request in the short window — so
  ``observe trace <dir> --request <rid>`` jumps straight from the page
  to the causal span tree.

Verdicts are pure functions of (store contents, injected clock), so the
tests drive fast-fires / slow-holds / recovery-clears with zero sleeps.
Transitions (fired → cleared) emit one ``alert`` event each through the
resilience emit schema — the same stream ``observe top`` and the run
report already render.

Config: :func:`SLOConfig.default` builds from ``KEYSTONE_SLO_*`` env
knobs; :func:`SLOConfig.from_file` reads a declarative JSON file (see
the README's example) with env knobs still applied on top.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable

from keystone_tpu.observe.timeseries import TimeSeriesStore

#: store series the collector ingests request outcomes into (one point
#: per serve.request / fleet.forward span: value = wall seconds, attrs
#: ok/trace/rid)
REQUEST_SERIES = "slo.requests"
#: throughput samples (tokens_per_s / rows_per_s from tailed step rows)
GOODPUT_SERIES = "slo.goodput"
#: SLO alert transitions persisted by the collector (value 1 = fired,
#: 0 = cleared) — what ``observe slo`` and the dashboard list as history
ALERT_SERIES = "slo.alert"

ENV_CONFIG = "KEYSTONE_SLO_CONFIG"
ENV_AVAILABILITY = "KEYSTONE_SLO_AVAILABILITY"
ENV_LATENCY_MS = "KEYSTONE_SLO_LATENCY_MS"
ENV_LATENCY_TARGET = "KEYSTONE_SLO_LATENCY_TARGET"
ENV_GOODPUT_FLOOR = "KEYSTONE_SLO_GOODPUT_FLOOR"
ENV_GOODPUT_TARGET = "KEYSTONE_SLO_GOODPUT_TARGET"
ENV_FAST_FACTOR = "KEYSTONE_SLO_FAST_FACTOR"
ENV_SLOW_FACTOR = "KEYSTONE_SLO_SLOW_FACTOR"
ENV_WINDOW_SCALE = "KEYSTONE_SLO_WINDOW_SCALE"
ENV_MIN_POINTS = "KEYSTONE_SLO_MIN_POINTS"


def _env_float(name: str) -> float | None:
    raw = os.environ.get(name, "").strip()
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return None


@dataclasses.dataclass(frozen=True)
class BurnWindow:
    """One alerting speed: short window (prompt detection + prompt
    recovery) gated by a long window (blip suppression)."""

    name: str
    short_s: float
    long_s: float
    factor: float


# the classic SRE pair: fast pages on 14.4x burn over 5m-and-1h (2% of
# a 30-day budget gone in an hour), slow tickets on 6x over 1h-and-6h
DEFAULT_FAST = BurnWindow("fast", 300.0, 3600.0, 14.4)
DEFAULT_SLOW = BurnWindow("slow", 3600.0, 21600.0, 6.0)


@dataclasses.dataclass
class Objective:
    """One declarative objective over one store series."""

    name: str
    kind: str  # "availability" | "latency" | "goodput"
    target: float = 0.999  # required good fraction
    threshold_s: float | None = None  # latency: bad above this wall
    floor: float | None = None  # goodput: bad below this rate
    series: str = ""
    min_points: int = 6  # short-window sample floor before verdicts arm

    def __post_init__(self) -> None:
        if not self.series:
            self.series = (
                GOODPUT_SERIES if self.kind == "goodput" else REQUEST_SERIES
            )
        if self.kind not in ("availability", "latency", "goodput"):
            raise ValueError(
                f"objective {self.name!r}: unknown kind {self.kind!r}"
            )
        if self.kind == "latency" and self.threshold_s is None:
            raise ValueError(
                f"latency objective {self.name!r} needs threshold_s"
            )
        if self.kind == "goodput" and self.floor is None:
            raise ValueError(f"goodput objective {self.name!r} needs floor")

    def budget(self) -> float:
        return max(1.0 - float(self.target), 1e-9)

    def is_good(self, point: dict) -> bool:
        """Classify one store point. A failed request is bad for the
        latency objective too — a request that errored never met its
        latency promise."""
        if self.kind == "availability":
            return bool(point.get("ok", True))
        if self.kind == "latency":
            if not point.get("ok", True):
                return False
            return float(point.get("value") or 0.0) <= self.threshold_s
        return float(point.get("value") or 0.0) >= self.floor

    def exemplar_of(self, bad_points: list[dict]) -> dict | None:
        """The one offending point an alert should link to: the slowest
        bad request for latency, the lowest sample for goodput, the
        newest failure for availability (the freshest lead)."""
        if not bad_points:
            return None
        if self.kind == "latency":
            return max(bad_points, key=lambda p: float(p.get("value") or 0.0))
        if self.kind == "goodput":
            return min(bad_points, key=lambda p: float(p.get("value") or 0.0))
        return bad_points[-1]


def _apply_min_points(objectives: list[Objective]) -> list[Objective]:
    """``KEYSTONE_SLO_MIN_POINTS`` overrides every objective's arming
    floor — the low-traffic-tier knob (6-sample windows paging a quiet
    fleet is noise, not signal)."""
    mp = _env_float(ENV_MIN_POINTS)
    if mp is not None:
        for o in objectives:
            o.min_points = max(int(mp), 1)
    return objectives


def default_objectives() -> list[Objective]:
    """The env-driven objective set: availability + latency always,
    goodput floor only when ``KEYSTONE_SLO_GOODPUT_FLOOR`` names one."""
    out = [
        Objective(
            "availability",
            "availability",
            target=_env_float(ENV_AVAILABILITY) or 0.999,
        ),
        Objective(
            "latency",
            "latency",
            target=_env_float(ENV_LATENCY_TARGET) or 0.95,
            threshold_s=(_env_float(ENV_LATENCY_MS) or 500.0) / 1e3,
        ),
    ]
    floor = _env_float(ENV_GOODPUT_FLOOR)
    if floor is not None:
        out.append(
            Objective(
                "goodput",
                "goodput",
                target=_env_float(ENV_GOODPUT_TARGET) or 0.9,
                floor=floor,
            )
        )
    return _apply_min_points(out)


@dataclasses.dataclass
class SLOConfig:
    objectives: list[Objective]
    windows: list[BurnWindow]

    @classmethod
    def default(cls) -> "SLOConfig":
        """Env-knob config (``KEYSTONE_SLO_*``); honors a declarative
        file named by ``KEYSTONE_SLO_CONFIG`` first."""
        path = os.environ.get(ENV_CONFIG, "").strip()
        if path:
            return cls.from_file(path)
        return cls(default_objectives(), _windows_from_env())

    @classmethod
    def from_file(cls, path: str) -> "SLOConfig":
        """Declarative JSON config::

            {"objectives": [
                {"name": "availability", "kind": "availability",
                 "target": 0.999},
                {"name": "latency", "kind": "latency",
                 "target": 0.95, "threshold_ms": 250},
                {"name": "goodput", "kind": "goodput",
                 "target": 0.9, "floor": 1000.0}],
             "fast": {"short_s": 300, "long_s": 3600, "factor": 14.4},
             "slow": {"short_s": 3600, "long_s": 21600, "factor": 6.0}}

        ``KEYSTONE_SLO_FAST_FACTOR`` / ``_SLOW_FACTOR`` /
        ``_WINDOW_SCALE`` / ``_MIN_POINTS`` still apply on top, so one
        ops override never requires editing the committed file."""
        with open(path) as f:
            raw = json.load(f)
        objectives: list[Objective] = []
        for spec in raw.get("objectives") or []:
            spec = dict(spec)
            if "threshold_ms" in spec:
                spec["threshold_s"] = float(spec.pop("threshold_ms")) / 1e3
            objectives.append(
                Objective(
                    name=str(spec.get("name") or spec.get("kind")),
                    kind=str(spec.get("kind")),
                    target=float(spec.get("target", 0.999)),
                    threshold_s=spec.get("threshold_s"),
                    floor=spec.get("floor"),
                    series=str(spec.get("series") or ""),
                    min_points=int(spec.get("min_points", 6)),
                )
            )
        if not objectives:
            objectives = default_objectives()
        else:
            _apply_min_points(objectives)
        windows = _windows_from_env(
            fast=_window_from(raw.get("fast"), DEFAULT_FAST),
            slow=_window_from(raw.get("slow"), DEFAULT_SLOW),
        )
        return cls(objectives, windows)


def _window_from(spec: dict | None, base: BurnWindow) -> BurnWindow:
    if not spec:
        return base
    return BurnWindow(
        base.name,
        float(spec.get("short_s", base.short_s)),
        float(spec.get("long_s", base.long_s)),
        float(spec.get("factor", base.factor)),
    )


def _windows_from_env(
    fast: BurnWindow = DEFAULT_FAST, slow: BurnWindow = DEFAULT_SLOW
) -> list[BurnWindow]:
    scale = _env_float(ENV_WINDOW_SCALE) or 1.0
    fast_factor = _env_float(ENV_FAST_FACTOR) or fast.factor
    slow_factor = _env_float(ENV_SLOW_FACTOR) or slow.factor
    return [
        BurnWindow("fast", fast.short_s * scale, fast.long_s * scale, fast_factor),
        BurnWindow("slow", slow.short_s * scale, slow.long_s * scale, slow_factor),
    ]


class SLOEngine:
    """Evaluates every (objective, speed) pair against the store and
    emits ``alert`` events on firing/cleared TRANSITIONS only — a burn
    that stays high across evaluations pages once, and recovery says so
    exactly once.

    ``emit=False`` collects verdicts without events/counters — the
    read-only form the ``observe slo`` CLI and the dashboard use against
    a store some other process's collector owns.
    """

    def __init__(
        self,
        store: TimeSeriesStore,
        config: SLOConfig | None = None,
        *,
        clock: Callable[[], float] = time.time,
        emit: bool = True,
    ):
        self.store = store
        self.config = config or SLOConfig.default()
        self.clock = clock
        self.emit = emit
        self.alerts: list[dict] = []  # transition history, oldest first
        self._firing: set[tuple[str, str]] = set()

    # ---------------------------------------------------------- verdicts

    def _burn(
        self, obj: Objective, points: list[dict], start: float, end: float
    ) -> dict:
        """Burn rate of one window over pre-fetched points (one store
        query per objective covers every window of both speeds)."""
        good = bad = 0
        bad_points: list[dict] = []
        for p in points:
            ts = p.get("ts")
            if not isinstance(ts, (int, float)) or ts < start or ts > end:
                continue
            if obj.is_good(p):
                good += 1
            else:
                bad += 1
                bad_points.append(p)
        total = good + bad
        rate = bad / total if total else 0.0
        return {
            "burn": rate / obj.budget(),
            "rate": rate,
            "total": total,
            "bad": bad,
            "exemplar": obj.exemplar_of(bad_points),
        }

    def evaluate(self, now: float | None = None) -> list[dict]:
        """One evaluation pass: a verdict per (objective, speed), with
        ``transition`` set on the passes where the state flipped."""
        now = self.clock() if now is None else float(now)
        verdicts: list[dict] = []
        max_window = max(
            (w.long_s for w in self.config.windows), default=0.0
        )
        # one disk read per SERIES, not per objective: availability and
        # latency both consume slo.requests over the same range
        points_by_series: dict[str, list[dict]] = {}
        for obj in self.config.objectives:
            points = points_by_series.get(obj.series)
            if points is None:
                points = points_by_series[obj.series] = self.store.query(
                    obj.series, start=now - max_window, end=now
                )
            for w in self.config.windows:
                short = self._burn(obj, points, now - w.short_s, now)
                long = self._burn(obj, points, now - w.long_s, now)
                firing = (
                    short["total"] >= obj.min_points
                    and short["burn"] > w.factor
                    and long["burn"] > w.factor
                )
                exemplar = short["exemplar"] or long["exemplar"] or {}
                verdict: dict[str, Any] = {
                    "objective": obj.name,
                    "kind": obj.kind,
                    "speed": w.name,
                    "factor": w.factor,
                    "short_s": w.short_s,
                    "long_s": w.long_s,
                    "burn_short": round(short["burn"], 4),
                    "burn_long": round(long["burn"], 4),
                    "error_rate": round(short["rate"], 4),
                    "total": short["total"],
                    "bad": short["bad"],
                    "target": obj.target,
                    "firing": firing,
                    "transition": None,
                }
                if exemplar:
                    if exemplar.get("trace"):
                        verdict["exemplar_trace"] = exemplar["trace"]
                    if exemplar.get("rid") is not None:
                        verdict["exemplar_rid"] = exemplar["rid"]
                key = (obj.name, w.name)
                if firing and key not in self._firing:
                    self._firing.add(key)
                    verdict["transition"] = "fired"
                    self._transition(verdict, "firing", now)
                elif not firing and key in self._firing:
                    self._firing.discard(key)
                    verdict["transition"] = "cleared"
                    self._transition(verdict, "cleared", now)
                verdicts.append(verdict)
        return verdicts

    def _transition(self, verdict: dict, state: str, now: float) -> None:
        action = f"slo.{verdict['objective']}.{verdict['speed']}_burn"
        rec = {"ts": now, "action": action, "state": state, **verdict}
        self.alerts.append(rec)
        if not self.emit:
            return
        from keystone_tpu.resilience.emit import decision

        detail = {
            k: verdict[k]
            for k in (
                "burn_short",
                "burn_long",
                "factor",
                "short_s",
                "long_s",
                "error_rate",
                "total",
                "bad",
                "target",
                "exemplar_trace",
                "exemplar_rid",
            )
            if verdict.get(k) is not None
        }
        decision(
            action,
            counter="alerts",
            counter_labels={"kind": action},
            event_kind="alert",
            phase="slo",
            state=state,
            objective=verdict["objective"],
            speed=verdict["speed"],
            **detail,
        )


# --------------------------------------------------------------- rendering


def resolve_store_dir(path: str) -> str:
    """Accept the collector's out dir (contains ``tsdb/``) or the tsdb
    directory itself."""
    sub = os.path.join(path, "tsdb")
    if os.path.isdir(sub):
        return sub
    if os.path.isdir(path):
        return path
    raise FileNotFoundError(f"no time-series store under {path!r}")


def render_status(
    store: TimeSeriesStore,
    config: SLOConfig | None = None,
    now: float | None = None,
) -> str:
    """The ``observe slo`` body: one line per (objective, speed) with
    burn rates vs factor, FIRING markers with their exemplar ids, and
    the collector-persisted alert history."""
    engine = SLOEngine(store, config, emit=False)
    verdicts = engine.evaluate(now)
    lines: list[str] = []
    lines.append(
        f"slo status  [{store.dir}]  "
        f"objectives={len(engine.config.objectives)}  "
        f"windows={'/'.join(w.name for w in engine.config.windows)}"
    )
    lines.append("")
    header = (
        f"{'objective':14} {'speed':5} {'burn(short)':>11} "
        f"{'burn(long)':>10} {'factor':>7} {'n':>6} {'bad':>5}  status"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for v in verdicts:
        status = "FIRING" if v["firing"] else "ok"
        if v["firing"] and v.get("exemplar_rid") is not None:
            status += f"  exemplar rid={v['exemplar_rid']}"
        if v["firing"] and v.get("exemplar_trace"):
            status += f" trace={v['exemplar_trace']}"
        lines.append(
            f"{v['objective']:14} {v['speed']:5} {v['burn_short']:>11.2f} "
            f"{v['burn_long']:>10.2f} {v['factor']:>7.1f} "
            f"{v['total']:>6} {v['bad']:>5}  {status}"
        )
    # both history and the count below are bounded to the slow window
    # so the segment-span cache prunes old segments — a status command
    # must not re-parse a day of retention
    horizon = max(w.long_s for w in engine.config.windows)
    t_now = time.time() if now is None else now
    history = store.query(
        ALERT_SERIES, start=t_now - horizon, end=t_now, limit=8
    )
    if history:
        lines.append("")
        lines.append("alert history (collector-persisted, newest last):")
        for rec in history:
            extras = []
            if rec.get("exemplar_rid") is not None:
                extras.append(f"rid={rec['exemplar_rid']}")
            if rec.get("exemplar_trace"):
                extras.append(f"trace={rec['exemplar_trace']}")
            lines.append(
                f"  {time.strftime('%H:%M:%S', time.localtime(rec.get('ts') or 0))}"
                f"  {rec.get('action', '?'):34} {rec.get('state', '?'):8}"
                f"  burn={rec.get('burn_short', '?')}"
                + ("  " + " ".join(extras) if extras else "")
            )
    reqs = store.query(REQUEST_SERIES, start=t_now - horizon, end=t_now)
    lines.append("")
    lines.append(
        f"store: {len(reqs)} request point(s) in the last "
        f"{horizon / 3600:g}h, {len(store.segments())} segment(s), "
        f"{len(store.series_names())} series"
    )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> None:
    """``python -m keystone_tpu observe slo <dir> [--config FILE]``."""
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    config = None
    if "--config" in argv:
        i = argv.index("--config")
        if i + 1 >= len(argv):
            raise SystemExit("--config needs a JSON file argument")
        config = SLOConfig.from_file(argv[i + 1])
        del argv[i : i + 2]
    if not argv or argv[0] in ("-h", "--help"):
        raise SystemExit(
            "usage: python -m keystone_tpu observe slo <dir> "
            "[--config FILE]\n"
            "<dir> is a collector output directory (contains tsdb/) or\n"
            "the tsdb directory itself; --config points at a declarative\n"
            "SLO JSON file (see the README's 'Fleet observability & "
            "SLOs')"
        )
    try:
        store_dir = resolve_store_dir(argv[0])
    except OSError as e:
        raise SystemExit(str(e)) from None
    store = TimeSeriesStore(store_dir)
    print(render_status(store, config))
