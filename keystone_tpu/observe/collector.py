"""The fleet collector daemon: one pane over N processes.

PR 12's fleet and the elastic multihost run many processes, each with
its own ``/metrics`` endpoint and its own run dir of
``steps``/``events``/``spans`` streams. This daemon aggregates them:

- **scrape**: every interval, fetch each target's Prometheus ``/metrics``
  exposition (targets are static, env-listed, or advertised live by the
  fleet router's ``/healthz`` — a replica relaunched on a rolling
  restart appears on the next cycle with no config change), parse it
  (:func:`..metrics.parse_prometheus`), and append every sample to the
  time-series store with an ``instance`` label;
- **tail**: discover run dirs under the watched observe base dirs
  (again: new dirs appear live) and incrementally ingest their streams —
  request spans (``serve.request`` / ``fleet.forward``) become
  :data:`..slo.REQUEST_SERIES` points carrying ``ok`` + the
  trace/request-id **exemplar**, step rows become goodput points, alert
  events become alert points;
- **evaluate**: run the SLO engine (:mod:`.slo`) over the store and
  persist firing/cleared transitions as :data:`..slo.ALERT_SERIES`
  points (the engine itself emits the ``alert`` events);
- **federate**: write ``federation.prom`` — the merged exposition of
  every target's last-good scrape plus a per-target ``up`` gauge — for
  external scrapers (served by ``observe serve``'s ``/metrics``).

Failure contract (the ``collector.scrape_fail`` drill pins it): a
target dying mid-scrape costs that target that cycle — a gap in its
series and a ``collector_scrape_fail`` bump — never a collector crash
and never a torn store segment. The last-good snapshot keeps serving
federation with ``up 0``.

``python -m keystone_tpu observe collect <out-dir> ...`` runs it; all
cadence comes from ``KEYSTONE_COLLECTOR_*`` env knobs (README table).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from typing import Any, Callable

from keystone_tpu.observe import events as _events
from keystone_tpu.observe import metrics as _metrics
from keystone_tpu.observe import slo as _slo
from keystone_tpu.observe.timeseries import TimeSeriesStore
from keystone_tpu.resilience import faults as _faults

ENV_INTERVAL_S = "KEYSTONE_COLLECTOR_INTERVAL_S"
ENV_TARGETS = "KEYSTONE_COLLECTOR_TARGETS"
ENV_TIMEOUT_S = "KEYSTONE_COLLECTOR_TIMEOUT_S"

FEDERATION_FILE = "federation.prom"
TARGETS_FILE = "targets.json"

#: span names ingested as request outcomes (the SLO request stream)
REQUEST_SPANS = ("serve.request", "fleet.forward")


def interval_from_env() -> float:
    try:
        v = float(os.environ.get(ENV_INTERVAL_S, "") or 5.0)
        return v if v > 0 else 5.0
    except ValueError:
        return 5.0


def timeout_from_env() -> float:
    try:
        v = float(os.environ.get(ENV_TIMEOUT_S, "") or 2.0)
        return v if v > 0 else 2.0
    except ValueError:
        return 2.0


def targets_from_env() -> list[str]:
    raw = os.environ.get(ENV_TARGETS, "")
    return [t.strip() for t in raw.split(",") if t.strip()]


def default_transport(
    url: str, timeout: float, as_json: bool = False
) -> Any:
    """Fetch one URL: exposition text by default, parsed JSON bodies for
    the discovery endpoints. Injectable on :class:`Collector` so the
    unit tests run with zero sockets."""
    headers = {"Accept": "application/json"} if as_json else {}
    req = urllib.request.Request(url, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        body = resp.read().decode("utf-8", "replace")
    return json.loads(body) if as_json else body


def _instance_of(url: str) -> str:
    """``http://host:port/path`` → ``host:port`` (the instance label)."""
    rest = url.split("://", 1)[-1]
    return rest.split("/", 1)[0] or url


class _Cursor:
    """Incremental JSONL reader returning only records appended since
    the previous poll. On first attach it reads the rotated ``.1``
    generation first (a capped stream's oldest records live there).

    Rotation mid-watch (:class:`..events.JsonlSink` renames the file to
    ``.1`` and starts fresh) is detected by INODE, not size — a
    same-size successor would fool a size check, and a bigger one would
    silently resume at a bogus byte offset. On rotation the unread TAIL
    of the old generation is recovered from ``.1`` before the new file
    is read from the top, so no record is lost (the SLO engine's
    availability math counts every request outcome, including the
    failures a writer emits right before rotating)."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self._ino: int | None = None
        self._first = True

    @staticmethod
    def _parse(chunk: bytes, out: list[dict]) -> int:
        """Parse the complete lines of ``chunk`` into ``out``; returns
        how many bytes were consumed (up to the final newline)."""
        end = chunk.rfind(b"\n")
        if end < 0:
            return 0
        for raw in chunk[: end + 1].splitlines():
            raw = raw.strip()
            if not raw:
                continue
            try:
                out.append(json.loads(raw))
            except ValueError:
                continue
        return end + 1

    def poll(self) -> list[dict]:
        out: list[dict] = []
        if self._first:
            self._first = False
            rotated = self.path + ".1"
            if os.path.isfile(rotated):
                out.extend(_events.read_jsonl(rotated))
        try:
            st = os.stat(self.path)
        except OSError:
            return out
        if self._ino is not None and st.st_ino != self._ino:
            # rotated underneath us: the old generation is now `.1` —
            # drain its unread tail before starting on the new file
            try:
                with open(self.path + ".1", "rb") as f:
                    f.seek(self.offset)
                    self._parse(f.read(), out)
            except OSError:
                pass  # second rotation raced us: that tail is gone
            self.offset = 0
        elif st.st_size < self.offset:
            # same inode, shrunk: a genuine truncation — start over
            self.offset = 0
        self._ino = st.st_ino
        if st.st_size == self.offset:
            return out
        try:
            with open(self.path, "rb") as f:
                f.seek(self.offset)
                chunk = f.read()
        except OSError:
            return out
        self.offset += self._parse(chunk, out)
        return out


class Collector:
    """The aggregation daemon. Everything time-driven takes the
    injected ``clock`` and every cycle stage is callable on its own
    (:meth:`scrape_once` / :meth:`tail_once` / :meth:`evaluate_slo`), so
    the tests drive whole scrape→store→alert scenarios with zero
    sleeps and zero sockets."""

    def __init__(
        self,
        out_dir: str,
        *,
        targets: list[str] | None = None,
        router: str | None = None,
        watch: list[str] | None = None,
        interval_s: float | None = None,
        slo_config: _slo.SLOConfig | None = None,
        clock: Callable[[], float] = time.time,
        transport: Callable[..., Any] = default_transport,
    ):
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self.store = TimeSeriesStore(
            os.path.join(out_dir, "tsdb"), clock=clock
        )
        self.targets = list(targets or []) or targets_from_env()
        self.router = router
        self.watch = list(watch or [])
        self.interval_s = (
            interval_from_env() if interval_s is None else float(interval_s)
        )
        self.clock = clock
        self.transport = transport
        self.timeout_s = timeout_from_env()
        self.engine = _slo.SLOEngine(self.store, slo_config, clock=clock)
        self._scrapes: dict[str, dict] = {}  # target → last scrape state
        self._router_targets: list[str] = []  # last-advertised replica set
        self._cursors: dict[str, _Cursor] = {}
        self._scrape_attempts = 0  # the collector.scrape_fail fault key
        self.cycles = 0
        # retention is only real if somebody RUNS compaction: the daemon
        # does, ~24 times per retention window (hourly at the 24h
        # default), so a long-lived collector's disk stays bounded
        self.compact_every_s = max(60.0, self.store.retention_s / 24.0)
        self._last_compact = clock()
        reg = _metrics.get_registry()
        reg.describe(
            "collector_scrape_fail",
            "scrapes that failed (target down or collector.scrape_fail "
            "drill) — each one is a gap in that target's series",
        )
        reg.describe(
            "collector_points", "points appended to the time-series store"
        )

    # ---------------------------------------------------------- discovery

    def discover_targets(self) -> list[str]:
        """Static targets plus whatever the fleet router currently
        advertises (``/healthz`` → ``scrape_targets``) — the live set,
        re-read every cycle so replicas relaunched on new incarnations
        show up without a collector restart."""
        out = list(self.targets)
        if self.router:
            base = self.router.rstrip("/")
            try:
                payload = self.transport(
                    base + "/healthz", self.timeout_s, True
                )
                self._router_targets = [
                    str(t) for t in payload.get("scrape_targets") or []
                ]
                out.extend(self._router_targets)
                out.append(base + "/metrics")
            except Exception as e:  # noqa: BLE001 — router down ≠ crash
                _metrics.get_registry().counter(
                    "collector_discover_fail"
                ).inc()
                self._note_router_error(e)
                # ONE router blip (rolling restart, slow /healthz) must
                # not flip every healthy replica to up=0 unscraped: keep
                # scraping the last-advertised set — replicas that
                # really died fail their own scrapes, which is the
                # honest per-target signal
                out.extend(self._router_targets)
                out.append(base + "/metrics")
        seen: set[str] = set()
        uniq = []
        for t in out:
            if t not in seen:
                seen.add(t)
                uniq.append(t)
        return uniq

    def _note_router_error(self, e: Exception) -> None:
        from keystone_tpu.core.logging import get_logger

        get_logger("keystone_tpu.observe").warning(
            "collector: router discovery at %s failed (%r)", self.router, e
        )

    def discover_run_dirs(self) -> list[str]:
        """Run directories under every watched base (or the base itself
        when it IS a run dir) — rescanned each cycle, so a replica that
        booted after the collector did is tailed from its first record
        (its rotated generation is read on attach)."""
        out: list[str] = []
        for base in self.watch:
            if not os.path.isdir(base):
                continue
            if self._is_run_dir(base):
                out.append(base)
                continue
            for name in sorted(os.listdir(base)):
                path = os.path.join(base, name)
                if os.path.isdir(path) and self._is_run_dir(path):
                    out.append(path)
        return out

    @staticmethod
    def _is_run_dir(path: str) -> bool:
        return any(
            os.path.isfile(os.path.join(path, f))
            for f in ("events.jsonl", "steps.jsonl", "spans.jsonl")
        )

    # ------------------------------------------------------------- scrape

    def scrape_once(self) -> dict:
        """One scrape pass over the discovered targets. A failing
        target is recorded (counter + last-error state + ``up 0`` in
        federation) and skipped — the collector survives any replica
        dying mid-scrape, by contract."""
        ok = failed = points = 0
        discovered = self.discover_targets()
        # a target that VANISHED from discovery (router died, replica
        # de-registered) is no longer scraped — its last-good snapshot
        # must not keep advertising up=1 forever; flip it down so
        # federation and the dashboard show the truth
        for target, state in self._scrapes.items():
            if target not in discovered and state.get("up"):
                state["up"] = False
                state["error"] = "target no longer discovered"
        for target in discovered:
            key = self._scrape_attempts
            self._scrape_attempts += 1
            instance = _instance_of(target)
            try:
                _faults.maybe_raise(
                    "collector.scrape_fail", key, note=target
                )
                text = self.transport(target, self.timeout_s)
                samples = _metrics.parse_prometheus(str(text))
                n = self._ingest_samples(samples, instance)
            except Exception as e:  # noqa: BLE001 — a dead replica is
                # routine; the gap IS the record
                failed += 1
                _metrics.get_registry().counter(
                    "collector_scrape_fail", target=instance
                ).inc()
                prev = self._scrapes.get(target) or {}
                self._scrapes[target] = {
                    **prev,
                    "instance": instance,
                    "ts": self.clock(),
                    "up": False,
                    "error": repr(e),
                }
                from keystone_tpu.resilience.emit import decision

                decision(
                    "collector_scrape_fail",
                    target=instance,
                    error=repr(e),
                )
                continue
            ok += 1
            points += n
            self._scrapes[target] = {
                "instance": instance,
                "ts": self.clock(),
                "up": True,
                "samples": samples,
                "points": n,
            }
        return {"targets_ok": ok, "targets_failed": failed, "points": points}

    def _ingest_samples(
        self, samples: list[_metrics.PromSample], instance: str
    ) -> int:
        now = self.clock()
        n = 0
        for s in samples:
            series = _metrics._series_key(
                s.name, {**s.labels, "instance": instance}
            )
            self.store.append(series, s.value, ts=now)
            n += 1
        if n:
            _metrics.get_registry().counter("collector_points").inc(n)
        return n

    # --------------------------------------------------------------- tail

    def tail_once(self) -> int:
        """One incremental pass over every discovered run dir's
        streams; returns the number of store points ingested."""
        points = 0
        for run_dir in self.discover_run_dirs():
            for fname, handler in (
                ("spans.jsonl", self._ingest_span),
                ("steps.jsonl", self._ingest_step),
                ("events.jsonl", self._ingest_event),
            ):
                path = os.path.join(run_dir, fname)
                cur = self._cursors.get(path)
                if cur is None:
                    if not os.path.isfile(path):
                        continue
                    cur = self._cursors[path] = _Cursor(path)
                for rec in cur.poll():
                    points += handler(rec)
        if points:
            _metrics.get_registry().counter("collector_points").inc(points)
        return points

    def _ingest_span(self, rec: dict) -> int:
        if rec.get("name") not in REQUEST_SPANS:
            return 0
        # one client request must be ONE availability sample: behind a
        # fleet, every request yields a router fleet.forward AND a
        # replica serve.request for the same outcome — counting both
        # halves the measured error rate. A serve.request with a parent
        # is the replica-side copy of a hop the router already counts;
        # only parentless ones (direct-serve deployments) are samples.
        if rec.get("name") == "serve.request" and rec.get("parent"):
            return 0
        self.store.append(
            _slo.REQUEST_SERIES,
            float(rec.get("wall_s") or 0.0),
            ts=rec.get("ts"),
            ok=rec.get("status") != "failed",
            trace=rec.get("trace"),
            rid=rec.get("rid"),
            name=rec.get("name"),
            run=rec.get("run"),
        )
        return 1

    def _ingest_step(self, rec: dict) -> int:
        n = 0
        ts = rec.get("ts")
        source = rec.get("source", "train")
        rate = rec.get("tokens_per_s") or rec.get("rows_per_s")
        if isinstance(rate, (int, float)):
            self.store.append(
                _slo.GOODPUT_SERIES,
                float(rate),
                ts=ts,
                source=source,
                run=rec.get("run"),
            )
            n += 1
        if isinstance(rec.get("loss"), (int, float)):
            self.store.append(
                "train.loss", float(rec["loss"]), ts=ts, run=rec.get("run")
            )
            n += 1
        if isinstance(rec.get("mfu"), (int, float)):
            self.store.append(
                "train.mfu", float(rec["mfu"]), ts=ts, run=rec.get("run")
            )
            n += 1
        return n

    def _ingest_event(self, rec: dict) -> int:
        if rec.get("event") != "alert":
            return 0
        # per-process anomaly alerts (observe/health.py) land beside the
        # SLO's own transitions so the dashboard lists one alert feed
        self.store.append(
            "alerts",
            1.0,
            ts=rec.get("ts"),
            action=rec.get("action"),
            run=rec.get("run"),
        )
        return 1

    # ---------------------------------------------------------------- slo

    def evaluate_slo(self) -> list[dict]:
        """Run the burn-rate engine; persist every pair's short-window
        burn as a ``slo_burn{objective=...,speed=...}`` gauge point (the
        dashboard's burn timelines) and the firing/cleared transitions
        as alert points (the engine already emitted the ``alert``
        events)."""
        verdicts = self.engine.evaluate()
        for v in verdicts:
            self.store.append(
                _metrics._series_key(
                    "slo_burn",
                    {"objective": v["objective"], "speed": v["speed"]},
                ),
                v["burn_short"],
                firing=bool(v["firing"]) or None,
            )
            if v["transition"] is None:
                continue
            self.store.append(
                _slo.ALERT_SERIES,
                1.0 if v["transition"] == "fired" else 0.0,
                action=f"slo.{v['objective']}.{v['speed']}_burn",
                state="firing" if v["transition"] == "fired" else "cleared",
                burn_short=v["burn_short"],
                burn_long=v["burn_long"],
                exemplar_trace=v.get("exemplar_trace"),
                exemplar_rid=v.get("exemplar_rid"),
            )
        return verdicts

    # --------------------------------------------------------- federation

    def write_federation(self) -> None:
        """Atomically publish the merged exposition + target states for
        external scrapers and the dashboard's ``/metrics``."""
        from keystone_tpu.core.serialization import atomic_write

        text = federation_text(self._scrapes)
        try:
            with atomic_write(os.path.join(self.out_dir, FEDERATION_FILE)) as f:
                f.write(text.encode())
            meta = {
                t: {k: v for k, v in s.items() if k != "samples"}
                for t, s in self._scrapes.items()
            }
            with atomic_write(os.path.join(self.out_dir, TARGETS_FILE)) as f:
                f.write(json.dumps(meta, default=repr).encode())
        except OSError as e:
            from keystone_tpu.core.logging import get_logger

            get_logger("keystone_tpu.observe").warning(
                "collector: federation write failed (%r)", e
            )

    # -------------------------------------------------------------- cycle

    def cycle(self) -> dict:
        """One full collection cycle — scrape, tail, evaluate, federate
        — with a ``collector`` event summarizing it when a sink is
        active."""
        scraped = self.scrape_once()
        tailed = self.tail_once()
        verdicts = self.evaluate_slo()
        self.write_federation()
        compacted = None
        if self.clock() - self._last_compact >= self.compact_every_s:
            self._last_compact = self.clock()
            compacted = self.store.compact()
        self.cycles += 1
        firing = sum(1 for v in verdicts if v["firing"])
        summary = {
            **scraped,
            "tailed_points": tailed,
            "run_dirs": len(
                {os.path.dirname(p) for p in self._cursors}
            ),
            "slo_firing": firing,
            "cycle": self.cycles,
        }
        if compacted is not None:
            summary["compacted"] = compacted
        reg = _metrics.get_registry()
        reg.gauge("collector_targets_up").set(scraped["targets_ok"])
        reg.gauge("collector_slo_firing").set(firing)
        log = _events.active()
        if log is not None:
            log.emit("collector", **summary)
        return summary

    def run(
        self,
        stop: threading.Event | None = None,
        max_cycles: int | None = None,
    ) -> None:
        """The daemon loop: cycle then wait the interval; a ``stop``
        event ends it promptly (the CLI's SIGTERM handler sets it)."""
        stop = stop or threading.Event()
        while not stop.is_set():
            self.cycle()
            if max_cycles is not None and self.cycles >= max_cycles:
                return
            stop.wait(self.interval_s)

    def close(self) -> None:
        self.store.close()


def federation_text(scrapes: dict[str, dict]) -> str:
    """Merge every target's last-good samples into one exposition body:
    families keep their TYPE across instances, every sample gains the
    target's ``instance`` label, and a synthetic ``up`` gauge per
    target says which scrapes are current — the Prometheus federation
    convention, so one external scraper ingests the whole tier."""
    families: dict[str, tuple[str | None, list[str]]] = {}

    def fam(name: str, kind: str | None) -> list[str]:
        hit = families.get(name)
        if hit is None:
            hit = (kind, [])
            families[name] = hit
        return hit[1]

    for target in sorted(scrapes):
        state = scrapes[target]
        instance = state.get("instance") or _instance_of(target)
        fam("up", "gauge").append(
            f'up{{instance="{instance}"}} {1 if state.get("up") else 0}'
        )
        for s in state.get("samples") or []:
            labels = _metrics._prom_labels(
                {**s.labels, "instance": instance}
            )
            # family key: quantile'd summary samples ride their bare
            # name; _count/_sum ride theirs (TYPE declared on the family)
            fam_name = s.name
            for suffix in ("_count", "_sum"):
                if s.kind == "summary" and s.name.endswith(suffix):
                    fam_name = s.name[: -len(suffix)]
            fam(fam_name, s.kind).append(
                f"{s.name}{labels} {_metrics._prom_value(s.value)}"
            )
    lines: list[str] = []
    for name in sorted(families):
        kind, samples = families[name]
        lines.append(
            f"# HELP {name} federated by the keystone_tpu collector"
        )
        if kind:
            lines.append(f"# TYPE {name} {kind}")
        lines.extend(samples)
    return "\n".join(lines) + ("\n" if lines else "")


# --------------------------------------------------------------------- CLI


USAGE = """usage: python -m keystone_tpu observe collect <out-dir> [options]
options:
  --targets URL,URL   static /metrics scrape targets
                      (default KEYSTONE_COLLECTOR_TARGETS)
  --router URL        fleet router base URL — its /healthz advertises the
                      replicas' scrape targets, re-read every cycle
  --watch DIR         observe base dir to tail run dirs under (repeatable;
                      default KEYSTONE_OBSERVE_DIR)
  --interval S        cycle cadence (default KEYSTONE_COLLECTOR_INTERVAL_S=5)
  --slo FILE          declarative SLO config JSON (default env knobs)
  --once              one cycle, print the summary, exit (tests/cron)
"""


def main(argv: list[str] | None = None) -> None:
    import signal
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        raise SystemExit(USAGE)
    out_dir = argv[0]
    rest = argv[1:]
    targets: list[str] = []
    router = None
    watch: list[str] = []
    interval = None
    slo_config = None
    once = False
    i = 0
    while i < len(rest):
        a = rest[i]
        if a == "--once":
            once = True
            i += 1
            continue
        if a in ("--targets", "--router", "--watch", "--interval", "--slo"):
            if i + 1 >= len(rest):
                raise SystemExit(f"{a} needs a value")
            val = rest[i + 1]
            if a == "--targets":
                targets.extend(t.strip() for t in val.split(",") if t.strip())
            elif a == "--router":
                router = val
            elif a == "--watch":
                watch.append(val)
            elif a == "--interval":
                try:
                    interval = float(val)
                except ValueError:
                    raise SystemExit(f"--interval: bad seconds {val!r}") from None
            elif a == "--slo":
                slo_config = _slo.SLOConfig.from_file(val)
            i += 2
            continue
        raise SystemExit(f"unknown option {a!r}\n{USAGE}")
    if not watch:
        base = os.environ.get(_events.ENV_DIR)
        if base:
            watch.append(base)
    collector = Collector(
        out_dir,
        targets=targets,
        router=router,
        watch=watch,
        interval_s=interval,
        slo_config=slo_config,
    )
    if once:
        summary = collector.cycle()
        collector.close()
        print(json.dumps(summary))
        return
    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    print(
        f"collector: store {os.path.join(out_dir, 'tsdb')}  "
        f"targets={len(targets)}{' +router' if router else ''}  "
        f"watch={watch}  every {collector.interval_s:g}s",
        flush=True,
    )
    try:
        collector.run(stop)
    finally:
        collector.close()
