"""Rolling-baseline anomaly monitor: a run that quietly degrades alerts.

The observe stack so far *records* — nobody notices when step time
drifts 2x, loss spikes, HBM creeps toward the limit, or the serving
path starts missing its SLO. This module closes that gap: cheap rolling
baselines over the live step stream (:mod:`.telemetry` feeds
:meth:`HealthMonitor.note_step`) and the serve request path
(:mod:`keystone_tpu.serve` feeds :meth:`note_request` /
:meth:`note_dispatch`), emitting one ``alert`` event per verdict
through the resilience emit schema (:func:`..resilience.emit.decision`
— one counter bump + one event when a sink is active, one global read
when not). ``observe top`` and the run report render them.

Alert kinds (the ``action`` field):

==========================  ============================================
``train.nan_loss``          a non-finite loss reached the step stream
``train.loss_spike``        loss > ``loss_spike_factor`` x its EMA
``train.step_time_drift``   rolling step-wall p95 >
                            ``step_p95_factor`` x the frozen baseline
``train.hbm_growth``        HBM peak watermark grew past
                            ``hbm_growth_factor`` x its first sample
``serve.slow_request``      one request's wall exceeded the tail-latency
                            threshold (``KEYSTONE_SERVE_SLOW_MS``)
``serve.deadline_miss``     dispatch-time deadline-miss rate over the
                            rolling window breached
``serve.shed_rate``         admission-shed rate breached
``serve.feature_drift``     incoming request rows drifted from the fit
                            state's accumulated feature means (the
                            shadow runner feeds this — it gates online-
                            learning promotion, :mod:`keystone_tpu.
                            learn.shadow`)
==========================  ============================================

Determinism: verdicts are pure functions of the fed values plus an
injectable clock (request-side cooldowns), so the fault drills —
``KEYSTONE_FAULTS="train.nan:@k:0"`` / ``serve.slow_request:@k:0`` —
produce the same alerts every run, and the tests drive everything with
zero sleeps. :func:`check_run` replays a finished run's ``steps.jsonl``
through a fresh (non-emitting) monitor, so the report can show what a
live monitor *would* have said about a run recorded without one.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import os
import threading
import time
from typing import Any, Callable

ENV_SLOW_MS = "KEYSTONE_SERVE_SLOW_MS"  # shared with serve/server.py


def _slow_threshold_s() -> float:
    try:
        return float(os.environ.get(ENV_SLOW_MS, "") or 100.0) / 1e3
    except ValueError:
        return 0.1


@dataclasses.dataclass
class HealthConfig:
    """Thresholds for every check; env overrides via
    ``KEYSTONE_ALERT_<FIELD>`` (floats/ints, upper-cased field name)."""

    baseline_steps: int = 16  # steps frozen as the step-wall baseline
    window: int = 32  # rolling window the drift p95 is taken over
    step_p95_factor: float = 2.0
    loss_spike_factor: float = 3.0
    loss_ema_alpha: float = 0.1
    loss_warmup: int = 4  # EMA samples before spike checks arm
    hbm_growth_factor: float = 1.5
    deadline_miss_rate: float = 0.5
    shed_rate: float = 0.05
    rate_min_requests: int = 20
    rate_window: int = 64  # requests the miss/shed rates slide over
    cooldown_steps: int = 32  # min steps between repeats of one kind
    cooldown_s: float = 30.0  # request-side repeat suppression
    slow_request_s: float | None = None  # None → KEYSTONE_SERVE_SLOW_MS
    # mean per-feature |x̄ − μ|/σ of an incoming batch vs the fit
    # state's accumulated statistics before serve.feature_drift fires
    feature_drift_z: float = 6.0

    @classmethod
    def from_env(cls) -> "HealthConfig":
        cfg = cls()
        for f in dataclasses.fields(cls):
            raw = os.environ.get(f"KEYSTONE_ALERT_{f.name.upper()}")
            if raw is None or not raw.strip():
                continue
            try:
                setattr(
                    cfg,
                    f.name,
                    int(raw) if f.type == "int" else float(raw),
                )
            except ValueError:
                pass
        return cfg


class HealthMonitor:
    """Per-process anomaly monitor. All methods are thread-safe and
    cheap on the no-verdict path (a few float compares); an alert costs
    one counter bump + one event emit (when a sink is active).

    ``emit=False`` collects verdicts in :attr:`alerts` only — the
    offline-replay form :func:`check_run` uses.
    """

    def __init__(
        self,
        config: HealthConfig | None = None,
        *,
        clock: Callable[[], float] = time.monotonic,
        emit: bool = True,
    ):
        self.config = config or HealthConfig.from_env()
        self.clock = clock
        self.emit = emit
        self.alerts: list[dict] = []
        self._lock = threading.Lock()
        c = self.config
        self._baseline: list[float] = []
        self._baseline_p95: float | None = None
        self._walls: collections.deque = collections.deque(maxlen=c.window)
        self._loss_ema: float | None = None
        self._loss_n = 0
        self._hbm_base: float | None = None
        self._req_total = 0
        # SLIDING windows, not lifetime totals: a server that was
        # healthy for hours must alert within one window of an SLO
        # collapse, and a cold-start burst must age out instead of
        # re-firing against healthy current traffic
        self._req_recent: collections.deque = collections.deque(
            maxlen=max(c.rate_window, 1)
        )
        self._disp_recent: collections.deque = collections.deque(
            maxlen=max(c.rate_window, 1)
        )
        self._last_step_fire: dict[str, int] = {}
        self._last_time_fire: dict[str, float] = {}

    # ------------------------------------------------------------ firing

    def _fire(
        self, kind: str, *, step: int | None = None, **detail: Any
    ) -> None:
        rec = {"kind": kind, "step": step, **detail}
        with self._lock:
            self.alerts.append(rec)
        if not self.emit:
            return
        from keystone_tpu.resilience.emit import decision

        decision(
            kind,
            counter="alerts",
            counter_labels={"kind": kind},
            event_kind="alert",
            phase="health",
            step=step,
            **detail,
        )

    def _step_cooldown_ok(self, kind: str, step: int) -> bool:
        last = self._last_step_fire.get(kind)
        if last is not None and step - last < self.config.cooldown_steps:
            return False
        self._last_step_fire[kind] = step
        return True

    def _time_cooldown_ok(self, kind: str) -> bool:
        now = self.clock()
        last = self._last_time_fire.get(kind)
        if last is not None and now - last < self.config.cooldown_s:
            return False
        self._last_time_fire[kind] = now
        return True

    # ------------------------------------------------------ train stream

    def note_step(
        self,
        *,
        step: int,
        loss: float | None = None,
        wall_s: float | None = None,
        hbm_peak_bytes: float | None = None,
    ) -> None:
        """One completed train step (the :class:`..telemetry.StepLog`
        hook — source="train" rows only)."""
        c = self.config
        fires: list[tuple[str, dict]] = []
        with self._lock:
            if loss is not None:
                loss = float(loss)
                if not math.isfinite(loss):
                    fires.append(("train.nan_loss", {"loss": repr(loss)}))
                else:
                    if (
                        self._loss_ema is not None
                        and self._loss_n >= c.loss_warmup
                        and loss > self._loss_ema * c.loss_spike_factor
                        and self._loss_ema > 0
                    ):
                        fires.append(
                            (
                                "train.loss_spike",
                                {
                                    "loss": round(loss, 6),
                                    "ema": round(self._loss_ema, 6),
                                    "factor": c.loss_spike_factor,
                                },
                            )
                        )
                    self._loss_ema = (
                        loss
                        if self._loss_ema is None
                        else (1 - c.loss_ema_alpha) * self._loss_ema
                        + c.loss_ema_alpha * loss
                    )
                    self._loss_n += 1
            if wall_s is not None and wall_s >= 0:
                if self._baseline_p95 is None:
                    # the first steps after compile ARE the baseline; the
                    # caller (train loop) starts feeding from step 1, and
                    # the first step's compile wall would poison it — so
                    # the baseline freezes over steps 2..baseline+1
                    if step > 1:
                        self._baseline.append(float(wall_s))
                        if len(self._baseline) >= c.baseline_steps:
                            self._baseline_p95 = _p95(self._baseline)
                else:
                    self._walls.append(float(wall_s))
                    if len(self._walls) >= max(c.window // 2, 4):
                        p95 = _p95(self._walls)
                        if p95 > self._baseline_p95 * c.step_p95_factor:
                            fires.append(
                                (
                                    "train.step_time_drift",
                                    {
                                        "p95_s": round(p95, 6),
                                        "baseline_p95_s": round(
                                            self._baseline_p95, 6
                                        ),
                                        "factor": c.step_p95_factor,
                                    },
                                )
                            )
            if hbm_peak_bytes:
                if self._hbm_base is None:
                    self._hbm_base = float(hbm_peak_bytes)
                elif hbm_peak_bytes > self._hbm_base * c.hbm_growth_factor:
                    fires.append(
                        (
                            "train.hbm_growth",
                            {
                                "hbm_peak_bytes": int(hbm_peak_bytes),
                                "baseline_bytes": int(self._hbm_base),
                                "factor": c.hbm_growth_factor,
                            },
                        )
                    )
                    # ratchet: re-alert only at the NEXT factor of growth
                    self._hbm_base = float(hbm_peak_bytes)
            fires = [
                (kind, detail)
                for kind, detail in fires
                if self._step_cooldown_ok(kind, step)
            ]
        for kind, detail in fires:
            self._fire(kind, step=step, **detail)

    # ------------------------------------------------------ serve stream

    def note_request(
        self, wall_s: float, *, shed: bool = False, rid: Any = None
    ) -> None:
        """One finished (or shed) front-end request."""
        c = self.config
        fires: list[tuple[str, dict]] = []
        with self._lock:
            self._req_total += 1
            self._req_recent.append(bool(shed))
            if shed:
                window_shed = sum(self._req_recent)
                if (
                    len(self._req_recent) >= c.rate_min_requests
                    and window_shed / len(self._req_recent) > c.shed_rate
                    and self._time_cooldown_ok("serve.shed_rate")
                ):
                    fires.append(
                        (
                            "serve.shed_rate",
                            {
                                "shed": window_shed,
                                "window": len(self._req_recent),
                            },
                        )
                    )
            threshold = (
                _slow_threshold_s()
                if c.slow_request_s is None
                else c.slow_request_s
            )
            if (
                not shed
                and wall_s > threshold
                and self._time_cooldown_ok("serve.slow_request")
            ):
                fires.append(
                    (
                        "serve.slow_request",
                        {
                            "wall_s": round(wall_s, 6),
                            "threshold_s": round(threshold, 6),
                            "rid": rid,
                        },
                    )
                )
        for kind, detail in fires:
            self._fire(kind, **detail)

    def note_feature_drift(self, z: float, *, rid: Any = None) -> None:
        """One shadow-scored request batch's feature-drift score: the
        mean per-feature ``|x̄ − μ|/σ`` of the incoming rows against the
        fit state's accumulated means/variances
        (:func:`keystone_tpu.learn.shadow.input_feature_stats`). Fires
        ``serve.feature_drift`` above the configured z — the signal
        that incoming traffic left the distribution the statistics were
        accumulated on, which gates online-learning promotion."""
        c = self.config
        fire = None
        with self._lock:
            if z > c.feature_drift_z and self._time_cooldown_ok(
                "serve.feature_drift"
            ):
                fire = {
                    "z": round(float(z), 4),
                    "threshold": c.feature_drift_z,
                    "rid": rid,
                }
        if fire is not None:
            self._fire("serve.feature_drift", **fire)

    def note_dispatch(self, *, requests: int, misses: int) -> None:
        """One micro-batch dispatch: how many of its requests had
        already waited past their SLO deadline when it shipped."""
        c = self.config
        fire = None
        with self._lock:
            for i in range(int(requests)):
                self._disp_recent.append(i < int(misses))
            window_miss = sum(self._disp_recent)
            if (
                len(self._disp_recent) >= c.rate_min_requests
                and window_miss / len(self._disp_recent)
                > c.deadline_miss_rate
                and self._time_cooldown_ok("serve.deadline_miss")
            ):
                fire = {
                    "missed": window_miss,
                    "window": len(self._disp_recent),
                }
        if fire is not None:
            self._fire("serve.deadline_miss", **fire)


def _p95(values) -> float:
    vals = sorted(values)
    if not vals:
        return 0.0
    return vals[min(int(round(0.95 * (len(vals) - 1))), len(vals) - 1)]


# ----------------------------------------------------------- the singleton

_monitor: HealthMonitor | None = None
_monitor_lock = threading.Lock()


def get_monitor() -> HealthMonitor:
    """The process-wide monitor the telemetry and serve hooks feed."""
    global _monitor
    m = _monitor
    if m is None:
        with _monitor_lock:
            if _monitor is None:
                _monitor = HealthMonitor()
            m = _monitor
    return m


def reset_monitor() -> None:
    """Fresh baselines (tests; a new run in the same process)."""
    global _monitor
    with _monitor_lock:
        _monitor = None


# -------------------------------------------------------- offline replay


def check_run(run_dir: str, config: HealthConfig | None = None) -> list[dict]:
    """Replay a finished run's ``steps.jsonl`` through a fresh,
    non-emitting monitor and return the verdict list — what a live
    monitor would have alerted on."""
    from keystone_tpu.observe import events as _events
    from keystone_tpu.observe import telemetry as _telemetry

    run_dir = _events.resolve_run_dir(run_dir)
    path = os.path.join(run_dir, _telemetry.STEPS_FILE)
    mon = HealthMonitor(config, emit=False)
    # rotation-aware: the drift baseline freezes on the run's FIRST
    # post-compile steps, which live in the rotated generation on a
    # long capped run
    for rec in _events.read_jsonl_rotated(path):
        if rec.get("source", "train") != "train" or "step" not in rec:
            continue
        mon.note_step(
            step=int(rec["step"]),
            loss=rec.get("loss"),
            wall_s=rec.get("wall_s"),
            hbm_peak_bytes=rec.get("hbm_peak_bytes"),
        )
    return mon.alerts
