"""Observability subsystem: metrics, structured run events, pipeline
instrumentation, cost profiles, and run reports.

KeystoneML's optimizer runs on per-operator runtime profiles; this
package is that substrate for the TPU rebuild (see each module's
docstring):

- :mod:`.metrics` — process-wide labeled counters/gauges/timers
- :mod:`.events` — JSONL run-event log, env-gated via
  ``KEYSTONE_OBSERVE_DIR``
- :mod:`.instrument` — ``instrument(pipeline)`` per-node wrappers
- :mod:`.cost` — per-node FLOPs/bytes/memory profiles from
  ``jax.jit(...).lower().compile().cost_analysis()``
- :mod:`.report` — per-node run summary + the ``observe`` CLI
- :mod:`.telemetry` — live per-step stream (``steps.jsonl``)
- :mod:`.spans` — end-to-end trace spans (``spans.jsonl``), goodput
  buckets, and the ``observe trace`` renderer
- :mod:`.health` — rolling-baseline anomaly monitor → ``alert`` events
- :mod:`.schema` — the single registry of structured event kinds
- :mod:`.devices` — per-device HBM watermark sampling
- :mod:`.tracing` — programmatic profiler trace windows
- :mod:`.top` — the ``observe top`` terminal dashboard
- :mod:`.timeseries` — the collector's segmented on-disk point store
- :mod:`.collector` — the fleet collector daemon (``observe collect``)
- :mod:`.slo` — multi-window burn-rate SLO engine (``observe slo``)
- :mod:`.dashboard` — the live fleet dashboard (``observe serve``)

``events`` and ``metrics`` are stdlib-light and imported eagerly (the
core pipeline hooks depend on them); ``instrument``/``cost``/``report``
import jax and the DSL, so they load lazily to keep
``import keystone_tpu.observe.events`` cycle-free from ``core``.
"""

from __future__ import annotations

from keystone_tpu.observe import events, metrics  # noqa: F401
from keystone_tpu.observe.events import EventLog, node_label  # noqa: F401
from keystone_tpu.observe.metrics import MetricsRegistry, get_registry  # noqa: F401

_LAZY = {
    "instrument": "keystone_tpu.observe.instrument",
    "cost": "keystone_tpu.observe.cost",
    "report": "keystone_tpu.observe.report",
    "telemetry": "keystone_tpu.observe.telemetry",
    "spans": "keystone_tpu.observe.spans",
    "health": "keystone_tpu.observe.health",
    "schema": "keystone_tpu.observe.schema",
    "devices": "keystone_tpu.observe.devices",
    "tracing": "keystone_tpu.observe.tracing",
    "top": "keystone_tpu.observe.top",
    "timeseries": "keystone_tpu.observe.timeseries",
    "collector": "keystone_tpu.observe.collector",
    "slo": "keystone_tpu.observe.slo",
    "dashboard": "keystone_tpu.observe.dashboard",
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        return importlib.import_module(_LAZY[name])
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
