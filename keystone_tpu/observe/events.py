"""Structured run-event log — the per-operator execution record.

KeystoneML's optimizer is driven by per-operator profiles sampled during
execution; Spark's event log + UI is where those observations live. The
TPU-native analog is this module: every pipeline node call (and coarse
run phase) becomes one JSON line in ``<dir>/<run-id>/events.jsonl`` so a
cost model, a report renderer, or plain ``jq`` can consume the run.

Activation is env-gated and near-zero cost when off:

- ``KEYSTONE_OBSERVE_DIR=/path`` — every process that touches the
  pipeline DSL appends events under a fresh run directory there.
- :func:`run` — explicit, scoped activation (the CLI launcher, bench,
  and tests use this); restores the previous sink on exit.
- disabled — :func:`active` is one module-global read returning None,
  and the pipeline hooks take their plain fast path.

Event schema (one JSON object per line; fields beyond these are free-form):

==============  =========================================================
``ts``          unix time (float, seconds)
``run``         run id (shared by all events of one run)
``event``       ``run_start`` | ``run_end`` | ``node`` | ``span`` |
                ``phase`` | ``optimize`` | ``bench``
``node``        node label (``node`` events), e.g. ``01:BlockLinearMapper``
``phase``       ``fit`` | ``apply`` | ``compile`` (first traced call)
``wall_s``      wall-clock duration of the bracket
``status``      ``ok`` | ``failed`` (+ ``error`` repr when failed)
==============  =========================================================
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
import uuid
from typing import Any, Iterator

from keystone_tpu.observe.schema import note as _schema_note

ENV_DIR = "KEYSTONE_OBSERVE_DIR"
ENV_MAX_MB = "KEYSTONE_OBSERVE_MAX_MB"
EVENTS_FILE = "events.jsonl"

# in-memory mirror cap: a runaway loop must not grow the host heap
# without bound just because observability is on
_MAX_MEMORY_RECORDS = 100_000


def max_bytes_from_env() -> int | None:
    """Size cap for the high-rate JSONL streams (``steps.jsonl`` /
    ``spans.jsonl``): ``KEYSTONE_OBSERVE_MAX_MB`` megabytes per file
    before rotation, None = unbounded (the default — events.jsonl is
    never rotated, a report needs its run_start/run_end brackets)."""
    raw = os.environ.get(ENV_MAX_MB, "").strip()
    if raw:
        try:
            mb = float(raw)
            if mb > 0:
                return int(mb * 2**20)
        except ValueError:
            pass
    return None


def node_label(node: Any, index: int | None = None) -> str:
    """Stable display label for a pipeline node.

    Shared by the pipeline hooks, :mod:`.instrument`, and :mod:`.cost` so
    wall-time events and cost profiles join on the same key. The index
    prefix keeps two like-typed nodes at different positions distinct.
    """
    name = getattr(node, "name", None)
    if not name or not isinstance(name, str):
        name = type(node).__name__
    return f"{index:02d}:{name}" if index is not None else name


def _encode(rec: dict) -> str | None:
    """One record → one JSONL line (``default=repr``: a non-JSON field
    is a per-record problem, stringify it rather than losing the
    record; a circular reference skips the record → None)."""
    try:
        return json.dumps(rec, default=repr)
    except ValueError:  # circular reference: skip this record
        return None


def write_record(fh, rec: dict, sink_name: str):
    """Serialize ``rec`` and append it to JSONL sink ``fh`` — the ONE
    home of the write-or-degrade contract shared by the event log and
    the per-record streams (an OSError disables the sink with one
    warning). Returns ``fh``, or None when the sink must be disabled.
    The caller holds its own lock."""
    line = _encode(rec)
    if line is None:
        return fh
    try:
        fh.write(line + "\n")
    except OSError as e:
        from keystone_tpu.core.logging import get_logger

        get_logger("keystone_tpu.observe").warning(
            "%s write failed (%r); file sink disabled", sink_name, e
        )
        return None
    return fh


class JsonlSink:
    """An append-only JSONL file with write-or-degrade semantics and
    size-based rotation — the sink behind the high-rate streams
    (``steps.jsonl``, ``spans.jsonl``), which otherwise grow without
    bound on long runs.

    When a write would push the file past ``max_bytes``
    (``KEYSTONE_OBSERVE_MAX_MB``; None = unbounded), the current file
    is renamed to ``<path>.1`` (replacing the previous generation) and
    a fresh file is started — so on-disk usage is bounded by ~2x the
    cap, and a reader always sees the newest records. The incremental
    tailer (:class:`keystone_tpu.observe.top.Tail`) detects the
    truncation and restarts; the tolerant reader (:func:`read_jsonl`)
    already survives any torn seam. NOT thread-safe — the owning log
    holds its own lock around :meth:`write`."""

    def __init__(
        self, path: str, sink_name: str, max_bytes: int | None = None
    ):
        self.path = path
        self.sink_name = sink_name
        self.max_bytes = (
            max_bytes_from_env() if max_bytes is None else max_bytes
        )
        self._fh = open(path, "a", buffering=1)  # noqa: SIM115 — run-lifetime
        self._size = self._fh.tell()

    def _rotate(self) -> None:
        try:
            self._fh.close()
            os.replace(self.path, self.path + ".1")
            self._fh = open(  # noqa: SIM115 — run-lifetime
                self.path, "a", buffering=1
            )
            self._size = 0
        except OSError as e:
            from keystone_tpu.core.logging import get_logger

            get_logger("keystone_tpu.observe").warning(
                "%s rotation failed (%r); file sink disabled",
                self.sink_name,
                e,
            )
            self._fh = None

    def write(self, rec: dict) -> None:
        if self._fh is None:
            return
        line = _encode(rec)
        if line is None:
            return
        # size in encoded BYTES (the unit the cap and tell() use) — a
        # code-point count under-measures multi-byte records and would
        # rotate late
        nbytes = len(line.encode("utf-8")) + 1
        if (
            self.max_bytes
            and self._size
            and self._size + nbytes > self.max_bytes
        ):
            self._rotate()
            if self._fh is None:
                return
        try:
            self._fh.write(line + "\n")
            self._size += nbytes
        except OSError as e:
            from keystone_tpu.core.logging import get_logger

            get_logger("keystone_tpu.observe").warning(
                "%s write failed (%r); file sink disabled",
                self.sink_name,
                e,
            )
            self._fh = None

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None


class EventLog:
    """A single run's event sink: JSONL file plus an in-memory mirror.

    ``base_dir=None`` gives a memory-only log (bench uses this to build
    per-node breakdowns without touching disk). All methods are
    thread-safe; a failing disk write disables the file sink with one
    warning rather than taking down the run.
    """

    def __init__(self, base_dir: str | None = None, run_id: str | None = None):
        self.run_id = run_id or (
            time.strftime("%Y%m%d-%H%M%S") + "-" + uuid.uuid4().hex[:6]
        )
        self.records: list[dict] = []
        self.dropped = 0
        self._lock = threading.Lock()
        self._fh = None
        self.run_dir: str | None = None
        if base_dir:
            self.run_dir = os.path.join(base_dir, self.run_id)
            os.makedirs(self.run_dir, exist_ok=True)
            self._fh = open(  # noqa: SIM115 — held for the run's lifetime
                os.path.join(self.run_dir, EVENTS_FILE), "a", buffering=1
            )

    def emit(self, event: str, **fields: Any) -> dict:
        # schema drift check: every kind must be declared in ONE place
        # (observe/schema.py); unknown kinds warn once, never drop
        _schema_note(event)
        rec = {"ts": time.time(), "run": self.run_id, "event": event}
        rec.update(fields)
        with self._lock:
            if len(self.records) < _MAX_MEMORY_RECORDS:
                self.records.append(rec)
            else:
                self.dropped += 1
            if self._fh is not None:
                self._fh = write_record(self._fh, rec, "event log")
        return rec

    @contextlib.contextmanager
    def node(self, node: str, phase: str = "apply", **fields: Any) -> Iterator[None]:
        """Bracket one node call: emits a ``node`` event with wall time
        and status, re-raising any exception."""
        t0 = time.perf_counter()
        try:
            yield
        except BaseException as e:
            self.emit(
                "node",
                node=node,
                phase=phase,
                wall_s=time.perf_counter() - t0,
                status="failed",
                error=repr(e),
                **fields,
            )
            raise
        self.emit(
            "node",
            node=node,
            phase=phase,
            wall_s=time.perf_counter() - t0,
            status="ok",
            **fields,
        )

    def close(self) -> None:
        # the per-step telemetry stream (observe/telemetry.py) and the
        # span trace stream (observe/spans.py) bind their sinks to this
        # log's lifetime — close them with the run
        for bound in ("_steplog", "_spanlog"):
            sub = self.__dict__.pop(bound, None)
            if sub is not None:
                sub.close()
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


# Lazy three-state active sink: _UNINIT → (EventLog | None) on first use,
# so a process launched under KEYSTONE_OBSERVE_DIR self-activates and a
# process without it pays one `is` check per pipeline call.
_UNINIT: Any = object()
_active: Any = _UNINIT
_state_lock = threading.Lock()


def active() -> EventLog | None:
    """The currently active event log, or None. The ONLY check the hot
    pipeline hooks make — keep it a plain read when initialized."""
    global _active
    log = _active
    if log is _UNINIT:
        with _state_lock:
            if _active is _UNINIT:
                base = os.environ.get(ENV_DIR)
                try:
                    _active = EventLog(base) if base else None
                except OSError as e:
                    # unwritable/full observe dir: observability must
                    # degrade, not crash the pipeline at its first hook
                    _active = None
                    from keystone_tpu.core.logging import get_logger

                    get_logger("keystone_tpu.observe").warning(
                        "cannot open event log under %s (%r); "
                        "observability disabled for this process",
                        base,
                        e,
                    )
                if _active is not None:
                    _active.emit("run_start", source="env", argv=sys.argv)
                    _close_at_exit(_active)
            log = _active
    return log


def _close_at_exit(log: EventLog) -> None:
    """Env-activated logs have no scoping context manager, so bracket
    them at process exit: emit run_end (wall measured from activation)
    and close the file — otherwise a report can't tell a completed run
    from a crashed one. An uncaught exception is observed via a chained
    ``sys.excepthook`` so the run_end carries status=failed. Known
    limitation: CPython never invokes the excepthook for ``SystemExit``,
    so env-activated runs aborted that way record status=ok — scoped
    activation (:func:`run`, used by the launcher) brackets those
    correctly."""
    import atexit

    t0 = time.perf_counter()
    state: dict = {"status": "ok"}
    prev_hook = sys.excepthook

    def hook(tp, val, tb):
        state["status"] = "failed"
        state["error"] = f"{tp.__name__}: {val}"
        prev_hook(tp, val, tb)

    sys.excepthook = hook

    def _finish() -> None:
        try:
            log.emit(
                "run_end",
                wall_s=time.perf_counter() - t0,
                **state,
            )
            log.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    atexit.register(_finish)


def reset() -> None:
    """Drop the active sink and re-arm env detection (tests, bench)."""
    global _active
    with _state_lock:
        if isinstance(_active, EventLog):
            _active.close()
        _active = _UNINIT


@contextlib.contextmanager
def run(
    base_dir: str | None = None, run_id: str | None = None, **meta: Any
) -> Iterator[EventLog]:
    """Scoped activation: install a fresh :class:`EventLog` as the active
    sink, bracket it with ``run_start``/``run_end`` events, and restore
    the previous sink (including the lazy-env sentinel) on exit.

    ``base_dir=None`` falls back to ``KEYSTONE_OBSERVE_DIR``; if that is
    unset too, the log is memory-only (still yielded, still active).
    """
    global _active
    if base_dir is None:
        base_dir = os.environ.get(ENV_DIR) or None
    try:
        log = EventLog(base_dir, run_id)
    except OSError as e:
        # same degrade invariant as env activation: a broken observe dir
        # must not abort the run — continue with a memory-only log
        from keystone_tpu.core.logging import get_logger

        get_logger("keystone_tpu.observe").warning(
            "cannot open event log under %s (%r); continuing memory-only",
            base_dir,
            e,
        )
        log = EventLog(None, run_id)
    with _state_lock:
        prev = _active
        _active = log
    # a new scoped run means new baselines: without this, the anomaly
    # monitor would carry a previous run's frozen step-wall p95 / loss
    # EMA into an unrelated workload and mis-alert (bench runs several
    # training loops of different sizes in one process)
    from keystone_tpu.observe.health import reset_monitor

    reset_monitor()
    log.emit("run_start", **meta)
    t0 = time.perf_counter()
    try:
        yield log
    except BaseException as e:
        log.emit(
            "run_end",
            wall_s=time.perf_counter() - t0,
            status="failed",
            error=repr(e),
        )
        raise
    else:
        log.emit("run_end", wall_s=time.perf_counter() - t0, status="ok")
    finally:
        with _state_lock:
            _active = prev
        log.close()


def resolve_run_dir(path: str) -> str:
    """Accept either a run directory (contains ``events.jsonl``) or a
    base observe directory (pick the newest run under it)."""
    if os.path.isfile(os.path.join(path, EVENTS_FILE)):
        return path
    candidates = [
        os.path.join(path, d)
        for d in os.listdir(path)
        if os.path.isfile(os.path.join(path, d, EVENTS_FILE))
    ]
    if not candidates:
        raise FileNotFoundError(f"no {EVENTS_FILE} under {path!r}")
    return max(candidates, key=os.path.getmtime)


def read_events(path: str) -> list[dict]:
    """Parse a run's ``events.jsonl``. Unparseable records — above all
    the torn FINAL line a crashed or SIGKILLed writer leaves mid-record
    — are skipped with one warning naming the line(s), so the run stays
    readable and the loss stays visible."""
    run_dir = resolve_run_dir(path)
    return read_jsonl(os.path.join(run_dir, EVENTS_FILE))


def read_jsonl_rotated(file_path: str) -> list[dict]:
    """Like :func:`read_jsonl`, but stitches the rotated generation a
    :class:`JsonlSink` may have left (``<path>.1`` first, then the
    current file — oldest→newest). The ONE reader for the size-capped
    streams (``steps.jsonl``, ``spans.jsonl``): a consumer that read
    only the current file would silently drop the run's earliest
    records — exactly the baseline window the drift checks freeze on."""
    out: list[dict] = []
    for path in (file_path + ".1", file_path):
        if os.path.isfile(path):
            out.extend(read_jsonl(path))
    return out


def read_jsonl(file_path: str) -> list[dict]:
    """Tolerant JSONL reader shared by the event log and the step
    telemetry stream (same crash-torn-tail failure mode)."""
    out: list[dict] = []
    bad: list[int] = []
    with open(file_path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                bad.append(lineno)
    if bad:
        from keystone_tpu.core.logging import get_logger

        get_logger("keystone_tpu.observe").warning(
            "%s: skipped %d unparseable record(s) at line(s) %s — torn "
            "final line from a killed writer, or corruption",
            file_path,
            len(bad),
            bad[:5],
        )
    return out
