"""End-to-end span tracing: the causal layer of observe/.

The event log records *what ran* and the step stream *how fast*; this
module records *what caused what*: every hop a unit of work takes —
request → micro-batch → plan segment → staged chunk → device — becomes
one span record in ``<run-dir>/spans.jsonl``, linked by
``(trace, span, parent)`` ids that survive thread boundaries. A served
request, a train step, or a planned pass can then be rendered as a tree
(``python -m keystone_tpu observe trace <dir>``) and its wall decomposed
into *where the time went* buckets — the per-stage stall/goodput signal
the self-tuning planner (ROADMAP item 3) needs.

Activation mirrors :mod:`.telemetry` exactly: a :class:`SpanLog` exists
only while an event sink is active, and :func:`active_span_log` /
:func:`span` cost ONE global read returning None on the disabled path.

Span record schema (one JSON object per line; extra fields free-form):

==============  ========================================================
``ts``          unix time at emission (float, seconds)
``run``         run id (same id as the run's events)
``trace``       trace id — all spans of one causal unit share it
``span``        this span's id
``parent``      parent span id (absent for roots)
``name``        span name, dotted by subsystem (``serve.queue_wait``,
                ``plan.segment``, ``staging.h2d``, ``train.step``)
``wall_s``      wall-clock duration
``bucket``      goodput bucket (see :data:`BUCKETS`), absent on
                structural spans whose children carry the time
``status``      ``failed`` when the bracket raised (absent = ok)
==============  ========================================================

Thread boundaries: the ambient span context rides a ``contextvars``
variable, which does NOT flow into an already-running worker thread —
so the micro-batcher captures :func:`current` at submit time, the
staging engine at stream creation, and the decode loop at prompt
submit, then records spans with that explicit parent. That is the whole
propagation protocol; there is no global registry of live spans.

Goodput buckets (:data:`BUCKETS`) classify a span's wall:

==============  ========================================================
``queue``       admitted but waiting for coalescing/capacity
``wait_host``   host-side input production + host→device transfer
``wait_device`` blocked on device results (``block_until_ready``)
``compute``     dispatched device work (incl. the queued dispatch wall)
``collective``  cross-host barriers / merges
``checkpoint``  checkpoint save/restore
==============  ========================================================

Spans can overlap (staging overlaps compute by design), so bucket
shares are reported over the *classified* wall, not the run wall.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import os
import threading
import time
import uuid
from typing import Any, Iterator, NamedTuple

from keystone_tpu.observe import events as _events

SPANS_FILE = "spans.jsonl"

#: the goodput taxonomy — every classified span names one of these
BUCKETS = (
    "queue",
    "wait_host",
    "wait_device",
    "compute",
    "collective",
    "checkpoint",
)

# in-memory mirror cap — enough for the bench's goodput summaries and
# the trace renderer without growing with run length
_MAX_MEMORY_SPANS = 8192

_bind_lock = threading.Lock()
_UNSET: Any = object()


class SpanContext(NamedTuple):
    """The ids a child span needs from its parent — pass this across
    thread boundaries explicitly (contextvars stop at threads)."""

    trace: str
    span: str


def _new_id() -> str:
    return uuid.uuid4().hex[:12]


def make_context(
    parent: SpanContext | None = None, trace: str | None = None
) -> SpanContext:
    """Pre-allocate a span's ids so children recorded earlier (e.g. a
    prefill recorded at admit, inside a generation span recorded at
    retire) can parent on it before it is emitted."""
    t = trace or (parent.trace if parent is not None else _new_id())
    return SpanContext(t, _new_id())


# the ambient span: what a nested `span()` parents on when no explicit
# parent is given. Context-local, so concurrent requests never cross.
_current: contextvars.ContextVar[SpanContext | None] = contextvars.ContextVar(
    "keystone_span", default=None
)


def current() -> SpanContext | None:
    """The ambient span context (None outside any span). A plain
    context-local read — safe on any hot path."""
    return _current.get()


class SpanLog:
    """One run's span sink: ``spans.jsonl`` (size-rotated under
    ``KEYSTONE_OBSERVE_MAX_MB``) plus a bounded in-memory mirror.

    ``run_dir=None`` gives a memory-only log. Thread-safe; disk-write
    failure degrades with one warning, same rule as the event log.
    """

    def __init__(self, run_dir: str | None = None, run_id: str | None = None):
        self.run_id = run_id
        self.records: collections.deque = collections.deque(
            maxlen=_MAX_MEMORY_SPANS
        )
        self._lock = threading.Lock()
        self._sink: _events.JsonlSink | None = None
        if run_dir:
            try:
                self._sink = _events.JsonlSink(
                    os.path.join(run_dir, SPANS_FILE), "span log"
                )
            except OSError as e:
                from keystone_tpu.core.logging import get_logger

                get_logger("keystone_tpu.observe").warning(
                    "cannot open %s under %s (%r); span tracing is "
                    "memory-only for this run",
                    SPANS_FILE,
                    run_dir,
                    e,
                )

    def record_span(
        self,
        name: str,
        *,
        wall_s: float,
        bucket: str | None = None,
        parent: SpanContext | None = None,
        trace: str | None = None,
        ctx: SpanContext | None = None,
        status: str | None = None,
        **attrs: Any,
    ) -> SpanContext:
        """Emit one already-measured span and return its context.

        ``ctx`` reuses pre-allocated ids (:func:`make_context`);
        otherwise the trace comes from ``trace``, else the ``parent``,
        else a fresh one (a root)."""
        if ctx is None:
            ctx = make_context(parent, trace)
        rec: dict[str, Any] = {
            "ts": time.time(),
            "trace": ctx.trace,
            "span": ctx.span,
            "name": name,
            "wall_s": round(float(wall_s), 6),
        }
        if self.run_id:
            rec["run"] = self.run_id
        if parent is not None:
            rec["parent"] = parent.span
        if bucket is not None:
            rec["bucket"] = bucket
        if status is not None:
            rec["status"] = status
        for k, v in attrs.items():
            if v is not None:
                rec[k] = v
        with self._lock:
            self.records.append(rec)
            if self._sink is not None:
                self._sink.write(rec)
        return ctx

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None


def active_span_log() -> SpanLog | None:
    """The :class:`SpanLog` riding the active event sink, or None.

    The ONLY check the hot paths make: with no sink active this is
    exactly one global read (``events.active()``) and constructs
    nothing — the same overhead contract as
    :func:`keystone_tpu.observe.telemetry.active_step_log`."""
    log = _events.active()
    if log is None:
        return None
    sl = log.__dict__.get("_spanlog")
    if sl is None:
        with _bind_lock:
            sl = log.__dict__.get("_spanlog")
            if sl is None:
                sl = SpanLog(log.run_dir, log.run_id)
                log._spanlog = sl
    return sl


@contextlib.contextmanager
def span(
    name: str,
    *,
    bucket: str | None = None,
    parent: Any = _UNSET,
    trace: str | None = None,
    log: Any = _UNSET,
    **attrs: Any,
) -> Iterator[SpanContext | None]:
    """Bracket a block as one span: measures wall, parents on the
    ambient context (or an explicit ``parent``), installs itself as the
    ambient context for the duration, and emits on exit (``status:
    failed`` rides a raised exception out).

    With no sink active this yields None after exactly one global read
    — pass ``log=`` (a :class:`SpanLog` or None) to skip even that when
    the caller already looked it up once for a whole batch/stream.
    """
    sl = active_span_log() if log is _UNSET else log
    if sl is None:
        yield None
        return
    pctx = _current.get() if parent is _UNSET else parent
    ctx = make_context(pctx, trace)
    token = _current.set(ctx)
    t0 = time.perf_counter()
    status = None
    try:
        yield ctx
    except BaseException:
        status = "failed"
        raise
    finally:
        _current.reset(token)
        sl.record_span(
            name,
            wall_s=time.perf_counter() - t0,
            bucket=bucket,
            parent=pctx,
            ctx=ctx,
            status=status,
            **attrs,
        )


# --------------------------------------------------------------- analysis


def read_spans(run_dir: str) -> list[dict]:
    """A run's span records, rotated generation first (so order is
    oldest→newest); [] when the run recorded none."""
    run_dir = _events.resolve_run_dir(run_dir)
    return _events.read_jsonl_rotated(os.path.join(run_dir, SPANS_FILE))


def read_spans_all(base_dir: str) -> list[dict]:
    """EVERY run's span records under a base observe directory, merged
    and sorted by emission time — the cross-process view. A fleet is
    several processes (router + N replicas) each writing its own run
    dir; one request's causal tree spans them (the router's
    ``X-Keystone-Trace`` hop header carries the ids across), so the
    trace renderer must read them together to show router queue →
    replica queue → device compute as one tree."""
    if os.path.isfile(os.path.join(base_dir, SPANS_FILE)):
        dirs = [base_dir]
    else:
        dirs = [
            os.path.join(base_dir, d)
            for d in (
                os.listdir(base_dir) if os.path.isdir(base_dir) else ()
            )
            if os.path.isfile(os.path.join(base_dir, d, SPANS_FILE))
        ]
    out: list[dict] = []
    for d in sorted(dirs):
        out.extend(
            _events.read_jsonl_rotated(os.path.join(d, SPANS_FILE))
        )
    out.sort(key=lambda r: float(r.get("ts") or 0.0))
    return out


def build_trees(spans: list[dict]) -> dict[str, list[dict]]:
    """Group spans into per-trace trees: trace id → list of root nodes,
    each node ``{"rec": span, "children": [nodes...]}`` (children in
    emission order). A span whose parent never got emitted (crashed
    writer) is promoted to a root rather than dropped."""
    by_trace: dict[str, list[dict]] = {}
    nodes: dict[str, dict] = {}
    for rec in spans:
        sid = rec.get("span")
        if not sid:
            continue
        nodes[sid] = {"rec": rec, "children": []}
    for node in nodes.values():
        rec = node["rec"]
        parent = nodes.get(rec.get("parent"))
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            by_trace.setdefault(str(rec.get("trace")), []).append(node)
    return by_trace


def critical_path(node: dict) -> float:
    """Critical-path seconds through one span node: its own wall, or
    its children's critical paths summed when they account for more
    (children measured on other threads can exceed the parent's
    bracket)."""
    own = float(node["rec"].get("wall_s") or 0.0)
    if not node["children"]:
        return own
    return max(own, sum(critical_path(c) for c in node["children"]))


def trace_critical_path(roots: list[dict]) -> float:
    return sum(critical_path(r) for r in roots)


def goodput_summary(spans: list[dict]) -> dict[str, Any]:
    """The per-run "where the time went" report: wall per goodput
    bucket with its share of the classified total, plus trace count and
    summed critical-path length. Structural spans (no ``bucket``) are
    skipped — their time lives in their classified children — so the
    shares never double-count a parent bracket."""
    walls: dict[str, float] = {}
    for rec in spans:
        bucket = rec.get("bucket")
        if not bucket:
            continue
        walls[bucket] = walls.get(bucket, 0.0) + float(
            rec.get("wall_s") or 0.0
        )
    total = sum(walls.values())
    trees = build_trees(spans)
    cp = sum(trace_critical_path(roots) for roots in trees.values())
    return {
        "total_s": round(total, 6),
        "buckets": {
            b: {
                "wall_s": round(w, 6),
                "share": round(w / total, 4) if total else 0.0,
            }
            for b, w in sorted(
                walls.items(), key=lambda kv: -kv[1]
            )
        },
        "traces": len(trees),
        "spans": len(spans),
        "critical_path_s": round(cp, 6),
    }


# -------------------------------------------------------------- rendering


def _render_node(node: dict, depth: int, lines: list[str]) -> None:
    rec = node["rec"]
    wall = float(rec.get("wall_s") or 0.0)
    extras = []
    if rec.get("bucket"):
        extras.append(rec["bucket"])
    if rec.get("status") == "failed":
        extras.append("FAILED")
    for key in ("rid", "step", "rows", "requests", "bucket_size", "tokens"):
        if key in rec:
            extras.append(f"{key}={rec[key]}")
    tag = f"  [{', '.join(extras)}]" if extras else ""
    lines.append(
        f"{'  ' * depth}{rec.get('name', '?'):{max(34 - 2 * depth, 8)}} "
        f"{wall * 1e3:9.3f} ms{tag}"
    )
    for child in node["children"]:
        _render_node(child, depth + 1, lines)


def _trace_matches_request(roots: list[dict], rid: str) -> bool:
    return any(str(r["rec"].get("rid")) == rid for r in roots)


def render_traces(
    spans: list[dict], request: str | None = None, limit: int = 20
) -> str:
    """The ``observe trace`` body: per-trace span trees (newest first)
    with a critical-path summary line each. ``request`` filters to
    traces whose root carries that ``rid`` — and follows their
    ``batch_trace`` links so the underlying micro-batch's segment/chunk
    tree renders beneath the request's own."""
    trees = build_trees(spans)
    if not trees:
        return "(no spans recorded — spans.jsonl absent or empty)"
    order = sorted(
        trees,
        key=lambda t: max(
            float(r["rec"].get("ts") or 0.0) for r in trees[t]
        ),
        reverse=True,
    )
    selected: list[str] = []
    if request is not None:
        selected = [t for t in order if _trace_matches_request(trees[t], request)]
        if not selected:
            return f"(no trace with a root span rid={request!r})"
        # follow request → batch links: the batch trace carries the
        # segment/staging tree the request's dispatch rode through
        linked: list[str] = []
        for t in selected:
            stack = list(trees[t])
            while stack:
                node = stack.pop()
                bt = node["rec"].get("batch_trace")
                if bt and bt in trees and bt not in selected + linked:
                    linked.append(str(bt))
                stack.extend(node["children"])
        selected.extend(linked)
    else:
        selected = order[:limit]
    lines: list[str] = []
    for t in selected:
        roots = trees[t]
        cp = trace_critical_path(roots)
        names = sorted(
            (
                (critical_path(r), r["rec"].get("name", "?"))
                for r in roots
            ),
            reverse=True,
        )
        head = names[0][1] if names else "?"
        lines.append(
            f"trace {t}  ({sum(1 for _ in _walk(roots))} span(s), "
            f"critical path {cp * 1e3:.3f} ms, root {head})"
        )
        for root in roots:
            _render_node(root, 1, lines)
        lines.append("")
    if request is None and len(order) > limit:
        lines.append(f"... {len(order) - limit} more trace(s); --limit N")
    return "\n".join(lines).rstrip()


def _walk(roots: list[dict]) -> Iterator[dict]:
    stack = list(roots)
    while stack:
        node = stack.pop()
        yield node
        stack.extend(node["children"])


def render_goodput(summary: dict[str, Any]) -> list[str]:
    """Text lines for the goodput section shared by ``observe trace``
    and the run report."""
    lines = [
        f"goodput (where the time went — {summary['spans']} span(s), "
        f"{summary['traces']} trace(s), classified "
        f"{summary['total_s']:.3f}s, critical path "
        f"{summary['critical_path_s']:.3f}s):"
    ]
    for bucket, row in summary["buckets"].items():
        bar = "#" * int(round(row["share"] * 30))
        lines.append(
            f"  {bucket:12} {row['wall_s']:9.3f}s  "
            f"{row['share'] * 100:5.1f}%  {bar}"
        )
    if not summary["buckets"]:
        lines.append("  (no classified spans)")
    return lines


def main(argv: list[str] | None = None) -> None:
    """``python -m keystone_tpu observe trace <run-dir> [--request ID]
    [--limit N]``."""
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    request = None
    if "--request" in argv:
        i = argv.index("--request")
        if i + 1 >= len(argv):
            raise SystemExit("--request needs an id argument")
        request = argv[i + 1]
        del argv[i : i + 2]
    limit = 20
    if "--limit" in argv:
        i = argv.index("--limit")
        if i + 1 >= len(argv):
            raise SystemExit("--limit needs a count argument")
        try:
            limit = int(argv[i + 1])
        except ValueError:
            raise SystemExit(
                f"--limit: bad count {argv[i + 1]!r}"
            ) from None
        del argv[i : i + 2]
    if not argv or argv[0] in ("-h", "--help"):
        raise SystemExit(
            "usage: python -m keystone_tpu observe trace <run-dir> "
            "[--request ID] [--limit N]\n"
            "<run-dir> is a directory containing spans.jsonl, or a base\n"
            "KEYSTONE_OBSERVE_DIR (the newest run under it is rendered)"
        )
    try:
        if request is not None:
            # a request id is a cross-process question: the fleet
            # router and its replicas each wrote their own run dir
            # under the base — merge them so the tree crosses the hop
            spans = read_spans_all(argv[0])
            if not spans:
                spans = read_spans(argv[0])
        else:
            spans = read_spans(argv[0])
    except OSError as e:
        raise SystemExit(str(e)) from None
    print(render_traces(spans, request=request, limit=limit))
    print()
    print("\n".join(render_goodput(goodput_summary(spans))))
