"""The single registry of every structured event kind this codebase emits.

Every ``event:`` kind that can appear in a run's ``events.jsonl`` is
declared here — the schema README documents, the report/top renderers
switch on, and the drift test (``tests/test_spans.py``) greps emit sites
against. Adding an emit site with a new kind and forgetting to declare
it fails CI, so the consumer surfaces (report, top, jq pipelines) can
rely on this table being the whole vocabulary.

Stdlib-only and import-free on purpose: :mod:`.events` calls
:func:`note` on every emit (one set lookup; unknown kinds warn once per
process, they are never dropped — observability must degrade, not
censor).
"""

from __future__ import annotations

#: kind → one-line description (the contract; see each emitter's module)
EVENT_KINDS: dict[str, str] = {
    "run_start": "run activation bracket open (events.py)",
    "run_end": "run bracket close, wall + ok/failed status (events.py)",
    "node": "one pipeline-node call: phase, wall, status (pipeline "
    "hooks, observe/instrument.py)",
    "span": "one log_time bracket: label + wall (core/logging.py); "
    "causal trace spans live in spans.jsonl, not here",
    "phase": "coarse run phase wall (model mains)",
    "optimize": "a planner / fusion / staging decision (plan/passes.py, "
    "core/fusion.py, core/staging.py)",
    "bench": "the bench.py result record routed through the run log",
    "resilience": "a survived resilience decision: fault, retry, guard, "
    "preemption (resilience/emit.py); fleet routing/failover/breaker/"
    "restart decisions ride the same kind with action=fleet_* "
    "(serve/fleet.py)",
    "cluster": "a membership decision: heartbeat, verdict, re-mesh "
    "(resilience/cluster.py)",
    "serve": "serving lifecycle: start/stop, model, port "
    "(serve/server.py)",
    "device_memory": "per-device HBM watermark sample "
    "(observe/devices.py)",
    "trace_window": "a programmatic profiler window opened/closed "
    "(observe/tracing.py)",
    "metrics_rollup": "multihost metrics merge completed "
    "(parallel/multihost.py)",
    "alert": "an anomaly-monitor verdict: step-time drift, loss spike, "
    "HBM growth, deadline miss / shed rate, feature drift "
    "(observe/health.py); SLO burn-rate firing/cleared transitions "
    "with trace exemplars (observe/slo.py, phase=slo)",
    "model_swap": "online-learning model lifecycle: hot-swap with "
    "old/new version ids, rollback of a failed candidate, shadow "
    "start/stop (learn/swap.py, serve/server.py)",
    "refit": "a refit-daemon decision: chunk folded/skipped, versioned "
    "model published, reload notify (learn/refit.py)",
    "tune": "an autotuner decision: knob adjust/commit/revert/hold/load "
    "with the current knob snapshot and window goodput (plan/tune.py)",
    "collector": "a fleet-collector cycle summary: targets scraped/"
    "failed, points ingested, run dirs tailed, SLO verdicts firing "
    "(observe/collector.py); SLO burn-rate transitions ride the "
    "'alert' kind with phase=slo (observe/slo.py)",
    "chaos": "a chaos-campaign lifecycle record: campaign_start with "
    "the compiled fault schedule, process-level chaos_action steps, "
    "and the final verdict with per-invariant PASS/FAIL "
    "(resilience/chaos.py)",
}

_warned: set[str] = set()


def declared() -> frozenset[str]:
    """Every registered event kind (the drift test's ground truth)."""
    return frozenset(EVENT_KINDS)


def note(kind: str) -> bool:
    """Record that ``kind`` is being emitted; warns ONCE per unknown
    kind per process and returns whether it is declared. Never raises —
    an undeclared kind is schema drift to fix, not a reason to lose the
    record."""
    if kind in EVENT_KINDS:
        return True
    if kind not in _warned:
        _warned.add(kind)
        from keystone_tpu.core.logging import get_logger

        get_logger("keystone_tpu.observe").warning(
            "event kind %r is not declared in observe/schema.py — "
            "add it to EVENT_KINDS (schema drift)",
            kind,
        )
    return False
