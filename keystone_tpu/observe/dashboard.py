"""``observe serve`` — the live fleet dashboard over a collector dir.

A stdlib HTTP server that turns the collector's output directory into
the tier's single pane: fleet timelines (range queries over the
time-series store rendered as sparklines), the SLO burn-rate table with
FIRING markers and their trace exemplars, the merged alert feed, and a
federation ``/metrics`` endpoint external scrapers can ingest (the
collector's last-good merged exposition).

Endpoints::

    GET /                 HTML dashboard (auto-refreshing, no deps)
    GET /api/series       {"series": [names...]}
    GET /api/query?series=S[&start=T][&end=T][&limit=N]   range query
    GET /api/slo          {"objectives": [verdicts...]}   live evaluation
    GET /api/summary      one call the dashboard page polls: slo +
                          alerts + targets + series
    GET /metrics          federation exposition (text 0.0.4)

Everything is read-only over the collector's files — run it anywhere
that can see the directory (the collector host, a laptop over NFS); it
never contends with the collector's writer.

Usage: ``python -m keystone_tpu observe serve <dir> [--port N]``.
"""

from __future__ import annotations

import json
import os
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from keystone_tpu.observe import slo as _slo
from keystone_tpu.observe.collector import FEDERATION_FILE, TARGETS_FILE
from keystone_tpu.observe.timeseries import TimeSeriesStore

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>keystone fleet</title>
<style>
 body { font: 13px/1.5 monospace; background: #101418; color: #d6dde4;
        margin: 2em; }
 h1 { font-size: 15px; } h2 { font-size: 13px; color: #8fa3b0; }
 .firing { color: #ff6b6b; font-weight: bold; }
 .ok { color: #69db7c; }
 td, th { padding: 0 12px 0 0; text-align: left; }
 .spark { color: #74c0fc; }
 #err { color: #ffa94a; }
</style></head><body>
<h1>keystone fleet observability</h1>
<div id="err"></div>
<h2>SLO burn rates</h2><table id="slo"></table>
<h2>timelines</h2><div id="lines"></div>
<h2>alerts (newest last)</h2><pre id="alerts"></pre>
<h2>scrape targets</h2><pre id="targets"></pre>
<script>
const BARS = "\\u2581\\u2582\\u2583\\u2584\\u2585\\u2586\\u2587\\u2588";
// series names, exemplar ids, and alert actions come from SCRAPED
// data — a hostile target's label values must render as text, never
// as markup in the operator's browser
function esc(s) {
  return String(s).replace(/&/g, "&amp;").replace(/</g, "&lt;")
    .replace(/>/g, "&gt;").replace(/"/g, "&quot;");
}
function spark(vals) {
  if (!vals.length) return "";
  const lo = Math.min(...vals), hi = Math.max(...vals);
  const span = (hi - lo) || 1;
  return vals.map(v => BARS[Math.round((v - lo) / span * 7)]).join("");
}
async function refresh() {
  try {
    const s = await (await fetch("/api/summary")).json();
    const slo = document.getElementById("slo");
    slo.innerHTML = "<tr><th>objective</th><th>speed</th>" +
      "<th>burn(short)</th><th>burn(long)</th><th>factor</th>" +
      "<th>n</th><th>status</th></tr>";
    for (const v of s.slo) {
      const row = slo.insertRow();
      const status = v.firing
        ? `FIRING${v.exemplar_rid !== undefined
            ? " rid=" + v.exemplar_rid : ""}${v.exemplar_trace
            ? " trace=" + v.exemplar_trace : ""}`
        : "ok";
      row.innerHTML = `<td>${esc(v.objective)}</td><td>${esc(v.speed)}</td>` +
        `<td>${esc(v.burn_short)}</td><td>${esc(v.burn_long)}</td>` +
        `<td>${esc(v.factor)}</td><td>${esc(v.total)}</td>` +
        `<td class="${v.firing ? "firing" : "ok"}">${esc(status)}</td>`;
    }
    const lines = document.getElementById("lines");
    lines.textContent = "";
    for (const name of s.timeline_series) {
      // bounded to the slow SLO window: an unbounded query would make
      // the server re-parse the whole retention on every 2s refresh
      const start = Date.now() / 1000 - 21600;
      const q = await (await fetch(
        "/api/query?limit=120&start=" + start +
        "&series=" + encodeURIComponent(name))).json();
      const vals = q.points.map(p => p.value);
      const last = vals.length ? vals[vals.length - 1] : "-";
      const div = document.createElement("div");
      div.innerHTML = `${esc(name.padEnd(28))} <span class="spark">` +
        `${spark(vals)}</span>  ${esc(typeof last === "number"
          ? last.toPrecision(4) : last)} (${vals.length} pts)`;
      lines.appendChild(div);
    }
    document.getElementById("alerts").textContent = s.alerts.map(a =>
      `${new Date(a.ts * 1000).toISOString()}  ${a.action || a.state || "?"}` +
      `  ${a.state || ""}${a.exemplar_rid !== undefined
        ? "  rid=" + a.exemplar_rid : ""}`).join("\\n") || "(none)";
    document.getElementById("targets").textContent =
      Object.entries(s.targets).map(([t, st]) =>
        `${st.up ? "up  " : "DOWN"}  ${t}${st.error
          ? "  " + st.error : ""}`).join("\\n") || "(none)";
    document.getElementById("err").textContent = "";
  } catch (e) { document.getElementById("err").textContent = String(e); }
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""

#: series the dashboard's timeline panel plots by default (plus every
#: ``slo.*`` family found in the store)
DEFAULT_TIMELINES = (_slo.REQUEST_SERIES, _slo.GOODPUT_SERIES, "train.loss")


def _handler_for(out_dir: str, slo_config: _slo.SLOConfig | None):
    store = TimeSeriesStore(_slo.resolve_store_dir(out_dir))
    base_dir = (
        out_dir
        if os.path.isdir(os.path.join(out_dir, "tsdb"))
        else os.path.dirname(out_dir.rstrip("/")) or out_dir
    )

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: D102 — keep quiet
            pass

        def _send_json(self, code: int, payload) -> None:
            body = json.dumps(payload).encode()
            self._send_bytes(code, body, "application/json")

        def _send_bytes(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — stdlib API
            parsed = urllib.parse.urlsplit(self.path)
            path = parsed.path
            qs = urllib.parse.parse_qs(parsed.query)
            try:
                if path == "/":
                    return self._send_bytes(
                        200, _PAGE.encode(), "text/html; charset=utf-8"
                    )
                if path == "/api/series":
                    return self._send_json(
                        200, {"series": store.series_names()}
                    )
                if path == "/api/query":
                    return self._query(qs)
                if path == "/api/slo":
                    return self._send_json(200, {"objectives": self._slo()})
                if path == "/api/summary":
                    return self._summary()
                if path == "/metrics":
                    return self._federation()
            except Exception as e:  # noqa: BLE001 — the pane must answer
                return self._send_json(500, {"error": repr(e)})
            return self._send_json(
                404,
                {
                    "error": f"unknown path {path}",
                    "paths": [
                        "/", "/api/series", "/api/query", "/api/slo",
                        "/api/summary", "/metrics",
                    ],
                },
            )

        def _query(self, qs: dict) -> None:
            series = (qs.get("series") or [None])[0]
            if not series:
                return self._send_json(
                    400, {"error": "series parameter required"}
                )

            def _f(key):
                raw = (qs.get(key) or [None])[0]
                return float(raw) if raw else None

            limit = int((qs.get("limit") or ["500"])[0])
            points = store.query(
                series, start=_f("start"), end=_f("end"), limit=limit
            )
            return self._send_json(
                200, {"series": series, "points": points}
            )

        def _slo(self) -> list[dict]:
            engine = _slo.SLOEngine(store, slo_config, emit=False)
            return engine.evaluate()

        def _summary(self) -> None:
            names = store.series_names()
            # default panels first, then every burn-rate gauge the
            # collector persists per (objective, speed) pair
            timelines = [
                n
                for n in DEFAULT_TIMELINES
                if n in names
            ] + [n for n in names if n.startswith("slo_burn{")]
            # alert feeds bounded to the slow window: the segment-span
            # index can then skip everything older without parsing it
            horizon = time.time() - 21600
            alerts = store.query(_slo.ALERT_SERIES, start=horizon, limit=10)
            alerts += store.query("alerts", start=horizon, limit=10)
            alerts.sort(key=lambda r: r.get("ts") or 0)
            targets = {}
            tpath = os.path.join(base_dir, TARGETS_FILE)
            if os.path.isfile(tpath):
                try:
                    with open(tpath) as f:
                        targets = json.load(f)
                except (OSError, ValueError):
                    targets = {}
            return self._send_json(
                200,
                {
                    "ts": time.time(),
                    "slo": self._slo(),
                    "alerts": alerts[-12:],
                    "targets": targets,
                    "series": names,
                    "timeline_series": timelines,
                },
            )

        def _federation(self) -> None:
            fpath = os.path.join(base_dir, FEDERATION_FILE)
            body = b""
            if os.path.isfile(fpath):
                try:
                    with open(fpath, "rb") as f:
                        body = f.read()
                except OSError:
                    body = b""
            return self._send_bytes(
                200, body, "text/plain; version=0.0.4; charset=utf-8"
            )

    return Handler


def serve(
    out_dir: str,
    port: int = 8200,
    host: str = "127.0.0.1",
    slo_config: _slo.SLOConfig | None = None,
) -> ThreadingHTTPServer:
    """Bind the dashboard server (caller runs ``serve_forever``); port 0
    asks the OS — read the bound port off ``server_address``."""
    return ThreadingHTTPServer(
        (host, port), _handler_for(out_dir, slo_config)
    )


def main(argv: list[str] | None = None) -> None:
    """``python -m keystone_tpu observe serve <dir> [--port N]
    [--host H] [--config FILE]``."""
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    port, host, config = 8200, "127.0.0.1", None
    for flag in ("--port", "--host", "--config"):
        if flag in argv:
            i = argv.index(flag)
            if i + 1 >= len(argv):
                raise SystemExit(f"{flag} needs a value")
            val = argv[i + 1]
            if flag == "--port":
                try:
                    port = int(val)
                except ValueError:
                    raise SystemExit(f"--port: bad port {val!r}") from None
            elif flag == "--host":
                host = val
            else:
                config = _slo.SLOConfig.from_file(val)
            del argv[i : i + 2]
    if not argv or argv[0] in ("-h", "--help"):
        raise SystemExit(
            "usage: python -m keystone_tpu observe serve <dir> "
            "[--port N] [--host H] [--config FILE]\n"
            "<dir> is a collector output directory (contains tsdb/,\n"
            "federation.prom); serves the live fleet dashboard, range-\n"
            "query API, SLO verdicts, and federation /metrics"
        )
    try:
        _slo.resolve_store_dir(argv[0])
    except OSError as e:
        raise SystemExit(str(e)) from None
    httpd = serve(argv[0], port=port, host=host, slo_config=config)
    bound = httpd.server_address[1]
    print(
        f"fleet dashboard for {argv[0]!r} on http://{host}:{bound}",
        flush=True,
    )
    try:
        httpd.serve_forever(poll_interval=0.1)
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
