"""Process-wide metrics registry: counters, gauges, and timers.

The in-process aggregate view that complements the per-event record in
:mod:`.events` — the event log answers "what happened when", this
answers "how many / how long in total". Series are keyed by metric name
plus a frozen label set (``counter("node_calls", node="01:Pooler")``),
mirroring the Prometheus data model without the dependency.

Everything is thread-safe: the registry guards series creation, each
series guards its own updates, so concurrent pipeline calls (e.g. the
streaming loaders' decode-ahead thread) can record freely.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Any, Iterable, Iterator, NamedTuple, Sequence

# label-value characters that collide with the key syntax itself — a
# value like a node repr ("f{x}, y=2") must not alias another series
_ESCAPES = ("\\", ",", "=", "{", "}")


def _escape(value: str) -> str:
    for ch in _ESCAPES:
        value = value.replace(ch, "\\" + ch)
    return value


def _unescape(value: str) -> str:
    out: list[str] = []
    i = 0
    while i < len(value):
        if value[i] == "\\" and i + 1 < len(value):
            out.append(value[i + 1])
            i += 2
        else:
            out.append(value[i])
            i += 1
    return "".join(out)


def _split_unescaped(s: str, sep: str, maxsplit: int = -1) -> list[str]:
    """Split on ``sep`` outside backslash escapes (escapes preserved)."""
    parts: list[str] = []
    cur: list[str] = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            cur.append(c)
            cur.append(s[i + 1])
            i += 2
            continue
        if c == sep and maxsplit != 0:
            parts.append("".join(cur))
            cur = []
            if maxsplit > 0:
                maxsplit -= 1
            i += 1
            continue
        cur.append(c)
        i += 1
    parts.append("".join(cur))
    return parts


def _series_key(name: str, labels: dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={_escape(str(labels[k]))}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def parse_series_key(key: str) -> tuple[str, dict[str, str]]:
    """Invert :func:`_series_key`: ``'calls{node=a}'`` →
    ``('calls', {'node': 'a'})``. Label values round-trip even when they
    contain ``,``/``=``/``{``/``}`` (escaped on the way in)."""
    if not key.endswith("}"):
        return key, {}
    brace = key.find("{")
    if brace < 0:
        return key, {}
    name, inner = key[:brace], key[brace + 1 : -1]
    labels: dict[str, str] = {}
    for part in _split_unescaped(inner, ","):
        kv = _split_unescaped(part, "=", maxsplit=1)
        if len(kv) != 2:
            continue
        labels[_unescape(kv[0])] = _unescape(kv[1])
    return name, labels


def percentiles(
    values: Sequence[float], qs: Iterable[float] = (50, 95, 99)
) -> dict[float, float]:
    """Nearest-rank percentiles of ``values`` (empty input → {})."""
    vals = sorted(values)
    if not vals:
        return {}
    out: dict[float, float] = {}
    for q in qs:
        idx = min(int(round(q / 100.0 * (len(vals) - 1))), len(vals) - 1)
        out[q] = vals[idx]
    return out


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0

    def inc(self, n: int | float = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = v


# per-timer reservoir cap: enough resolution for p99 on long runs,
# bounded so a million-step loop can't grow the host heap
_RESERVOIR_CAP = 512


class Timer:
    """Duration summary: count / total / min / max seconds, plus a
    bounded reservoir (Vitter's algorithm R, deterministic seed) so
    :meth:`summary` can report p50/p95/p99 — the tail a min/max pair
    hides — without unbounded memory."""

    __slots__ = ("_lock", "count", "total", "min", "max", "samples", "_rng")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0
        self.samples: list[float] = []
        self._rng = random.Random(0x5EED)

    def observe(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total += seconds
            self.min = min(self.min, seconds)
            self.max = max(self.max, seconds)
            if len(self.samples) < _RESERVOIR_CAP:
                self.samples.append(seconds)
            else:
                j = self._rng.randrange(self.count)
                if j < _RESERVOIR_CAP:
                    self.samples[j] = seconds

    def percentile(self, q: float) -> float:
        """Estimated q-th percentile from the reservoir (0.0 when empty)."""
        with self._lock:
            samples = list(self.samples)
        return percentiles(samples, (q,)).get(q, 0.0)

    @contextlib.contextmanager
    def time(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0)

    def summary(self) -> dict:
        with self._lock:
            mean = self.total / self.count if self.count else 0.0
            out = {
                "count": self.count,
                "total_s": self.total,
                "mean_s": mean,
                "min_s": self.min if self.count else 0.0,
                "max_s": self.max,
            }
            if self.samples:
                p = percentiles(self.samples, (50, 95, 99))
                out.update(p50_s=p[50], p95_s=p[95], p99_s=p[99])
            return out


def _prom_name(name: str) -> str:
    """Sanitize to the Prometheus metric-name charset
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (our names are already snake_case;
    this guards dynamically-built ones)."""
    out = "".join(
        c if c.isascii() and (c.isalnum() or c in "_:") else "_"
        for c in name
    )
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def _prom_counter_name(name: str) -> str:
    """Exposition name of a counter: the conformant ``_total`` suffix
    (a scraper's counter-vs-gauge heuristics and recording rules key off
    it), added once — a name already ending in ``_total`` stays put."""
    pname = _prom_name(name)
    return pname if pname.endswith("_total") else pname + "_total"


def _prom_help(text: str) -> str:
    """Escape a HELP string per the exposition rules (backslash and
    newline only; quotes are legal in HELP)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _prom_labels(labels: dict[str, Any]) -> str:
    if not labels:
        return ""
    parts = []
    for k in sorted(labels):
        v = str(labels[k])
        v = (
            v.replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )
        parts.append(f'{_prom_name(str(k))}="{v}"')
    return "{" + ",".join(parts) + "}"


def _prom_value(v: Any) -> str:
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 2**53 else repr(f)


class PromSample(NamedTuple):
    """One parsed exposition sample: family kind rides along (None when
    the text declared no TYPE for it)."""

    name: str
    kind: str | None
    labels: dict[str, str]
    value: float


def _parse_prom_labels(inner: str) -> dict[str, str]:
    """Parse ``a="x",b="y"`` with exposition escapes (``\\\\``, ``\\"``,
    ``\\n``) inside the quoted values."""
    labels: dict[str, str] = {}
    i = 0
    n = len(inner)
    while i < n:
        eq = inner.find("=", i)
        if eq < 0:
            break
        key = inner[i:eq].strip().lstrip(",").strip()
        i = eq + 1
        if i >= n or inner[i] != '"':
            break
        i += 1
        out: list[str] = []
        while i < n:
            c = inner[i]
            if c == "\\" and i + 1 < n:
                nxt = inner[i + 1]
                out.append({"n": "\n"}.get(nxt, nxt))
                i += 2
                continue
            if c == '"':
                i += 1
                break
            out.append(c)
            i += 1
        labels[key] = "".join(out)
    return labels


def parse_prometheus(text: str) -> list[PromSample]:
    """Parse Prometheus 0.0.4 text exposition into samples — the
    collector's scrape decoder (and the conformance check that
    :meth:`MetricsRegistry.to_prometheus` round-trips). ``# TYPE`` lines
    attach the family kind to every sample of that family, including
    summary ``_count``/``_sum`` suffixed lines; unparseable lines are
    skipped (a scrape must degrade, not crash)."""
    kinds: dict[str, str] = {}
    samples: list[PromSample] = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3].strip()
            continue
        if line.startswith("{"):
            continue
        brace = line.find("{")
        if brace >= 0:
            name = line[:brace]
            close = line.rfind("}")
            if close < brace:
                continue
            labels = _parse_prom_labels(line[brace + 1 : close])
            rest = line[close + 1 :].strip()
        else:
            name, _, rest = line.partition(" ")
            labels = {}
            rest = rest.strip()
        if not name or not rest:
            continue
        try:
            value = float(rest.split()[0])
        except ValueError:
            continue
        kind = kinds.get(name)
        if kind is None:
            for suffix in ("_count", "_sum"):
                if name.endswith(suffix):
                    kind = kinds.get(name[: -len(suffix)])
                    break
        samples.append(PromSample(name, kind, labels, value))
    return samples


class MetricsRegistry:
    """Get-or-create home of all labeled series in one process."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "timer": Timer}

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._series: dict[str, tuple[str, Any]] = {}
        self._help: dict[str, str] = {}

    def describe(self, name: str, help_text: str) -> None:
        """Attach a ``# HELP`` string to a metric family (by its bare
        name, pre-``_total``); families without one get an auto-generated
        line so the exposition stays conformant either way."""
        with self._lock:
            self._help[name] = str(help_text)

    def _get(self, kind: str, name: str, labels: dict[str, Any]):
        key = _series_key(name, labels)
        with self._lock:
            hit = self._series.get(key)
            if hit is None:
                hit = (kind, self._KINDS[kind]())
                self._series[key] = hit
            elif hit[0] != kind:
                raise ValueError(
                    f"metric {key!r} already registered as {hit[0]}, not {kind}"
                )
            return hit[1]

    def counter(self, name: str, **labels: Any) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels: Any) -> Gauge:
        return self._get("gauge", name, labels)

    def timer(self, name: str, **labels: Any) -> Timer:
        return self._get("timer", name, labels)

    def snapshot(self) -> dict[str, Any]:
        """Point-in-time dump: series key → value (counters/gauges) or
        summary dict (timers)."""
        with self._lock:
            items = list(self._series.items())
        out: dict[str, Any] = {}
        for key, (kind, series) in items:
            out[key] = series.summary() if kind == "timer" else series.value
        return out

    def dump(self) -> dict[str, dict]:
        """Kind-tagged snapshot for cross-process merging (the multihost
        roll-up): series key → ``{"kind": ..., "value": ...}`` for
        counters/gauges, ``{"kind": "timer", **summary, "samples":
        [...]}`` for timers — the reservoir rides along so merged
        percentiles come from pooled samples, not averaged quantiles."""
        with self._lock:
            items = list(self._series.items())
        out: dict[str, dict] = {}
        for key, (kind, series) in items:
            if kind == "timer":
                entry: dict[str, Any] = {"kind": "timer", **series.summary()}
                with series._lock:
                    entry["samples"] = list(series.samples)
                out[key] = entry
            else:
                out[key] = {"kind": kind, "value": series.value}
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every series —
        the ``/metrics`` scrape body. Conformance an external scraper
        (and the collector's federation endpoint) relies on: every
        family gets ``# HELP`` and ``# TYPE`` lines (auto-generated HELP
        when :meth:`describe` never named one), counters expose under
        the ``_total`` suffix, gauges map directly, and timers render as
        a summary family: ``<name>_count``, ``<name>_sum`` (seconds),
        and reservoir-estimated ``quantile="0.5|0.95|0.99"`` sample
        lines. Metric names are sanitized to the Prometheus charset;
        label values escape backslash, quote, and newline per the
        exposition rules. The JSON negotiation path (:meth:`snapshot`)
        is untouched — its keys stay the registry's bare series keys."""
        with self._lock:
            items = list(self._series.items())
            help_texts = dict(self._help)
        families: dict[tuple[str, str], list[str]] = {}
        bare_names: dict[tuple[str, str], str] = {}
        for key, (kind, series) in sorted(items):
            name, labels = parse_series_key(key)
            pname = (
                _prom_counter_name(name)
                if kind == "counter"
                else _prom_name(name)
            )
            fam = families.setdefault((pname, kind), [])
            bare_names[(pname, kind)] = name
            if kind == "timer":
                summ = series.summary()
                fam.append(
                    f"{pname}_count{_prom_labels(labels)} {summ['count']}"
                )
                fam.append(
                    f"{pname}_sum{_prom_labels(labels)} "
                    f"{_prom_value(summ['total_s'])}"
                )
                for q, field in ((0.5, "p50_s"), (0.95, "p95_s"), (0.99, "p99_s")):
                    if field in summ:
                        fam.append(
                            f"{pname}"
                            f"{_prom_labels({**labels, 'quantile': q})} "
                            f"{_prom_value(summ[field])}"
                        )
            else:
                fam.append(
                    f"{pname}{_prom_labels(labels)} "
                    f"{_prom_value(series.value)}"
                )
        lines: list[str] = []
        type_names = {"counter": "counter", "gauge": "gauge", "timer": "summary"}
        kind_help = {
            "counter": "monotonic count",
            "gauge": "last-written value",
            "timer": "duration summary (seconds)",
        }
        for (pname, kind), fam in families.items():
            bare = bare_names[(pname, kind)]
            help_text = help_texts.get(bare) or (
                f"keystone_tpu {kind_help[kind]} '{bare}'"
            )
            lines.append(f"# HELP {pname} {_prom_help(help_text)}")
            lines.append(f"# TYPE {pname} {type_names[kind]}")
            lines.extend(fam)
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (instrumentation records here)."""
    return _registry
