"""Process-wide metrics registry: counters, gauges, and timers.

The in-process aggregate view that complements the per-event record in
:mod:`.events` — the event log answers "what happened when", this
answers "how many / how long in total". Series are keyed by metric name
plus a frozen label set (``counter("node_calls", node="01:Pooler")``),
mirroring the Prometheus data model without the dependency.

Everything is thread-safe: the registry guards series creation, each
series guards its own updates, so concurrent pipeline calls (e.g. the
streaming loaders' decode-ahead thread) can record freely.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Iterator


def _series_key(name: str, labels: dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonically increasing count."""

    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0

    def inc(self, n: int | float = 1) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self.value = v


class Timer:
    """Duration summary: count / total / min / max seconds."""

    __slots__ = ("_lock", "count", "total", "min", "max")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def observe(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total += seconds
            self.min = min(self.min, seconds)
            self.max = max(self.max, seconds)

    @contextlib.contextmanager
    def time(self) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0)

    def summary(self) -> dict:
        with self._lock:
            mean = self.total / self.count if self.count else 0.0
            return {
                "count": self.count,
                "total_s": self.total,
                "mean_s": mean,
                "min_s": self.min if self.count else 0.0,
                "max_s": self.max,
            }


class MetricsRegistry:
    """Get-or-create home of all labeled series in one process."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "timer": Timer}

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._series: dict[str, tuple[str, Any]] = {}

    def _get(self, kind: str, name: str, labels: dict[str, Any]):
        key = _series_key(name, labels)
        with self._lock:
            hit = self._series.get(key)
            if hit is None:
                hit = (kind, self._KINDS[kind]())
                self._series[key] = hit
            elif hit[0] != kind:
                raise ValueError(
                    f"metric {key!r} already registered as {hit[0]}, not {kind}"
                )
            return hit[1]

    def counter(self, name: str, **labels: Any) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels: Any) -> Gauge:
        return self._get("gauge", name, labels)

    def timer(self, name: str, **labels: Any) -> Timer:
        return self._get("timer", name, labels)

    def snapshot(self) -> dict[str, Any]:
        """Point-in-time dump: series key → value (counters/gauges) or
        summary dict (timers)."""
        with self._lock:
            items = list(self._series.items())
        out: dict[str, Any] = {}
        for key, (kind, series) in items:
            out[key] = series.summary() if kind == "timer" else series.value
        return out

    def reset(self) -> None:
        with self._lock:
            self._series.clear()


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (instrumentation records here)."""
    return _registry
