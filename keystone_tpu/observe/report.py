"""Run-report renderer: ``python -m keystone_tpu observe <run-dir>``.

Joins a run's wall-time events (:mod:`.events`) with its per-node cost
profiles (:mod:`.cost`) into the KeystoneML-style operator summary: per
node — calls, total/mean wall time, share of run, modeled GFLOPs and
bytes from ``cost_analysis()``, achieved FLOP/s, and the fraction of the
chip's bf16 peak (roofline basis: ROOFLINE.md — one v5e chip ≈ 197 TF/s
bf16, HBM ≈ 819 GB/s; CPU runs have no peak entry and show ``-``).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any

from keystone_tpu.observe import cost as _cost
from keystone_tpu.observe import events as _events

# The roofline basis lives in ONE place now:
# :data:`keystone_tpu.plan.costs.DEVICE_PEAKS` (bf16 MXU peak, HBM B/s,
# PCIe B/s, ICI B/s per device kind — ROOFLINE.md). Re-exported here so
# bench.py / tools/mfu_sweep.py keep their historical import site and
# the report's vs_peak column can never drift from the planner's
# transfer/recompute estimates.
from keystone_tpu.plan.costs import (  # noqa: F401 — re-exports
    DEVICE_PEAKS,
    peak_flops_for,
)

#: legacy aliases (pre-single-sourcing callers): bf16 peaks per chip and
#: the v5e HBM stream rate, both views of DEVICE_PEAKS
PEAK_FLOPS = {
    kind: peaks[0] for kind, peaks in DEVICE_PEAKS.items() if kind != "cpu"
}
HBM_BYTES_PER_S = DEVICE_PEAKS["v5 lite"][1]


def summarize(events: list[dict]) -> dict[str, Any]:
    """Aggregate a run's events: per-node execute stats, compile brackets,
    coarse phases/spans, and run metadata."""
    nodes: dict[str, dict] = {}
    compiles: dict[str, float] = {}
    phases: list[dict] = []
    spans: list[dict] = []
    optimizes: list[dict] = []
    clusters: list[dict] = []
    serves: list[dict] = []
    fleets: list[dict] = []
    swaps: list[dict] = []
    refits: list[dict] = []
    tunes: list[dict] = []
    collectors: list[dict] = []
    alerts: list[dict] = []
    device_memory: dict | None = None
    trace_windows: list[dict] = []
    meta: dict[str, Any] = {"run": None, "wall_s": None, "status": None}
    for ev in events:
        kind = ev.get("event")
        if meta["run"] is None and ev.get("run"):
            meta["run"] = ev["run"]
        if kind == "node":
            label = str(ev.get("node", "?"))
            if ev.get("phase") == "compile":
                compiles[label] = compiles.get(label, 0.0) + ev.get("wall_s", 0.0)
                continue
            stat = nodes.setdefault(
                label,
                {"calls": 0, "total_s": 0.0, "max_s": 0.0, "failed": 0,
                 "phase": ev.get("phase", "apply")},
            )
            stat["calls"] += 1
            stat["total_s"] += ev.get("wall_s", 0.0)
            stat["max_s"] = max(stat["max_s"], ev.get("wall_s", 0.0))
            if ev.get("status") != "ok":
                stat["failed"] += 1
        elif kind == "phase":
            phases.append(ev)
        elif kind == "span":
            spans.append(ev)
        elif kind == "optimize":
            optimizes.append(ev)
        elif kind == "cluster":
            clusters.append(ev)
        elif kind == "serve":
            serves.append(ev)
        elif kind == "resilience" and str(ev.get("action", "")).startswith(
            "fleet_"
        ):
            # fleet routing/failover/restart decisions get their own
            # section (they ride the resilience schema on the wire)
            fleets.append(ev)
        elif kind == "model_swap":
            swaps.append(ev)
        elif kind == "refit":
            refits.append(ev)
        elif kind == "tune":
            tunes.append(ev)
        elif kind == "collector":
            collectors.append(ev)
        elif kind == "alert":
            alerts.append(ev)
        elif kind == "device_memory":
            device_memory = ev  # latest sample carries current watermarks
        elif kind == "trace_window":
            trace_windows.append(ev)
        elif kind == "run_end":
            meta["wall_s"] = ev.get("wall_s")
            meta["status"] = ev.get("status")
    return {
        "meta": meta,
        "nodes": nodes,
        "compiles": compiles,
        "phases": phases,
        "spans": spans,
        "optimizes": optimizes,
        "clusters": clusters,
        "serves": serves,
        "fleet": fleets,
        "model_swaps": swaps,
        "refits": refits,
        "tunes": tunes,
        "collectors": collectors,
        "alerts": alerts,
        "device_memory": device_memory,
        "trace_windows": trace_windows,
    }


def _fmt(value: float | None, scale: float = 1.0, digits: int = 2) -> str:
    if value is None:
        return "-"
    return f"{value / scale:.{digits}f}"


def render(run_dir: str) -> str:
    """The full text report for one run directory.

    The GFLOP/s and vs_peak columns assume the counted calls processed
    batches of the shape the cost profile was lowered for (the probe
    batch in the standard ``record_pipeline_profile`` flow); calls on
    other batch sizes shift those two columns by the size ratio — the
    wall-time columns are always measured truth.
    """
    # resolve ONCE so events and cost profiles come from the same run
    # even if a concurrent process appends a newer run mid-render
    run_dir = _events.resolve_run_dir(run_dir)
    events = _events.read_events(run_dir)
    summary = summarize(events)
    costs = _cost.load_profiles(run_dir)
    profiles = costs.get("profiles", {})
    peak = peak_flops_for(costs.get("device_kind"))

    lines: list[str] = []
    meta = summary["meta"]
    dev = costs.get("device_kind") or "unknown"
    ndev = costs.get("num_devices")
    lines.append(
        f"run {meta['run'] or '?'}  [{run_dir}]  "
        f"device={dev}{f' x{ndev}' if ndev else ''}  "
        f"events={len(events)}"
        + (f"  wall={meta['wall_s']:.2f}s" if meta["wall_s"] else "")
        + (f"  status={meta['status']}" if meta["status"] else "")
    )
    lines.append("")

    nodes = summary["nodes"]
    labels = sorted(set(nodes) | set(profiles))
    if labels:
        total_wall = sum(s["total_s"] for s in nodes.values()) or None
        header = (
            f"{'node':36} {'phase':7} {'calls':>5} {'total_s':>8} "
            f"{'mean_ms':>8} {'share%':>6} {'GFLOP':>9} {'MB_acc':>9} "
            f"{'GFLOP/s':>8} {'vs_peak':>7}"
        )
        lines.append(header)
        lines.append("-" * len(header))
        for label in labels:
            stat = nodes.get(label)
            prof = profiles.get(label, {})
            flops = prof.get("flops")
            bytes_acc = prof.get("bytes_accessed")
            calls = stat["calls"] if stat else 0
            total = stat["total_s"] if stat else None
            mean = total / calls if stat and calls else None
            share = (
                100.0 * total / total_wall if total is not None and total_wall else None
            )
            rate = (
                flops * calls / total
                if flops is not None and total
                else None
            )
            vs_peak = rate / peak if rate is not None and peak else None
            failed = f" ({stat['failed']} FAILED)" if stat and stat["failed"] else ""
            lines.append(
                f"{label:36} {(stat or {}).get('phase', '-'):7} {calls:>5} "
                f"{_fmt(total, digits=3):>8} {_fmt(mean, 1e-3, 1):>8} "
                f"{_fmt(share, digits=1):>6} {_fmt(flops, 1e9):>9} "
                f"{_fmt(bytes_acc, 1e6):>9} {_fmt(rate, 1e9, 1):>8} "
                f"{_fmt(vs_peak, digits=4):>7}{failed}"
            )
        lines.append("")

    if summary["compiles"]:
        lines.append("compile (first traced call):")
        for label, secs in sorted(summary["compiles"].items()):
            lines.append(f"  {label:36} {secs:8.3f}s")
        lines.append("")
    if summary["phases"]:
        lines.append("phases:")
        for ev in summary["phases"]:
            lines.append(
                f"  {str(ev.get('phase', '?')):16} "
                f"{ev.get('wall_s', 0.0):8.3f}s"
            )
        lines.append("")
    if summary["spans"]:
        lines.append("spans (log_time):")
        for ev in summary["spans"]:
            status = "" if ev.get("status") == "ok" else "  FAILED"
            lines.append(
                f"  {str(ev.get('label', '?')):36} "
                f"{ev.get('wall_s', 0.0):8.3f}s{status}"
            )
        lines.append("")
    if summary.get("optimizes"):
        lines.append("optimizer decisions (planner / staging):")
        for ev in summary["optimizes"]:
            src = ev.get("source", "?")
            decisions = ev.get("decisions")
            if decisions:
                for d in decisions:
                    fields = ", ".join(
                        f"{k}={v}" for k, v in d.items() if k != "action"
                    )
                    lines.append(
                        f"  [{src}] {d.get('action', '?')}: {fields}"
                    )
            else:
                fields = ", ".join(
                    f"{k}={v}"
                    for k, v in ev.items()
                    if k not in ("event", "source", "ts", "run", "seq")
                )
                lines.append(f"  [{src}] {fields}")
        lines.append("")
    if summary.get("clusters"):
        lines.append("cluster membership (heartbeats / supervisor):")
        for ev in summary["clusters"]:
            fields = ", ".join(
                f"{k}={v}"
                for k, v in ev.items()
                if k not in ("event", "ts", "run", "phase", "action")
            )
            lines.append(f"  {ev.get('action', '?')}: {fields}")
        lines.append("")
    if summary.get("serves"):
        lines.append("serving (request path lifecycle):")
        for ev in summary["serves"]:
            fields = ", ".join(
                f"{k}={v}"
                for k, v in ev.items()
                if k not in ("event", "ts", "run", "phase", "action")
            )
            lines.append(f"  {ev.get('action', '?')}: {fields}")
        lines.append("")
    if summary.get("fleet"):
        by_action: dict[str, int] = {}
        for ev in summary["fleet"]:
            action = str(ev.get("action", "?"))
            by_action[action] = by_action.get(action, 0) + 1
        lines.append(
            "serving fleet (router / replica lifecycle): "
            + "  ".join(
                f"{k.removeprefix('fleet_')}={v}"
                for k, v in sorted(by_action.items())
            )
        )
        for ev in summary["fleet"][-8:]:
            fields = ", ".join(
                f"{k}={v}"
                for k, v in ev.items()
                if k not in ("event", "ts", "run", "phase", "action")
                and v is not None
            )
            lines.append(f"  {ev.get('action', '?')}: {fields}")
        lines.append("")
    for key, title in (
        ("model_swaps", "model swaps (online-learning lifecycle):"),
        ("refits", "refit daemon (online-learning folds):"),
    ):
        if summary.get(key):
            lines.append(title)
            for ev in summary[key]:
                fields = ", ".join(
                    f"{k}={v}"
                    for k, v in ev.items()
                    if k not in ("event", "ts", "run", "phase", "action")
                    and v is not None
                )
                lines.append(f"  {ev.get('action', '?')}: {fields}")
            lines.append("")
    lines.extend(_tune_section(summary))
    lines.extend(_collector_section(summary))
    lines.extend(_alert_section(run_dir, summary))
    lines.extend(_goodput_section(run_dir))
    lines.extend(_telemetry_sections(run_dir, summary))
    if peak is None and profiles:
        lines.append(
            "(no bf16 peak known for this device kind — vs_peak omitted; "
            "roofline basis: ROOFLINE.md)"
        )
    return "\n".join(lines)


def _tune_section(summary: dict) -> list[str]:
    """The self-tuning controller's record: decision counts by action,
    the converged knob values, and the last few adjustments."""
    tunes = summary.get("tunes") or []
    if not tunes:
        return []
    by_action: dict[str, int] = {}
    knobs: dict | None = None
    for ev in tunes:
        action = str(ev.get("action", "?"))
        by_action[action] = by_action.get(action, 0) + 1
        if isinstance(ev.get("knobs"), dict):
            knobs = ev["knobs"]
    lines = [
        "autotuner (self-tuning decisions): "
        + "  ".join(f"{k}={v}" for k, v in sorted(by_action.items()))
    ]
    if knobs:
        lines.append(
            "  knobs: "
            + "  ".join(f"{k}={v}" for k, v in sorted(knobs.items()))
        )
    moves = [ev for ev in tunes if ev.get("action") != "hold"]
    for ev in moves[-6:]:
        fields = ", ".join(
            f"{k}={v}"
            for k, v in ev.items()
            if k not in ("event", "ts", "run", "action", "knobs")
            and v is not None
        )
        lines.append(f"  {ev.get('action', '?')}: {fields}")
    lines.append("")
    return lines


def _collector_section(summary: dict) -> list[str]:
    """The fleet-collector lifecycle: cycle count, last cycle's scrape
    outcome, and how many SLO pairs were firing at the end."""
    cycles = summary.get("collectors") or []
    if not cycles:
        return []
    last = cycles[-1]
    lines = ["collector:"]
    lines.append(
        f"  {len(cycles)} cycle(s); last: "
        f"{last.get('targets_ok', 0)} target(s) ok, "
        f"{last.get('targets_failed', 0)} failed, "
        f"{last.get('points', 0)} scraped point(s), "
        f"{last.get('tailed_points', 0)} tailed, "
        f"{last.get('run_dirs', 0)} run dir(s)"
    )
    firing = last.get("slo_firing")
    if firing:
        lines.append(f"  SLO: {firing} (objective, window) pair(s) FIRING")
    lines.append("")
    return lines


def _alert_section(run_dir: str, summary: dict) -> list[str]:
    """Recorded ``alert`` events (the live anomaly monitor's verdicts);
    when the run recorded none, the step stream is replayed offline
    through the same checks so a sink-only run still gets a verdict."""
    lines: list[str] = []
    alerts = summary.get("alerts") or []
    offline = False
    if not alerts:
        try:
            from keystone_tpu.observe import health as _health

            alerts = [
                {"action": a.get("kind"), **a} for a in _health.check_run(run_dir)
            ]
            offline = True
        except Exception:  # noqa: BLE001 — the report must render
            alerts = []
    if not alerts:
        return lines
    by_kind: dict[str, int] = {}
    for a in alerts:
        kind = str(a.get("action", "?"))
        by_kind[kind] = by_kind.get(kind, 0) + 1
    lines.append(
        "alerts"
        + (" (offline scan of steps.jsonl)" if offline else "")
        + ": "
        + "  ".join(f"{k}={v}" for k, v in sorted(by_kind.items()))
    )
    for a in alerts[-5:]:
        fields = ", ".join(
            f"{k}={v}"
            for k, v in a.items()
            if k not in ("event", "ts", "run", "phase", "action", "kind")
            and v is not None
        )
        lines.append(f"  {a.get('action', '?')}: {fields}")
    lines.append("")
    return lines


def _goodput_section(run_dir: str) -> list[str]:
    """The span stream's "where the time went" breakdown, when the run
    recorded spans."""
    from keystone_tpu.observe import spans as _spans

    try:
        span_recs = _spans.read_spans(run_dir)
    except OSError:
        return []
    if not span_recs:
        return []
    lines = _spans.render_goodput(_spans.goodput_summary(span_recs))
    lines.append(
        "  (span trees: python -m keystone_tpu observe trace <run-dir>)"
    )
    lines.append("")
    return lines


def _telemetry_sections(run_dir: str, summary: dict) -> list[str]:
    """Live-telemetry report sections: the per-step stream summary
    (``steps.jsonl``), device-memory watermarks, profiler trace windows,
    and the multihost cluster roll-up (``metrics_cluster.json``)."""
    from keystone_tpu.observe import telemetry as _telemetry
    from keystone_tpu.observe.metrics import percentiles

    lines: list[str] = []
    steps_path = os.path.join(run_dir, _telemetry.STEPS_FILE)
    if os.path.isfile(steps_path) or os.path.isfile(steps_path + ".1"):
        # rotation-aware: a size-capped run's earliest records live in
        # the .1 generation
        recs = _events.read_jsonl_rotated(steps_path)
        # plan chunk-stream rows (source="plan") and fused-fit solver
        # rows (source="solver") carry whole-stream walls on a
        # process-lifetime sequence — summarized separately so they
        # can't inflate the per-step percentiles
        steps = [
            r
            for r in recs
            if "step" in r and r.get("source", "train") == "train"
        ]
        plan_rows = [r for r in recs if r.get("source") == "plan"]
        solver_rows = [r for r in recs if r.get("source") == "solver"]
        if steps:
            last = steps[-1]
            walls = [
                r["wall_s"]
                for r in steps
                if isinstance(r.get("wall_s"), (int, float))
            ]
            p = percentiles(walls, (50, 95, 99)) if walls else {}
            line = f"live telemetry: {len(steps)} step record(s)"
            if "step" in last:
                line += f", last step {last['step']}"
            if isinstance(last.get("loss"), (int, float)):
                line += f", loss {last['loss']:.4f}"
            lines.append(line)
            if p:
                lines.append(
                    f"  step wall p50 {p[50] * 1e3:.1f} ms  "
                    f"p95 {p[95] * 1e3:.1f} ms  p99 {p[99] * 1e3:.1f} ms"
                )
            rates = [
                r["tokens_per_s"]
                for r in steps
                if isinstance(r.get("tokens_per_s"), (int, float))
            ]
            mfus = [
                r["mfu"]
                for r in steps
                if isinstance(r.get("mfu"), (int, float))
            ]
            if rates:
                lines.append(
                    f"  tokens/s last {rates[-1]:,.0f}  "
                    f"best {max(rates):,.0f}"
                    + (f"  mfu last {mfus[-1]:.4f}" if mfus else "")
                )
            lines.append("")
        if plan_rows:
            rows = sum(
                r["rows"]
                for r in plan_rows
                if isinstance(r.get("rows"), (int, float))
            )
            rps = [
                r["rows_per_s"]
                for r in plan_rows
                if isinstance(r.get("rows_per_s"), (int, float))
            ]
            lines.append(
                f"plan chunk streams: {len(plan_rows)} record(s), "
                f"{int(rows)} row(s)"
                + (f", last {rps[-1]:,.0f} rows/s" if rps else "")
            )
            lines.append("")
        if solver_rows:
            # fused streaming fits get their own heading: one row per
            # fit (rows/s, chunks, cost-priced MFU, chosen Gram
            # operator), not mixed into the generic plan chunk lines
            lines.append(
                f"solver streams (fused streaming fits): "
                f"{len(solver_rows)} fit(s)"
            )
            for r in solver_rows[-8:]:
                parts = [f"  {r.get('estimator', '?')}"]
                if isinstance(r.get("rows"), (int, float)):
                    parts.append(f"{int(r['rows'])} rows")
                if isinstance(r.get("chunks"), (int, float)):
                    parts.append(f"{int(r['chunks'])} chunk(s)")
                if isinstance(r.get("rows_per_s"), (int, float)):
                    parts.append(f"{r['rows_per_s']:,.0f} rows/s")
                if isinstance(r.get("mfu"), (int, float)):
                    parts.append(f"mfu {r['mfu']:.4f}")
                if r.get("gram"):
                    parts.append(f"gram={r['gram']}")
                lines.append("  ".join(parts))
            lines.append("")
        serve_rows = [r for r in recs if r.get("source") == "serve"]
        if serve_rows:
            batches = [r for r in serve_rows if "bucket" in r]
            decodes = [r for r in serve_rows if r.get("kind") == "decode"]
            parts = []
            if batches:
                rows = sum(
                    r["rows"]
                    for r in batches
                    if isinstance(r.get("rows"), (int, float))
                )
                fills = [
                    r["batch_fill"]
                    for r in batches
                    if isinstance(r.get("batch_fill"), (int, float))
                ]
                part = f"{len(batches)} batch(es), {int(rows)} row(s)"
                if fills:
                    part += f", mean fill {sum(fills) / len(fills):.2f}"
                parts.append(part)
            if decodes:
                toks = sum(
                    r["tokens"]
                    for r in decodes
                    if isinstance(r.get("tokens"), (int, float))
                )
                parts.append(
                    f"{len(decodes)} generation(s), {int(toks)} token(s)"
                )
            lines.append("serving stream: " + "; ".join(parts))
            # two different walls, NOT poolable: batch rows carry the
            # per-dispatch wall, decode rows the submit-to-finish wall
            # of a whole generation (orders of magnitude apart)
            batch_walls = [
                r["wall_s"]
                for r in batches
                if isinstance(r.get("wall_s"), (int, float))
            ]
            if batch_walls:
                p = percentiles(batch_walls, (50, 95))
                lines.append(
                    f"  dispatch wall p50 {p[50] * 1e3:.1f} ms  "
                    f"p95 {p[95] * 1e3:.1f} ms"
                )
            gen_walls = [
                r["wall_s"]
                for r in decodes
                if isinstance(r.get("wall_s"), (int, float))
            ]
            if gen_walls:
                p = percentiles(gen_walls, (50, 95))
                lines.append(
                    f"  generation wall p50 {p[50] * 1e3:.1f} ms  "
                    f"p95 {p[95] * 1e3:.1f} ms"
                )
            lines.append("")
    devmem = summary.get("device_memory")
    if devmem:
        lines.append("device memory (HBM watermarks, latest sample):")
        for d in devmem.get("devices") or []:
            limit = d.get("bytes_limit") or 0
            pct = (
                f"  ({100.0 * d['peak_bytes_in_use'] / limit:.0f}% of limit)"
                if limit
                else ""
            )
            lines.append(
                f"  {d.get('device', '?'):12} "
                f"in-use {d.get('bytes_in_use', 0) / 2**30:7.2f} GiB  "
                f"peak {d.get('peak_bytes_in_use', 0) / 2**30:7.2f} GiB{pct}"
            )
        lines.append("")
    if summary.get("trace_windows"):
        started = [
            ev
            for ev in summary["trace_windows"]
            if ev.get("status") == "started"
        ]
        if started:
            lines.append("profiler trace windows:")
            for ev in started:
                lines.append(
                    f"  step {ev.get('step', '?')} x{ev.get('steps', '?')} "
                    f"({ev.get('reason', '?')}) -> {ev.get('dir', '?')}"
                )
            lines.append("")
    cluster_path = os.path.join(run_dir, "metrics_cluster.json")
    if os.path.isfile(cluster_path):
        try:
            with open(cluster_path) as f:
                cluster = json.load(f)
        except (OSError, ValueError):
            cluster = None
        if cluster and cluster.get("metrics"):
            series = cluster["metrics"]
            lines.append(
                f"cluster metrics roll-up ({cluster.get('hosts', '?')} "
                f"host(s), {len(series)} series):"
            )
            for key in sorted(series)[:40]:
                val = series[key]
                if isinstance(val, dict):
                    parts = f"count={val.get('count', 0)}"
                    if "total_s" in val:
                        parts += f" total={val['total_s']:.3f}s"
                    if "p95_s" in val:
                        parts += f" p95={val['p95_s'] * 1e3:.1f}ms"
                    lines.append(f"  {key:44} {parts}")
                else:
                    lines.append(f"  {key:44} {val}")
            if len(series) > 40:
                lines.append(f"  ... {len(series) - 40} more")
            lines.append("")
    return lines


def per_node_breakdown(
    log: "_events.EventLog",
    profiles: dict[str, dict] | None = None,
    since: int = 0,
) -> dict[str, dict]:
    """Compact per-node dict for embedding in machine artifacts (bench):
    node label → calls/wall plus flops/bytes when profiled. ``since``
    restricts to records appended after that index — pass the record
    count captured before your instrumented apply when reusing an
    ambient log, so unrelated earlier events don't leak in."""
    summary = summarize(log.records[since:])
    out: dict[str, dict] = {}
    for label, stat in summary["nodes"].items():
        entry = {
            "calls": stat["calls"],
            "wall_s": round(stat["total_s"], 6),
        }
        prof = (profiles or {}).get(label, {})
        if "flops" in prof:
            entry["flops"] = prof["flops"]
        if "bytes_accessed" in prof:
            entry["bytes_accessed"] = prof["bytes_accessed"]
        out[label] = entry
    if not out and getattr(log, "dropped", 0):
        # the in-memory mirror hit its cap before these events: say so
        # rather than returning {} that reads as "no nodes ran" (the
        # file sink, when present, still has the full record)
        return {
            "error": f"{log.dropped} event records dropped (in-memory cap)"
        }
    return out


# ------------------------------------------------------------- run diff


def _diff_profile(run_dir: str) -> dict[str, Any]:
    """One run's comparable summary: goodput bucket shares (spans),
    train step-wall percentiles + rates (steps.jsonl), and per-kind /
    per-action event counts — the three axes ``observe diff`` renders."""
    from keystone_tpu.observe import spans as _spans
    from keystone_tpu.observe import telemetry as _telemetry
    from keystone_tpu.observe import top as _top
    from keystone_tpu.observe.metrics import percentiles

    run_dir = _top.resolve_run_dir(run_dir)
    out: dict[str, Any] = {
        "dir": run_dir,
        "goodput": None,
        "steps": {},
        "counts": {},
    }
    try:
        span_recs = _spans.read_spans(run_dir)
    except OSError:
        span_recs = []
    if span_recs:
        out["goodput"] = _spans.goodput_summary(span_recs)
    steps_path = os.path.join(run_dir, _telemetry.STEPS_FILE)
    if os.path.isfile(steps_path) or os.path.isfile(steps_path + ".1"):
        recs = _events.read_jsonl_rotated(steps_path)
        train = [
            r
            for r in recs
            if "step" in r and r.get("source", "train") == "train"
        ]
        walls = [
            r["wall_s"]
            for r in train
            if isinstance(r.get("wall_s"), (int, float))
        ]
        st: dict[str, Any] = {"n": len(train)}
        if walls:
            st["wall_p"] = percentiles(walls, (50, 95, 99))
        rates = [
            r["tokens_per_s"]
            for r in train
            if isinstance(r.get("tokens_per_s"), (int, float))
        ]
        if rates:
            st["tokens_per_s_best"] = max(rates)
        stream_rates = [
            r["rows_per_s"]
            for r in recs
            if r.get("source") in ("plan", "solver")
            and isinstance(r.get("rows_per_s"), (int, float))
        ]
        if stream_rates:
            st["rows_per_s_best"] = max(stream_rates)
        out["steps"] = st
    try:
        events = _events.read_events(run_dir)
    except OSError:
        events = []
    counts: dict[str, int] = {}
    for ev in events:
        kind = str(ev.get("event", "?"))
        counts[kind] = counts.get(kind, 0) + 1
        if ev.get("action") and kind in (
            "resilience",
            "cluster",
            "alert",
            "tune",
            "model_swap",
            "refit",
            "serve",
        ):
            key = f"{kind}.{ev['action']}"
            counts[key] = counts.get(key, 0) + 1
    out["counts"] = counts
    return out


def render_diff(dir_a: str, dir_b: str) -> str:
    """``observe diff <dirA> <dirB>``: side-by-side goodput shares,
    step-time percentiles, and event-counter deltas between two run
    dirs — the tuned-vs-static comparison, by hand."""
    a = _diff_profile(dir_a)
    b = _diff_profile(dir_b)
    lines = [
        f"A: {a['dir']}",
        f"B: {b['dir']}",
        "",
    ]
    ga, gb = a["goodput"], b["goodput"]
    if ga or gb:
        lines.append(
            f"goodput shares (A: {len((ga or {}).get('buckets', {}))} "
            f"bucket(s) over {(ga or {}).get('total_s', 0.0):.3f}s, "
            f"B: over {(gb or {}).get('total_s', 0.0):.3f}s):"
        )
        buckets = sorted(
            set((ga or {}).get("buckets", {}))
            | set((gb or {}).get("buckets", {}))
        )
        lines.append(f"  {'bucket':12} {'A':>8} {'B':>8} {'Δ':>9}")
        for bucket in buckets:
            sa = ((ga or {}).get("buckets", {}).get(bucket) or {}).get(
                "share", 0.0
            )
            sb = ((gb or {}).get("buckets", {}).get(bucket) or {}).get(
                "share", 0.0
            )
            lines.append(
                f"  {bucket:12} {sa * 100:7.1f}% {sb * 100:7.1f}% "
                f"{(sb - sa) * 100:+8.1f}pp"
            )
        lines.append("")
    sa, sb = a["steps"], b["steps"]
    if sa or sb:
        lines.append(
            f"steps: A {sa.get('n', 0)} record(s), B {sb.get('n', 0)}"
        )
        pa, pb = sa.get("wall_p") or {}, sb.get("wall_p") or {}
        for q in (50, 95, 99):
            if q in pa or q in pb:
                va, vb = pa.get(q), pb.get(q)
                delta = (
                    f"{(vb - va) / va * 100:+6.1f}%"
                    if va and vb is not None
                    else "      -"
                )
                lines.append(
                    f"  wall p{q:<3} "
                    f"{_fmt(va, 1e-3, 1):>8} ms {_fmt(vb, 1e-3, 1):>8} ms "
                    f"{delta}"
                )
        for key, label in (
            ("tokens_per_s_best", "tokens/s best"),
            ("rows_per_s_best", "rows/s best"),
        ):
            va, vb = sa.get(key), sb.get(key)
            if va is not None or vb is not None:
                delta = (
                    f"{(vb - va) / va * 100:+6.1f}%"
                    if va and vb is not None
                    else "      -"
                )
                lines.append(
                    f"  {label:12} {_fmt(va, digits=1):>10} "
                    f"{_fmt(vb, digits=1):>10} {delta}"
                )
        lines.append("")
    keys = sorted(set(a["counts"]) | set(b["counts"]))
    if keys:
        lines.append("event counts (A -> B):")
        for key in keys:
            ca, cb = a["counts"].get(key, 0), b["counts"].get(key, 0)
            if ca == cb:
                continue
            lines.append(f"  {key:28} {ca:>6} -> {cb:<6} ({cb - ca:+d})")
        if all(
            a["counts"].get(k, 0) == b["counts"].get(k, 0) for k in keys
        ):
            lines.append("  (identical)")
    return "\n".join(lines).rstrip()


def main(argv: list[str] | None = None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "diff":
        # tuned-vs-static comparison: `observe diff <dirA> <dirB>`
        if len(argv) != 3:
            raise SystemExit(
                "usage: python -m keystone_tpu observe diff <dirA> <dirB>"
            )
        try:
            print(render_diff(argv[1], argv[2]))
        except OSError as e:
            raise SystemExit(str(e)) from None
        return
    if argv and argv[0] == "top":
        # the live dashboard: `observe top <dir> [--once] [--interval S]`
        from keystone_tpu.observe import top as _top

        return _top.main(argv[1:])
    if argv and argv[0] == "trace":
        # span trees: `observe trace <dir> [--request ID] [--limit N]`
        from keystone_tpu.observe import spans as _spans

        return _spans.main(argv[1:])
    if argv and argv[0] == "collect":
        # the fleet collector daemon: scrape + tail → time-series store
        from keystone_tpu.observe import collector as _collector

        return _collector.main(argv[1:])
    if argv and argv[0] == "slo":
        # burn-rate status over a collector store: `observe slo <dir>`
        from keystone_tpu.observe import slo as _slo

        return _slo.main(argv[1:])
    if argv and argv[0] == "serve":
        # the live fleet dashboard: `observe serve <dir> --port N`
        from keystone_tpu.observe import dashboard as _dashboard

        return _dashboard.main(argv[1:])
    if not argv or argv[0] in ("-h", "--help"):
        raise SystemExit(
            "usage: python -m keystone_tpu observe <run-dir>\n"
            "       python -m keystone_tpu observe top <run-dir> [--once]"
            " [--interval S]\n"
            "       python -m keystone_tpu observe trace <run-dir>"
            " [--request ID] [--limit N]\n"
            "       python -m keystone_tpu observe diff <dirA> <dirB>\n"
            "       python -m keystone_tpu observe collect <out-dir>"
            " [--router URL] [--watch DIR] [--once]\n"
            "       python -m keystone_tpu observe slo <out-dir>"
            " [--config FILE]\n"
            "       python -m keystone_tpu observe serve <out-dir>"
            " [--port N]\n"
            "<run-dir> is a directory containing events.jsonl, or a base\n"
            "KEYSTONE_OBSERVE_DIR (the newest run under it is rendered;\n"
            "`top` on a base dir tails EVERY run dir, live);\n"
            "`trace` renders spans.jsonl as per-trace span trees with a\n"
            "critical-path summary and the goodput bucket breakdown;\n"
            "`diff` renders side-by-side goodput shares, step-time\n"
            "percentiles, and event-counter deltas between two runs;\n"
            "`collect` runs the fleet collector (scrapes /metrics,\n"
            "tails run dirs, evaluates SLOs into <out-dir>/tsdb);\n"
            "`slo` renders burn-rate status + alert history over a\n"
            "collector store; `serve` is the live fleet dashboard with\n"
            "/api/query range queries and federation /metrics"
        )
    try:
        print(render(argv[0]))
    except OSError as e:
        # missing dir, events.jsonl passed instead of its directory, ...
        raise SystemExit(str(e)) from None
