"""Multiclass evaluation (reference evaluation/MulticlassClassifierEvaluator.scala).

The reference builds the confusion matrix with a single-pass Spark
``aggregate``; here it's a one-hot scatter-add over the sharded batch — the
cross-device combine is XLA's psum. Metrics and the pretty printer mirror the
reference's (Mahout-style) report.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("num_classes",))
def _confusion(predicted, actual, num_classes: int, n_valid=None):
    """(num_classes, num_classes) matrix, rows = actual, cols = predicted."""
    n = predicted.shape[0]
    valid = (
        jnp.ones((n,), jnp.float32)
        if n_valid is None
        else (jnp.arange(n) < n_valid).astype(jnp.float32)
    )
    flat = actual * num_classes + predicted
    counts = jnp.zeros((num_classes * num_classes,), jnp.float32).at[flat].add(valid)
    return counts.reshape(num_classes, num_classes)


@dataclasses.dataclass
class MulticlassMetrics:
    confusion: np.ndarray  # rows = actual class, cols = predicted class

    @property
    def num_classes(self) -> int:
        return self.confusion.shape[0]

    @property
    def total(self) -> float:
        return float(self.confusion.sum())

    @property
    def accuracy(self) -> float:
        return float(np.trace(self.confusion) / max(self.confusion.sum(), 1))

    @property
    def error(self) -> float:
        return 1.0 - self.accuracy

    def class_precision(self) -> np.ndarray:
        pred_totals = self.confusion.sum(axis=0)
        return np.divide(
            np.diag(self.confusion),
            pred_totals,
            out=np.zeros(self.num_classes),
            where=pred_totals > 0,
        )

    def class_recall(self) -> np.ndarray:
        actual_totals = self.confusion.sum(axis=1)
        return np.divide(
            np.diag(self.confusion),
            actual_totals,
            out=np.zeros(self.num_classes),
            where=actual_totals > 0,
        )

    def class_f1(self) -> np.ndarray:
        p, r = self.class_precision(), self.class_recall()
        denom = np.where(p + r > 0, p + r, 1.0)
        return np.where(p + r > 0, 2 * p * r / denom, 0.0)

    @property
    def macro_precision(self) -> float:
        return float(self.class_precision().mean())

    @property
    def macro_recall(self) -> float:
        return float(self.class_recall().mean())

    @property
    def macro_f1(self) -> float:
        return float(self.class_f1().mean())

    # Micro-averaged P/R/F all equal accuracy for single-label multiclass,
    # as in the reference's MulticlassMetrics.
    @property
    def micro_precision(self) -> float:
        return self.accuracy

    @property
    def micro_recall(self) -> float:
        return self.accuracy

    @property
    def micro_f1(self) -> float:
        return self.accuracy

    def summary(self, class_names: list[str] | None = None) -> str:
        names = class_names or [str(i) for i in range(self.num_classes)]
        lines = [
            "=" * 60,
            "Summary",
            "-" * 60,
            f"Correctly Classified Instances   : {int(np.trace(self.confusion))}"
            f"  ({100 * self.accuracy:.4f}%)",
            f"Incorrectly Classified Instances : "
            f"{int(self.total - np.trace(self.confusion))}"
            f"  ({100 * self.error:.4f}%)",
            f"Total Classified Instances       : {int(self.total)}",
            f"Macro Precision/Recall/F1        : {self.macro_precision:.4f} / "
            f"{self.macro_recall:.4f} / {self.macro_f1:.4f}",
            "-" * 60,
            "Confusion Matrix (rows=actual, cols=predicted)",
        ]
        width = max(6, max(len(n) for n in names) + 1)
        header = " " * width + "".join(f"{n:>{width}}" for n in names)
        lines.append(header)
        for i, row in enumerate(self.confusion.astype(int)):
            lines.append(
                f"{names[i]:>{width}}" + "".join(f"{v:>{width}}" for v in row)
            )
        lines.append("=" * 60)
        return "\n".join(lines)


class MulticlassClassifierEvaluator:
    """Evaluate predicted vs actual int labels → :class:`MulticlassMetrics`."""

    def __init__(self, num_classes: int):
        self.num_classes = num_classes

    def evaluate(self, predicted, actual, n_valid: int | None = None):
        predicted = jnp.asarray(predicted).astype(jnp.int32)
        actual = jnp.asarray(actual).astype(jnp.int32)
        conf = _confusion(predicted, actual, self.num_classes, n_valid)
        return MulticlassMetrics(confusion=np.asarray(conf, dtype=np.float64))

    __call__ = evaluate
