"""Binary evaluation (reference evaluation/BinaryClassifierEvaluator.scala):
contingency table + derived metrics, mergeable across shards/batches."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class BinaryClassificationMetrics:
    tp: float
    fp: float
    tn: float
    fn: float

    def merge(self, other: "BinaryClassificationMetrics"):
        return BinaryClassificationMetrics(
            tp=self.tp + other.tp,
            fp=self.fp + other.fp,
            tn=self.tn + other.tn,
            fn=self.fn + other.fn,
        )

    __add__ = merge

    @property
    def total(self) -> float:
        return self.tp + self.fp + self.tn + self.fn

    @property
    def accuracy(self) -> float:
        return (self.tp + self.tn) / max(self.total, 1.0)

    @property
    def error(self) -> float:
        return 1.0 - self.accuracy

    @property
    def precision(self) -> float:
        d = self.tp + self.fp
        return self.tp / d if d else 1.0

    @property
    def recall(self) -> float:
        d = self.tp + self.fn
        return self.tp / d if d else 1.0

    @property
    def specificity(self) -> float:
        d = self.tn + self.fp
        return self.tn / d if d else 1.0

    def f_score(self, beta: float = 1.0) -> float:
        p, r = self.precision, self.recall
        b2 = beta * beta
        d = b2 * p + r
        return (1 + b2) * p * r / d if d else 0.0

    @property
    def f1(self) -> float:
        return self.f_score(1.0)


class BinaryClassifierEvaluator:
    """Evaluate boolean predictions vs boolean actuals."""

    @staticmethod
    def evaluate(predicted, actual, n_valid: int | None = None):
        predicted = np.asarray(jnp.asarray(predicted)).astype(bool)
        actual = np.asarray(jnp.asarray(actual)).astype(bool)
        if n_valid is not None:
            predicted, actual = predicted[:n_valid], actual[:n_valid]
        tp = float(np.sum(predicted & actual))
        fp = float(np.sum(predicted & ~actual))
        tn = float(np.sum(~predicted & ~actual))
        fn = float(np.sum(~predicted & actual))
        return BinaryClassificationMetrics(tp=tp, fp=fp, tn=tn, fn=fn)

    __call__ = evaluate
