"""Evaluators (reference ``src/main/scala/evaluation/``, SURVEY.md §2.8)."""

from keystone_tpu.evaluation.binary import (
    BinaryClassificationMetrics,
    BinaryClassifierEvaluator,
)
from keystone_tpu.evaluation.mean_ap import MeanAveragePrecisionEvaluator
from keystone_tpu.evaluation.multiclass import (
    MulticlassClassifierEvaluator,
    MulticlassMetrics,
)

__all__ = [
    "BinaryClassificationMetrics",
    "BinaryClassifierEvaluator",
    "MeanAveragePrecisionEvaluator",
    "MulticlassClassifierEvaluator",
    "MulticlassMetrics",
]
