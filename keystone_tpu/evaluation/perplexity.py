"""Held-out LM evaluation: cross-entropy, perplexity, bits per token.

The reference's evaluation layer scores classifiers
(``evaluation/*.scala``); this is the sequence-model member: slide
non-overlapping (S+1)-token windows over a held-out stream, run the
model's next-token loss in one jitted batch loop, and report the
standard aggregates (for byte-level corpora, bits_per_token IS
bits-per-byte, the enwik8 headline metric).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("logit_chunk",))
def _ce(model, toks, logit_chunk: int = 0):
    """Pure cross-entropy (module-level so the jit cache persists across
    evaluate_perplexity calls): next_token_loss adds the MoE load-balance
    aux, which is a training regularizer, not model quality.
    ``logit_chunk`` mirrors the training option — at long eval sequences
    the (B, S, V) f32 logits are the same HBM object to avoid."""
    from keystone_tpu.models.lm_transformer import (
        chunked_token_cross_entropy,
        token_cross_entropy,
    )

    if logit_chunk:
        x, _ = model.backbone(toks[:, :-1])
        return chunked_token_cross_entropy(
            x, model.embed, toks[:, 1:],
            jnp.dtype(model.compute_dtype), logit_chunk,
        )
    logits, _ = model.forward_with_aux(toks[:, :-1])
    return token_cross_entropy(logits, toks[:, 1:])


def evaluate_perplexity(
    model,
    tokens: np.ndarray,
    *,
    seq: int,
    batch: int = 8,
    logit_chunk: int = 0,
) -> dict:
    """Mean next-token cross-entropy of ``model`` over ``tokens``.

    Non-overlapping windows of S+1 tokens (each token predicted once,
    except window-leading tokens which are conditioned on nothing from
    the previous window — the standard simple protocol); a ragged tail
    shorter than S+1 is dropped. Returns {loss, perplexity,
    bits_per_token, tokens_scored}. ``logit_chunk`` evaluates the CE in
    S-chunks (see ``models/lm``) — identical numbers up to FP order.
    """
    window = seq + 1
    n_win = len(tokens) // window
    if n_win == 0:
        raise ValueError(
            f"held-out stream of {len(tokens)} tokens is shorter than one "
            f"window ({window})"
        )
    wins = np.asarray(tokens[: n_win * window], np.int32).reshape(
        n_win, window
    )

    total, count = 0.0, 0
    for i in range(0, n_win, batch):
        chunk = jnp.asarray(wins[i : i + batch])
        # next_token_loss averages over the chunk's predicted tokens;
        # re-weight by token count so uneven tail chunks don't skew
        n_tok = chunk.shape[0] * seq
        total += float(_ce(model, chunk, logit_chunk)) * n_tok
        count += n_tok
    loss = total / count
    return {
        "loss": loss,
        "perplexity": math.exp(loss),
        "bits_per_token": loss / math.log(2.0),
        "tokens_scored": count,
    }
