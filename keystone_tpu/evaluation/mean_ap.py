"""VOC-style mean average precision
(reference evaluation/MeanAveragePrecisionEvaluator.scala).

11-point interpolated AP per class over score-ranked examples; host-side
numpy — the inputs are (N, K) score and indicator arrays that already fit on
one host (the reference likewise groupByKey-collects per class).
"""

from __future__ import annotations

import numpy as np


class MeanAveragePrecisionEvaluator:
    """AP per class from multi-label indicators and class scores."""

    def __init__(self, num_classes: int):
        self.num_classes = num_classes

    def evaluate(
        self, actuals, scores, n_valid: int | None = None
    ) -> np.ndarray:
        """actuals: (N, K) ±1 (or 0/1) indicators; scores: (N, K) floats.
        Returns per-class AP (K,); mean() of it is the MAP headline."""
        actuals = np.asarray(actuals)
        scores = np.asarray(scores)
        if n_valid is not None:
            actuals, scores = actuals[:n_valid], scores[:n_valid]
        pos = actuals > 0
        aps = np.zeros(self.num_classes)
        for k in range(self.num_classes):
            aps[k] = self._average_precision(pos[:, k], scores[:, k])
        return aps

    __call__ = evaluate

    @staticmethod
    def _average_precision(is_pos: np.ndarray, score: np.ndarray) -> float:
        order = np.argsort(-score, kind="stable")
        hits = is_pos[order]
        n_pos = int(hits.sum())
        if n_pos == 0:
            return 0.0
        tp = np.cumsum(hits)
        precision = tp / np.arange(1, len(hits) + 1)
        recall = tp / n_pos
        # 11-point interpolation: max precision at recall >= t, t = 0,.1,...,1
        ap = 0.0
        for t in np.linspace(0.0, 1.0, 11):
            mask = recall >= t
            ap += precision[mask].max() if mask.any() else 0.0
        return float(ap / 11.0)
