"""Ridge-path model selection over a λ sweep.

The reference's solver engine accepted an array of lambdas precisely so
pipelines could sweep regularization while reusing the normal-equation
statistics (mlmatrix ``solveLeastSquaresWithL2(A, b, Array(lambda), ..)``;
the KeystoneML paper leans on this for model search). Here
:meth:`BlockLeastSquaresEstimator.fit_sweep` batches the solves over λ on
the sweep axis, and :func:`select_lambda` scores each fitted model on
held-out data and returns the winner.
"""

from __future__ import annotations

import numpy as np

from keystone_tpu.evaluation.multiclass import MulticlassClassifierEvaluator
from keystone_tpu.ops.util import MaxClassifier


def select_lambda(
    est,
    train_inputs,
    train_indicators,
    lams,
    val_inputs,
    val_label_idx,
    *,
    num_classes: int,
    n_valid: int | None = None,
    n_valid_val: int | None = None,
):
    """Fit one model per λ (shared Grams) and pick the best by held-out
    multiclass error.

    ``train_inputs``/``val_inputs`` are whatever the estimator/model
    consume (a feature matrix or a list of feature blocks);
    ``val_label_idx`` are integer class labels for the held-out rows.
    Returns ``(best_model, report)`` where report lists per-λ errors.
    """
    models = est.fit_sweep(
        train_inputs, train_indicators, lams, n_valid=n_valid
    )
    classify = MaxClassifier()
    evaluator = MulticlassClassifierEvaluator(num_classes)
    errors = [
        float(
            evaluator(
                classify(m(val_inputs)), val_label_idx, n_valid=n_valid_val
            ).error
        )
        for m in models
    ]
    best = int(np.argmin(errors))
    return models[best], {
        "lams": [float(l) for l in lams],
        "val_errors": errors,
        "best_lam": float(lams[best]),
        "best_error": errors[best],
    }
