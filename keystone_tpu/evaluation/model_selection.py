"""Ridge-path model selection over a λ sweep.

The reference's solver engine accepted an array of lambdas precisely so
pipelines could sweep regularization while reusing the normal-equation
statistics (mlmatrix ``solveLeastSquaresWithL2(A, b, Array(lambda), ..)``;
the KeystoneML paper leans on this for model search). Here
:meth:`BlockLeastSquaresEstimator.fit_sweep` batches the solves over λ on
the sweep axis, and :func:`select_lambda` scores each fitted model on
held-out data and returns the winner.
"""

from __future__ import annotations

import numpy as np

from keystone_tpu.evaluation.multiclass import MulticlassClassifierEvaluator
from keystone_tpu.ops.util import MaxClassifier


def select_lambda(
    est,
    train_inputs,
    train_indicators,
    lams,
    val_inputs,
    val_label_idx,
    *,
    num_classes: int,
    n_valid: int | None = None,
    n_valid_val: int | None = None,
):
    """Fit one model per λ (shared Grams) and pick the best by held-out
    multiclass error.

    ``train_inputs``/``val_inputs`` are whatever the estimator/model
    consume (a feature matrix or a list of feature blocks);
    ``val_label_idx`` are integer class labels for the held-out rows.
    Returns ``(best_model, report)`` where report lists per-λ errors.
    """
    models = est.fit_sweep(
        train_inputs, train_indicators, lams, n_valid=n_valid
    )
    classify = MaxClassifier()
    evaluator = MulticlassClassifierEvaluator(num_classes)
    errors = [
        float(
            evaluator(
                classify(m(val_inputs)), val_label_idx, n_valid=n_valid_val
            ).error
        )
        for m in models
    ]
    best, report = _pick_best(lams, errors)
    return models[best], report


def _pick_best(lams, losses):
    """argmin selection + the report dict shared by every sweep path."""
    best = int(np.argmin(losses))
    return best, {
        "lams": [float(l) for l in lams],
        "val_errors": [float(e) for e in losses],
        "best_lam": float(lams[best]),
        "best_error": float(losses[best]),
    }


def holdout_lambda_sweep(
    est,
    train_inputs,
    train_indicators,
    train_label_idx,
    lams,
    *,
    n_train: int,
    num_classes: int | None = None,
    holdout_frac: float = 0.1,
    scorer=None,
):
    """λ selection on a held-out suffix of the training rows.

    Fits the sweep on the first ``1 − holdout_frac`` of the valid rows
    (padded rows already sit past ``n_train``, so validity masks stay
    prefix-shaped) and scores each λ on the held-out tail. Returns the
    report dict (``best_lam``, per-λ ``val_errors``); callers refit on
    the full training set at ``best_lam``. The shared wiring behind the
    model CLIs' ``--lam-sweep`` flag — ``lams`` may be the raw
    comma-separated flag string or a sequence of floats.

    Default scoring is multiclass error on ``train_label_idx`` (requires
    ``num_classes``). Other metrics pass ``scorer(model, val_inputs,
    (lo, hi)) -> loss`` (lower = better; ``lo:hi`` is the held-out row
    range of the original training arrays) — e.g. VOC scores −MAP over
    multi-label indicators.
    """
    if isinstance(lams, str):
        lams = [float(x) for x in lams.split(",") if x.strip()]
    lams = list(lams)
    if not lams:
        raise ValueError(
            "lambda sweep got no values — pass e.g. "
            '--lam-sweep "1e-3,1e-2,1e-1"'
        )
    n_hold = int(n_train * holdout_frac)
    if n_hold < 1:
        raise ValueError(
            f"lambda sweep holdout is empty: n_train={n_train} at "
            f"holdout_frac={holdout_frac} leaves no validation rows"
        )
    n_fit = n_train - n_hold
    if isinstance(train_inputs, (list, tuple)):
        val_inputs = [b[n_fit:] for b in train_inputs]
        pad_rows = val_inputs[0].shape[0]
    else:
        val_inputs = train_inputs[n_fit:]
        pad_rows = val_inputs.shape[0]
    if scorer is not None:
        models = est.fit_sweep(
            train_inputs, train_indicators, lams, n_valid=n_fit
        )
        losses = [
            float(scorer(m, val_inputs, (n_fit, n_train))) for m in models
        ]
        _, report = _pick_best(lams, losses)
        return report
    if num_classes is None:
        raise ValueError("num_classes is required for the default scorer")
    val_y = np.asarray(train_label_idx[n_fit:n_train], np.int32)
    _, report = select_lambda(
        est,
        train_inputs,
        train_indicators,
        lams,
        val_inputs,
        np.pad(val_y, (0, pad_rows - len(val_y))),
        num_classes=num_classes,
        n_valid=n_fit,
        n_valid_val=len(val_y),
    )
    return report
