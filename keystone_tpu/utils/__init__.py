"""Shared utilities: image batch types and math helpers (reference
``src/main/scala/utils/``, SURVEY.md §2.9)."""

from keystone_tpu.utils.images import LabeledImages, conv2d_separable, rgb_to_gray

__all__ = ["LabeledImages", "conv2d_separable", "rgb_to_gray"]
