"""Image batch representation and core image math.

The reference carries an ``Image`` trait with five array-layout classes
(``utils/images/Image.scala``: ByteArray, ChannelMajor, ColumnMajor,
RowMajor, RowColumnMajorByte) because JVM code touches pixels one at a time.
On TPU layout belongs to XLA: a batch of images is ONE ``(N, H, W, C)``
float array and the layout classes disappear (SURVEY.md §7.1). Per-image
metadata is the shape.

Reference quirk inherited deliberately: the reference's ``xDim`` is image
*height* (``Image.scala`` ImageMetadata); here H is explicit so nothing is
swapped.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

# MATLAB rgb2gray weights, as in the reference (utils/images/ImageUtils.scala
# toGrayScale: 0.2989 R + 0.5870 G + 0.1140 B).
GRAY_WEIGHTS = (0.2989, 0.5870, 0.1140)


@dataclasses.dataclass
class LabeledImages:
    """(labels, images) bundle — reference ``LabeledImage`` batches.

    ``images``: (N, H, W, C) float array; ``labels``: (N,) ints or
    (N, k)/ragged multi-labels (VOC-style).
    """

    labels: np.ndarray
    images: np.ndarray

    def __len__(self) -> int:
        return self.images.shape[0]


def rgb_to_gray(images):
    """NTSC/MATLAB grayscale, keeping a single channel
    (reference ImageUtils.toGrayScale)."""
    w = jnp.asarray(GRAY_WEIGHTS, images.dtype)
    return jnp.tensordot(images, w, axes=[[-1], [0]])[..., None]


def conv2d_separable(images, kernel_x, kernel_y):
    """Separable 2-pass 2-D convolution with zero padding, per channel —
    the reference's hot kernel under Daisy/LCS (ImageUtils.conv2D).

    ``images``: (N, H, W, C); ``kernel_x``: (kx,) applied along W;
    ``kernel_y``: (ky,) applied along H. Same-size output (zero-padded),
    matching the reference's edge behavior.
    """
    import jax

    kx = jnp.asarray(kernel_x, images.dtype)[::-1]
    ky = jnp.asarray(kernel_y, images.dtype)[::-1]
    n, h, w, c = images.shape
    x = jnp.transpose(images, (0, 3, 1, 2)).reshape(n * c, 1, h, w)
    # pass 1: along W (asymmetric pad keeps same-size output for even kernels)
    kw = kx.reshape(1, 1, 1, -1)
    x = jax.lax.conv_general_dilated(
        x, kw, window_strides=(1, 1), padding=((0, 0), _pad(kx))
    )
    # pass 2: along H
    kh = ky.reshape(1, 1, -1, 1)
    x = jax.lax.conv_general_dilated(
        x, kh, window_strides=(1, 1), padding=(_pad(ky), (0, 0))
    )
    return jnp.transpose(x.reshape(n, c, h, w), (0, 2, 3, 1))


def _pad(k) -> tuple[int, int]:
    return ((k.shape[0] - 1) // 2, k.shape[0] // 2)
