"""Numeric test/metric helpers (reference utils/Stats.scala)."""

from __future__ import annotations

import numpy as np


def about_eq(a, b, tol: float = 1e-8) -> bool:
    """Approximate equality for scalars/arrays (reference Stats.aboutEq)."""
    return bool(np.all(np.abs(np.asarray(a) - np.asarray(b)) <= tol))


def classification_error(predicted_topk, actual, k: int | None = None) -> float:
    """Top-k error: fraction of rows whose actual label is NOT in the first
    k predicted columns (reference Stats.classificationError/getErrPercent).

    ``predicted_topk``: (N, K) ranked predictions (TopKClassifier output)
    or (N,) argmax predictions.
    """
    predicted_topk = np.asarray(predicted_topk)
    actual = np.asarray(actual)
    if predicted_topk.ndim == 1:
        predicted_topk = predicted_topk[:, None]
    if k is not None:
        predicted_topk = predicted_topk[:, :k]
    hits = (predicted_topk == actual[:, None]).any(axis=1)
    return float(1.0 - hits.mean())


def get_err_percent(predicted_topk, actual, k: int | None = None) -> float:
    return 100.0 * classification_error(predicted_topk, actual, k)
