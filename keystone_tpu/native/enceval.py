"""XLA FFI bindings for the native GMM-EM / Fisher-vector host kernels.

The reference's EncEval JNI shim (``src/main/cpp/EncEval.cxx``) runs GMM EM
and Fisher-vector encoding in native code on the host; the parity
equivalents here live in ``native/enceval_ffi.cpp`` and register as XLA
CPU custom calls through :mod:`jax.ffi` (no JNI, no host round-trip
management — XLA owns the buffers). The on-device jnp path in
:mod:`keystone_tpu.ops.gmm` remains the fast default; both implement the
same equations, so results agree to float tolerance and artifacts are
interchangeable.

The shared library builds on demand (``make`` in ``native/``); everything
degrades gracefully when the toolchain, headers, or a CPU backend are
unavailable — check :func:`available` or pass ``backend="device"``.
"""

from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

from keystone_tpu.core.logging import get_logger
from keystone_tpu.native import _NATIVE_DIR, _build

logger = get_logger("keystone_tpu.native.enceval")

_LIB_PATH = os.path.abspath(
    os.path.join(_NATIVE_DIR, "libkeystone_enceval.so")
)

_lock = threading.Lock()
_available: bool | None = None


def _cpu_device():
    import jax

    try:
        return jax.local_devices(backend="cpu")[0]
    except Exception:  # noqa: BLE001 — backend not configured
        return None


def _ensure_registered() -> bool:
    global _available
    with _lock:
        if _available is not None:
            return _available
        _available = False
        if not os.path.exists(_LIB_PATH) and not _build():
            return False
        if not os.path.exists(_LIB_PATH):
            logger.info("libkeystone_enceval.so not built; native path off")
            return False
        if _cpu_device() is None:
            logger.info("no CPU jax backend; native enceval path off")
            return False
        try:
            import jax

            lib = ctypes.CDLL(_LIB_PATH)
            jax.ffi.register_ffi_target(
                "keystone_gmm_em",
                jax.ffi.pycapsule(lib.KeystoneGmmEm),
                platform="cpu",
            )
            jax.ffi.register_ffi_target(
                "keystone_fisher",
                jax.ffi.pycapsule(lib.KeystoneFisher),
                platform="cpu",
            )
        except Exception as e:  # noqa: BLE001
            logger.info("ffi registration failed: %s", e)
            return False
        _available = True
        return True


def available() -> bool:
    """True when the native kernels can be used (lib built + CPU backend)."""
    return _ensure_registered()


def gmm_em(x, k: int, max_iter: int = 100, seed: int = 42,
           var_floor: float = 1e-5):
    """Fit a diagonal GMM with the native EM kernel.

    Same contract as ``keystone_tpu.ops.gmm._gmm_em`` (identical random
    init, update equations, and (d, k) layouts); returns numpy
    ``(means, variances, weights)``.
    """
    if not _ensure_registered():
        raise RuntimeError(
            "native enceval kernels unavailable (build native/ and ensure "
            "a CPU jax backend); use the on-device estimator instead"
        )
    import jax
    import jax.numpy as jnp

    from keystone_tpu.ops.gmm import gmm_init

    x = np.ascontiguousarray(np.asarray(x, np.float32))
    n, d = x.shape
    mu0, var0, w0 = (
        np.ascontiguousarray(np.asarray(a))
        for a in gmm_init(jnp.asarray(x), k, seed, var_floor)
    )

    call = jax.ffi.ffi_call(
        "keystone_gmm_em",
        (
            jax.ShapeDtypeStruct((d, k), jnp.float32),
            jax.ShapeDtypeStruct((d, k), jnp.float32),
            jax.ShapeDtypeStruct((k,), jnp.float32),
        ),
    )
    with jax.default_device(_cpu_device()):
        mu, var, w = call(
            x, mu0, var0, w0,
            max_iter=np.int64(max_iter),
            var_floor=np.float32(var_floor),
        )
    return np.asarray(mu), np.asarray(var), np.asarray(w)


def fisher_vectors(batch, means, variances, weights):
    """Fisher-vector encode (N, d, m) descriptor batches natively.

    Output layout matches ``keystone_tpu.ops.gmm.FisherVector``:
    (N, d, 2k) with mean gradients in columns 0..k-1, variance gradients
    in k..2k-1.
    """
    if not _ensure_registered():
        raise RuntimeError(
            "native enceval kernels unavailable (build native/ and ensure "
            "a CPU jax backend); use the on-device FisherVector instead"
        )
    import jax
    import jax.numpy as jnp

    batch = np.ascontiguousarray(np.asarray(batch, np.float32))
    n, d, m = batch.shape
    k = int(np.asarray(weights).shape[0])
    call = jax.ffi.ffi_call(
        "keystone_fisher",
        jax.ShapeDtypeStruct((n, d, 2 * k), jnp.float32),
    )
    with jax.default_device(_cpu_device()):
        out = call(
            batch,
            np.ascontiguousarray(np.asarray(means, np.float32)),
            np.ascontiguousarray(np.asarray(variances, np.float32)),
            np.ascontiguousarray(np.asarray(weights, np.float32)),
        )
    return np.asarray(out)
