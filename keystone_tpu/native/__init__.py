"""ctypes bindings for the native IO kernels (``native/fastio.cpp``).

The library builds on demand (``make`` in ``native/``) the first time it's
requested; every caller has a pure-Python fallback, so missing toolchains
degrade gracefully rather than fail.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from keystone_tpu.core.logging import get_logger

logger = get_logger("keystone_tpu.native")

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libkeystone_io.so"))

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s"],
            cwd=os.path.abspath(_NATIVE_DIR),
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception as e:  # noqa: BLE001
        logger.info("native build unavailable (%s); using python fallbacks", e)
        return False


def get_lib() -> ctypes.CDLL | None:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            logger.info("failed to load %s: %s", _LIB_PATH, e)
            return None
        _bind_io(lib)
        if not _bind_dsift(lib):
            # stale prebuilt library without the dsift symbols: rebuild
            # once and reload (re-binding EVERY symbol on the fresh
            # handle); if that fails, keep the IO symbols and let
            # native_dsift degrade to None
            if _build():
                try:
                    lib = ctypes.CDLL(_LIB_PATH)
                except OSError:
                    _lib = None
                    return None
                _bind_io(lib)
                _bind_dsift(lib)
        _lib = lib
        return _lib


def _bind_io(lib: ctypes.CDLL) -> None:
    lib.csv_dims.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.csv_dims.restype = ctypes.c_int
    lib.csv_read.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64,
        ctypes.c_int64,
    ]
    lib.csv_read.restype = ctypes.c_int
    lib.cifar_read.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64,
    ]
    lib.cifar_read.restype = ctypes.c_int64


def _bind_dsift(lib: ctypes.CDLL) -> bool:
    try:
        lib.dsift_descriptor_count.argtypes = [ctypes.c_int] * 6
        lib.dsift_descriptor_count.restype = ctypes.c_int
        lib.dsift_flat_batch.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int16),
        ]
        lib.dsift_flat_batch.restype = ctypes.c_int
    except AttributeError:
        return False
    return True


def native_load_csv(path: str) -> np.ndarray | None:
    """Parse a float CSV with the native kernel; None → caller falls back."""
    lib = get_lib()
    if lib is None:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    if lib.csv_dims(path.encode(), ctypes.byref(rows), ctypes.byref(cols)):
        return None
    out = np.empty((rows.value, cols.value), np.float32)
    rc = lib.csv_read(
        path.encode(),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        rows.value,
        cols.value,
    )
    if rc != 0:
        logger.info("native csv parse failed (rc=%d) for %s", rc, path)
        return None
    return out


def native_dsift(
    images: np.ndarray,
    *,
    step: int = 3,
    bin_size: int = 4,
    num_scales: int = 5,
    scale_step: int = 0,
) -> np.ndarray | None:
    """Host dense SIFT (``native/dsift.cpp`` — the VLFeat-shim parity
    fallback; same flat-window algorithm and output layout as the
    on-device ``ops.sift.SIFTExtractor``).

    images: (N, H, W) grayscale in [0, 1] → (N, 128, M) float32, or None
    when the native library is unavailable (caller falls back).
    """
    if step < 1 or bin_size < 1 or num_scales < 1:
        raise ValueError("dsift needs step >= 1, bin_size >= 1, num_scales >= 1")
    if any(step + s * scale_step < 1 for s in range(num_scales)):
        raise ValueError(
            f"scale_step={scale_step} drives the per-scale step below 1"
        )
    lib = get_lib()
    if lib is None or not hasattr(lib, "dsift_flat_batch"):
        return None
    images = np.ascontiguousarray(images, np.float32)
    n, h, w = images.shape
    count = lib.dsift_descriptor_count(
        h, w, step, bin_size, num_scales, scale_step
    )
    out = np.empty((n, count, 128), np.int16)
    got = lib.dsift_flat_batch(
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
        h,
        w,
        step,
        bin_size,
        num_scales,
        scale_step,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
    )
    if got != count:
        logger.info("native dsift count mismatch: %d != %d", got, count)
        return None
    return np.transpose(out, (0, 2, 1)).astype(np.float32)


def native_load_cifar(path: str) -> tuple[np.ndarray, np.ndarray] | None:
    """Parse CIFAR-10 binary records natively → (labels, NHWC images)."""
    lib = get_lib()
    if lib is None:
        return None
    size = os.path.getsize(path)
    record = 1 + 3072
    if size % record:
        return None
    n = size // record
    labels = np.empty(n, np.int32)
    images = np.empty((n, 32, 32, 3), np.float32)
    got = lib.cifar_read(
        path.encode(),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
    )
    if got != n:
        return None
    return labels, images
