"""Device mesh and sharding helpers.

The mesh has two named axes:

- ``"data"``  — data parallelism. One shard of the batch per mesh slot; the
  successor of a Spark RDD partition (reference ``Transformer.scala:22``:
  every node application is an SPMD map over partitions).
- ``"model"`` — model/feature-block parallelism. Columns of wide feature /
  weight matrices are sharded here; partial products are combined by XLA
  ``psum`` over ICI — the successor of the reference's ``VectorSplitter`` +
  block solvers (``nodes/util/VectorSplitter.scala:15-24``,
  ``nodes/learning/BlockLinearMapper.scala:47-74``).

Replication (Spark ``broadcast``, e.g. ``BlockWeightedLeastSquares.scala:223-226``)
is just a sharding spec with no named axes — XLA materializes one copy per
device.

Everything works mesh-less too (single chip): helpers accept ``mesh=None``
and degrade to plain arrays so the same pipeline code runs from a laptop CPU
test to a pod.
"""

from __future__ import annotations

import contextlib
import math
import threading
from typing import Iterator, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"

_state = threading.local()


def create_mesh(
    data: int | None = None,
    model: int = 1,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Create a 2-axis ("data", "model") mesh.

    ``data=None`` uses all remaining devices on the data axis. A v5p pod
    slice's ICI torus is contiguous in ``jax.devices()`` order, so adjacent
    mesh slots get adjacent chips and collectives ride ICI.

    Multi-slice environments (devices reporting distinct ``slice_index``)
    get a HYBRID mesh: the slice dimension lands on the OUTER part of the
    "data" axis so data-parallel Gram/gradient reductions cross DCN only
    at the top of the reduction tree, while "model"-axis collectives stay
    entirely within one slice's ICI — the moral successor of the
    reference's ``spark.mlmatrix.treeBranchingFactor`` hierarchy control
    (``BlockWeightedLeastSquares.scala:186-188``).
    """
    devs = list(devices if devices is not None else jax.devices())
    if model < 1:
        raise ValueError(f"model axis size must be >= 1, got {model}")
    if data is None:
        if len(devs) % model:
            raise ValueError(f"{len(devs)} devices not divisible by model={model}")
        data = len(devs) // model
    n = data * model
    if n > len(devs):
        raise ValueError(f"mesh {data}x{model} needs {n} devices, have {len(devs)}")
    n_slices = len(_slice_groups(devs[:n]))
    if n_slices > 1 and data % n_slices == 0 and n == len(devs):
        from jax.experimental import mesh_utils

        grid = mesh_utils.create_hybrid_device_mesh(
            (data // n_slices, model),
            (n_slices, 1),  # DCN spans the data axis only
            devices=devs,
        )
        return Mesh(grid, (DATA_AXIS, MODEL_AXIS))
    grid = np.asarray(devs[:n]).reshape(data, model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def _slice_groups(devs: Sequence) -> dict:
    """Group devices by their DCN slice (``slice_index``); single-slice and
    CPU devices (no attribute) collapse to one group."""
    groups: dict = {}
    for d in devs:
        groups.setdefault(getattr(d, "slice_index", 0), []).append(d)
    return groups


@contextlib.contextmanager
def use_mesh(mesh: Mesh | None) -> Iterator[Mesh | None]:
    """Context manager installing ``mesh`` as the ambient default mesh."""
    stack = getattr(_state, "stack", None)
    if stack is None:
        stack = _state.stack = []
    stack.append(mesh)
    try:
        yield mesh
    finally:
        stack.pop()


def current_mesh() -> Mesh | None:
    """The innermost mesh installed by :func:`use_mesh`, else None."""
    stack = getattr(_state, "stack", None)
    return stack[-1] if stack else None


def data_sharding(mesh: Mesh | None = None, ndim: int = 2) -> NamedSharding | None:
    """Sharding for a batch: leading axis split over "data", rest replicated."""
    mesh = mesh or current_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def model_sharding(mesh: Mesh | None = None, ndim: int = 2) -> NamedSharding | None:
    """Sharding for a weight/feature-block matrix: last axis over "model"."""
    mesh = mesh or current_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, P(*([None] * (ndim - 1)), MODEL_AXIS))


def replicated_sharding(mesh: Mesh | None = None) -> NamedSharding | None:
    """Full replication — the successor of Spark ``sc.broadcast``."""
    mesh = mesh or current_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, P())


def data_sharding_fn(mesh: Mesh | None):
    """Per-chunk placement callable for the staging engine: maps a chunk
    — a bare array OR a pytree of arrays (the fused fit stages
    (data, labels) pairs) — to rank-matched data-axis sharding specs
    per leaf (None mesh → None, plain placement). The ONE home of the
    chunk→spec rule."""
    if mesh is None:
        return None
    import jax

    return lambda chunk: jax.tree_util.tree_map(
        lambda leaf: data_sharding(mesh, getattr(leaf, "ndim", 1)), chunk
    )


def data_axis_size(mesh: Mesh | None) -> int:
    """Size of the "data" axis; 1 for no mesh or a mesh without one —
    the ONE home of the shard-count read (planner, staging, bench)."""
    if mesh is None:
        return 1
    try:
        return int(dict(mesh.shape).get(DATA_AXIS, 1))
    except Exception:  # noqa: BLE001 — foreign mesh-like object
        return 1


def shard_chunk_size(chunk_size: int, mesh: Mesh | None) -> int:
    """``chunk_size`` rounded UP to a data-axis multiple, so a staged
    chunk splits into even, static shard shapes."""
    n = data_axis_size(mesh)
    return -(-int(chunk_size) // n) * n


def pad_batch(
    x: np.ndarray | jax.Array, multiple: int
) -> tuple[np.ndarray | jax.Array, int]:
    """Zero-pad the leading axis to a multiple; returns (padded, n_valid).

    XLA needs static, evenly-divisible shard shapes where Spark tolerated
    ragged partitions. Downstream reductions must mask rows >= n_valid
    (evaluators and solvers in this framework all accept ``n_valid``).
    """
    n = x.shape[0]
    target = math.ceil(n / multiple) * multiple if n else multiple
    if target == n:
        return x, n
    pad_widths = [(0, target - n)] + [(0, 0)] * (x.ndim - 1)
    if isinstance(x, np.ndarray):
        return np.pad(x, pad_widths), n
    import jax.numpy as jnp

    return jnp.pad(x, pad_widths), n


def shard_batch(
    x,
    mesh: Mesh | None = None,
    *,
    pad: bool = True,
):
    """Place a host batch onto the mesh, sharded over the "data" axis.

    Pads the leading axis to the data-axis size when ``pad`` (returns the
    original row count via the companion :func:`pad_batch` if you need it —
    here the padded array only). Without a mesh: plain ``device_put``.
    """
    mesh = mesh or current_mesh()
    import jax.numpy as jnp

    x = jnp.asarray(x) if not isinstance(x, (np.ndarray, jax.Array)) else x
    if mesh is None:
        return jax.device_put(x)
    n_data = mesh.shape[DATA_AXIS]
    if pad and x.shape[0] % n_data:
        x, _ = pad_batch(x, n_data)
    return jax.device_put(x, data_sharding(mesh, x.ndim))
