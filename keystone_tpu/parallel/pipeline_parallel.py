"""GPipe-style pipeline parallelism over a mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.11: stages run to
completion; Spark's lazy evaluation is the only overlap). Here stage
overlap is a first-class mechanism completing the DP/TP/PP/SP/EP matrix:
a chain of equal-width stages is sharded one-stage-per-device along a mesh
axis, and microbatches stream through the chain with activations handed to
the next stage via ``ppermute`` over ICI. After the ``n_stages - 1``-step
fill, every device computes every step — the classic GPipe schedule with
bubble fraction ``(S-1)/(S-1+M)``.

Design notes (TPU-first):
- the schedule is a ``lax.scan`` of length ``M + S - 1`` inside one
  ``shard_map`` — one compiled program, no per-step dispatch;
- stage parameters are a stacked pytree (leading axis = stage) sharded
  along the pipeline axis, so each device holds exactly its stage;
- outputs are collected on the last stage and ``psum``-broadcast so the
  caller sees a replicated result.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_shard(params, x, *, stage_fn, axis_name: str, n_micro: int):
    """Runs on one device = one stage. params: stage-local pytree (leading
    stage axis already sliced to size 1); x: (n_micro, ...) microbatches
    (replicated)."""
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    params = jax.tree_util.tree_map(lambda p: p[0], params)
    is_first = stage == 0
    is_last = stage == n_stages - 1
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    # mark the zero-init carries as varying over the pipeline axis (jax 0.9
    # tracks varying-manual-axes through scan and rejects mixed carries)
    act0 = lax.pcast(jnp.zeros_like(x[0]), (axis_name,), to="varying")
    outs0 = lax.pcast(jnp.zeros_like(x), (axis_name,), to="varying")

    def step(carry, t):
        act_in, outs = carry
        # stage 0 injects microbatch t (clamped; masked below), others use
        # the activation handed over by the previous stage
        mb = lax.dynamic_index_in_dim(
            x, jnp.clip(t, 0, n_micro - 1), keepdims=False
        )
        inp = jnp.where(is_first, mb, act_in)
        y = stage_fn(params, inp)
        # device `stage` holds a live value at step t iff stage <= t <
        # stage + n_micro (its microbatch index is t - stage)
        live = jnp.logical_and(t >= stage, t < stage + n_micro)
        y = jnp.where(live, y, jnp.zeros_like(y))
        out_idx = jnp.clip(t - stage, 0, n_micro - 1)
        outs = jnp.where(
            jnp.logical_and(is_last, live),
            lax.dynamic_update_index_in_dim(outs, y, out_idx, axis=0),
            outs,
        )
        act_next = lax.ppermute(y, axis_name, perm)
        return (act_next, outs), None

    (_, outs), _ = lax.scan(
        step, (act0, outs0), jnp.arange(n_micro + n_stages - 1)
    )
    # outputs live on the last stage only; psum replicates them everywhere
    return lax.psum(outs, axis_name)


def gpipe(
    stage_fn,
    stacked_params,
    x,
    mesh: Mesh,
    *,
    axis: str = "model",
    n_micro: int | None = None,
    data_axis: str | None = None,
):
    """Apply a pipeline of stages to microbatched input.

    ``stage_fn(params, act) -> act`` — one stage's computation; every
    stage must preserve the activation shape (equal-width chain).
    ``stacked_params`` — pytree whose leaves have leading axis
    ``n_stages``; sharded one-stage-per-device along ``axis``.
    ``x`` — (n_micro, B, ...) microbatches, or (N, ...) with ``n_micro``
    given to split the batch evenly.

    ``data_axis`` composes dp × pp: the per-microbatch batch dim (axis 1)
    is sharded over it, so each data-row of devices pipelines its own
    batch slice instead of replicating the whole batch (None = replicate,
    the single-row behavior).

    Returns the chain output with the microbatch structure of ``x``
    (sharded over ``data_axis`` when given, else replicated).
    """
    n_stages = mesh.shape[axis]
    reshaped = False
    if n_micro is not None and (x.ndim == 0 or x.shape[0] != n_micro):
        n = x.shape[0]
        if n % n_micro:
            raise ValueError(f"batch {n} not divisible by n_micro={n_micro}")
        x = x.reshape(n_micro, n // n_micro, *x.shape[1:])
        reshaped = True
    m = x.shape[0]

    for path, leaf in jax.tree_util.tree_leaves_with_path(stacked_params):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"stacked param {jax.tree_util.keystr(path)} has "
                f"{leaf.shape[0]} stages on its leading axis; pipeline "
                f"axis {axis!r} has {n_stages} devices"
            )

    if data_axis is not None:
        n_data = mesh.shape[data_axis]
        if x.ndim < 2:
            raise ValueError(
                f"data_axis={data_axis!r} needs microbatches with a batch "
                f"dim to shard — got rank-{x.ndim} input"
            )
        if x.shape[1] % n_data:
            raise ValueError(
                f"microbatch batch dim {x.shape[1]} not divisible by "
                f"data axis {data_axis!r} ({n_data})"
            )
    pspec = P(axis)
    xspec = P(None, data_axis) if data_axis is not None else P()
    # only the pipeline (and optional dp) axes go manual: any OTHER mesh
    # axis stays automatic, so tensor-parallel weight shardings propagate
    # INTO the stage bodies and XLA places their psums — pp x dp x tp
    # composes on a 3-axis mesh with no pipeline-code knowledge of tp
    manual = {axis} | ({data_axis} if data_axis is not None else set())
    fn = jax.shard_map(
        partial(
            _pipeline_shard,
            stage_fn=stage_fn,
            axis_name=axis,
            n_micro=m,
        ),
        mesh=mesh,
        in_specs=(
            jax.tree_util.tree_map(lambda _: pspec, stacked_params),
            xspec,
        ),
        out_specs=xspec,
        axis_names=frozenset(manual),
    )
    out = fn(stacked_params, x)
    if reshaped:
        out = out.reshape(-1, *out.shape[2:])
    return out
