"""Multi-host (multi-process) execution helpers.

The reference scales out with spark-submit + EC2 provisioning scripts
(``bin/pipelines-ec2.sh``); the TPU-native equivalent is JAX multi-process:
every host runs the same program, ``jax.distributed.initialize`` wires the
processes into one runtime, and global arrays are assembled from
process-local shards. Collectives ride ICI within a slice and DCN across
slices — the mesh construction in :mod:`keystone_tpu.parallel.mesh` is
unchanged because ``jax.devices()`` spans all hosts after initialization.

Typical launch (the SAME command on every host, e.g. via ``gcloud compute
tpus ... ssh --worker=all``; ``initialize()`` must run inside the process
that executes the pipeline, which is what the launcher flag does):

    python -m keystone_tpu --multihost <pipeline> ...
"""

from __future__ import annotations

import os

import jax
import numpy as np

from keystone_tpu.core.logging import get_logger

logger = get_logger("keystone_tpu.parallel.multihost")

#: env override for :func:`initialize`'s ``init_timeout_s``.
ENV_INIT_TIMEOUT = "KEYSTONE_INIT_TIMEOUT_S"
_DEFAULT_INIT_TIMEOUT_S = 300.0


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    init_timeout_s: float | None = None,
) -> None:
    """Join this process into the multi-host runtime.

    With TPU VMs all arguments are discovered from the environment
    (``jax.distributed.initialize()`` no-arg form); explicit values support
    CPU/GPU test rigs.

    ``init_timeout_s`` (default ``KEYSTONE_INIT_TIMEOUT_S``, else 300)
    bounds the join: a missing peer or dead coordinator fails in
    seconds with the coordinator address in the message instead of
    hanging the launch forever — on a preempted slice rejoin, the
    hang IS the failure mode (see tunnel_watch.log). Non-coordinator
    processes preflight the coordinator's TCP port under this timeout
    (a clean, catchable RuntimeError names the address); the in-barrier
    wait is then bounded by jax's own ``initialization_timeout``, whose
    expiry the jax client escalates to a fatal process exit — bounded
    either way, never a silent hang.
    """
    if init_timeout_s is None:
        init_timeout_s = float(
            os.environ.get(ENV_INIT_TIMEOUT, "") or _DEFAULT_INIT_TIMEOUT_S
        )
    kwargs = {"initialization_timeout": max(int(init_timeout_s), 1)}
    if coordinator_address is not None:
        kwargs.update(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        if process_id not in (None, 0):
            _preflight_coordinator(
                coordinator_address, init_timeout_s, process_id
            )
    try:
        jax.distributed.initialize(**kwargs)
    except Exception as e:  # noqa: BLE001 — re-raised with diagnosis
        addr = (
            coordinator_address
            or os.environ.get("JAX_COORDINATOR_ADDRESS")
            or "<auto-discovered>"
        )
        raise RuntimeError(
            f"multihost initialize failed (timeout {init_timeout_s:.0f}s, "
            f"coordinator {addr}, process_id={process_id}, "
            f"num_processes={num_processes}): every host must run the "
            "same command and reach the coordinator; check that no "
            f"worker died or was preempted. Underlying error: {e!r}"
        ) from e
    logger.info(
        "multihost: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def _preflight_coordinator(
    addr: str, timeout_s: float, process_id: int
) -> None:
    """Bounded poll of the coordinator's TCP port before handing the
    process to ``jax.distributed.initialize``. The jax client reacts to
    its own init deadline with a FATAL process exit (no Python
    exception to catch), so the reachable-at-all check must happen out
    here where a dead coordinator can fail cleanly, fast, and with the
    address in the message."""
    import socket
    import time

    host, _, port = addr.rpartition(":")
    host = host.strip("[]")  # bracketed IPv6
    if not host or not port.isdigit():
        # unparseable address: let jax.distributed do the validating —
        # the preflight exists to diagnose reachability, not syntax
        return
    deadline = time.monotonic() + timeout_s
    last: Exception | None = None
    while True:
        # at least ONE attempt even when timeout_s is 0/tiny — a live
        # coordinator must never be reported unreachable unprobed
        try:
            with socket.create_connection((host, int(port)), timeout=1.0):
                return
        except OSError as e:
            last = e
        if time.monotonic() >= deadline:
            break
        time.sleep(0.2)
    raise RuntimeError(
        f"multihost initialize: coordinator {addr} unreachable after "
        f"{timeout_s:.0f}s (process_id={process_id}); the coordinator "
        "(process 0) must be running and reachable before workers join. "
        f"Last error: {last!r}"
    )


def global_batch_from_local(local_batch: np.ndarray, mesh, ndim: int | None = None):
    """Assemble a global data-sharded array from this process's local rows
    (the successor of per-executor RDD partitions; wraps
    ``jax.make_array_from_process_local_data``)."""
    from keystone_tpu.parallel.mesh import data_sharding

    sharding = data_sharding(mesh, ndim or local_batch.ndim)
    return jax.make_array_from_process_local_data(sharding, local_batch)
