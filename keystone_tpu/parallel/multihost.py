"""Multi-host (multi-process) execution helpers.

The reference scales out with spark-submit + EC2 provisioning scripts
(``bin/pipelines-ec2.sh``); the TPU-native equivalent is JAX multi-process:
every host runs the same program, ``jax.distributed.initialize`` wires the
processes into one runtime, and global arrays are assembled from
process-local shards. Collectives ride ICI within a slice and DCN across
slices — the mesh construction in :mod:`keystone_tpu.parallel.mesh` is
unchanged because ``jax.devices()`` spans all hosts after initialization.

Typical launch (the SAME command on every host, e.g. via ``gcloud compute
tpus ... ssh --worker=all``; ``initialize()`` must run inside the process
that executes the pipeline, which is what the launcher flag does):

    python -m keystone_tpu --multihost <pipeline> ...
"""

from __future__ import annotations

import itertools
import json
import os

import jax
import numpy as np

from keystone_tpu.core.logging import get_logger

logger = get_logger("keystone_tpu.parallel.multihost")

#: merged cluster metrics written by :func:`rollup_metrics` on host 0,
#: rendered by ``python -m keystone_tpu observe <run-dir>``
CLUSTER_METRICS_FILE = "metrics_cluster.json"

# per-process roll-up sequence: every host calls rollup_metrics in the
# same program order (SPMD), so the counter yields matching KV keys and
# barrier ids without any extra coordination
_rollup_seq = itertools.count()

#: env override for :func:`initialize`'s ``init_timeout_s``.
ENV_INIT_TIMEOUT = "KEYSTONE_INIT_TIMEOUT_S"
_DEFAULT_INIT_TIMEOUT_S = 300.0


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    init_timeout_s: float | None = None,
) -> None:
    """Join this process into the multi-host runtime.

    With TPU VMs all arguments are discovered from the environment
    (``jax.distributed.initialize()`` no-arg form); explicit values support
    CPU/GPU test rigs. Under the run supervisor (``python -m keystone_tpu
    supervise``) the per-generation wiring arrives as
    ``KEYSTONE_COORDINATOR`` / ``KEYSTONE_PROCESS_ID`` /
    ``KEYSTONE_NUM_PROCESSES`` — consumed here as defaults, so
    ``supervise -- python -m keystone_tpu --multihost ...`` needs no
    placeholder plumbing; explicit arguments still win.

    ``init_timeout_s`` (default ``KEYSTONE_INIT_TIMEOUT_S``, else 300)
    bounds the join: a missing peer or dead coordinator fails in
    seconds with the coordinator address in the message instead of
    hanging the launch forever — on a preempted slice rejoin, the
    hang IS the failure mode (see tunnel_watch.log). Non-coordinator
    processes preflight the coordinator's TCP port under this timeout
    (a clean, catchable RuntimeError names the address); the in-barrier
    wait is then bounded by jax's own ``initialization_timeout``, whose
    expiry the jax client escalates to a fatal process exit — bounded
    either way, never a silent hang.
    """
    if init_timeout_s is None:
        init_timeout_s = float(
            os.environ.get(ENV_INIT_TIMEOUT, "") or _DEFAULT_INIT_TIMEOUT_S
        )
    if coordinator_address is None and os.environ.get("KEYSTONE_COORDINATOR"):
        # the run supervisor's per-generation wiring (recomputed on
        # every relaunch — a stale value can't leak across generations
        # because the supervisor rewrites all three per child)
        coordinator_address = os.environ["KEYSTONE_COORDINATOR"]
        missing = [
            name
            for arg, name in (
                (num_processes, "KEYSTONE_NUM_PROCESSES"),
                (process_id, "KEYSTONE_PROCESS_ID"),
            )
            if arg is None and name not in os.environ
        ]
        if missing:
            raise RuntimeError(
                "KEYSTONE_COORDINATOR is set "
                f"({coordinator_address!r}) but {' and '.join(missing)} "
                "is not — the three variables wire one cluster together "
                "and must be set as a group (the run supervisor exports "
                "all of them; a manual launch must too). Unset "
                "KEYSTONE_COORDINATOR to use jax's own environment "
                "discovery instead."
            )
        if num_processes is None:
            num_processes = int(os.environ["KEYSTONE_NUM_PROCESSES"])
        if process_id is None:
            process_id = int(os.environ["KEYSTONE_PROCESS_ID"])
    kwargs = {"initialization_timeout": max(int(init_timeout_s), 1)}
    if coordinator_address is not None:
        kwargs.update(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        if process_id not in (None, 0):
            _preflight_coordinator(
                coordinator_address, init_timeout_s, process_id
            )
    try:
        jax.distributed.initialize(**kwargs)
    except Exception as e:  # noqa: BLE001 — re-raised with diagnosis
        addr = (
            coordinator_address
            or os.environ.get("JAX_COORDINATOR_ADDRESS")
            or "<auto-discovered>"
        )
        raise RuntimeError(
            f"multihost initialize failed (timeout {init_timeout_s:.0f}s, "
            f"coordinator {addr}, process_id={process_id}, "
            f"num_processes={num_processes}): every host must run the "
            "same command and reach the coordinator; check that no "
            f"worker died or was preempted. Underlying error: {e!r}"
        ) from e
    logger.info(
        "multihost: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )
    # every multihost worker start warm-starts from the persistent XLA
    # cache (KEYSTONE_COMPILE_CACHE_DIR): a relaunched/rejoining host's
    # cold-start cost is compilation, and the supervisor's whole loss
    # budget assumes rejoin takes seconds, not minutes
    from keystone_tpu.core.runtime import enable_compilation_cache

    cache = enable_compilation_cache()
    if cache:
        logger.info("multihost: persistent compilation cache at %s", cache)


def _preflight_coordinator(
    addr: str, timeout_s: float, process_id: int
) -> None:
    """Bounded poll of the coordinator's TCP port before handing the
    process to ``jax.distributed.initialize``. The jax client reacts to
    its own init deadline with a FATAL process exit (no Python
    exception to catch), so the reachable-at-all check must happen out
    here where a dead coordinator can fail cleanly, fast, and with the
    address in the message."""
    import socket
    import time

    host, _, port = addr.rpartition(":")
    host = host.strip("[]")  # bracketed IPv6
    if not host or not port.isdigit():
        # unparseable address: let jax.distributed do the validating —
        # the preflight exists to diagnose reachability, not syntax
        return
    deadline = time.monotonic() + timeout_s
    last: Exception | None = None
    while True:
        # at least ONE attempt even when timeout_s is 0/tiny — a live
        # coordinator must never be reported unreachable unprobed
        try:
            with socket.create_connection((host, int(port)), timeout=1.0):
                return
        except OSError as e:
            last = e
        if time.monotonic() >= deadline:
            break
        time.sleep(0.2)
    raise RuntimeError(
        f"multihost initialize: coordinator {addr} unreachable after "
        f"{timeout_s:.0f}s (process_id={process_id}); the coordinator "
        "(process 0) must be running and reachable before workers join. "
        f"Last error: {last!r}"
    )


def merge_metric_dumps(dumps: list[dict]) -> dict:
    """Merge per-host kind-tagged metric dumps
    (:meth:`keystone_tpu.observe.metrics.MetricsRegistry.dump`) into
    cluster totals: counters sum, gauges take the max (watermark
    semantics — the cluster's HBM peak is the worst host's peak), timers
    pool count/total/min/max and recompute percentiles from the pooled
    reservoirs rather than averaging per-host quantiles.

    Returns a snapshot-shaped dict (series key → number, or summary dict
    for timers) ready for a report to render.
    """
    from keystone_tpu.observe.metrics import percentiles

    acc: dict[str, dict] = {}
    for dump in dumps:
        for key, entry in (dump or {}).items():
            if not isinstance(entry, dict):
                continue
            kind = entry.get("kind", "counter")
            cur = acc.get(key)
            if cur is None:
                cur = dict(entry)
                if kind == "timer":
                    cur["samples"] = list(entry.get("samples") or [])
                acc[key] = cur
                continue
            if kind == "counter":
                cur["value"] = cur.get("value", 0) + entry.get("value", 0)
            elif kind == "gauge":
                cur["value"] = max(
                    cur.get("value", 0.0), entry.get("value", 0.0)
                )
            else:  # timer
                n_cur, n_new = cur.get("count", 0), entry.get("count", 0)
                cur["count"] = n_cur + n_new
                cur["total_s"] = cur.get("total_s", 0.0) + entry.get(
                    "total_s", 0.0
                )
                mins = [
                    d["min_s"]
                    for d, n in ((cur, n_cur), (entry, n_new))
                    if n and "min_s" in d
                ]
                if mins:
                    cur["min_s"] = min(mins)
                cur["max_s"] = max(
                    cur.get("max_s", 0.0), entry.get("max_s", 0.0)
                )
                cur["samples"].extend(entry.get("samples") or [])
    out: dict[str, object] = {}
    for key, entry in acc.items():
        if entry.get("kind") == "timer":
            samples = entry.pop("samples", [])
            entry.pop("kind", None)
            if entry.get("count"):
                entry["mean_s"] = entry["total_s"] / entry["count"]
            for pkey in ("p50_s", "p95_s", "p99_s"):
                entry.pop(pkey, None)
            if samples:
                p = percentiles(samples, (50, 95, 99))
                entry.update(p50_s=p[50], p95_s=p[95], p99_s=p[99])
            out[key] = entry
        else:
            out[key] = entry.get("value")
    return out


def _coordination_client():
    """The jax coordination-service KV client for this process, or None
    when ``jax.distributed`` was never initialized. Private jax surface
    (``jax._src.distributed``) by necessity — there is no public KV API
    — so every caller treats None/AttributeError as "transport
    unavailable" and degrades."""
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None)
    except Exception:  # noqa: BLE001 — jax refactor moved the module
        return None


def _gather_dumps(
    payload: str, pid: int, nprocs: int, timeout_s: float
) -> list[dict] | None:
    """Gather every host's serialized metrics dump onto host 0. Primary
    transport: the coordination-service KV store (works wherever
    ``jax.distributed`` init works — including CPU test rigs whose XLA
    build has no multiprocess collectives). Fallback: a padded uint8
    ``process_allgather`` over device collectives. Returns the dump list
    on host 0, None on other hosts and on total transport failure."""
    client = _coordination_client()
    seq = next(_rollup_seq)
    if client is not None:
        # No cross-path fallback here: whether a coordination-service
        # client exists IS cluster-consistent (jax.distributed init), but
        # a mid-path failure on one host is not — if host 0 alone fell
        # through to the collective below after the barrier passed, it
        # would block forever in an allgather no other host joins.
        # Degrading to per-host metrics is the safe failure.
        try:
            client.key_value_set(f"keystone/metrics/{seq}/{pid}", payload)
            client.wait_at_barrier(
                f"keystone_metrics_rollup_{seq}", int(timeout_s * 1000)
            )
            if pid != 0:
                return None
            dumps = [
                json.loads(
                    client.blocking_key_value_get(
                        f"keystone/metrics/{seq}/{i}",
                        int(timeout_s * 1000),
                    )
                )
                for i in range(nprocs)
            ]
            try:
                # reclaim the payloads: a long-lived job rolling up
                # periodically must not grow the coordinator's memory
                # by one dump per host per call
                client.key_value_delete(f"keystone/metrics/{seq}/")
            except Exception:  # noqa: BLE001 — older jaxlib, best-effort
                pass
            return dumps
        except Exception as e:  # noqa: BLE001 — degraded, never fatal
            logger.warning(
                "metrics roll-up over the coordination service failed "
                "(%r); each host keeps only its own metrics",
                e,
            )
            return None
    try:
        from jax.experimental import multihost_utils

        blob = np.frombuffer(payload.encode(), np.uint8)
        lens = np.asarray(
            multihost_utils.process_allgather(
                np.array([blob.size], np.int32)
            )
        ).reshape(nprocs)
        padded = np.zeros(int(lens.max()), np.uint8)
        padded[: blob.size] = blob
        gathered = np.asarray(multihost_utils.process_allgather(padded))
        if pid != 0:
            return None
        return [
            json.loads(bytes(gathered[i, : int(lens[i])]).decode())
            for i in range(nprocs)
        ]
    except Exception as e:  # noqa: BLE001 — degraded, never fatal
        logger.warning(
            "metrics roll-up failed (%r); each host keeps only its own "
            "metrics",
            e,
        )
        return None


def rollup_metrics(
    out_dir: str | None = None, timeout_s: float = 60.0
) -> dict | None:
    """Cluster-wide metrics roll-up: every host serializes its metrics
    registry dump, host 0 gathers and merges them (counters summed,
    gauge watermarks maxed, timer reservoirs pooled) so a run report
    shows cluster totals instead of host-0-only numbers.

    ALL hosts must call this (it synchronizes at a barrier) — the
    launcher does so after a ``--multihost`` pipeline returns. Host 0
    writes ``metrics_cluster.json`` under ``out_dir`` (when given) and
    emits a ``metrics_rollup`` event; it returns the merged dict. Other
    hosts return None. Transport failure degrades to a warning and None
    — observability must not take down the run it watches."""
    from keystone_tpu.observe import events as _events
    from keystone_tpu.observe import metrics as _metrics

    try:
        nprocs = jax.process_count()
        pid = jax.process_index()
    except Exception:  # noqa: BLE001 — backend init failure
        nprocs, pid = 1, 0
    local = {"process": pid, "metrics": _metrics.get_registry().dump()}
    if nprocs == 1:
        dumps: list[dict] | None = [local]
    else:
        # the gather is a real cross-host collective: its wall is
        # classified (bucket="collective") in the goodput report
        from keystone_tpu.observe import spans as _spans

        with _spans.span(
            "multihost.rollup_gather", bucket="collective", hosts=nprocs
        ):
            dumps = _gather_dumps(json.dumps(local), pid, nprocs, timeout_s)
        if dumps is None:
            return None
    merged = {
        "hosts": nprocs,
        "metrics": merge_metric_dumps([d.get("metrics", {}) for d in dumps]),
    }
    if out_dir:
        try:
            path = os.path.join(out_dir, CLUSTER_METRICS_FILE)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(merged, f, indent=1)
            os.replace(tmp, path)
        except OSError as e:
            logger.warning(
                "cannot write %s under %s (%r)",
                CLUSTER_METRICS_FILE,
                out_dir,
                e,
            )
    log = _events.active()
    if log is not None:
        log.emit(
            "metrics_rollup",
            hosts=nprocs,
            series=len(merged["metrics"]),
        )
    return merged


def global_batch_from_local(local_batch: np.ndarray, mesh, ndim: int | None = None):
    """Assemble a global data-sharded array from this process's local rows
    (the successor of per-executor RDD partitions; wraps
    ``jax.make_array_from_process_local_data``)."""
    from keystone_tpu.parallel.mesh import data_sharding

    sharding = data_sharding(mesh, ndim or local_batch.ndim)
    return jax.make_array_from_process_local_data(sharding, local_batch)
