"""Multi-host (multi-process) execution helpers.

The reference scales out with spark-submit + EC2 provisioning scripts
(``bin/pipelines-ec2.sh``); the TPU-native equivalent is JAX multi-process:
every host runs the same program, ``jax.distributed.initialize`` wires the
processes into one runtime, and global arrays are assembled from
process-local shards. Collectives ride ICI within a slice and DCN across
slices — the mesh construction in :mod:`keystone_tpu.parallel.mesh` is
unchanged because ``jax.devices()`` spans all hosts after initialization.

Typical launch (the SAME command on every host, e.g. via ``gcloud compute
tpus ... ssh --worker=all``; ``initialize()`` must run inside the process
that executes the pipeline, which is what the launcher flag does):

    python -m keystone_tpu --multihost <pipeline> ...
"""

from __future__ import annotations

import jax
import numpy as np

from keystone_tpu.core.logging import get_logger

logger = get_logger("keystone_tpu.parallel.multihost")


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join this process into the multi-host runtime.

    With TPU VMs all arguments are discovered from the environment
    (``jax.distributed.initialize()`` no-arg form); explicit values support
    CPU/GPU test rigs.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    jax.distributed.initialize(**kwargs)
    logger.info(
        "multihost: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def global_batch_from_local(local_batch: np.ndarray, mesh, ndim: int | None = None):
    """Assemble a global data-sharded array from this process's local rows
    (the successor of per-executor RDD partitions; wraps
    ``jax.make_array_from_process_local_data``)."""
    from keystone_tpu.parallel.mesh import data_sharding

    sharding = data_sharding(mesh, ndim or local_batch.ndim)
    return jax.make_array_from_process_local_data(sharding, local_batch)
