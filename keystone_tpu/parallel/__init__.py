"""Parallelism: mesh construction, sharding specs, distributed reductions.

This package is the TPU-native successor of two reference subsystems at once
(SURVEY.md §2.11):

- Spark's execution substrate (RDD partitions, treeAggregate/treeReduce,
  broadcast, shuffle) → a ``jax.sharding.Mesh`` with a "data" axis, XLA
  collectives over ICI/DCN, and replication-by-sharding-spec.
- the ``mlmatrix`` distributed linear-algebra jar (RowPartitionedMatrix,
  NormalEquations, BlockCoordinateDescent) → sharded normal-equation
  reductions in :mod:`keystone_tpu.ops.linear`.
"""

from keystone_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    create_mesh,
    current_mesh,
    data_sharding,
    model_sharding,
    pad_batch,
    replicated_sharding,
    shard_batch,
    use_mesh,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "create_mesh",
    "current_mesh",
    "data_sharding",
    "model_sharding",
    "pad_batch",
    "replicated_sharding",
    "shard_batch",
    "use_mesh",
]
