"""Stupid Backoff n-gram language model pipeline
(reference ``pipelines/nlp/StupidBackoffPipeline.scala``):
tokenize → frequency-encode words → 3-grams → counts → Stupid Backoff
scores; the model serves point queries."""

from __future__ import annotations

import dataclasses
import glob
import os
import time

import numpy as np

from keystone_tpu.core.config import arg, parse_config
from keystone_tpu.core.logging import get_logger
from keystone_tpu.ops.nlp import (
    NGramsCounts,
    NGramsFeaturizer,
    StupidBackoffEstimator,
    Tokenizer,
    WordFrequencyEncoder,
)

logger = get_logger("keystone_tpu.models.stupid_backoff")


@dataclasses.dataclass
class StupidBackoffConfig:
    train_location: str = arg(default="", help="text file/dir/glob")
    max_order: int = arg(default=3)
    alpha: float = arg(default=0.4)
    synthetic: int = arg(default=0, help="if > 0, N synthetic sentences")


def _load_lines(conf: StupidBackoffConfig) -> list[str]:
    if conf.synthetic:
        rng = np.random.default_rng(0)
        vocab = ["the", "cat", "dog", "sat", "on", "mat", "ran", "fast", "a"]
        probs = np.asarray([0.25, 0.12, 0.12, 0.1, 0.1, 0.08, 0.08, 0.05, 0.1])
        return [
            " ".join(rng.choice(vocab, size=rng.integers(4, 12), p=probs))
            for _ in range(conf.synthetic)
        ]
    path = conf.train_location
    files = (
        sorted(glob.glob(os.path.join(path, "*")))
        if os.path.isdir(path)
        else sorted(glob.glob(path)) or [path]
    )
    lines: list[str] = []
    for f in files:
        with open(f, errors="replace") as fh:
            lines.extend(line for line in fh.read().splitlines() if line.strip())
    return lines


def run(conf: StupidBackoffConfig) -> dict:
    t0 = time.perf_counter()
    lines = _load_lines(conf)
    tokens = Tokenizer()(lines)

    encoder_model = WordFrequencyEncoder().fit(tokens)
    encoded = encoder_model(tokens)

    grams = NGramsFeaturizer(orders=tuple(range(1, conf.max_order + 1)))(encoded)
    counts = dict(NGramsCounts()(grams))
    # split unigram counts out (the estimator takes them separately)
    unigrams = {k[0]: v for k, v in counts.items() if len(k) == 1}
    ngram_counts = {k: v for k, v in counts.items() if len(k) > 1}

    model = StupidBackoffEstimator(unigrams, alpha=conf.alpha).fit(ngram_counts)

    # sanity scores: every seen ngram in (0, 1]
    n_scored = len(ngram_counts)
    result = {
        "num_tokens": model.num_tokens,
        "vocab_size": len(encoder_model.word_index),
        "num_ngrams": n_scored,
        "total_s": time.perf_counter() - t0,
    }
    logger.info(
        "StupidBackoff: %d tokens, %d vocab, %d ngrams scored",
        result["num_tokens"],
        result["vocab_size"],
        result["num_ngrams"],
    )
    return result, model, encoder_model


def main(argv=None):
    conf = parse_config(StupidBackoffConfig, argv)
    if not conf.synthetic and not conf.train_location:
        raise SystemExit("need --train-location, or --synthetic N")
    return run(conf)[0]


if __name__ == "__main__":
    main()
