"""CIFAR-10 random-filter convolution pipeline
(reference ``pipelines/images/cifar/RandomCifar.scala:16-60``).

The simplest conv CIFAR app: a RANDOM gaussian filter bank (no patch
sampling, no ZCA) convolved with patch normalization, then
SymmetricRectifier → sum Pooler → vectorize → StandardScaler (with std
division) → exact ridge ``LinearMapEstimator`` (not block BCD) → argmax →
multiclass eval. Distinct from ``cifar_random_patch`` (RandomPatchCifar),
which whitens sampled patches and solves with block least squares.

TPU shape: featurization is the conv-algebra Convolver in one jitted
chunked program; the exact solve is sharded normal equations + replicated
Cholesky.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import numpy as np

from keystone_tpu.core.batching import apply_in_chunks
from keystone_tpu.core.config import arg, parse_config
from keystone_tpu.core.fusion import optimize
from keystone_tpu.core.logging import get_logger
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.models.cifar_linear_pixels import _load as _load_cifar_or_synth
from keystone_tpu.ops.images import (
    Convolver,
    ImageVectorizer,
    Pooler,
    SymmetricRectifier,
)
from keystone_tpu.ops.linear import LinearMapEstimator
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.ops.util import ClassLabelIndicators, MaxClassifier
from keystone_tpu.parallel.mesh import create_mesh, shard_batch

logger = get_logger("keystone_tpu.models.cifar_random")

NUM_CLASSES = 10


@dataclasses.dataclass
class RandomCifarFilterConfig:
    """Random-filter CIFAR workload (reference RandomCifarConfig,
    RandomCifar.scala:72-81)."""

    train_location: str = arg(default="", help="CIFAR-10 binary file/dir")
    test_location: str = arg(default="", help="CIFAR-10 binary file/dir")
    num_filters: int = arg(default=100)
    patch_size: int = arg(default=6)
    pool_size: int = arg(default=14)
    pool_stride: int = arg(default=13)
    alpha: float = arg(default=0.25, help="rectifier offset")
    lam: float = arg(default=0.0, help="L2 regularization (0 = OLS)")
    chunk_size: int = arg(default=1024, help="featurization chunk (images)")
    sample_frac: float = arg(default=0.0, help="if > 0, subsample train")
    seed: int = arg(default=0)
    synthetic: int = arg(default=0, help="if > 0, N synthetic samples")


def run(conf: RandomCifarFilterConfig, mesh=None) -> dict:
    if mesh is None and len(jax.devices()) > 1:
        mesh = create_mesh()
    t0 = time.perf_counter()
    train = _load_cifar_or_synth(_as_lp_conf(conf), "train")
    test = _load_cifar_or_synth(_as_lp_conf(conf), "test")

    rng = np.random.default_rng(conf.seed)
    if conf.sample_frac > 0.0:
        keep = rng.random(len(train)) < conf.sample_frac
        train = dataclasses.replace(
            train, images=train.images[keep], labels=train.labels[keep]
        )

    # random gaussian filter bank — RandomCifar.scala:37
    filters = rng.normal(
        size=(conf.num_filters, conf.patch_size**2 * 3)
    ).astype(np.float32)

    featurizer = (
        Convolver(
            filters=filters,
            whitener_means=None,
            patch_size=conf.patch_size,
            normalize_patches=True,
        )
        >> SymmetricRectifier(alpha=conf.alpha)
        >> Pooler(stride=conf.pool_stride, pool_size=conf.pool_size)
        >> ImageVectorizer()
    )
    # operator-fusion pass: pools each rectifier half before the
    # channel concat so the (N, oh, ow, 2F) map never hits HBM
    feat_fn = jax.jit(lambda b, p=optimize(featurizer): p(b))
    t_setup = time.perf_counter()

    def featurize(images: np.ndarray):
        x = shard_batch(images, mesh)
        return apply_in_chunks(feat_fn, x, conf.chunk_size)

    f_train_raw = featurize(train.images)
    # reference StandardScaler() divides by std (normalizeStdDev default
    # true) — unlike RandomPatchCifar's center-only scaling
    scaler = StandardScaler(normalize_std_dev=True).fit(
        f_train_raw, n_valid=len(train)
    )

    y = np.zeros(f_train_raw.shape[0], np.int32)
    y[: len(train)] = train.labels
    indicators = ClassLabelIndicators(num_classes=NUM_CLASSES)(y)
    t_feat = time.perf_counter()

    from keystone_tpu import plan as plan_mod

    if plan_mod.enabled():
        # KEYSTONE_PLAN: scale + normal-equation accumulation stream as
        # one fused jitted chunk step (plan/fused_fit.py) — the SCALED
        # feature copy (a second N×D resident array on the classic
        # path) never materializes; the fitted pipeline applies the
        # scaler per batch instead
        from keystone_tpu.core.pipeline import ChainedLabelEstimator

        fitted = plan_mod.fit_streaming(
            ChainedLabelEstimator(
                prefix=scaler, est=LinearMapEstimator(lam=conf.lam)
            ),
            f_train_raw,
            indicators,
            n_valid=len(train),
            mesh=mesh,
        )
        model = jax.block_until_ready(fitted[-1])
        apply_model = fitted
    else:
        f_train = scaler(f_train_raw)
        model = jax.block_until_ready(
            LinearMapEstimator(lam=conf.lam).fit(
                f_train, indicators, n_valid=len(train)
            )
        )
        apply_model = lambda raw: model(scaler(raw))  # noqa: E731
    t_fit = time.perf_counter()

    classify = MaxClassifier()
    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    # classic path: the scaled copy is already resident — score it
    # directly instead of re-standardizing the raw features
    train_scores = (
        apply_model(f_train_raw) if plan_mod.enabled() else model(f_train)
    )
    train_eval = evaluator(classify(train_scores), y, n_valid=len(train))

    f_test_raw = featurize(test.images)
    y_test = np.zeros(f_test_raw.shape[0], np.int32)
    y_test[: len(test)] = test.labels
    test_eval = evaluator(
        classify(apply_model(f_test_raw)), y_test, n_valid=len(test)
    )
    t_end = time.perf_counter()

    result = {
        "train_error": train_eval.error,
        "test_error": test_eval.error,
        "n_train": len(train),
        "n_test": len(test),
        "setup_s": t_setup - t0,
        "featurize_s": t_feat - t_setup,
        "fit_s": t_fit - t_feat,
        "total_s": t_end - t0,
        "featurize_fit_samples_per_s": len(train) / (t_fit - t_setup),
    }
    logger.info(
        "RandomCifar: train err %.4f, test err %.4f, %.0f samples/s",
        train_eval.error,
        test_eval.error,
        result["featurize_fit_samples_per_s"],
    )
    return result


def _as_lp_conf(conf: RandomCifarFilterConfig):
    from keystone_tpu.models.cifar_linear_pixels import LinearPixelsConfig

    return LinearPixelsConfig(
        train_location=conf.train_location,
        test_location=conf.test_location,
        synthetic=conf.synthetic,
    )


def main(argv=None) -> dict:
    conf = parse_config(RandomCifarFilterConfig, argv)
    if not conf.synthetic and not (conf.train_location and conf.test_location):
        raise SystemExit(
            "need --train-location AND --test-location, or --synthetic N"
        )
    return run(conf)


if __name__ == "__main__":
    main()
