"""20-Newsgroups text classification
(reference ``pipelines/text/NewsgroupsPipeline.scala``):
trim → lowercase → tokenize → n-grams (1..n) → binary term frequency →
top-K sparse features dense-ified → multinomial naive Bayes → argmax."""

from __future__ import annotations

import dataclasses
import time

import jax
import numpy as np

from keystone_tpu.core.config import arg, parse_config
from keystone_tpu.core.logging import get_logger
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.newsgroups import CLASSES, TextData, load_newsgroups
from keystone_tpu.ops.naive_bayes import NaiveBayesEstimator
from keystone_tpu.ops.nlp import LowerCase, NGramsFeaturizer, Tokenizer, Trim
from keystone_tpu.ops.sparse import CommonSparseFeatures
from keystone_tpu.ops.stats import TermFrequency
from keystone_tpu.ops.util import MaxClassifier
from keystone_tpu.parallel.mesh import create_mesh, shard_batch

logger = get_logger("keystone_tpu.models.newsgroups")

NUM_CLASSES = len(CLASSES)

_SYNTH_VOCAB = [
    ["galaxy", "rocket", "orbit", "launch", "telescope"],
    ["goal", "hockey", "puck", "season", "playoff"],
    ["windows", "driver", "graphics", "monitor", "software"],
    ["engine", "motorcycle", "ride", "helmet", "brake"],
]


@dataclasses.dataclass
class NewsgroupsConfig:
    """Newsgroups workload (reference NewsgroupsConfig)."""

    train_location: str = arg(default="", help="dir of class subdirectories")
    test_location: str = arg(default="")
    n_grams: int = arg(default=2, help="use 1..n grams")
    common_features: int = arg(default=100_000, help="vocabulary cap")
    corenlp: bool = arg(
        default=False,
        help="featurize with CoreNLPFeatureExtractor (lemmatize + "
        "entity-type replacement, sentence-bounded n-grams) instead of "
        "the plain tokenizer chain",
    )
    synthetic: int = arg(default=0, help="if > 0, N synthetic documents")


def _load(conf: NewsgroupsConfig, which: str) -> TextData:
    if conf.synthetic:
        n = conf.synthetic if which == "train" else max(conf.synthetic // 4, 1)
        rng = np.random.default_rng(0 if which == "train" else 1)
        docs, labels = [], []
        for _ in range(n):
            label = int(rng.integers(0, len(_SYNTH_VOCAB)))
            words = list(rng.choice(_SYNTH_VOCAB[label], size=30)) + list(
                rng.choice(["the", "a", "and", "of"], size=10)
            )
            rng.shuffle(words)
            docs.append(" ".join(words))
            labels.append(label)
        return TextData(labels=np.asarray(labels, np.int32), data=docs)
    return load_newsgroups(
        conf.train_location if which == "train" else conf.test_location
    )


def run(conf: NewsgroupsConfig, mesh=None) -> dict:
    if mesh is None and len(jax.devices()) > 1:
        mesh = create_mesh()
    t0 = time.perf_counter()
    train, test = _load(conf, "train"), _load(conf, "test")

    if conf.corenlp:
        from keystone_tpu.ops.corenlp import CoreNLPFeatureExtractor

        featurizer_host = CoreNLPFeatureExtractor(
            orders=tuple(range(1, conf.n_grams + 1))
        ) >> TermFrequency(fn=lambda x: 1)
    else:
        featurizer_host = (
            Trim()
            >> LowerCase()
            >> Tokenizer()
            >> NGramsFeaturizer(orders=tuple(range(1, conf.n_grams + 1)))
            >> TermFrequency(fn=lambda x: 1)
        )
    train_tf = featurizer_host(train.data)
    vectorizer = CommonSparseFeatures(conf.common_features).fit(train_tf)

    x_train = shard_batch(vectorizer(train_tf), mesh)
    n_train = len(train)
    y_train = np.zeros(x_train.shape[0], np.int32)
    y_train[:n_train] = train.labels

    est = NaiveBayesEstimator(num_classes=NUM_CLASSES)
    model = est.fit(x_train, y_train, n_valid=n_train)
    predict = model >> MaxClassifier()
    predict_jit = jax.jit(lambda p, b: p(b))

    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    train_eval = evaluator(
        predict_jit(predict, x_train), y_train, n_valid=n_train
    )

    x_test = shard_batch(vectorizer(featurizer_host(test.data)), mesh)
    n_test = len(test)
    y_test = np.zeros(x_test.shape[0], np.int32)
    y_test[:n_test] = test.labels
    test_eval = evaluator(predict_jit(predict, x_test), y_test, n_valid=n_test)

    result = {
        "train_error": train_eval.error,
        "test_error": test_eval.error,
        "n_train": n_train,
        "n_test": n_test,
        "vocab_size": len(vectorizer.feature_space),
        "total_s": time.perf_counter() - t0,
    }
    logger.info(
        "Newsgroups: train err %.4f, test err %.4f (vocab %d)\n%s",
        train_eval.error,
        test_eval.error,
        result["vocab_size"],
        test_eval.summary(list(CLASSES)) if not conf.synthetic else "",
    )
    return result


def main(argv=None) -> dict:
    conf = parse_config(NewsgroupsConfig, argv)
    if not conf.synthetic and not (conf.train_location and conf.test_location):
        raise SystemExit("need --train-location AND --test-location, or --synthetic N")
    return run(conf)


if __name__ == "__main__":
    main()
