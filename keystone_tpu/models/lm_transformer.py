"""Decoder-only transformer LM — stable import path and CLI.

The implementation lives in :mod:`keystone_tpu.models.lm`
(``model`` / ``train`` / ``decode``); this module re-exports that
surface (existing imports and pickled checkpoints keep resolving here)
and owns the config/CLI entry: ``python -m
keystone_tpu.models.lm_transformer``.

The reference has no sequence models at all (SURVEY §5: long-context
"absent"); the LM is the training/serving-side consumer of the
framework's sequence-parallel + pipeline-parallel + quantization stack —
a beyond-reference capability in the spirit of ``models/vit_ridge.py``.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import numpy as np

from keystone_tpu.core.config import arg, parse_config
from keystone_tpu.core.logging import get_logger
from keystone_tpu.models.lm import (  # noqa: F401  (re-exported surface)
    KVCache,
    LMBlock,
    TransformerLM,
    chunked_token_cross_entropy,
    decode_step,
    generate,
    make_optimizer,
    make_pp_train_step,
    make_train_step,
    next_token_loss,
    next_token_loss_pp,
    pp_forward,
    prefill,
    quantize_for_decode,
    shard_params,
    synthetic_corpus,
    token_cross_entropy,
    train,
    train_step_flops,
)
from keystone_tpu.models.lm.decode import _filter_logits  # noqa: F401
from keystone_tpu.models.lm.model import (  # noqa: F401
    has_quantized_leaves as _has_quantized_leaves,
)
from keystone_tpu.models.lm.train import _step_batch  # noqa: F401

logger = get_logger("keystone_tpu.models.lm_transformer")


@dataclasses.dataclass
class LMConfig:
    steps: int = arg(default=60, help="training steps")
    batch: int = arg(default=8)
    seq: int = arg(default=256)
    dim: int = arg(default=256)
    depth: int = arg(default=4)
    num_heads: int = arg(default=8)
    num_kv_heads: int = arg(
        default=0,
        help="GQA: K/V heads (0 = num_heads/MHA, 1 = MQA); shrinks the "
        "decode cache by num_heads/num_kv_heads",
    )
    vocab: int = arg(default=256)
    lr: float = arg(default=3e-4)
    seq_mode: str = arg(
        default="local", help="attention strategy: local | ring | ulysses"
    )
    compute_dtype: str = arg(
        default="float32",
        help="matmul/activation dtype (params stay float32); "
        "bfloat16 is the TPU-native choice",
    )
    seed: int = arg(default=0)
    moe_every: int = arg(
        default=0,
        help="replace every k-th block's FFN with a top-2 MoE (0 = dense)",
    )
    num_experts: int = arg(default=8)
    pos_encoding: str = arg(
        default="learned", help="position encoding: learned | rope"
    )
    corpus: str = arg(
        default="",
        help="path to a text file/dir (byte-level tokens, vocab forced to "
        "256, 10%% held out for perplexity); default: synthetic Markov",
    )
    schedule: str = arg(
        default="constant", help="lr schedule: constant | cosine (warmup)"
    )
    grad_clip: float = arg(
        default=0.0, help="global-norm gradient clip (0 = off)"
    )
    checkpoint_dir: str = arg(
        default="",
        help="orbax checkpoint/resume directory (preemption-safe training)",
    )
    checkpoint_every: int = arg(
        default=0,
        help="steps between checkpoints (0 = steps//10, ~10 per run)",
    )
    logit_chunk: int = arg(
        default=0,
        help="compute the CE in this many-position chunks so the "
        "(B, S, V) f32 logits never materialize (0 = dense; must divide "
        "seq; the long-context memory/bandwidth lever)",
    )


def run(conf: LMConfig, mesh=None) -> dict:
    from keystone_tpu.parallel.mesh import create_mesh

    if conf.schedule not in ("constant", "cosine"):
        # fail before the (possibly minutes-long) corpus load / model init
        raise ValueError(
            f"--schedule {conf.schedule!r}; expected constant|cosine"
        )
    if mesh is None and len(jax.devices()) > 1:
        mesh = create_mesh()
    valid = None
    if conf.corpus:
        from keystone_tpu.loaders.text import BYTE_VOCAB, load_text_corpus

        corpus, valid = load_text_corpus(conf.corpus)
        conf = dataclasses.replace(conf, vocab=BYTE_VOCAB)
    key = jax.random.key(conf.seed)
    model = TransformerLM.create(
        key,
        vocab=conf.vocab,
        max_seq=conf.seq,
        dim=conf.dim,
        depth=conf.depth,
        num_heads=conf.num_heads,
        seq_mode=conf.seq_mode,
        mesh=mesh if conf.seq_mode != "local" else None,
        compute_dtype=conf.compute_dtype,
        moe_every=conf.moe_every,
        num_experts=conf.num_experts,
        pos_encoding=conf.pos_encoding,
        num_kv_heads=conf.num_kv_heads,
    )
    model = shard_params(model, mesh)
    if not conf.corpus:
        corpus = synthetic_corpus(200_000, conf.vocab, seed=conf.seed)
    t0 = time.time()
    model, losses = train(
        model,
        corpus,
        steps=conf.steps,
        batch=conf.batch,
        seq=conf.seq,
        lr=conf.lr,
        mesh=mesh,
        seed=conf.seed,
        log_every=max(conf.steps // 5, 1),
        checkpoint_dir=conf.checkpoint_dir,
        checkpoint_every=conf.checkpoint_every,
        schedule=conf.schedule,
        grad_clip=conf.grad_clip,
        logit_chunk=conf.logit_chunk,
    )
    dt = time.time() - t0
    steps_ran = len(losses)
    if not losses:
        # a resume that found the run already complete trains 0 steps
        losses = [float("nan")]
    res = {
        # loss_first is the first loss of THIS segment; on a resumed run
        # (steps_ran < steps) it is not the run's true initial loss —
        # downstream records key off `resumed` to tell the cases apart
        "loss_first": losses[0],
        "loss_last": float(np.mean(losses[-5:])),
        "steps": conf.steps,
        "steps_ran": steps_ran,
        "resumed": steps_ran < conf.steps,
        "params": model.num_params(),
        "tokens_per_s": steps_ran * conf.batch * conf.seq / dt,
        "wall_s": dt,
    }
    if valid is not None:
        if len(valid) >= conf.seq + 1:
            from keystone_tpu.evaluation.perplexity import (
                evaluate_perplexity,
            )

            ev = evaluate_perplexity(
                model, valid, seq=conf.seq, batch=conf.batch,
                logit_chunk=conf.logit_chunk,
            )
            res["valid_loss"] = ev["loss"]
            res["valid_bits_per_token"] = ev["bits_per_token"]
            res["valid_perplexity"] = ev["perplexity"]
        else:
            logger.warning(
                "held-out tail (%d tokens) is shorter than one seq+1=%d "
                "window — skipping the perplexity evaluation the corpus "
                "flag promises; shorten --seq or grow the corpus",
                len(valid),
                conf.seq + 1,
            )
    logger.info(
        "lm: %d params, loss %.3f -> %.3f, %.0f tokens/s",
        res["params"],
        res["loss_first"],
        res["loss_last"],
        res["tokens_per_s"],
    )
    return res


def main(argv=None) -> dict:
    return run(parse_config(LMConfig, argv))


if __name__ == "__main__":
    main()
