"""Decoder-only transformer LM with a fully sharded training step.

The reference has no sequence models at all (SURVEY §5: long-context
"absent"), but long-context + distributed are first-class capabilities of
this framework, not parity afterthoughts. This model is the training-side
consumer of that stack:

- causal attention via :mod:`keystone_tpu.ops.attention` — dense, fused
  Pallas flash, or sequence-parallel ring / Ulysses (`seq_mode`), so one
  flag takes the same model from a single chip to a sequence-sharded mesh
  for contexts that don't fit one device;
- tensor parallelism by sharding each weight over the mesh ``model`` axis
  (head-parallel attention, column/row-parallel MLP, vocab-parallel tied
  embedding) — XLA inserts the psums, the model code stays purely
  functional;
- data parallelism over the ``data`` axis;
- one jitted, buffer-donated train step (AdamW via optax) — the whole
  update is a single XLA program, the idiom the rest of the framework uses
  for its solvers (one launch per step, no host round-trips).

This is a beyond-reference capability in the same spirit as
``models/vit_ridge.py``.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from keystone_tpu.core.config import arg, parse_config
from keystone_tpu.core.logging import get_logger
from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.ops.attention import (
    dense_attention,
    ring_attention,
    ulysses_attention,
)
from keystone_tpu.ops.quantization import QTensor, mm, quantize_int8
from keystone_tpu.ops.vit import _layer_norm

logger = get_logger("keystone_tpu.models.lm_transformer")


@treenode
class LMBlock:
    wq: jnp.ndarray  # (d, d)
    wk: jnp.ndarray
    wv: jnp.ndarray
    wo: jnp.ndarray
    w1: jnp.ndarray  # (d, ff)
    w2: jnp.ndarray  # (ff, d)


def _ln(x, cdt):
    # normalization stats in f32 even under a bf16 policy: the
    # mean/variance cancellation is exactly what bf16 loses
    return _layer_norm(x.astype(jnp.float32)).astype(cdt)


def _split_heads(y, w, h):
    n, s, _ = y.shape
    out = mm(y, w, y.dtype)  # (n, s, h·hd) — rectangular for GQA K/V
    return out.reshape(n, s, h, out.shape[-1] // h).transpose(0, 2, 1, 3)


def _rope(x, positions, base: float = 10_000.0):
    """Rotary position embedding. x: (..., S, hd), hd even; positions:
    (S,) int32 global token positions. Angles in f32 (bf16 loses phase
    accuracy fast at long context), rotated result back in x.dtype."""
    hd = x.shape[-1]
    half = hd // 2
    inv = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    freqs = positions.astype(jnp.float32)[:, None] * inv  # (S, half)
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)


def _block_apply(x, blk: LMBlock, cdt, attn, moe=None):
    """Pre-LN residual block shared by training forward, prefill, and
    decode: ``attn(y, blk) -> (attention output (N,S,d), aux)``. When
    ``moe`` is given it replaces the dense FFN; returns
    (x, attn_aux, moe_aux_loss)."""
    a, aux = attn(_ln(x, cdt), blk)
    x = x + a
    y = _ln(x, cdt)
    if moe is not None:
        f, moe_aux = moe(y)
        return x + f, aux, moe_aux
    hdn = mm(y, blk.w1, cdt)
    return x + mm(jax.nn.gelu(hdn), blk.w2, cdt), aux, jnp.float32(0)


def _gather_embed(embed, tokens):
    """Embedding-row gather handling the int8 row-quantized table (the
    per-token scales apply to the gathered rows)."""
    if isinstance(embed, QTensor):
        return embed.q[tokens].astype(jnp.float32) * embed.scale[tokens]
    return embed[tokens]


def _embed(model, tokens, cdt):
    """Token embedding + optional learned positions, cast to the compute
    dtype — the one preamble shared by training forward, prefill, and the
    pipeline-parallel forward."""
    d = model.embed.shape[-1]
    x = _gather_embed(model.embed, tokens) * math.sqrt(d)
    if model.pos_encoding == "learned":
        x = x + model.pos_embed[: tokens.shape[1]]
    return x.astype(cdt)


def _tied_logits(x, embed, cdt):
    # bf16 operands, f32 accumulate/output: the logits feed a logsumexp —
    # bf16 logits would cost real perplexity precision
    if isinstance(embed, QTensor):
        # (V, 1) row scales become per-output-channel under the transpose
        return jnp.matmul(
            _ln(x, cdt), embed.q.T.astype(cdt),
            preferred_element_type=jnp.float32,
        ) * embed.scale[:, 0]
    return jnp.matmul(
        _ln(x, cdt), embed.T.astype(cdt), preferred_element_type=jnp.float32
    )


@treenode
class TransformerLM:
    """Pre-LN decoder-only LM; logits tied to the token embedding."""

    embed: jnp.ndarray  # (V, d)
    pos_embed: jnp.ndarray  # (S_max, d)
    blocks: tuple  # of LMBlock
    num_heads: int = static_field(default=8)
    # attention strategy: "local" (dense or Pallas flash on TPU),
    # "ring" / "ulysses" (sequence-parallel over `seq_axis` of `mesh`)
    seq_mode: str = static_field(default="local")
    mesh: object = static_field(default=None)
    seq_axis: str = static_field(default="data")
    # rematerialize each block in the backward pass: activation memory
    # drops from O(depth · S · d) per-layer intermediates to the block
    # boundaries only — the jax.checkpoint successor of the reference's
    # nothing (it never trained deep models)
    remat: bool = static_field(default=False)
    # mixed precision: params/optimizer state stay float32; activations
    # and the matmul operands run in this dtype ("bfloat16" halves HBM
    # traffic and feeds the MXU its native input width). LayerNorm stats
    # and the loss reduction stay float32 regardless.
    compute_dtype: str = static_field(default="float32")
    # expert parallelism: per-block MoE layers (None entries keep the
    # dense FFN). Tuple parallel to `blocks`; empty = no MoE anywhere.
    moe_layers: tuple = ()
    moe_aux_weight: float = static_field(default=0.01)
    # "learned" = trained absolute table (pos_embed, capped at max_seq);
    # "rope" = rotary q/k phases — no table, no length cap beyond memory,
    # the right pairing for the blockwise long-context backward
    pos_encoding: str = static_field(default="learned")
    # grouped-query attention: K/V carry this many heads (0 = num_heads,
    # plain MHA; 1 = MQA). The decode cache shrinks by num_heads/kv_heads
    # — composing with kv_dtype="int8" for the full serving story
    num_kv_heads: int = static_field(default=0)

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    def _qkv_heads(self, x, blk: LMBlock, positions=None):
        """(q with H heads, k/v with KV heads, rope applied).
        ``positions`` defaults to 0..S-1 (full-sequence forward); decode
        passes the single global position of its new token."""
        q = _split_heads(x, blk.wq, self.num_heads)
        k = _split_heads(x, blk.wk, self.kv_heads)
        v = _split_heads(x, blk.wv, self.kv_heads)
        if self.pos_encoding == "rope":
            if positions is None:
                positions = jnp.arange(x.shape[1])
            q = _rope(q, positions)
            k = _rope(k, positions)
        return q, k, v

    def _attention(self, x, blk: LMBlock, return_kv: bool = False):
        n, s, d = x.shape
        h = self.num_heads

        # x is always the full (global) sequence here — the
        # sequence-parallel paths shard inside ring/ulysses_attention
        q, k, v = self._qkv_heads(x, blk)
        kv_raw = (k, v)  # pre-broadcast: what the decode cache stores
        if self.kv_heads != h:
            # training/prefill compute broadcasts K/V up to H heads
            # (activation-sized, the standard GQA training treatment);
            # the grouped decode path never materializes this
            g = h // self.kv_heads
            k = jnp.repeat(k, g, axis=1)
            v = jnp.repeat(v, g, axis=1)
        # sequence-parallel training runs the custom-VJP bodies: the ring
        # backward circulates dk/dv accumulators around the ring (the
        # per-hop Pallas forward kernels are forward-only), Ulysses
        # differentiates the flash trainable wrapper through all_to_all.
        # use_flash auto-selects: Pallas-rate on TPU, jnp off it.
        if self.seq_mode == "ring":
            out = ring_attention(
                q, k, v, self.mesh, seq_axis=self.seq_axis, causal=True,
                trainable=True,
            )
        elif self.seq_mode == "ulysses":
            out = ulysses_attention(
                q, k, v, self.mesh, seq_axis=self.seq_axis, causal=True,
                trainable=True,
            )
        else:
            from keystone_tpu.ops.flash_attention import on_tpu

            if on_tpu():
                # fused Pallas forward with a recompute VJP — training
                # never materializes the (S, S) probabilities
                from keystone_tpu.ops.flash_attention import (
                    flash_attention_trainable,
                )

                out = flash_attention_trainable(q, k, v, True)
            else:
                out = dense_attention(q, k, v, causal=True)
        proj = mm(
            out.transpose(0, 2, 1, 3).reshape(n, s, d).astype(x.dtype),
            blk.wo,
            x.dtype,
        )
        if return_kv:
            return proj, kv_raw
        return proj

    def _moe(self, i: int):
        return self.moe_layers[i] if self.moe_layers else None

    def __call__(self, tokens):
        """(B, S) int tokens → (B, S, V) float32 logits."""
        return self.forward_with_aux(tokens)[0]

    def forward_with_aux(self, tokens):
        """(logits (B, S, V) f32, total MoE load-balance aux loss)."""
        cdt = jnp.dtype(self.compute_dtype)
        x = _embed(self, tokens, cdt)

        def block_fn(x, blk, moe):
            out, _, moe_aux = _block_apply(
                x, blk, cdt,
                lambda y, b: (self._attention(y, b), None),
                moe=moe,
            )
            return out, moe_aux

        if self.remat:
            block_fn = jax.checkpoint(block_fn)
        aux = jnp.float32(0)
        for i, blk in enumerate(self.blocks):
            x, moe_aux = block_fn(x, blk, self._moe(i))
            aux = aux + moe_aux
        return _tied_logits(x, self.embed, cdt), aux

    @staticmethod
    def create(
        key,
        vocab: int = 256,
        max_seq: int = 512,
        dim: int = 256,
        depth: int = 4,
        num_heads: int = 8,
        ff_mult: int = 4,
        seq_mode: str = "local",
        mesh=None,
        seq_axis: str = "data",
        compute_dtype: str = "float32",
        moe_every: int = 0,
        num_experts: int = 8,
        capacity_factor: float = 1.25,
        pos_encoding: str = "learned",
        num_kv_heads: int = 0,
    ) -> "TransformerLM":
        """``moe_every=k`` replaces the dense FFN of every k-th block with
        a top-2 routed :class:`~keystone_tpu.ops.moe.MoELayer` of
        ``num_experts`` experts (0 = dense everywhere).
        ``pos_encoding="rope"`` drops the learned table (and its max_seq
        cap) for rotary q/k phases."""
        if pos_encoding not in ("learned", "rope"):
            raise ValueError(
                f"pos_encoding={pos_encoding!r}; expected learned|rope"
            )
        if pos_encoding == "rope" and (dim // num_heads) % 2:
            raise ValueError(
                f"rope needs an even head dim; got dim/num_heads = "
                f"{dim}/{num_heads} = {dim // num_heads}"
            )
        kvh = num_kv_heads or num_heads
        if kvh <= 0 or num_heads % kvh:
            raise ValueError(
                f"num_heads={num_heads} not divisible by "
                f"num_kv_heads={kvh}"
            )
        # canonical static field: 0 means MHA, so kvh == num_heads
        # normalizes to 0 (num_kv_heads=H and =0 are the same model)
        num_kv_heads = 0 if kvh == num_heads else kvh
        kv_dim = kvh * (dim // num_heads)
        # the split count and per-block stride must not depend on
        # moe_every: dense models seeded before MoE existed must keep
        # bit-identical weights, so MoE keys are folded in separately
        keys = jax.random.split(key, 2 + 6 * depth)

        def init(k, shape, fan_in):
            return jax.random.normal(k, shape, jnp.float32) / math.sqrt(fan_in)

        blocks = []
        moes = []
        for i in range(depth):
            ks = keys[2 + 6 * i : 8 + 6 * i]
            is_moe = bool(moe_every) and (i + 1) % moe_every == 0
            blocks.append(
                LMBlock(
                    wq=init(ks[0], (dim, dim), dim),
                    wk=init(ks[1], (dim, kv_dim), dim),
                    wv=init(ks[2], (dim, kv_dim), dim),
                    wo=init(ks[3], (dim, dim), dim),
                    # a MoE block's dense FFN is never applied — zero-width
                    # placeholders keep the pytree structure uniform
                    # without dead parameters
                    w1=jnp.zeros((dim, 0), jnp.float32)
                    if is_moe
                    else init(ks[4], (dim, ff_mult * dim), dim),
                    w2=jnp.zeros((0, dim), jnp.float32)
                    if is_moe
                    else init(ks[5], (ff_mult * dim, dim), ff_mult * dim),
                )
            )
            if is_moe:
                from keystone_tpu.ops.moe import MoELayer

                moes.append(
                    MoELayer.create(
                        jax.random.fold_in(key, 1_000_003 + i),
                        dim, ff_mult * dim, num_experts, capacity_factor,
                    )
                )
            else:
                moes.append(None)
        return TransformerLM(
            embed=0.02 * jax.random.normal(keys[0], (vocab, dim)),
            # rope keeps a zero-width placeholder: no table params, no cap
            pos_embed=jnp.zeros((0, dim), jnp.float32)
            if pos_encoding == "rope"
            else 0.02 * jax.random.normal(keys[1], (max_seq, dim)),
            blocks=tuple(blocks),
            num_heads=num_heads,
            seq_mode=seq_mode,
            mesh=mesh,
            seq_axis=seq_axis,
            compute_dtype=compute_dtype,
            moe_layers=tuple(moes) if moe_every else (),
            pos_encoding=pos_encoding,
            num_kv_heads=num_kv_heads,
        )

    def num_params(self) -> int:
        return sum(
            int(np.prod(leaf.shape)) for leaf in jax.tree_util.tree_leaves(self)
        )


def shard_params(model: TransformerLM, mesh) -> TransformerLM:
    """Lay the weights out for tensor parallelism over the mesh ``model``
    axis: attention q/k/v column-sharded (head-parallel) with wo
    row-sharded, MLP column- then row-sharded, embedding vocab-sharded.
    XLA then inserts exactly the two psums per block that hand-written
    Megatron-style TP would — the layout IS the parallelism.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None or mesh.shape.get("model", 1) == 1:
        return model
    n_model = mesh.shape["model"]

    def put(x, spec):
        # a dim not divisible by the axis (e.g. an unpadded vocab) is
        # replicated rather than rejected
        spec = P(
            *(
                a
                if a is None or x.shape[i] % n_model == 0
                else None
                for i, a in enumerate(spec)
            )
        )
        return jax.device_put(x, NamedSharding(mesh, spec))

    blocks = tuple(
        LMBlock(
            wq=put(b.wq, P(None, "model")),
            wk=put(b.wk, P(None, "model")),
            wv=put(b.wv, P(None, "model")),
            wo=put(b.wo, P("model", None)),
            w1=put(b.w1, P(None, "model")),
            w2=put(b.w2, P("model", None)),
        )
        for b in model.blocks
    )
    moes = tuple(
        m
        if m is None
        else dataclasses.replace(
            m,
            # expert-parallel: one expert group per model-axis device;
            # the router stays replicated (every token scores every
            # expert) — XLA places the dispatch/combine all_to_alls
            w_router=put(m.w_router, P()),
            w1=put(m.w1, P("model", None, None)),
            w2=put(m.w2, P("model", None, None)),
        )
        for m in model.moe_layers
    )
    return dataclasses.replace(
        model,
        embed=put(model.embed, P("model", None)),
        pos_embed=put(model.pos_embed, P()),
        blocks=blocks,
        moe_layers=moes,
    )


@treenode
class KVCache:
    """Preallocated decode cache: static (L, B, KV_heads, S_max, hd)
    buffers (KV_heads < num_heads under GQA — that ratio IS the cache
    saving) plus the number of valid positions. Static shapes are the point — the whole
    generate loop compiles to ONE program (prefill + a lax.scan of decode
    steps) with in-place `dynamic_update_slice` writes, no retracing as
    the sequence grows (the XLA analog of the reference's nothing: it has
    no autoregressive models).

    With ``kv_dtype="int8"`` the buffers hold per-position symmetric int8
    with (L, B, H, S_max, 1) scales: at long context the cache, not the
    weights, dominates each decode step's HBM reads, and the scales pull
    OUT of both dots exactly (scores = (q·k_q^T)·scale_k; out =
    (p·scale_v)·v_q), so nothing dequantized ever materializes."""

    k: jnp.ndarray
    v: jnp.ndarray
    pos: jnp.ndarray  # scalar int32
    k_scale: jnp.ndarray | None = None
    v_scale: jnp.ndarray | None = None


def _kv_quant(t):
    """(..., hd) → (int8 codes, f32 scale (..., 1)) per-position — the
    shared symmetric recipe pooling over the head dim."""
    from keystone_tpu.ops.quantization import symmetric_int8

    return symmetric_int8(t, (-1,))


def prefill(model: TransformerLM, tokens, s_max: int,
            kv_dtype: str | None = None):
    """Run the prompt through the model once, capturing per-layer K/V into
    an ``s_max``-long cache (optionally int8 — see :class:`KVCache`).
    Returns (last-position logits (B, V), cache). Local attention only
    (sequence-parallel decode shards the cache — use ring/Ulysses for
    training, gather to local for decode)."""
    if model.seq_mode != "local":
        raise ValueError("prefill/decode require seq_mode='local'")
    if kv_dtype not in (None, "int8"):
        raise ValueError(f"kv_dtype={kv_dtype!r}; expected None|'int8'")
    cdt = jnp.dtype(model.compute_dtype)
    n, s = tokens.shape
    x = _embed(model, tokens, cdt)

    ks, vs = [], []
    for i, blk in enumerate(model.blocks):
        x, (k, v), _ = _block_apply(
            x, blk, cdt,
            lambda y, b: model._attention(y, b, return_kv=True),
            moe=model._moe(i),
        )
        ks.append(k)
        vs.append(v)
    logits = _tied_logits(x[:, -1:], model.embed, cdt)[:, 0]
    pad = [(0, 0), (0, 0), (0, s_max - s), (0, 0)]
    k_stack = jnp.stack([jnp.pad(k, pad) for k in ks])
    v_stack = jnp.stack([jnp.pad(v, pad) for v in vs])
    if kv_dtype == "int8":
        kq, ksc = _kv_quant(k_stack)
        vq, vsc = _kv_quant(v_stack)
        cache = KVCache(
            k=kq, v=vq, pos=jnp.asarray(s, jnp.int32),
            k_scale=ksc, v_scale=vsc,
        )
    else:
        cache = KVCache(
            k=k_stack, v=v_stack, pos=jnp.asarray(s, jnp.int32)
        )
    return logits, cache


def decode_step(model: TransformerLM, token, cache: KVCache):
    """One autoregressive step: (B,) token at position ``cache.pos`` →
    ((B, V) logits, updated cache). Attention reads the full static-shape
    cache with positions ≥ pos masked — compiler-friendly in exchange for
    O(S_max) work per step."""
    cdt = jnp.dtype(model.compute_dtype)
    d = model.embed.shape[-1]
    h = model.num_heads
    hd = d // h
    n = token.shape[0]
    pos = cache.pos
    x = _gather_embed(model.embed, token)[:, None] * math.sqrt(d)
    if model.pos_encoding == "learned":
        x = x + jax.lax.dynamic_slice_in_dim(model.pos_embed, pos, 1)
    x = x.astype(cdt)

    valid = (jnp.arange(cache.k.shape[3]) <= pos)[None, None, None, :]
    quantized = cache.k_scale is not None
    new_k, new_v = cache.k, cache.v
    new_ks, new_vs = cache.k_scale, cache.v_scale

    kvh = model.kv_heads
    g = h // kvh  # query heads per K/V head (1 = plain MHA)

    def cached_attn(i):
        def attn(y, blk):
            nonlocal new_k, new_v, new_ks, new_vs
            # the shared split+rope helper, at the new token's global
            # position; cached keys were stored rotated by prefill /
            # earlier steps
            q, k1, v1 = model._qkv_heads(y, blk, positions=pos[None])
            if quantized:
                k1, k1s = _kv_quant(k1)
                v1, v1s = _kv_quant(v1)
                new_ks = jax.lax.dynamic_update_slice(
                    new_ks, k1s[None], (i, 0, 0, pos, 0)
                )
                new_vs = jax.lax.dynamic_update_slice(
                    new_vs, v1s[None], (i, 0, 0, pos, 0)
                )
            # one 5-D in-place update per buffer — not gather + rewrite,
            # which XLA may lower to an O(L·S_max) cache copy per layer
            new_k = jax.lax.dynamic_update_slice(
                new_k, k1[None].astype(new_k.dtype), (i, 0, 0, pos, 0)
            )
            new_v = jax.lax.dynamic_update_slice(
                new_v, v1[None].astype(new_v.dtype), (i, 0, 0, pos, 0)
            )
            layer_k, layer_v = new_k[i], new_v[i]
            # grouped attention (MHA is the g=1 special case): q heads
            # regroup as (KV, G) against the KV-head cache — no repeated
            # K/V ever materializes, which is GQA's decode point
            qg = q.reshape(n, kvh, g, 1, hd).astype(cdt)
            scores = jnp.einsum(
                "bkgqd,bksd->bkgqs", qg, layer_k.astype(cdt),
                preferred_element_type=jnp.float32,
            ) / math.sqrt(hd)
            if quantized:
                # per-position scales pull out of the contraction exactly
                scores = scores * new_ks[i][..., 0][:, :, None, None, :]
            scores = jnp.where(valid[:, :, None], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            if quantized:
                probs = probs * new_vs[i][..., 0][:, :, None, None, :]
            out = jnp.einsum(
                "bkgqs,bksd->bkgqd", probs.astype(cdt),
                layer_v.astype(cdt),
                preferred_element_type=jnp.float32,
            )
            proj = mm(
                out.reshape(n, h, 1, hd).transpose(0, 2, 1, 3).reshape(
                    n, 1, d
                ).astype(cdt),
                blk.wo,
                cdt,
            )
            return proj, None

        return attn

    for i, blk in enumerate(model.blocks):
        x, _, _ = _block_apply(x, blk, cdt, cached_attn(i), moe=model._moe(i))
    logits = _tied_logits(x, model.embed, cdt)[:, 0]
    # past-capacity poison: at pos >= S_max the cache write would clamp
    # onto S_max-1 and return plausible-but-wrong logits; pos is traced,
    # so the honest device-side failure is loud NaNs, not an exception
    logits = jnp.where(pos < cache.k.shape[3], logits, jnp.nan)
    return logits, KVCache(
        k=new_k, v=new_v, pos=pos + 1, k_scale=new_ks, v_scale=new_vs
    )


def _filter_logits(logits, top_k: int, top_p: float):
    """Top-k then nucleus filtering on (B, V) logits (already temperature
    -scaled — the nucleus mass is meaningful only on the distribution
    actually sampled): everything outside the keep-set drops to -inf.
    Static-shape throughout, one descending sort shared by both filters.
    """
    v = logits.shape[-1]
    sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
    if top_k:
        kth = sorted_l[:, top_k - 1][:, None]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
        # the nucleus below must see the top-k-filtered distribution
        sorted_l = jnp.where(
            jnp.arange(v)[None, :] < top_k, sorted_l, -jnp.inf
        )
    if top_p:
        probs = jax.nn.softmax(sorted_l, axis=-1)
        # exclusive cumulative mass BEFORE each token: a token stays while
        # the mass above it is < top_p (the first token always stays)
        csum = jnp.cumsum(probs, axis=-1) - probs
        keep = csum < top_p
        # smallest kept logit per row = the threshold
        thresh = jnp.min(
            jnp.where(keep, sorted_l, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits >= thresh, logits, -jnp.inf)
    return logits


@functools.partial(
    jax.jit,
    static_argnames=("max_new", "temperature", "top_k", "top_p", "kv_dtype"),
)
def generate(
    model: TransformerLM,
    prompt,
    *,
    max_new: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    kv_dtype: str | None = None,
    key=None,
):
    """Greedy (temperature=0) or sampled decode of ``max_new`` tokens after
    ``prompt`` (B, P). One jitted program: prefill + lax.scan over steps.
    ``top_k``/``top_p`` (nucleus) restrict sampling to the head of the
    distribution (0 = off; both compose); ``kv_dtype="int8"`` halves the
    cache stream at long context (see :class:`KVCache`). Returns
    (B, max_new) int32."""
    if key is None:
        key = jax.random.key(0)
    s_max = prompt.shape[1] + max_new
    if model.pos_encoding == "learned" and s_max > model.pos_embed.shape[0]:
        raise ValueError(
            f"prompt+max_new={s_max} exceeds max_seq={model.pos_embed.shape[0]}"
        )
    logits0, cache = prefill(model, prompt, s_max, kv_dtype=kv_dtype)

    def pick(logits, k):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # temperature FIRST: the nucleus cut must measure mass on the
        # distribution being sampled, not the unscaled one
        logits = _filter_logits(logits / temperature, top_k, top_p)
        return jax.random.categorical(k, logits).astype(jnp.int32)

    keys = jax.random.split(key, max_new)
    tok0 = pick(logits0, keys[0])

    # scan max_new-1 steps: the token for step i is picked from step i-1's
    # logits, so the final logits need no decode step of their own
    def step(carry, k):
        tok, cache = carry
        logits, cache2 = decode_step(model, tok, cache)
        tok2 = pick(logits, k)
        return (tok2, cache2), tok2

    if max_new == 1:
        return tok0[:, None]
    (_, _), rest = jax.lax.scan(step, (tok0, cache), keys[1:])
    return jnp.concatenate([tok0[:, None], rest.T], axis=1)  # (B, max_new)


def next_token_loss(model: TransformerLM, tokens) -> jnp.ndarray:
    """Mean cross-entropy of predicting ``tokens[:, 1:]`` from the prefix
    (the model runs on the first S tokens of an S+1 window), plus the
    weighted MoE load-balance auxiliary when the model routes."""
    logits, aux = model.forward_with_aux(tokens[:, :-1])
    ce = token_cross_entropy(logits, tokens[:, 1:])
    return ce + model.moe_aux_weight * aux


def quantize_for_decode(model: TransformerLM) -> TransformerLM:
    """Weight-only int8 quantization for serving: every block matrix gets
    symmetric per-output-channel int8 (``ops/quantization.py``), the tied
    embedding per-row scales (serving both the gather and the logit
    transpose). Decode is HBM-bound — every step re-reads all params — so
    halving the weight stream is the decode-rate lever on TPU. Inference
    only: ``train`` rejects quantized models (gradients through rounding
    are silently zero). MoE experts and pos_embed stay full precision
    (experts want per-(expert, channel) scales; the table is tiny)."""

    def qmat(w):
        return quantize_int8(w) if w.size else w

    blocks = tuple(
        LMBlock(
            wq=qmat(b.wq), wk=qmat(b.wk), wv=qmat(b.wv), wo=qmat(b.wo),
            w1=qmat(b.w1), w2=qmat(b.w2),
        )
        for b in model.blocks
    )
    return dataclasses.replace(
        model,
        embed=quantize_int8(model.embed, channel_axis=0),
        blocks=blocks,
    )


def _has_quantized_leaves(model) -> bool:
    return any(
        isinstance(l, QTensor)
        for l in jax.tree_util.tree_leaves(
            model, is_leaf=lambda x: isinstance(x, QTensor)
        )
    )


def pp_forward(model: TransformerLM, tokens, mesh, *, n_micro: int,
               axis: str = "model", data_axis: str | None = None):
    """Pipeline-parallel forward: the block chain runs as GPipe stages
    over the mesh ``axis`` (one group of ``depth/n_stages`` blocks per
    device, microbatches streamed via ppermute —
    :func:`keystone_tpu.parallel.pipeline_parallel.gpipe`), embedding and
    tied logits replicated outside the pipe. Completes the LM's
    parallelism matrix (dp × tp × sp × ep × pp). Dense blocks only (MoE
    routing wants the expert axis, not the stage axis); parameters stay
    replicated in HBM — pp here parallelizes compute, the memory story
    is remat + the other axes.
    """
    if any(m is not None for m in model.moe_layers):
        raise ValueError(
            "pipeline-parallel path supports dense blocks only (route "
            "experts over the model axis with moe_every instead)"
        )
    if model.seq_mode != "local":
        raise ValueError(
            "pipeline-parallel path requires seq_mode='local': the "
            f"{model.seq_mode!r} attention opens its own shard_map, which "
            "cannot nest inside the pipeline's"
        )
    n_stages = mesh.shape[axis]
    depth = len(model.blocks)
    if depth % n_stages:
        raise ValueError(
            f"depth {depth} not divisible by {n_stages} pipeline stages"
        )
    b = tokens.shape[0]
    if b % n_micro:
        raise ValueError(
            f"batch {b} not divisible by n_micro={n_micro}"
        )
    per = depth // n_stages
    cdt = jnp.dtype(model.compute_dtype)
    x = _embed(model, tokens, cdt)
    # pre-split microbatches HERE: gpipe's n_micro reshape heuristic is
    # ambiguous when B == n_micro (it would mistake (B, S, d) for an
    # already-microbatched (n_micro, S, d))
    x = x.reshape(n_micro, b // n_micro, *x.shape[1:])

    # stack the per-block pytrees: leading axis depth → (stages, per)
    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *model.blocks
    )
    stacked = jax.tree_util.tree_map(
        lambda l: l.reshape(n_stages, per, *l.shape[1:]), stacked
    )

    def stage_fn(stage_params, act):
        for j in range(per):
            blk = jax.tree_util.tree_map(lambda l: l[j], stage_params)
            act = _block_apply(
                act, blk, cdt,
                lambda y, bb: (model._attention(y, bb), None),
            )[0]
        return act

    if model.remat:
        stage_fn = jax.checkpoint(stage_fn)
    from keystone_tpu.parallel.pipeline_parallel import gpipe

    out = gpipe(stage_fn, stacked, x, mesh, axis=axis, data_axis=data_axis)
    out = out.reshape(b, *out.shape[2:])
    return _tied_logits(out, model.embed, cdt)


def next_token_loss_pp(model: TransformerLM, tokens, mesh, *,
                       n_micro: int, axis: str = "model",
                       data_axis: str | None = None) -> jnp.ndarray:
    """Next-token CE through the GPipe forward (differentiable: scan,
    ppermute, and psum all have transposes — the backward is the reverse
    pipeline schedule, derived by AD rather than hand-scheduled)."""
    logits = pp_forward(
        model, tokens[:, :-1], mesh, n_micro=n_micro, axis=axis,
        data_axis=data_axis,
    )
    return token_cross_entropy(logits, tokens[:, 1:])


def make_pp_train_step(optimizer, mesh, *, n_micro: int,
                       axis: str = "model",
                       data_axis: str | None = None):
    """Buffer-donated jitted pipeline-parallel train step. ``data_axis``
    composes dp × pp: each data-row of devices pipelines its own batch
    slice (grad psums across rows come from XLA's sharding propagation —
    params are replicated over the data axis)."""

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(model, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda m, t: next_token_loss_pp(
                m, t, mesh, n_micro=n_micro, axis=axis,
                data_axis=data_axis,
            )
        )(model, tokens)
        updates, opt_state = optimizer.update(
            grads, opt_state, params=model
        )
        model = optax.apply_updates(model, updates)
        return model, opt_state, loss

    return step


def make_train_step(optimizer):
    """One buffer-donated jitted program: grads + AdamW update + loss."""

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(model, opt_state, tokens):
        loss, grads = jax.value_and_grad(next_token_loss)(model, tokens)
        updates, opt_state = optimizer.update(
            grads, opt_state, params=model
        )
        model = optax.apply_updates(model, updates)
        return model, opt_state, loss

    return step


def token_cross_entropy(logits, targets) -> jnp.ndarray:
    """Mean next-token cross-entropy. logits: (B, S, V) f32; targets:
    (B, S) int. The single source of the numerically sensitive
    ``logsumexp - gold`` form, shared by training loss and evaluation."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)


def _step_batch(corpus, seed: int, i: int, batch: int, seq: int):
    """Step ``i``'s token windows, derived from ``(seed, i)`` alone — no
    sequential RNG state, so a resumed run regenerates the exact batch
    sequence an uninterrupted run would have seen."""
    rng = np.random.default_rng(np.random.SeedSequence((seed, i)))
    starts = rng.integers(0, len(corpus) - seq - 1, size=batch)
    return np.stack([corpus[s : s + seq + 1] for s in starts])


def make_optimizer(
    lr: float,
    *,
    steps: int = 0,
    schedule: str = "constant",
    warmup_frac: float = 0.05,
    grad_clip: float = 0.0,
    weight_decay: float = 0.01,
):
    """The LM training optimizer: AdamW, optionally behind global-norm
    gradient clipping, with a constant or warmup-cosine learning rate.
    ``schedule="cosine"`` warms up over ``warmup_frac`` of ``steps`` and
    decays to lr/10 — the standard LM recipe."""
    if schedule not in ("constant", "cosine"):
        raise ValueError(
            f"schedule={schedule!r}; expected constant|cosine"
        )
    if schedule == "cosine":
        if steps <= 0:
            raise ValueError("schedule='cosine' needs the total steps")
        lr = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=lr,
            warmup_steps=max(1, int(steps * warmup_frac)),
            decay_steps=steps,
            end_value=lr / 10.0,
        )
    opt = optax.adamw(lr, weight_decay=weight_decay)
    if grad_clip > 0.0:
        opt = optax.chain(optax.clip_by_global_norm(grad_clip), opt)
    return opt


def train(
    model: TransformerLM,
    corpus: np.ndarray,
    *,
    steps: int,
    batch: int,
    seq: int,
    lr: float = 3e-4,
    mesh=None,
    seed: int = 0,
    log_every: int = 0,
    checkpoint_dir: str = "",
    checkpoint_every: int = 0,
    schedule: str = "constant",
    grad_clip: float = 0.0,
):
    """Train on random windows of ``corpus`` (1-D int array). Returns
    (model, losses). Batches are dp-sharded over the mesh ``data`` axis
    unless the model is sequence-parallel (then S is the sharded axis and
    the batch is replicated).

    ``checkpoint_dir`` makes the run preemption-safe: model + optimizer
    state are orbax-checkpointed every ``checkpoint_every`` steps (default
    0 = ``steps // 10``, ~10 checkpoints per run), and a rerun with the
    same arguments
    resumes from the last completed step on the *identical* trajectory —
    batches are derived per-step from ``(seed, i)``, not from sequential
    RNG state (the LM analog of the solvers' ``resumable_fit``). ``losses``
    covers only the steps this invocation ran. Note: ``schedule="cosine"``
    derives its decay horizon from THIS invocation's ``steps`` — resuming
    with a longer schedule is allowed (steps are not run identity) but
    stretches the cosine rather than replaying the original horizon.
    """
    from keystone_tpu.parallel.mesh import data_sharding

    if len(corpus) < seq + 2:
        raise ValueError(
            f"corpus of {len(corpus)} tokens is too short for seq={seq} "
            f"(needs at least seq+2 = {seq + 2}); shorten --seq or grow "
            "the corpus"
        )
    if _has_quantized_leaves(model):
        raise ValueError(
            "model holds int8 QTensor weights (quantize_for_decode is "
            "inference-only) — gradients through the rounding would be "
            "silently zero; train the float model and re-quantize"
        )
    optimizer = make_optimizer(
        lr, steps=steps, schedule=schedule, grad_clip=grad_clip
    )
    opt_state = optimizer.init(model)
    step = make_train_step(optimizer)
    losses = []
    sharding = None
    if (
        mesh is not None
        and model.seq_mode == "local"
        and batch % mesh.shape.get("data", 1) == 0
    ):
        sharding = data_sharding(mesh, ndim=2)

    ckpt = None
    start = 0
    if checkpoint_dir:
        import hashlib

        from keystone_tpu.core.checkpoint import TrainCheckpointer

        # default cadence: ~10 checkpoints per run, not one per step — a
        # jitted LM step is milliseconds while a synchronous full-state
        # orbax save is not (resumable_fit's every=1 default amortizes
        # over whole BCD passes, a much coarser unit)
        every = checkpoint_every or max(steps // 10, 1)
        corpus_head = np.asarray(corpus[:64], np.int64)
        ckpt = TrainCheckpointer(
            checkpoint_dir,
            # `steps` is deliberately absent (resuming with a longer
            # schedule is the point — the over-trained guard below covers
            # the short case), mirroring resumable_fit's num_iter rule.
            # Everything else that shapes the trajectory is here: a
            # param-shape match alone would silently accept a different
            # model function (num_heads, dtype policy, seq_mode...)
            {
                "kind": "lm_transformer",
                "batch": batch,
                "seq": seq,
                "lr": lr,
                "seed": seed,
                "schedule": schedule,
                "grad_clip": grad_clip,
                "num_heads": model.num_heads,
                # normalized (kv_heads, never the 0 alias) so MHA spelled
                # either way compares equal
                "num_kv_heads": model.kv_heads,
                "seq_mode": model.seq_mode,
                "compute_dtype": model.compute_dtype,
                "pos_encoding": model.pos_encoding,
                "remat": model.remat,
                "moe_aux_weight": model.moe_aux_weight,
                "moe_experts": [
                    None if m is None else m.num_experts
                    for m in model.moe_layers
                ],
                "moe_capacity": [
                    None if m is None else m.capacity_factor
                    for m in model.moe_layers
                ],
                "corpus_len": int(len(corpus)),
                "corpus_head_sha": hashlib.sha256(
                    corpus_head.tobytes()
                ).hexdigest()[:16],
                "param_shapes": [
                    list(map(int, leaf.shape))
                    for leaf in jax.tree_util.tree_leaves(model)
                ],
            },
            # keys added after checkpoints already existed in the wild:
            # an older sidecar without them must compare as the value the
            # code used at the time, not brick the resume
            legacy_defaults={
                "pos_encoding": "learned",
                "schedule": "constant",
                "grad_clip": 0.0,
                # pre-GQA checkpoints were all MHA
                "num_kv_heads": model.num_heads,
            },
        )
    try:
        if ckpt is not None:
            (model, opt_state), start = ckpt.restore((model, opt_state))
            if start > steps:
                raise ValueError(
                    f"{checkpoint_dir} holds a step-{start} checkpoint but "
                    f"this run is only {steps} steps — refusing to return "
                    "an over-trained model; point at a fresh directory"
                )
        for i in range(start, steps):
            toks = jnp.asarray(_step_batch(corpus, seed, i, batch, seq))
            if sharding is not None:
                toks = jax.device_put(toks, sharding)
            model, opt_state, loss = step(model, opt_state, toks)
            # keep the loss on device: a float() here would block a host
            # round-trip into every step and serialize the dispatch queue
            losses.append(loss)
            if log_every and (i + 1) % log_every == 0:
                logger.info("step %d loss %.4f", i + 1, float(loss))
            if ckpt is not None and (
                (i + 1) % every == 0 or (i + 1) == steps
            ):
                ckpt.save((model, opt_state), i + 1)
    finally:
        if ckpt is not None:
            ckpt.close()
    return model, [float(l) for l in losses]


def train_step_flops(model: TransformerLM, batch: int, seq: int) -> float:
    """Analytic FLOPs of one train step: ~6·P_active·tokens for the matmul
    work plus the attention score/value terms (12·L·d·S²·B fwd+bwd). MoE
    expert gemms execute over ALL E·C static capacity slots (drops included
    — that's the static-shape trade), so expert params count at C/G weight,
    not the idealized 2/E."""
    p = model.num_params()
    tokens = batch * seq
    for m in model.moe_layers:
        if m is not None:
            expert_p = int(np.prod(m.w1.shape)) + int(np.prod(m.w2.shape))
            slots = m.num_experts * m._capacity(tokens)
            p -= expert_p * (1.0 - min(slots / (tokens * m.num_experts), 1.0))
    d = model.embed.shape[-1]
    attn = 12 * len(model.blocks) * d * seq * seq * batch
    return 6.0 * p * tokens + attn


def synthetic_corpus(n: int, vocab: int, seed: int = 0) -> np.ndarray:
    """A learnable-but-not-trivial token stream: an order-1 Markov chain
    with a sparse, deterministic-ish transition structure."""
    rng = np.random.default_rng(seed)
    succ = rng.integers(0, vocab, size=(vocab, 4))
    probs = np.array([0.7, 0.15, 0.1, 0.05])
    out = np.empty(n, np.int32)
    out[0] = 0
    choices = rng.choice(4, size=n, p=probs)
    for i in range(1, n):
        out[i] = succ[out[i - 1], choices[i]]
    return out


@dataclasses.dataclass
class LMConfig:
    steps: int = arg(default=60, help="training steps")
    batch: int = arg(default=8)
    seq: int = arg(default=256)
    dim: int = arg(default=256)
    depth: int = arg(default=4)
    num_heads: int = arg(default=8)
    num_kv_heads: int = arg(
        default=0,
        help="GQA: K/V heads (0 = num_heads/MHA, 1 = MQA); shrinks the "
        "decode cache by num_heads/num_kv_heads",
    )
    vocab: int = arg(default=256)
    lr: float = arg(default=3e-4)
    seq_mode: str = arg(
        default="local", help="attention strategy: local | ring | ulysses"
    )
    compute_dtype: str = arg(
        default="float32",
        help="matmul/activation dtype (params stay float32); "
        "bfloat16 is the TPU-native choice",
    )
    seed: int = arg(default=0)
    moe_every: int = arg(
        default=0,
        help="replace every k-th block's FFN with a top-2 MoE (0 = dense)",
    )
    num_experts: int = arg(default=8)
    pos_encoding: str = arg(
        default="learned", help="position encoding: learned | rope"
    )
    corpus: str = arg(
        default="",
        help="path to a text file/dir (byte-level tokens, vocab forced to "
        "256, 10%% held out for perplexity); default: synthetic Markov",
    )
    schedule: str = arg(
        default="constant", help="lr schedule: constant | cosine (warmup)"
    )
    grad_clip: float = arg(
        default=0.0, help="global-norm gradient clip (0 = off)"
    )
    checkpoint_dir: str = arg(
        default="",
        help="orbax checkpoint/resume directory (preemption-safe training)",
    )
    checkpoint_every: int = arg(
        default=0,
        help="steps between checkpoints (0 = steps//10, ~10 per run)",
    )


def run(conf: LMConfig, mesh=None) -> dict:
    from keystone_tpu.parallel.mesh import create_mesh

    if conf.schedule not in ("constant", "cosine"):
        # fail before the (possibly minutes-long) corpus load / model init
        raise ValueError(
            f"--schedule {conf.schedule!r}; expected constant|cosine"
        )
    if mesh is None and len(jax.devices()) > 1:
        mesh = create_mesh()
    valid = None
    if conf.corpus:
        from keystone_tpu.loaders.text import BYTE_VOCAB, load_text_corpus

        corpus, valid = load_text_corpus(conf.corpus)
        conf = dataclasses.replace(conf, vocab=BYTE_VOCAB)
    key = jax.random.key(conf.seed)
    model = TransformerLM.create(
        key,
        vocab=conf.vocab,
        max_seq=conf.seq,
        dim=conf.dim,
        depth=conf.depth,
        num_heads=conf.num_heads,
        seq_mode=conf.seq_mode,
        mesh=mesh if conf.seq_mode != "local" else None,
        compute_dtype=conf.compute_dtype,
        moe_every=conf.moe_every,
        num_experts=conf.num_experts,
        pos_encoding=conf.pos_encoding,
        num_kv_heads=conf.num_kv_heads,
    )
    model = shard_params(model, mesh)
    if not conf.corpus:
        corpus = synthetic_corpus(200_000, conf.vocab, seed=conf.seed)
    t0 = time.time()
    model, losses = train(
        model,
        corpus,
        steps=conf.steps,
        batch=conf.batch,
        seq=conf.seq,
        lr=conf.lr,
        mesh=mesh,
        seed=conf.seed,
        log_every=max(conf.steps // 5, 1),
        checkpoint_dir=conf.checkpoint_dir,
        checkpoint_every=conf.checkpoint_every,
        schedule=conf.schedule,
        grad_clip=conf.grad_clip,
    )
    dt = time.time() - t0
    steps_ran = len(losses)
    if not losses:
        # a resume that found the run already complete trains 0 steps
        losses = [float("nan")]
    res = {
        # loss_first is the first loss of THIS segment; on a resumed run
        # (steps_ran < steps) it is not the run's true initial loss —
        # downstream records key off `resumed` to tell the cases apart
        "loss_first": losses[0],
        "loss_last": float(np.mean(losses[-5:])),
        "steps": conf.steps,
        "steps_ran": steps_ran,
        "resumed": steps_ran < conf.steps,
        "params": model.num_params(),
        "tokens_per_s": steps_ran * conf.batch * conf.seq / dt,
        "wall_s": dt,
    }
    if valid is not None:
        if len(valid) >= conf.seq + 1:
            from keystone_tpu.evaluation.perplexity import (
                evaluate_perplexity,
            )

            ev = evaluate_perplexity(
                model, valid, seq=conf.seq, batch=conf.batch
            )
            res["valid_loss"] = ev["loss"]
            res["valid_bits_per_token"] = ev["bits_per_token"]
            res["valid_perplexity"] = ev["perplexity"]
        else:
            logger.warning(
                "held-out tail (%d tokens) is shorter than one seq+1=%d "
                "window — skipping the perplexity evaluation the corpus "
                "flag promises; shorten --seq or grow the corpus",
                len(valid),
                conf.seq + 1,
            )
    logger.info(
        "lm: %d params, loss %.3f -> %.3f, %.0f tokens/s",
        res["params"],
        res["loss_first"],
        res["loss_last"],
        res["tokens_per_s"],
    )
    return res


def main(argv=None) -> dict:
    return run(parse_config(LMConfig, argv))


if __name__ == "__main__":
    main()
