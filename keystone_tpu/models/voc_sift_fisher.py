"""PASCAL VOC SIFT + Fisher Vector pipeline
(reference ``pipelines/images/voc/VOCSIFTFisher.scala``).

Stages: pixel-scale → grayscale → dense SIFT → PCA projection (fit on
sampled descriptor columns, or loaded from a CSV artifact) → GMM (fit on
sampled projected descriptors, or loaded) → Fisher vectors → vectorize →
L2-normalize → signed-sqrt → L2-normalize → block least squares on ±1
multi-label indicators → mean average precision.

The reference's "cache expensive fitted stages to disk, reload by flag"
capability (SURVEY.md §5 checkpoint/resume) is preserved: PCA/GMM artifacts
save/load as CSVs compatible with the reference's file formats.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.batching import apply_in_chunks
from keystone_tpu.core.config import arg, parse_config
from keystone_tpu.core.logging import get_logger
from keystone_tpu.evaluation import MeanAveragePrecisionEvaluator
from keystone_tpu.loaders.image_loaders import VOC_NUM_CLASSES, load_voc
from keystone_tpu.models.fisher_common import FisherBranch
from keystone_tpu.ops.images import GrayScaler, PixelScaler
from keystone_tpu.ops.linear import BlockLeastSquaresEstimator
from keystone_tpu.ops.sift import SIFTExtractor
from keystone_tpu.ops.util import ClassLabelIndicators
from keystone_tpu.parallel.mesh import create_mesh, shard_batch
from keystone_tpu.utils.images import LabeledImages

logger = get_logger("keystone_tpu.models.voc_sift_fisher")


@dataclasses.dataclass
class VOCConfig:
    """VOC SIFT/Fisher workload (reference SIFTFisherConfig defaults:
    descDim 80, vocabSize 256, 1e6 PCA/GMM samples)."""

    train_location: str = arg(default="", help="train tar file/dir/glob")
    train_labels: str = arg(default="", help="train multi-label csv")
    test_location: str = arg(default="", help="test tar file/dir/glob")
    test_labels: str = arg(default="", help="test multi-label csv")
    name_prefix: str = arg(
        default="VOCdevkit/VOC2007/JPEGImages/",
        help="tar entry prefix to load (reference VOCDataPath.namePrefix)",
    )
    desc_dim: int = arg(default=80, help="PCA output dim")
    vocab_size: int = arg(default=256, help="GMM centroids")
    num_pca_samples: int = arg(default=1_000_000)
    num_gmm_samples: int = arg(default=1_000_000)
    lam: float = arg(default=0.5)
    lam_sweep: str = arg(
        default="",
        help="comma-separated λ list: ridge path at shared-Gram cost, "
        "selected by mean-AP on a held-out 10%% of train (overrides "
        "--lam)",
    )
    block_size: int = arg(default=4096)
    chunk_size: int = arg(default=64, help="images per featurize chunk")
    image_size: int = arg(default=256)
    sift_scales: int = arg(default=5)
    seed: int = arg(default=0)
    pca_file: str = arg(default="", help="load/save PCA matrix csv")
    gmm_mean_file: str = arg(default="")
    gmm_var_file: str = arg(default="")
    gmm_wt_file: str = arg(default="")
    synthetic: int = arg(default=0, help="if > 0, N synthetic images")


def _load(conf: VOCConfig, which: str) -> LabeledImages:
    if conf.synthetic:
        n = conf.synthetic if which == "train" else max(conf.synthetic // 4, 1)
        rng = np.random.default_rng(0 if which == "train" else 1)
        centers = np.random.default_rng(42).normal(
            loc=128, scale=30, size=(VOC_NUM_CLASSES, 8, 8, 3)
        )
        labels = -np.ones((n, 2), np.int32)
        labels[:, 0] = rng.integers(0, VOC_NUM_CLASSES, size=n)
        # upsample class-pattern to image size so SIFT sees class structure
        base = centers[labels[:, 0]]
        imgs = np.kron(
            base, np.ones((1, conf.image_size // 8, conf.image_size // 8, 1))
        )
        imgs += rng.normal(scale=20, size=imgs.shape)
        return LabeledImages(
            labels=labels, images=np.clip(imgs, 0, 255).astype(np.float32)
        )
    if which == "train":
        return load_voc(
            conf.train_location,
            conf.train_labels,
            target_size=conf.image_size,
            name_prefix=conf.name_prefix or None,
        )
    return load_voc(
        conf.test_location,
        conf.test_labels,
        target_size=conf.image_size,
        name_prefix=conf.name_prefix or None,
    )


def run(conf: VOCConfig, mesh=None) -> dict:
    if mesh is None and len(jax.devices()) > 1:
        mesh = create_mesh()
    t0 = time.perf_counter()
    train = _load(conf, "train")
    test = _load(conf, "test")
    n_train, n_test = len(train), len(test)

    gray = PixelScaler() >> GrayScaler()
    sift = SIFTExtractor(num_scales=conf.sift_scales)
    gray_sift = jax.jit(lambda b: sift(gray(b)))

    branch = FisherBranch(
        conf.desc_dim,
        conf.vocab_size,
        conf.num_pca_samples,
        conf.num_gmm_samples,
        conf.seed,
        pca_file=conf.pca_file,
        gmm_files=(conf.gmm_mean_file, conf.gmm_var_file, conf.gmm_wt_file),
    )
    train_imgs = shard_batch(train.images, mesh)
    sift_train = apply_in_chunks(gray_sift, train_imgs, conf.chunk_size)
    pca_train = branch.fit(sift_train, conf.chunk_size, n_valid=n_train)
    f_train = branch.featurize_projected(pca_train, conf.chunk_size)
    t_feat = time.perf_counter()

    y = -np.ones((f_train.shape[0], train.labels.shape[1]), np.int32)
    y[:n_train] = train.labels
    indicators = ClassLabelIndicators(num_classes=VOC_NUM_CLASSES)(
        jnp.asarray(y)
    )
    lam = conf.lam
    if conf.lam_sweep:
        from keystone_tpu.evaluation.model_selection import (
            holdout_lambda_sweep,
        )

        sweep_eval = MeanAveragePrecisionEvaluator(VOC_NUM_CLASSES)

        def map_scorer(model, val_inputs, rows):
            lo, hi = rows
            scores = np.asarray(model(val_inputs))[: hi - lo]
            aps = sweep_eval(np.asarray(indicators)[lo:hi], scores)
            return -float(np.mean(aps))  # lower loss = higher MAP

        report = holdout_lambda_sweep(
            BlockLeastSquaresEstimator(
                block_size=conf.block_size, num_iter=1
            ),
            f_train,
            indicators,
            None,
            conf.lam_sweep,
            n_train=n_train,
            scorer=map_scorer,
        )
        lam = report["best_lam"]
        logger.info(
            "lambda sweep %s -> val -MAP %s; refitting at best lam=%g",
            report["lams"],
            [round(e, 4) for e in report["val_errors"]],
            lam,
        )
    model = BlockLeastSquaresEstimator(
        block_size=conf.block_size, num_iter=1, lam=lam
    ).fit(f_train, indicators, n_valid=n_train)
    t_fit = time.perf_counter()

    def featurize_test(images):
        x = shard_batch(images, mesh)
        s = apply_in_chunks(gray_sift, x, conf.chunk_size)
        return branch.featurize(s, conf.chunk_size)

    evaluator = MeanAveragePrecisionEvaluator(VOC_NUM_CLASSES)
    test_scores = model(featurize_test(test.images))
    y_test = ClassLabelIndicators(num_classes=VOC_NUM_CLASSES)(
        jnp.asarray(test.labels)
    )
    aps = evaluator(np.asarray(y_test), np.asarray(test_scores)[:n_test])
    train_scores = model(f_train)
    train_aps = evaluator(
        np.asarray(indicators)[:n_train], np.asarray(train_scores)[:n_train]
    )

    result = {
        "test_map": float(aps.mean()),
        "train_map": float(train_aps.mean()),
        "n_train": n_train,
        "n_test": n_test,
        "featurize_s": t_feat - t0,
        "fit_s": t_fit - t_feat,
        "total_s": time.perf_counter() - t0,
    }
    logger.info(
        "VOCSIFTFisher: train MAP %.4f, test MAP %.4f", result["train_map"], result["test_map"]
    )
    return result


def main(argv=None) -> dict:
    conf = parse_config(VOCConfig, argv)
    if not conf.synthetic and not (conf.train_location and conf.train_labels):
        raise SystemExit(
            "need --train-location/--train-labels (+ test), or --synthetic N"
        )
    return run(conf)


if __name__ == "__main__":
    main()
