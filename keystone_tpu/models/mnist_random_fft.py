"""MNIST random-FFT pipeline — the framework's minimum end-to-end slice.

Rebuild of the reference's ``pipelines/images/mnist/MnistRandomFFT.scala``:
random-sign flip → padded FFT → rectify, ``num_ffts`` independent draws
grouped into feature batches of ``block_size`` columns (512 FFT features per
draw on 28×28 inputs), solved with block least squares, argmax classified,
multiclass-evaluated.

TPU shape of the same computation: each feature batch is one jitted
chain over the sharded (N, 784) batch; the solver contracts Grams over the
mesh "data" axis. The whole pipeline is pure jnp — no native kernels.
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.config import arg, parse_config
from keystone_tpu.core.logging import get_logger
from keystone_tpu.core.pipeline import Pipeline, Transformer
from keystone_tpu.core.treenode import treenode
from keystone_tpu.loaders.csv_loader import load_labeled_csv
from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.ops.linear import BlockLeastSquaresEstimator
from keystone_tpu.ops.stats import LinearRectifier, PaddedFFT, RandomSignNode
from keystone_tpu.ops.util import ClassLabelIndicators, MaxClassifier, ZipVectors
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.observe import events as observe_events
from keystone_tpu.parallel.mesh import create_mesh, shard_batch

logger = get_logger("keystone_tpu.models.mnist_random_fft")

NUM_CLASSES = 10
IMAGE_SIZE = 784  # 28 x 28
FFT_FEATURES = 512  # PaddedFFT output dim for 784 → next pow2 1024 → half


def fft_features(image_size: int) -> int:
    """PaddedFFT output width for a given input dim: next_pow2 // 2."""
    n = 1 << max(int(np.ceil(np.log2(image_size))), 0) if image_size > 1 else 1
    return n // 2


@dataclasses.dataclass
class MnistRandomFFTConfig:
    """MNIST random-FFT workload (reference MnistRandomFFTConfig)."""

    train_location: str = arg(default="", help="train csv (label first, 1-indexed)")
    test_location: str = arg(default="", help="test csv")
    num_ffts: int = arg(default=200, help="number of random FFT draws")
    block_size: int = arg(default=2048, help="solver block size (multiple of 512)")
    lam: float = arg(default=0.0, help="L2 regularization")
    lam_sweep: str = arg(
        default="",
        help="comma-separated λ list: fit the whole ridge path at shared-"
        "Gram cost, pick the best on a held-out 10%% of train, refit on "
        "all of train at that λ (overrides --lam)",
    )
    seed: int = arg(default=0)
    synthetic: int = arg(
        default=0, help="if > 0, run on N synthetic samples instead of csvs"
    )


def build_batch_featurizers(
    num_ffts: int, block_size: int, seed: int, image_size: int = IMAGE_SIZE
) -> list[list[Pipeline]]:
    """Group ``num_ffts`` (sign → fft → relu) chains into batches whose
    concatenated width is ``block_size`` (last batch may be smaller)."""
    ffts_per_batch = max(block_size // fft_features(image_size), 1)
    keys = jax.random.split(jax.random.key(seed), num_ffts)
    chains = [
        RandomSignNode.create(image_size, keys[i]) >> PaddedFFT() >> LinearRectifier()
        for i in range(num_ffts)
    ]
    return [
        chains[i : i + ffts_per_batch]
        for i in range(0, num_ffts, ffts_per_batch)
    ]


@jax.jit
def _featurize_batch(chains: tuple, data):
    return ZipVectors()([chain(data) for chain in chains])


@treenode
class FeaturizerBank(Transformer):
    """The full random-FFT featurizer as one Transformer: applies every
    feature batch and returns the list of (N, ≤block_size) blocks.

    Being a treenode Transformer lets the whole featurize+fit run as a
    single traced program via ``ChainedLabelEstimator.fit_fused`` — the
    block solver consumes the block list directly, so featurize output
    never round-trips through a host dispatch boundary.
    """

    batches: tuple  # tuple of tuples of (sign → fft → relu) Pipelines

    @staticmethod
    def create(
        num_ffts: int, block_size: int, seed: int, image_size: int = IMAGE_SIZE
    ) -> "FeaturizerBank":
        groups = build_batch_featurizers(num_ffts, block_size, seed, image_size)
        return FeaturizerBank(batches=tuple(tuple(g) for g in groups))

    def __call__(self, data):
        return featurize(self.batches, data)


def _sign_fft_relu_parts(chain):
    """Match the ``RandomSignNode >> PaddedFFT >> LinearRectifier`` shape;
    returns (signs, fft_impl, alpha, max_val) or None."""
    nodes = getattr(chain, "nodes", ())
    if len(nodes) != 3:
        return None
    s, f, r = nodes
    if not (
        isinstance(s, RandomSignNode)
        and isinstance(f, PaddedFFT)
        and isinstance(r, LinearRectifier)
    ):
        return None
    return s.signs, f.impl, r.alpha, r.max_val


@functools.partial(jax.jit, static_argnames=("n", "alpha", "max_val"))
def _featurize_fused(signs_mat, data, n: int, alpha: float, max_val: float):
    """All chains of one feature batch as ONE gemm: the sign flip is a
    diagonal on the gemm's contraction side, so k chains fold into
    ``relu(X @ [diag(s_1)C | … | diag(s_k)C])`` — one MXU pass over the
    batch instead of k (reads X once; wider output tile)."""
    from keystone_tpu.ops.stats import _cos_matrix

    d = data.shape[-1]
    cos = _cos_matrix(d, n, str(data.dtype))  # (d, n//2)
    # build w directly in (d, k·n/2) chain-major layout (no transpose:
    # a transposed operand can drag a copy or refuse a clean gemm tiling)
    w = (signs_mat.T[:, :, None] * cos[:, None, :]).reshape(d, -1)
    # materialize w BEFORE the gemm: without the barrier XLA may fuse the
    # signs x cos construction into the dot's RHS loads, recomputing it
    # per k-tile — measured slower than the unfused chain path despite
    # equal nominal FLOPs (MFU_SWEEP round 3, VERDICT r3 weak #3)
    w = jax.lax.optimization_barrier(w)
    return jnp.maximum(max_val, data @ w - alpha)


def featurize(batch_featurizers: list[list[Pipeline]], data) -> list:
    """Apply each batch of chains → list of (N, ≤block_size) feature blocks.

    When a batch is all (sign → fft → relu) chains and the FFT resolves
    to the matmul backend (TPU), the whole batch runs as one fused gemm;
    identical values either way (the matmul backend IS the fft values).
    """
    from keystone_tpu.ops.flash_attention import on_tpu

    out = []
    for chains in batch_featurizers:
        parts = [_sign_fft_relu_parts(c) for c in chains]
        fusable = all(p is not None for p in parts) and len(parts) > 0
        if fusable:
            signs, impls, alphas, maxvals = zip(*parts)
            fusable = (
                len(set(alphas)) == 1
                and len(set(maxvals)) == 1
                and all(i in ("auto", "matmul") for i in impls)
                and (on_tpu() or all(i == "matmul" for i in impls))
            )
        if fusable:
            d = signs[0].shape[-1]
            n = 2 * fft_features(d)
            out.append(
                _featurize_fused(
                    jnp.stack(signs), data, n, alphas[0], maxvals[0]
                )
            )
        else:
            out.append(_featurize_batch(tuple(chains), data))
    return out


def _load(conf: MnistRandomFFTConfig, which: str) -> LabeledData:
    if conf.synthetic:
        n = conf.synthetic if which == "train" else max(conf.synthetic // 6, 1)
        rng = np.random.default_rng(0 if which == "train" else 1)
        labels = rng.integers(0, NUM_CLASSES, size=n).astype(np.int32)
        # class-dependent means (shared across splits) so the linear model
        # has signal to find
        centers = (
            np.random.default_rng(42)
            .normal(size=(NUM_CLASSES, IMAGE_SIZE))
            .astype(np.float32)
        )
        data = centers[labels] + rng.normal(size=(n, IMAGE_SIZE)).astype(np.float32)
        return LabeledData(labels=labels, data=data)
    path = conf.train_location if which == "train" else conf.test_location
    return _load_mnist_csv(path)


def _load_mnist_csv(path: str) -> LabeledData:
    from keystone_tpu.loaders.idx import (
        guess_labels_path,
        is_idx_path,
        load_labeled_idx,
    )

    if is_idx_path(path):
        # upstream MNIST ubyte distribution (0-indexed labels); labels
        # file located by the conventional sibling name
        labels = guess_labels_path(path)
        if labels is None:
            raise FileNotFoundError(
                f"{path} looks like an IDX images file but no labels "
                "sibling (…labels-idx1…) was found next to it"
            )
        return load_labeled_idx(path, labels)
    # the reference's MNIST csvs carry 1-indexed labels (MnistRandomFFT.scala)
    return load_labeled_csv(path, label_offset=1)


def run(conf: MnistRandomFFTConfig, mesh=None) -> dict:
    if mesh is None and len(jax.devices()) > 1:
        mesh = create_mesh()
    t0 = time.perf_counter()

    train = _load(conf, "train")
    test = _load(conf, "test")
    n_train, n_test = len(train), len(test)

    train_x = shard_batch(train.data, mesh)
    test_x = shard_batch(test.data, mesh)
    train_y = np.zeros(train_x.shape[0], np.int32)
    train_y[:n_train] = train.labels
    label_indicators = ClassLabelIndicators(num_classes=NUM_CLASSES)(train_y)

    batch_featurizers = build_batch_featurizers(
        conf.num_ffts,
        conf.block_size,
        conf.seed,
        # width from the data, not the MNIST constant — the reference's
        # CsvDataLoader accepts any row width (CsvDataLoader.scala:69-82)
        image_size=train.data.shape[-1],
    )
    t_load = time.perf_counter()

    from keystone_tpu import plan as plan_mod

    # KEYSTONE_PLAN: the TRAIN fit streams — featurize + normal-equation
    # accumulation fused into one jitted chunk step by the planner
    # (plan/fused_fit.py), so the feature blocks are never materialized
    # for the fit; the λ-sweep and eval paths still need them resident.
    streamed_fit = plan_mod.enabled() and not conf.lam_sweep
    # ONE bank object for the fit, the train eval, and the test pass —
    # planner prefix sharing keys on node identity
    bank = (
        FeaturizerBank(batches=tuple(tuple(g) for g in batch_featurizers))
        if plan_mod.enabled()
        else None
    )
    train_blocks = None
    if not streamed_fit:
        train_blocks = jax.block_until_ready(
            featurize(batch_featurizers, train_x)
        )
    t_feat = time.perf_counter()

    lam = conf.lam
    if conf.lam_sweep:
        from keystone_tpu.evaluation.model_selection import (
            holdout_lambda_sweep,
        )

        report = holdout_lambda_sweep(
            BlockLeastSquaresEstimator(
                block_size=conf.block_size, num_iter=1
            ),
            train_blocks,
            label_indicators,
            train_y,
            conf.lam_sweep,
            n_train=n_train,
            num_classes=NUM_CLASSES,
        )
        lam = report["best_lam"]
        logger.info(
            "lambda sweep %s -> val errors %s; refitting at best lam=%g",
            report["lams"],
            [round(e, 4) for e in report["val_errors"]],
            lam,
        )
    est = BlockLeastSquaresEstimator(
        block_size=conf.block_size, num_iter=1, lam=lam
    )
    if streamed_fit:
        from keystone_tpu.core.pipeline import ChainedLabelEstimator

        fitted_fit = plan_mod.fit_streaming(
            ChainedLabelEstimator(prefix=bank, est=est),
            train_x,
            label_indicators,
            n_valid=n_train,
            mesh=mesh,
        )
        model = jax.block_until_ready(fitted_fit[-1])
    else:
        model = jax.block_until_ready(
            est.fit(train_blocks, label_indicators, n_valid=n_train)
        )
    t_fit = time.perf_counter()

    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    classify = MaxClassifier()

    errors: dict[str, float] = {}

    def streaming_eval(name: str, labels: np.ndarray, n_valid: int):
        def cb(partial_pred):
            metrics = evaluator(classify(partial_pred), labels, n_valid=n_valid)
            errors[name] = metrics.error
            logger.info("%s error so far: %.2f%%", name, 100 * metrics.error)

        return cb

    if streamed_fit:
        # blocks were never materialized: the train error comes from the
        # same planned apply pass the test pass uses
        pred = plan_mod.execute(
            Pipeline.of(bank, model, MaxClassifier()), train_x, mesh=mesh
        )
        errors["train"] = evaluator(pred, train_y, n_valid=n_train).error
        logger.info(
            "train error (planned): %.2f%%", 100 * errors["train"]
        )
    else:
        model.apply_and_evaluate(
            train_blocks, streaming_eval("train", train_y, n_train)
        )
    test_y = np.zeros(test_x.shape[0], np.int32)
    test_y[:n_test] = test.labels

    if plan_mod.enabled():
        # KEYSTONE_PLAN: the test pass runs through the cost-based
        # planner's executor — one planned apply pipeline (featurizer
        # bank → block model → argmax), jitted segments, chunked with
        # bounded in-flight dispatch when the plan says so, and — with a
        # mesh — dispatched data-sharded so the pass runs as one SPMD
        # program per segment. Predictions are identical to the block
        # path; only the execution differs.
        pred = plan_mod.execute(
            Pipeline.of(bank, model, MaxClassifier()), test_x, mesh=mesh
        )
        errors["test"] = evaluator(pred, test_y, n_valid=n_test).error
        logger.info("test error (planned): %.2f%%", 100 * errors["test"])
    else:
        test_blocks = featurize(batch_featurizers, test_x)
        model.apply_and_evaluate(
            test_blocks, streaming_eval("test", test_y, n_test)
        )
    t_end = time.perf_counter()

    ev = observe_events.active()
    if ev is not None:
        for phase, wall in (
            ("load", t_load - t0),
            ("featurize", t_feat - t_load),
            ("fit", t_fit - t_feat),
            ("eval", t_end - t_fit),
        ):
            ev.emit("phase", phase=phase, wall_s=wall)
        try:
            _record_observability(ev, batch_featurizers, model, test_x)
        except Exception as e:  # noqa: BLE001 — observability must not
            # fail a pipeline run that already trained and evaluated
            logger.warning("observability recording failed: %r", e)

    result = {
        "train_error": errors["train"],
        "test_error": errors["test"],
        "n_train": n_train,
        "n_test": n_test,
        "load_s": t_load - t0,
        "featurize_s": t_feat - t_load,
        "fit_s": t_fit - t_feat,
        "total_s": t_end - t0,
        "train_samples_per_s": n_train / (t_fit - t_load),
    }
    logger.info(
        "MnistRandomFFT: train err %.2f%%, test err %.2f%%, "
        "featurize+fit %.1f samples/s",
        100 * result["train_error"],
        100 * result["test_error"],
        result["train_samples_per_s"],
    )
    return result


def _record_observability(ev, batch_featurizers, model, test_x) -> None:
    """Per-node wall-time events + compiler cost profiles for the fitted
    apply pipeline (featurizer bank → block model → argmax), recorded on
    a bounded probe batch so observability cost stays a small constant.
    This is the KeystoneML operator-profile sample for this pipeline."""
    from keystone_tpu.observe.cost import record_pipeline_profile

    bank = FeaturizerBank(batches=tuple(tuple(g) for g in batch_featurizers))
    pipe = Pipeline.of(bank, model, MaxClassifier())
    probe = test_x[: min(2048, test_x.shape[0])]
    record_pipeline_profile(pipe, probe, save_dir=ev.run_dir)


def main(argv=None) -> dict:
    conf = parse_config(MnistRandomFFTConfig, argv)
    if not conf.synthetic and not (conf.train_location and conf.test_location):
        raise SystemExit("need --train-location AND --test-location, or --synthetic N")
    return run(conf)


if __name__ == "__main__":
    main()
