"""End-to-end applications (reference ``src/main/scala/pipelines/``, SURVEY.md §2 layer 7).

Each module exposes a config dataclass, ``run(conf, mesh=None)`` returning a
metrics dict, and ``main(argv)`` wiring the auto-generated CLI — the
successor of the reference's scopt ``parse``/``run``/``main`` objects.
"""
