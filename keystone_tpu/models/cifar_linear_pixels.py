"""CIFAR-10 LinearPixels — grayscale pixels + exact linear solve
(reference ``pipelines/images/cifar/LinearPixels.scala``)."""

from __future__ import annotations

import dataclasses
import time

import jax
import numpy as np

from keystone_tpu.core.config import arg, parse_config
from keystone_tpu.core.logging import get_logger
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.cifar import load_cifar
from keystone_tpu.ops.images import GrayScaler, ImageVectorizer
from keystone_tpu.ops.linear import LinearMapEstimator
from keystone_tpu.ops.util import ClassLabelIndicators, MaxClassifier
from keystone_tpu.parallel.mesh import create_mesh, shard_batch
from keystone_tpu.utils.images import LabeledImages

logger = get_logger("keystone_tpu.models.cifar_linear_pixels")

NUM_CLASSES = 10


@dataclasses.dataclass
class LinearPixelsConfig:
    """CIFAR LinearPixels workload (reference LinearPixelsConfig)."""

    train_location: str = arg(default="", help="CIFAR-10 binary file/dir")
    test_location: str = arg(default="", help="CIFAR-10 binary file/dir")
    lam: float = arg(default=0.0, help="L2 regularization")
    synthetic: int = arg(default=0, help="if > 0, N synthetic samples")


def _load(conf: LinearPixelsConfig, which: str) -> LabeledImages:
    if conf.synthetic:
        n = conf.synthetic if which == "train" else max(conf.synthetic // 5, 1)
        rng = np.random.default_rng(0 if which == "train" else 1)
        labels = rng.integers(0, NUM_CLASSES, size=n).astype(np.int32)
        centers = np.random.default_rng(42).normal(
            loc=128, scale=40, size=(NUM_CLASSES, 32, 32, 3)
        )
        images = (
            centers[labels] + rng.normal(scale=25, size=(n, 32, 32, 3))
        ).astype(np.float32)
        return LabeledImages(labels=labels, images=images)
    return load_cifar(conf.train_location if which == "train" else conf.test_location)


def run(conf: LinearPixelsConfig, mesh=None) -> dict:
    if mesh is None and len(jax.devices()) > 1:
        mesh = create_mesh()
    t0 = time.perf_counter()
    train, test = _load(conf, "train"), _load(conf, "test")

    featurizer = GrayScaler() >> ImageVectorizer()
    feat_jit = jax.jit(lambda p, b: p(b))

    x_train = shard_batch(train.images, mesh)
    x_test = shard_batch(test.images, mesh)
    y = np.zeros(x_train.shape[0], np.int32)
    y[: len(train)] = train.labels
    indicators = ClassLabelIndicators(num_classes=NUM_CLASSES)(y)

    f_train = feat_jit(featurizer, x_train)
    model = LinearMapEstimator(lam=conf.lam).fit(
        f_train, indicators, n_valid=len(train)
    )

    predict = featurizer >> model >> MaxClassifier()
    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    pred_train = feat_jit(predict, x_train)
    train_eval = evaluator(pred_train, y, n_valid=len(train))
    y_test = np.zeros(x_test.shape[0], np.int32)
    y_test[: len(test)] = test.labels
    test_eval = evaluator(feat_jit(predict, x_test), y_test, n_valid=len(test))

    result = {
        "train_error": train_eval.error,
        "test_error": test_eval.error,
        "n_train": len(train),
        "n_test": len(test),
        "total_s": time.perf_counter() - t0,
    }
    logger.info(
        "LinearPixels: train acc %.4f, test acc %.4f",
        train_eval.accuracy,
        test_eval.accuracy,
    )
    return result


def main(argv=None) -> dict:
    conf = parse_config(LinearPixelsConfig, argv)
    if not conf.synthetic and not (conf.train_location and conf.test_location):
        raise SystemExit("need --train-location AND --test-location, or --synthetic N")
    return run(conf)


if __name__ == "__main__":
    main()
