"""CIFAR-10 random-patch convolution pipeline — the flagship image workload
(reference ``pipelines/images/cifar/RandomPatchCifar.scala``).

Stages (reference-parity):
1. sample random patches from training images (Windower → vectorize → sample)
2. per-patch normalize (``Stats.normalizeRows`` var-constant 10) and fit a
   ZCA whitener on the patch sample
3. filters = whitened, L2-normalized random patches, folded back through
   ``W.T`` so convolution operates on mean-subtracted normalized patches
4. featurize: im2col Convolver → SymmetricRectifier → sum Pooler →
   vectorize → StandardScaler
5. block least squares on ±1 indicators → argmax → multiclass eval

TPU shape: featurization streams image chunks through one jitted program
(im2col patches are the big intermediate); the solver contracts over the
sharded data axis.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.batching import apply_in_chunks
from keystone_tpu.core.config import arg, parse_config
from keystone_tpu.core.fusion import optimize
from keystone_tpu.core.logging import get_logger
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.models.cifar_linear_pixels import _load as _load_cifar_or_synth
from keystone_tpu.ops.images import (
    Convolver,
    ImageVectorizer,
    Pooler,
    SymmetricRectifier,
    Windower,
    normalize_patch_rows,
)
from keystone_tpu.ops.linalg import ZCAWhitenerEstimator
from keystone_tpu.ops.linear import BlockLeastSquaresEstimator
from keystone_tpu.ops.stats import StandardScaler
from keystone_tpu.ops.util import ClassLabelIndicators, MaxClassifier
from keystone_tpu.parallel.mesh import create_mesh, shard_batch

logger = get_logger("keystone_tpu.models.cifar_random_patch")

NUM_CLASSES = 10
WHITENER_SAMPLES = 100_000


@dataclasses.dataclass
class RandomCifarConfig:
    """Random-patch CIFAR workload (reference RandomCifarConfig)."""

    train_location: str = arg(default="", help="CIFAR-10 binary file/dir")
    test_location: str = arg(default="", help="CIFAR-10 binary file/dir")
    num_filters: int = arg(default=100)
    patch_size: int = arg(default=6)
    patch_steps: int = arg(default=1)
    pool_size: int = arg(default=14)
    pool_stride: int = arg(default=13)
    alpha: float = arg(default=0.25, help="rectifier offset")
    lam: float = arg(default=0.0, help="L2 regularization")
    block_size: int = arg(default=4096)
    chunk_size: int = arg(default=1024, help="featurization chunk (images)")
    seed: int = arg(default=0)
    synthetic: int = arg(default=0, help="if > 0, N synthetic samples")


def build_filters(
    images: np.ndarray, conf: RandomCifarConfig
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sample patches, fit ZCA, construct whitened-normalized filters.

    Returns (filters (F, k²C), whitener_means (k²C,)) — the whitener itself
    is folded into the filters (reference: ``(normalized) * whitener.t``).
    """
    rng = np.random.default_rng(conf.seed)
    # sample enough images that their windows cover WHITENER_SAMPLES
    per_image = (
        (images.shape[1] - conf.patch_size) // conf.patch_steps + 1
    ) ** 2
    n_img = min(images.shape[0], max(WHITENER_SAMPLES // max(per_image, 1), 1) * 2)
    idx = rng.choice(images.shape[0], size=n_img, replace=False)
    windows = Windower(stride=conf.patch_steps, window_size=conf.patch_size)(
        jnp.asarray(images[np.sort(idx)])
    )
    flat = ImageVectorizer()(windows)
    if flat.shape[0] > WHITENER_SAMPLES:
        sel = rng.choice(flat.shape[0], WHITENER_SAMPLES, replace=False)
        flat = jnp.take(flat, jnp.asarray(np.sort(sel)), axis=0)

    base = normalize_patch_rows(flat, 10.0)
    whitener = ZCAWhitenerEstimator().fit(base)

    sel = rng.choice(base.shape[0], conf.num_filters, replace=False)
    sample_filters = jnp.take(base, jnp.asarray(np.sort(sel)), axis=0)
    unnorm = whitener(sample_filters)
    norms = jnp.linalg.norm(unnorm, axis=1, keepdims=True)
    filters = (unnorm / (norms + 1e-10)) @ whitener.whitener.T
    return filters, whitener.means


def run(conf: RandomCifarConfig, mesh=None) -> dict:
    if mesh is None and len(jax.devices()) > 1:
        mesh = create_mesh()
    t0 = time.perf_counter()
    train = _load_cifar_or_synth(_as_lp_conf(conf), "train")
    test = _load_cifar_or_synth(_as_lp_conf(conf), "test")

    filters, means = build_filters(train.images, conf)
    conv_featurizer = (
        Convolver(
            filters=filters,
            whitener_means=means,
            patch_size=conf.patch_size,
            normalize_patches=True,
        )
        >> SymmetricRectifier(alpha=conf.alpha)
        >> Pooler(stride=conf.pool_stride, pool_size=conf.pool_size)
        >> ImageVectorizer()
    )
    # operator-fusion pass: pools each rectifier half before the
    # channel concat so the (N, oh, ow, 2F) map never hits HBM
    feat_fn = jax.jit(lambda b, p=optimize(conv_featurizer): p(b))
    t_setup = time.perf_counter()

    def featurize(images: np.ndarray):
        x = shard_batch(images, mesh)
        return apply_in_chunks(feat_fn, x, conf.chunk_size)

    f_train_raw = featurize(train.images)
    scaler = StandardScaler().fit(f_train_raw, n_valid=len(train))
    f_train = scaler(f_train_raw)

    y = np.zeros(f_train.shape[0], np.int32)
    y[: len(train)] = train.labels
    indicators = ClassLabelIndicators(num_classes=NUM_CLASSES)(y)
    t_feat = time.perf_counter()

    est = BlockLeastSquaresEstimator(
        block_size=conf.block_size, num_iter=1, lam=conf.lam
    )
    model = jax.block_until_ready(
        est.fit(f_train, indicators, n_valid=len(train))
    )
    t_fit = time.perf_counter()

    classify = MaxClassifier()
    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    pred_train = classify(model(f_train))
    train_eval = evaluator(pred_train, y, n_valid=len(train))

    f_test = scaler(featurize(test.images))
    y_test = np.zeros(f_test.shape[0], np.int32)
    y_test[: len(test)] = test.labels
    test_eval = evaluator(classify(model(f_test)), y_test, n_valid=len(test))
    t_end = time.perf_counter()

    result = {
        "train_error": train_eval.error,
        "test_error": test_eval.error,
        "n_train": len(train),
        "n_test": len(test),
        "setup_s": t_setup - t0,
        "featurize_s": t_feat - t_setup,
        "fit_s": t_fit - t_feat,
        "total_s": t_end - t0,
        "featurize_fit_samples_per_s": len(train) / (t_fit - t_setup),
    }
    logger.info(
        "RandomPatchCifar: train err %.4f, test err %.4f, %.0f samples/s",
        train_eval.error,
        test_eval.error,
        result["featurize_fit_samples_per_s"],
    )
    return result


def _as_lp_conf(conf: RandomCifarConfig):
    from keystone_tpu.models.cifar_linear_pixels import LinearPixelsConfig

    return LinearPixelsConfig(
        train_location=conf.train_location,
        test_location=conf.test_location,
        synthetic=conf.synthetic,
    )


def main(argv=None) -> dict:
    conf = parse_config(RandomCifarConfig, argv)
    if not conf.synthetic and not (conf.train_location and conf.test_location):
        raise SystemExit("need --train-location AND --test-location, or --synthetic N")
    return run(conf)


if __name__ == "__main__":
    main()
