"""ImageNet SIFT + LCS Fisher-vector pipeline
(reference ``pipelines/images/imagenet/ImageNetSiftLcsFV.scala``).

Two descriptor branches — grayscale dense SIFT and color LCS — each with
its own PCA + GMM + Fisher-vector featurization, zipped into one feature
family and solved with the class-weighted block least squares estimator;
headline metric is top-5 error (reference defaults: descDim 64, vocabSize
16, mixtureWeight, 4096-column solver blocks, 1000 classes).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.batching import apply_in_chunks
from keystone_tpu.core.config import arg, parse_config
from keystone_tpu.core.logging import get_logger
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.image_loaders import load_imagenet
from keystone_tpu.models.fisher_common import FisherBranch
from keystone_tpu.ops.images import GrayScaler, PixelScaler
from keystone_tpu.ops.lcs import LCSExtractor
from keystone_tpu.ops.sift import SIFTExtractor
from keystone_tpu.ops.util import ClassLabelIndicators, TopKClassifier, ZipVectors
from keystone_tpu.ops.weighted_linear import BlockWeightedLeastSquaresEstimator
from keystone_tpu.parallel.mesh import create_mesh, shard_batch
from keystone_tpu.utils.images import LabeledImages

logger = get_logger("keystone_tpu.models.imagenet_sift_lcs_fv")


@dataclasses.dataclass
class ImageNetConfig:
    """ImageNet SIFT/LCS FV workload (reference ImageNetSiftLcsFVConfig)."""

    train_location: str = arg(default="", help="train tar file/dir/glob")
    test_location: str = arg(default="", help="test tar file/dir/glob")
    label_map: str = arg(default="", help="'synset class_idx' map file")
    num_classes: int = arg(default=1000)
    desc_dim: int = arg(default=64, help="PCA dim per branch")
    vocab_size: int = arg(default=16, help="GMM centroids per branch")
    num_pca_samples: int = arg(default=10_000_000)
    num_gmm_samples: int = arg(default=10_000_000)
    mixture_weight: float = arg(default=0.25)
    lam: float = arg(default=6e-5)
    block_size: int = arg(default=4096)
    num_iter: int = arg(default=1)
    chunk_size: int = arg(default=32)
    image_size: int = arg(default=256)
    sift_scales: int = arg(default=5)
    lcs_stride: int = arg(default=4)
    lcs_border: int = arg(default=16)
    lcs_patch: int = arg(default=6)
    seed: int = arg(default=0)
    synthetic: int = arg(default=0, help="if > 0, N synthetic images")
    synthetic_classes: int = arg(default=8)


def _load(conf: ImageNetConfig, which: str) -> tuple[LabeledImages, int]:
    if conf.synthetic:
        k = conf.synthetic_classes
        n = conf.synthetic if which == "train" else max(conf.synthetic // 4, 1)
        rng = np.random.default_rng(0 if which == "train" else 1)
        labels = rng.integers(0, k, size=n).astype(np.int32)
        centers = np.random.default_rng(42).normal(
            loc=128, scale=30, size=(k, 8, 8, 3)
        )
        imgs = np.kron(
            centers[labels],
            np.ones((1, conf.image_size // 8, conf.image_size // 8, 1)),
        )
        imgs += rng.normal(scale=20, size=imgs.shape)
        return (
            LabeledImages(
                labels=labels, images=np.clip(imgs, 0, 255).astype(np.float32)
            ),
            k,
        )
    data = load_imagenet(
        conf.train_location if which == "train" else conf.test_location,
        conf.label_map,
        target_size=conf.image_size,
    )
    return data, conf.num_classes


def run(conf: ImageNetConfig, mesh=None) -> dict:
    if mesh is None and len(jax.devices()) > 1:
        mesh = create_mesh()
    t0 = time.perf_counter()
    train, num_classes = _load(conf, "train")
    test, _ = _load(conf, "test")
    n_train, n_test = len(train), len(test)

    gray = PixelScaler() >> GrayScaler()
    sift = SIFTExtractor(num_scales=conf.sift_scales)
    lcs = LCSExtractor(
        stride=conf.lcs_stride,
        stride_start=conf.lcs_border,
        sub_patch_size=conf.lcs_patch,
    )
    sift_fn = jax.jit(lambda b: sift(gray(b)))
    lcs_fn = jax.jit(lambda b: lcs(PixelScaler()(b)))

    sift_branch = FisherBranch(
        conf.desc_dim,
        conf.vocab_size,
        conf.num_pca_samples,
        conf.num_gmm_samples,
        conf.seed,
    )
    lcs_branch = FisherBranch(
        conf.desc_dim,
        conf.vocab_size,
        conf.num_pca_samples,
        conf.num_gmm_samples,
        conf.seed + 100,
    )

    def featurize_train(images):
        x = shard_batch(images, mesh)
        sift_desc = apply_in_chunks(sift_fn, x, conf.chunk_size)
        lcs_desc = apply_in_chunks(lcs_fn, x, conf.chunk_size)
        ps = sift_branch.fit(sift_desc, conf.chunk_size, n_valid=n_train)
        pl = lcs_branch.fit(lcs_desc, conf.chunk_size, n_valid=n_train)
        return ZipVectors()(
            [
                sift_branch.featurize_projected(ps, conf.chunk_size),
                lcs_branch.featurize_projected(pl, conf.chunk_size),
            ]
        )

    def featurize_test(images):
        x = shard_batch(images, mesh)
        return ZipVectors()(
            [
                sift_branch.featurize(
                    apply_in_chunks(sift_fn, x, conf.chunk_size), conf.chunk_size
                ),
                lcs_branch.featurize(
                    apply_in_chunks(lcs_fn, x, conf.chunk_size), conf.chunk_size
                ),
            ]
        )

    f_train = featurize_train(train.images)
    t_feat = time.perf_counter()

    y = np.zeros(f_train.shape[0], np.int32)
    y[:n_train] = train.labels
    indicators = ClassLabelIndicators(num_classes=num_classes)(jnp.asarray(y))
    est = BlockWeightedLeastSquaresEstimator(
        block_size=conf.block_size,
        num_iter=conf.num_iter,
        lam=conf.lam,
        mixture_weight=conf.mixture_weight,
        class_chunk=min(16, num_classes),
    )
    model = jax.block_until_ready(
        est.fit(f_train, indicators, n_valid=n_train)
    )
    t_fit = time.perf_counter()

    top5 = TopKClassifier(k=min(5, num_classes))
    evaluator = MulticlassClassifierEvaluator(num_classes)

    def top_errors(scores, labels_np, n_valid):
        topk = np.asarray(top5(scores))[:n_valid]
        labels_np = labels_np[:n_valid]
        top1 = evaluator(
            jnp.asarray(topk[:, 0]), jnp.asarray(labels_np)
        ).error
        top5_err = 1.0 - float(
            np.mean((topk == labels_np[:, None]).any(axis=1))
        )
        return top1, top5_err

    train_top1, train_top5 = top_errors(model(f_train), y, n_train)
    f_test = featurize_test(test.images)
    y_test = np.zeros(f_test.shape[0], np.int32)
    y_test[:n_test] = test.labels
    test_top1, test_top5 = top_errors(model(f_test), y_test, n_test)

    result = {
        "train_top1_error": train_top1,
        "train_top5_error": train_top5,
        "test_top1_error": test_top1,
        "test_top5_error": test_top5,
        "n_train": n_train,
        "n_test": n_test,
        "featurize_s": t_feat - t0,
        "fit_s": t_fit - t_feat,
        "total_s": time.perf_counter() - t0,
    }
    logger.info(
        "ImageNetSiftLcsFV: train top1/top5 err %.4f/%.4f, "
        "test top1/top5 err %.4f/%.4f",
        train_top1,
        train_top5,
        test_top1,
        test_top5,
    )
    return result


def main(argv=None) -> dict:
    conf = parse_config(ImageNetConfig, argv)
    if not conf.synthetic and not (conf.train_location and conf.label_map):
        raise SystemExit(
            "need --train-location/--test-location/--label-map, or --synthetic N"
        )
    return run(conf)


if __name__ == "__main__":
    main()
