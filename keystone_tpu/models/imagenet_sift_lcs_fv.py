"""ImageNet SIFT + LCS Fisher-vector pipeline
(reference ``pipelines/images/imagenet/ImageNetSiftLcsFV.scala``).

Two descriptor branches — grayscale dense SIFT and color LCS — each with
its own PCA + GMM + Fisher-vector featurization, zipped into one feature
family and solved with the class-weighted block least squares estimator;
headline metric is top-5 error (reference defaults: descDim 64, vocabSize
16, mixtureWeight, 4096-column solver blocks, 1000 classes).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.batching import apply_in_chunks
from keystone_tpu.core.config import arg, parse_config
from keystone_tpu.core.logging import get_logger
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.image_loaders import load_imagenet
from keystone_tpu.loaders.imagenet_stream import (
    assemble_global as _assemble_global,
    render_classes as _render_classes,
    synthetic_centers as _synthetic_centers,
    synthetic_source as _synthetic_source,
    tar_source as _tar_source,
)
from keystone_tpu.models.fisher_common import FisherBranch
from keystone_tpu.ops.images import GrayScaler, PixelScaler
from keystone_tpu.ops.lcs import LCSExtractor
from keystone_tpu.ops.sift import SIFTExtractor
from keystone_tpu.ops.util import ClassLabelIndicators, TopKClassifier, ZipVectors
from keystone_tpu.ops.weighted_linear import BlockWeightedLeastSquaresEstimator
from keystone_tpu.parallel.mesh import create_mesh, shard_batch
from keystone_tpu.utils.images import LabeledImages

logger = get_logger("keystone_tpu.models.imagenet_sift_lcs_fv")


@dataclasses.dataclass
class ImageNetConfig:
    """ImageNet SIFT/LCS FV workload (reference ImageNetSiftLcsFVConfig)."""

    train_location: str = arg(default="", help="train tar file/dir/glob")
    test_location: str = arg(default="", help="test tar file/dir/glob")
    label_map: str = arg(default="", help="'synset class_idx' map file")
    num_classes: int = arg(default=1000)
    desc_dim: int = arg(default=64, help="PCA dim per branch")
    vocab_size: int = arg(default=16, help="GMM centroids per branch")
    num_pca_samples: int = arg(default=10_000_000)
    num_gmm_samples: int = arg(default=10_000_000)
    mixture_weight: float = arg(default=0.25)
    lam: float = arg(default=6e-5)
    block_size: int = arg(default=4096)
    num_iter: int = arg(default=1)
    chunk_size: int = arg(default=32)
    image_size: int = arg(default=256)
    sift_scales: int = arg(default=5)
    lcs_stride: int = arg(default=4)
    lcs_border: int = arg(default=16)
    lcs_patch: int = arg(default=6)
    checkpoint_dir: str = arg(
        default="",
        help="if set, checkpoint the weighted solver between BCD passes "
        "and resume from this directory",
    )
    checkpoint_every: int = arg(default=1)
    seed: int = arg(default=0)
    synthetic: int = arg(default=0, help="if > 0, N synthetic images")
    synthetic_classes: int = arg(default=8)
    label_noise: float = arg(
        default=0.0,
        help="fraction q of synthetic images rendered from a random OTHER "
        "class's center while keeping their label: a provable top-1 error "
        "floor of exactly q (flips never land on the labeled class), so a "
        "scale eval can assert a nonzero target band in both directions "
        "(an eval reading 0.000 cannot detect a quality regression)",
    )
    streaming: bool = arg(
        default=False,
        help="two-pass streaming ingestion: never materializes the image "
        "corpus or its descriptors on the host (ImageNet-scale)",
    )
    stream_batch: int = arg(default=256, help="host images per stream batch")


def _load(conf: ImageNetConfig, which: str) -> tuple[LabeledImages, int]:
    if conf.synthetic:
        k = conf.synthetic_classes
        n = conf.synthetic if which == "train" else max(conf.synthetic // 4, 1)
        rng = np.random.default_rng(0 if which == "train" else 1)
        labels = rng.integers(0, k, size=n).astype(np.int32)
        centers = _synthetic_centers(k)
        render = _render_classes(labels, k, conf.label_noise, rng)
        imgs = np.kron(
            centers[render],
            np.ones((1, conf.image_size // 8, conf.image_size // 8, 1)),
        )
        imgs += rng.normal(scale=20, size=imgs.shape)
        return (
            LabeledImages(
                labels=labels, images=np.clip(imgs, 0, 255).astype(np.float32)
            ),
            k,
        )
    data = load_imagenet(
        conf.train_location if which == "train" else conf.test_location,
        conf.label_map,
        target_size=conf.image_size,
    )
    return data, conf.num_classes


def _descriptor_cols(desc) -> np.ndarray:
    """(N, d, m) device descriptors → (N·m, d) host rows for the reservoir."""
    n, d, m = desc.shape
    return np.asarray(jnp.transpose(desc, (0, 2, 1)).reshape(n * m, d))


def run_streaming(
    conf: ImageNetConfig, mesh=None, train_source=None, test_source=None
) -> dict:
    """Two-pass streaming variant of :func:`run` — ImageNet-scale.

    Pass 1 streams the corpus once, filling bounded descriptor-column
    reservoirs (PCA/GMM samples); pass 2 streams it again, emitting only
    the Fisher features + labels. Host memory never holds more than one
    image batch + the reservoirs + the feature matrix — the reference's
    per-executor tar streaming economics (ImageLoaderUtils.scala:177-216).
    Sources are callables returning a fresh (images, labels) iterator,
    defaulting to this process's share of the tar corpus; multi-host, each
    process streams a disjoint file set and the per-process features are
    assembled into one global training set before the fit.
    """
    from keystone_tpu.loaders.streaming import ColumnReservoir

    if mesh is None and len(jax.devices()) > 1:
        mesh = create_mesh()
    if train_source is None:
        train_source = (
            _synthetic_source(conf, "train")
            if conf.synthetic
            else _tar_source(conf, "train")
        )
    if test_source is None:
        test_source = (
            _synthetic_source(conf, "test")
            if conf.synthetic
            else _tar_source(conf, "test")
        )
    num_classes = (
        conf.synthetic_classes if conf.synthetic else conf.num_classes
    )
    t0 = time.perf_counter()

    gray = PixelScaler() >> GrayScaler()
    sift = SIFTExtractor(num_scales=conf.sift_scales)
    lcs = LCSExtractor(
        stride=conf.lcs_stride,
        stride_start=conf.lcs_border,
        sub_patch_size=conf.lcs_patch,
    )
    sift_fn = jax.jit(lambda b: sift(gray(b)))
    lcs_fn = jax.jit(lambda b: lcs(PixelScaler()(b)))

    sift_branch = FisherBranch(
        conf.desc_dim, conf.vocab_size, conf.num_pca_samples,
        conf.num_gmm_samples, conf.seed,
    )
    lcs_branch = FisherBranch(
        conf.desc_dim, conf.vocab_size, conf.num_pca_samples,
        conf.num_gmm_samples, conf.seed + 100,
    )

    # ---- pass 1: bounded descriptor-column reservoirs, sized for the
    # larger of the PCA and GMM sample budgets ----
    res_cap = max(conf.num_pca_samples, conf.num_gmm_samples)
    res_sift = ColumnReservoir(res_cap, conf.seed)
    res_lcs = ColumnReservoir(res_cap, conf.seed + 1)
    from keystone_tpu import plan as plan_mod

    if plan_mod.enabled():
        # KEYSTONE_PLAN: both descriptor branches ride one shared
        # pixel-scaling prefix per chunk (the planner's shared-prefix
        # fit, in its streaming per-chunk form) — the corpus is scaled
        # once instead of once per branch, chunk residency unchanged
        scale_fn = jax.jit(lambda b: PixelScaler()(b))
        sift_tail = jax.jit(lambda s: sift(GrayScaler()(s)))
        lcs_tail = jax.jit(lambda s: lcs(s))
        for imgs, _ in train_source():
            sift_desc, lcs_desc = plan_mod.apply_shared(
                scale_fn,
                (sift_tail, lcs_tail),
                np.asarray(imgs),
                chunk_size=conf.chunk_size,
                mesh=mesh,
            )
            res_sift.add(_descriptor_cols(sift_desc))
            res_lcs.add(_descriptor_cols(lcs_desc))
        # one CORPUS pass over pixel scaling eliminated, however many
        # batches the stream took (apply_shared counts per-call applies)
        from keystone_tpu.observe import metrics as _metrics

        _metrics.get_registry().counter(
            "plan_featurize_passes_saved"
        ).inc()
    else:
        for imgs, _ in train_source():
            res_sift.add(
                _descriptor_cols(
                    apply_in_chunks(sift_fn, imgs, conf.chunk_size)
                )
            )
            res_lcs.add(
                _descriptor_cols(
                    apply_in_chunks(lcs_fn, imgs, conf.chunk_size)
                )
            )
    sift_branch.fit_from_samples(res_sift.sample())
    lcs_branch.fit_from_samples(res_lcs.sample())
    t_sample = time.perf_counter()

    # ---- pass 2: featurize stream → (N, D) fisher features + labels.
    # One jitted executable (fixed chunk shape, mesh-sharded) serves every
    # chunk of both the train and test streams.
    featurize_chunk = jax.jit(
        lambda b: ZipVectors()(
            [
                _branch_apply(sift_branch, sift_fn(b)),
                _branch_apply(lcs_branch, lcs_fn(b)),
            ]
        )
    )

    def features_labels_of(source):
        from keystone_tpu.loaders.streaming import (
            featurize_stream,
            prefetch_batches,
        )

        label_parts: list[np.ndarray] = []

        def image_batches():
            for imgs, labels in source():
                label_parts.append(np.asarray(labels, np.int32))
                yield imgs

        # decode-ahead thread + bounded in-flight device chunks: host
        # decode of batch k+1 overlaps the device featurize of batch k
        feats = featurize_stream(
            prefetch_batches(image_batches(), depth=2), featurize_chunk,
            chunk_size=conf.chunk_size, mesh=mesh,
        )
        labels = (
            np.concatenate(label_parts)
            if label_parts
            else np.zeros(0, np.int32)
        )
        return feats, labels

    f_train_local, y_train_local = features_labels_of(train_source)
    f_train_np, y_train = _assemble_global(f_train_local, y_train_local)
    n_train = len(y_train)
    f_train = shard_batch(f_train_np, mesh)
    t_feat = time.perf_counter()

    y_pad = np.zeros(f_train.shape[0], np.int32)
    y_pad[:n_train] = y_train
    indicators = ClassLabelIndicators(num_classes=num_classes)(
        jnp.asarray(y_pad)
    )
    est = BlockWeightedLeastSquaresEstimator(
        block_size=conf.block_size,
        num_iter=conf.num_iter,
        lam=conf.lam,
        mixture_weight=conf.mixture_weight,
        class_chunk=min(16, num_classes),
    )
    if plan_mod.enabled() and not conf.checkpoint_dir:
        # KEYSTONE_PLAN: the weighted fit streams chunks through the
        # per-class normal-equation accumulators (plan/fused_fit.py).
        # fit_streaming's planner prices the (C, D, D) state against
        # the memory budget and falls back to the materialized fit —
        # with a recorded decision — when per-class Grams at real
        # ImageNet class counts don't fit.
        from keystone_tpu.core.pipeline import (
            ChainedLabelEstimator,
            Identity,
        )

        fitted = plan_mod.fit_streaming(
            ChainedLabelEstimator(prefix=Identity(), est=est),
            f_train,
            indicators,
            n_valid=n_train,
            mesh=mesh,
        )
        model = jax.block_until_ready(fitted[-1])
    else:
        from keystone_tpu.core.checkpoint import checkpointed_fit

        model = jax.block_until_ready(
            checkpointed_fit(
                est,
                f_train,
                indicators,
                checkpoint_dir=conf.checkpoint_dir,
                every=conf.checkpoint_every,
                n_valid=n_train,
            )
        )
    t_fit = time.perf_counter()

    top5 = TopKClassifier(k=min(5, num_classes))
    evaluator = MulticlassClassifierEvaluator(num_classes)

    def top_errors(scores, labels_np):
        topk = np.asarray(top5(scores))[: len(labels_np)]
        top1 = evaluator(
            jnp.asarray(topk[:, 0]), jnp.asarray(labels_np)
        ).error
        top5_err = 1.0 - float(
            np.mean((topk == labels_np[:, None]).any(axis=1))
        )
        return top1, top5_err

    train_top1, train_top5 = top_errors(model(f_train), y_train)
    f_test_local, y_test_local = features_labels_of(test_source)
    f_test, y_test = _assemble_global(f_test_local, y_test_local)
    test_top1, test_top5 = top_errors(
        model(shard_batch(f_test, mesh)), y_test
    )

    result = {
        "train_top1_error": train_top1,
        "train_top5_error": train_top5,
        "test_top1_error": test_top1,
        "test_top5_error": test_top5,
        "n_train": n_train,
        "n_test": len(y_test),
        "sample_pass_s": t_sample - t0,
        "featurize_s": t_feat - t_sample,
        "fit_s": t_fit - t_feat,
        "total_s": time.perf_counter() - t0,
    }
    logger.info(
        "ImageNetSiftLcsFV[streaming]: train top1/top5 err %.4f/%.4f, "
        "test top1/top5 err %.4f/%.4f (%d train imgs)",
        train_top1, train_top5, test_top1, test_top5, n_train,
    )
    return result


def _branch_apply(branch: FisherBranch, desc):
    """Project + fisher-post one descriptor batch (traced path)."""
    return branch.post(branch.pca(desc))


def run(conf: ImageNetConfig, mesh=None) -> dict:
    if mesh is None and len(jax.devices()) > 1:
        mesh = create_mesh()
    t0 = time.perf_counter()
    train, num_classes = _load(conf, "train")
    test, _ = _load(conf, "test")
    n_train, n_test = len(train), len(test)

    gray = PixelScaler() >> GrayScaler()
    sift = SIFTExtractor(num_scales=conf.sift_scales)
    lcs = LCSExtractor(
        stride=conf.lcs_stride,
        stride_start=conf.lcs_border,
        sub_patch_size=conf.lcs_patch,
    )
    sift_fn = jax.jit(lambda b: sift(gray(b)))
    lcs_fn = jax.jit(lambda b: lcs(PixelScaler()(b)))

    sift_branch = FisherBranch(
        conf.desc_dim,
        conf.vocab_size,
        conf.num_pca_samples,
        conf.num_gmm_samples,
        conf.seed,
    )
    lcs_branch = FisherBranch(
        conf.desc_dim,
        conf.vocab_size,
        conf.num_pca_samples,
        conf.num_gmm_samples,
        conf.seed + 100,
    )

    def featurize_train(images):
        x = shard_batch(images, mesh)
        sift_desc = apply_in_chunks(sift_fn, x, conf.chunk_size)
        lcs_desc = apply_in_chunks(lcs_fn, x, conf.chunk_size)
        ps = sift_branch.fit(sift_desc, conf.chunk_size, n_valid=n_train)
        pl = lcs_branch.fit(lcs_desc, conf.chunk_size, n_valid=n_train)
        return ZipVectors()(
            [
                sift_branch.featurize_projected(ps, conf.chunk_size),
                lcs_branch.featurize_projected(pl, conf.chunk_size),
            ]
        )

    def featurize_test(images):
        x = shard_batch(images, mesh)
        return ZipVectors()(
            [
                sift_branch.featurize(
                    apply_in_chunks(sift_fn, x, conf.chunk_size), conf.chunk_size
                ),
                lcs_branch.featurize(
                    apply_in_chunks(lcs_fn, x, conf.chunk_size), conf.chunk_size
                ),
            ]
        )

    f_train = featurize_train(train.images)
    t_feat = time.perf_counter()

    y = np.zeros(f_train.shape[0], np.int32)
    y[:n_train] = train.labels
    indicators = ClassLabelIndicators(num_classes=num_classes)(jnp.asarray(y))
    est = BlockWeightedLeastSquaresEstimator(
        block_size=conf.block_size,
        num_iter=conf.num_iter,
        lam=conf.lam,
        mixture_weight=conf.mixture_weight,
        class_chunk=min(16, num_classes),
    )
    from keystone_tpu.core.checkpoint import checkpointed_fit

    model = jax.block_until_ready(
        checkpointed_fit(
            est,
            f_train,
            indicators,
            checkpoint_dir=conf.checkpoint_dir,
            every=conf.checkpoint_every,
            n_valid=n_train,
        )
    )
    t_fit = time.perf_counter()

    top5 = TopKClassifier(k=min(5, num_classes))
    evaluator = MulticlassClassifierEvaluator(num_classes)

    def top_errors(scores, labels_np, n_valid):
        topk = np.asarray(top5(scores))[:n_valid]
        labels_np = labels_np[:n_valid]
        top1 = evaluator(
            jnp.asarray(topk[:, 0]), jnp.asarray(labels_np)
        ).error
        top5_err = 1.0 - float(
            np.mean((topk == labels_np[:, None]).any(axis=1))
        )
        return top1, top5_err

    train_top1, train_top5 = top_errors(model(f_train), y, n_train)
    f_test = featurize_test(test.images)
    y_test = np.zeros(f_test.shape[0], np.int32)
    y_test[:n_test] = test.labels
    test_top1, test_top5 = top_errors(model(f_test), y_test, n_test)

    result = {
        "train_top1_error": train_top1,
        "train_top5_error": train_top5,
        "test_top1_error": test_top1,
        "test_top5_error": test_top5,
        "n_train": n_train,
        "n_test": n_test,
        "featurize_s": t_feat - t0,
        "fit_s": t_fit - t_feat,
        "total_s": time.perf_counter() - t0,
    }
    logger.info(
        "ImageNetSiftLcsFV: train top1/top5 err %.4f/%.4f, "
        "test top1/top5 err %.4f/%.4f",
        train_top1,
        train_top5,
        test_top1,
        test_top5,
    )
    return result


def main(argv=None) -> dict:
    conf = parse_config(ImageNetConfig, argv)
    if not conf.synthetic and not (conf.train_location and conf.label_map):
        raise SystemExit(
            "need --train-location/--test-location/--label-map, or --synthetic N"
        )
    if conf.streaming:
        return run_streaming(conf)
    return run(conf)


if __name__ == "__main__":
    main()
