"""Decoder-only transformer LM, split by lifecycle:

- :mod:`.model` — architecture (TransformerLM/LMBlock), analytic FLOPs;
- :mod:`.losses` — next-token CE, dense and logit-chunked;
- :mod:`.sharding` — the Megatron-style tensor-parallel weight layout;
- :mod:`.train` — optimizers, the jitted dp/tp and pipeline-parallel
  train steps, the checkpointed training loop, corpora;
- :mod:`.decode` — KV-cache serving: prefill, decode, sampling,
  weight-only int8 quantization.

:mod:`keystone_tpu.models.lm_transformer` re-exports this surface (plus
the CLI) and remains the stable import path.
"""

from keystone_tpu.models.lm.decode import (
    KVCache,
    decode_step,
    generate,
    prefill,
    quantize_for_decode,
)
from keystone_tpu.models.lm.losses import (
    chunked_token_cross_entropy,
    next_token_loss,
    token_cross_entropy,
)
from keystone_tpu.models.lm.model import (
    LMBlock,
    TransformerLM,
    train_step_flops,
)
from keystone_tpu.models.lm.sharding import shard_params
from keystone_tpu.models.lm.train import (
    make_optimizer,
    make_pp_train_step,
    make_train_step,
    next_token_loss_pp,
    pp_forward,
    synthetic_corpus,
    train,
)

__all__ = [
    "KVCache",
    "LMBlock",
    "TransformerLM",
    "chunked_token_cross_entropy",
    "decode_step",
    "generate",
    "make_optimizer",
    "make_pp_train_step",
    "make_train_step",
    "next_token_loss",
    "next_token_loss_pp",
    "pp_forward",
    "prefill",
    "quantize_for_decode",
    "shard_params",
    "synthetic_corpus",
    "token_cross_entropy",
    "train",
    "train_step_flops",
]
