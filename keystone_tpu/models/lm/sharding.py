"""Tensor-parallel weight layout for the transformer LM.

The layout IS the parallelism: annotate each weight's sharding over the
mesh ``model`` axis and XLA inserts exactly the two psums per block that
hand-written Megatron-style TP would (see shard_params). The same layout
feeds the pipeline-parallel path unchanged — gpipe leaves non-manual
mesh axes automatic, so these shardings propagate into stage bodies on a
3-axis (pipe, data, model) mesh.
"""

from __future__ import annotations

import dataclasses

import jax

from keystone_tpu.models.lm.model import LMBlock, TransformerLM


def shard_params(model: TransformerLM, mesh) -> TransformerLM:
    """Lay the weights out for tensor parallelism over the mesh ``model``
    axis: attention q/k/v column-sharded (head-parallel) with wo
    row-sharded, MLP column- then row-sharded, embedding vocab-sharded.
    XLA then inserts exactly the two psums per block that hand-written
    Megatron-style TP would — the layout IS the parallelism.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None or mesh.shape.get("model", 1) == 1:
        return model
    n_model = mesh.shape["model"]

    def put(x, spec):
        # a dim not divisible by the axis (e.g. an unpadded vocab) is
        # replicated rather than rejected
        spec = P(
            *(
                a
                if a is None or x.shape[i] % n_model == 0
                else None
                for i, a in enumerate(spec)
            )
        )
        return jax.device_put(x, NamedSharding(mesh, spec))

    blocks = tuple(
        LMBlock(
            wq=put(b.wq, P(None, "model")),
            wk=put(b.wk, P(None, "model")),
            wv=put(b.wv, P(None, "model")),
            wo=put(b.wo, P("model", None)),
            w1=put(b.w1, P(None, "model")),
            w2=put(b.w2, P("model", None)),
        )
        for b in model.blocks
    )
    moes = tuple(
        m
        if m is None
        else dataclasses.replace(
            m,
            # expert-parallel: one expert group per model-axis device;
            # the router stays replicated (every token scores every
            # expert) — XLA places the dispatch/combine all_to_alls
            w_router=put(m.w_router, P()),
            w1=put(m.w1, P("model", None, None)),
            w2=put(m.w2, P("model", None, None)),
        )
        for m in model.moe_layers
    )
    return dataclasses.replace(
        model,
        embed=put(model.embed, P("model", None)),
        pos_embed=put(model.pos_embed, P()),
        blocks=blocks,
        moe_layers=moes,
    )


