"""Decoder-only transformer LM with a fully sharded training step.

The reference has no sequence models at all (SURVEY §5: long-context
"absent"), but long-context + distributed are first-class capabilities of
this framework, not parity afterthoughts. This model is the training-side
consumer of that stack:

- causal attention via :mod:`keystone_tpu.ops.attention` — dense, fused
  Pallas flash, or sequence-parallel ring / Ulysses (`seq_mode`), so one
  flag takes the same model from a single chip to a sequence-sharded mesh
  for contexts that don't fit one device;
- tensor parallelism by sharding each weight over the mesh ``model`` axis
  (head-parallel attention, column/row-parallel MLP, vocab-parallel tied
  embedding) — XLA inserts the psums, the model code stays purely
  functional;
- data parallelism over the ``data`` axis;
- one jitted, buffer-donated train step (AdamW via optax) — the whole
  update is a single XLA program, the idiom the rest of the framework uses
  for its solvers (one launch per step, no host round-trips).

This is a beyond-reference capability in the same spirit as
``models/vit_ridge.py``.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.treenode import static_field, treenode
from keystone_tpu.ops.attention import (
    dense_attention,
    ring_attention,
    ulysses_attention,
)
from keystone_tpu.ops.quantization import QTensor, mm
from keystone_tpu.ops.vit import _layer_norm


@treenode
class LMBlock:
    wq: jnp.ndarray  # (d, d)
    wk: jnp.ndarray
    wv: jnp.ndarray
    wo: jnp.ndarray
    w1: jnp.ndarray  # (d, ff)
    w2: jnp.ndarray  # (ff, d)


def _ln(x, cdt):
    # normalization stats in f32 even under a bf16 policy: the
    # mean/variance cancellation is exactly what bf16 loses
    return _layer_norm(x.astype(jnp.float32)).astype(cdt)


def model_mm(model):
    """The matmul the model's int8 weights go through: plain ``mm`` or,
    under ``int8_kernel="pallas"``, the fused dequant kernel for
    per-output-channel-scaled QTensors (float weights always take
    ``mm``)."""
    if model.int8_kernel == "xla":
        return mm
    if model.int8_kernel != "pallas":
        raise ValueError(
            f"int8_kernel={model.int8_kernel!r}; expected xla|pallas"
        )

    def pallas_mm(y, w, dt):
        # decode-sized M only: mm_fused carries the whole M extent in
        # one VMEM tile, which is the right shape for a handful of
        # decode rows and a VMEM blow-up for prefill/forward (B·S rows)
        m_rows = int(np.prod(y.shape[:-1]))
        if (
            isinstance(w, QTensor)
            and w.scale.shape == (1, w.q.shape[1])
            and m_rows <= 64
        ):
            from keystone_tpu.ops.int8_matmul import mm_fused

            return mm_fused(y.astype(dt), w).astype(dt)
        return mm(y, w, dt)

    return pallas_mm


def _split_heads(y, w, h, mm_fn=mm):
    n, s, _ = y.shape
    out = mm_fn(y, w, y.dtype)  # (n, s, h·hd) — rectangular for GQA K/V
    return out.reshape(n, s, h, out.shape[-1] // h).transpose(0, 2, 1, 3)


def _rope(x, positions, base: float = 10_000.0):
    """Rotary position embedding. x: (..., S, hd), hd even; positions:
    (S,) int32 global token positions — or (B, S) when sequences in the
    batch sit at different positions (the serving decode pool: each slot
    carries its own sequence, so each rotates at its own phase). Angles
    in f32 (bf16 loses phase accuracy fast at long context), rotated
    result back in x.dtype."""
    hd = x.shape[-1]
    half = hd // 2
    inv = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    freqs = positions.astype(jnp.float32)[..., None] * inv  # (..., S, half)
    cos, sin = jnp.cos(freqs), jnp.sin(freqs)
    if freqs.ndim == 3:
        # (B, S, half) phases meet (B, H, S, hd/2) halves: insert the
        # head axis so each batch row broadcasts over its own heads
        cos, sin = cos[:, None], sin[:, None]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)


def _block_apply(x, blk: LMBlock, cdt, attn, moe=None, mm_fn=mm):
    """Pre-LN residual block shared by training forward, prefill, and
    decode: ``attn(y, blk) -> (attention output (N,S,d), aux)``. When
    ``moe`` is given it replaces the dense FFN; returns
    (x, attn_aux, moe_aux_loss)."""
    a, aux = attn(_ln(x, cdt), blk)
    x = x + a
    y = _ln(x, cdt)
    if moe is not None:
        f, moe_aux = moe(y)
        return x + f, aux, moe_aux
    hdn = mm_fn(y, blk.w1, cdt)
    return x + mm_fn(jax.nn.gelu(hdn), blk.w2, cdt), aux, jnp.float32(0)


def _gather_embed(embed, tokens):
    """Embedding-row gather handling the int8 row-quantized table (the
    per-token scales apply to the gathered rows)."""
    if isinstance(embed, QTensor):
        return embed.q[tokens].astype(jnp.float32) * embed.scale[tokens]
    return embed[tokens]


def _embed(model, tokens, cdt):
    """Token embedding + optional learned positions, cast to the compute
    dtype — the one preamble shared by training forward, prefill, and the
    pipeline-parallel forward."""
    d = model.embed.shape[-1]
    x = _gather_embed(model.embed, tokens) * math.sqrt(d)
    if model.pos_encoding == "learned":
        x = x + model.pos_embed[: tokens.shape[1]]
    return x.astype(cdt)


def _tied_logits(x, embed, cdt):
    # bf16 operands, f32 accumulate/output: the logits feed a logsumexp —
    # bf16 logits would cost real perplexity precision
    if isinstance(embed, QTensor):
        # (V, 1) row scales become per-output-channel under the transpose
        return jnp.matmul(
            _ln(x, cdt), embed.q.T.astype(cdt),
            preferred_element_type=jnp.float32,
        ) * embed.scale[:, 0]
    return jnp.matmul(
        _ln(x, cdt), embed.T.astype(cdt), preferred_element_type=jnp.float32
    )


@treenode
class TransformerLM:
    """Pre-LN decoder-only LM; logits tied to the token embedding."""

    embed: jnp.ndarray  # (V, d)
    pos_embed: jnp.ndarray  # (S_max, d)
    blocks: tuple  # of LMBlock
    num_heads: int = static_field(default=8)
    # attention strategy: "local" (dense or Pallas flash on TPU),
    # "ring" / "ulysses" (sequence-parallel over `seq_axis` of `mesh`)
    seq_mode: str = static_field(default="local")
    mesh: object = static_field(default=None)
    seq_axis: str = static_field(default="data")
    # rematerialize each block in the backward pass: activation memory
    # drops from O(depth · S · d) per-layer intermediates to the block
    # boundaries only — the jax.checkpoint successor of the reference's
    # nothing (it never trained deep models)
    remat: bool = static_field(default=False)
    # "full" recomputes everything inside the block (max memory saving,
    # ~1/3 extra forward FLOPs in the backward); "dots" saves the matmul
    # outputs and recomputes only the cheap elementwise/LN work — the
    # memory/MFU middle ground (ROOFLINE.md §6): the MXU never re-runs,
    # so measured step FLOPs stay at the analytic 6·P·tokens
    remat_policy: str = static_field(default="full")
    # mixed precision: params/optimizer state stay float32; activations
    # and the matmul operands run in this dtype ("bfloat16" halves HBM
    # traffic and feeds the MXU its native input width). LayerNorm stats
    # and the loss reduction stay float32 regardless.
    compute_dtype: str = static_field(default="float32")
    # expert parallelism: per-block MoE layers (None entries keep the
    # dense FFN). Tuple parallel to `blocks`; empty = no MoE anywhere.
    moe_layers: tuple = ()
    moe_aux_weight: float = static_field(default=0.01)
    # "learned" = trained absolute table (pos_embed, capped at max_seq);
    # "rope" = rotary q/k phases — no table, no length cap beyond memory,
    # the right pairing for the blockwise long-context backward
    pos_encoding: str = static_field(default="learned")
    # grouped-query attention: K/V carry this many heads (0 = num_heads,
    # plain MHA; 1 = MQA). The decode cache shrinks by num_heads/kv_heads
    # — composing with kv_dtype="int8" for the full serving story
    num_kv_heads: int = static_field(default=0)
    # how int8 QTensor weights multiply: "xla" trusts the convert-into-
    # dot fusion (ops/quantization.mm); "pallas" streams the codes as
    # int8 via the fused kernel (ops/int8_matmul.mm_fused) — the A/B the
    # bench measures e2e (ROOFLINE.md §6 decode note)
    int8_kernel: str = static_field(default="xla")

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    def _qkv_heads(self, x, blk: LMBlock, positions=None):
        """(q with H heads, k/v with KV heads, rope applied).
        ``positions`` defaults to 0..S-1 (full-sequence forward); decode
        passes the single global position of its new token."""
        mm_fn = model_mm(self)
        q = _split_heads(x, blk.wq, self.num_heads, mm_fn)
        k = _split_heads(x, blk.wk, self.kv_heads, mm_fn)
        v = _split_heads(x, blk.wv, self.kv_heads, mm_fn)
        if self.pos_encoding == "rope":
            if positions is None:
                positions = jnp.arange(x.shape[1])
            q = _rope(q, positions)
            k = _rope(k, positions)
        return q, k, v

    def _attention(self, x, blk: LMBlock, return_kv: bool = False):
        n, s, d = x.shape
        h = self.num_heads

        # x is always the full (global) sequence here — the
        # sequence-parallel paths shard inside ring/ulysses_attention
        q, k, v = self._qkv_heads(x, blk)
        kv_raw = (k, v)  # pre-broadcast: what the decode cache stores
        if self.kv_heads != h:
            # training/prefill compute broadcasts K/V up to H heads
            # (activation-sized, the standard GQA training treatment);
            # the grouped decode path never materializes this
            g = h // self.kv_heads
            k = jnp.repeat(k, g, axis=1)
            v = jnp.repeat(v, g, axis=1)
        # sequence-parallel training runs the custom-VJP bodies: the ring
        # backward circulates dk/dv accumulators around the ring (the
        # per-hop Pallas forward kernels are forward-only), Ulysses
        # differentiates the flash trainable wrapper through all_to_all.
        # use_flash auto-selects: Pallas-rate on TPU, jnp off it.
        if self.seq_mode == "ring":
            out = ring_attention(
                q, k, v, self.mesh, seq_axis=self.seq_axis, causal=True,
                trainable=True,
            )
        elif self.seq_mode == "ulysses":
            out = ulysses_attention(
                q, k, v, self.mesh, seq_axis=self.seq_axis, causal=True,
                trainable=True,
            )
        else:
            from keystone_tpu.ops.flash_attention import on_tpu

            # KST_LOCAL_ATTN overrides the auto-select (read per call,
            # like the KST_FLASH_* knobs): the S=2048 flagship shape sits
            # in the regime where dense XLA attention can rival the
            # Pallas kernel (TPU_VALIDATION 0.98-1.27x at <=8k), so the
            # MFU push sweeps this axis too (tools/lm_mfu_push2.py)
            import os as _os

            mode = _os.environ.get("KST_LOCAL_ATTN", "auto")
            if mode not in ("auto", "flash", "dense"):
                raise ValueError(
                    f"KST_LOCAL_ATTN={mode!r}; expected auto|flash|dense"
                )
            use_flash = on_tpu() if mode == "auto" else mode == "flash"
            if use_flash:
                # fused Pallas forward with a recompute VJP — training
                # never materializes the (S, S) probabilities
                from keystone_tpu.ops.flash_attention import (
                    flash_attention_trainable,
                )

                out = flash_attention_trainable(q, k, v, True)
            else:
                out = dense_attention(q, k, v, causal=True)
        proj = model_mm(self)(
            out.transpose(0, 2, 1, 3).reshape(n, s, d).astype(x.dtype),
            blk.wo,
            x.dtype,
        )
        if return_kv:
            return proj, kv_raw
        return proj

    def _moe(self, i: int):
        return self.moe_layers[i] if self.moe_layers else None

    def __call__(self, tokens):
        """(B, S) int tokens → (B, S, V) float32 logits."""
        return self.forward_with_aux(tokens)[0]

    def backbone(self, tokens):
        """(final hidden states (B, S, d) pre-logits, MoE aux loss) —
        the forward minus the tied-logits projection, so losses can
        choose how (or whether) to materialize logits."""
        cdt = jnp.dtype(self.compute_dtype)
        x = _embed(self, tokens, cdt)

        def block_fn(x, blk, moe):
            out, _, moe_aux = _block_apply(
                x, blk, cdt,
                lambda y, b: (self._attention(y, b), None),
                moe=moe,
                mm_fn=model_mm(self),
            )
            return out, moe_aux

        if self.remat:
            block_fn = remat_wrap(block_fn, self.remat_policy)
        aux = jnp.float32(0)
        for i, blk in enumerate(self.blocks):
            x, moe_aux = block_fn(x, blk, self._moe(i))
            aux = aux + moe_aux
        return x, aux

    def forward_with_aux(self, tokens):
        """(logits (B, S, V) f32, total MoE load-balance aux loss)."""
        x, aux = self.backbone(tokens)
        cdt = jnp.dtype(self.compute_dtype)
        return _tied_logits(x, self.embed, cdt), aux

    @staticmethod
    def create(
        key,
        vocab: int = 256,
        max_seq: int = 512,
        dim: int = 256,
        depth: int = 4,
        num_heads: int = 8,
        ff_mult: int = 4,
        seq_mode: str = "local",
        mesh=None,
        seq_axis: str = "data",
        compute_dtype: str = "float32",
        moe_every: int = 0,
        num_experts: int = 8,
        capacity_factor: float = 1.25,
        pos_encoding: str = "learned",
        num_kv_heads: int = 0,
    ) -> "TransformerLM":
        """``moe_every=k`` replaces the dense FFN of every k-th block with
        a top-2 routed :class:`~keystone_tpu.ops.moe.MoELayer` of
        ``num_experts`` experts (0 = dense everywhere).
        ``pos_encoding="rope"`` drops the learned table (and its max_seq
        cap) for rotary q/k phases."""
        if pos_encoding not in ("learned", "rope"):
            raise ValueError(
                f"pos_encoding={pos_encoding!r}; expected learned|rope"
            )
        if pos_encoding == "rope" and (dim // num_heads) % 2:
            raise ValueError(
                f"rope needs an even head dim; got dim/num_heads = "
                f"{dim}/{num_heads} = {dim // num_heads}"
            )
        kvh = num_kv_heads or num_heads
        if kvh <= 0 or num_heads % kvh:
            raise ValueError(
                f"num_heads={num_heads} not divisible by "
                f"num_kv_heads={kvh}"
            )
        # canonical static field: 0 means MHA, so kvh == num_heads
        # normalizes to 0 (num_kv_heads=H and =0 are the same model)
        num_kv_heads = 0 if kvh == num_heads else kvh
        kv_dim = kvh * (dim // num_heads)
        # the split count and per-block stride must not depend on
        # moe_every: dense models seeded before MoE existed must keep
        # bit-identical weights, so MoE keys are folded in separately
        keys = jax.random.split(key, 2 + 6 * depth)

        def init(k, shape, fan_in):
            return jax.random.normal(k, shape, jnp.float32) / math.sqrt(fan_in)

        blocks = []
        moes = []
        for i in range(depth):
            ks = keys[2 + 6 * i : 8 + 6 * i]
            is_moe = bool(moe_every) and (i + 1) % moe_every == 0
            blocks.append(
                LMBlock(
                    wq=init(ks[0], (dim, dim), dim),
                    wk=init(ks[1], (dim, kv_dim), dim),
                    wv=init(ks[2], (dim, kv_dim), dim),
                    wo=init(ks[3], (dim, dim), dim),
                    # a MoE block's dense FFN is never applied — zero-width
                    # placeholders keep the pytree structure uniform
                    # without dead parameters
                    w1=jnp.zeros((dim, 0), jnp.float32)
                    if is_moe
                    else init(ks[4], (dim, ff_mult * dim), dim),
                    w2=jnp.zeros((0, dim), jnp.float32)
                    if is_moe
                    else init(ks[5], (ff_mult * dim, dim), ff_mult * dim),
                )
            )
            if is_moe:
                from keystone_tpu.ops.moe import MoELayer

                moes.append(
                    MoELayer.create(
                        jax.random.fold_in(key, 1_000_003 + i),
                        dim, ff_mult * dim, num_experts, capacity_factor,
                    )
                )
            else:
                moes.append(None)
        return TransformerLM(
            embed=0.02 * jax.random.normal(keys[0], (vocab, dim)),
            # rope keeps a zero-width placeholder: no table params, no cap
            pos_embed=jnp.zeros((0, dim), jnp.float32)
            if pos_encoding == "rope"
            else 0.02 * jax.random.normal(keys[1], (max_seq, dim)),
            blocks=tuple(blocks),
            num_heads=num_heads,
            seq_mode=seq_mode,
            mesh=mesh,
            seq_axis=seq_axis,
            compute_dtype=compute_dtype,
            moe_layers=tuple(moes) if moe_every else (),
            pos_encoding=pos_encoding,
            num_kv_heads=num_kv_heads,
        )

    def num_params(self) -> int:
        return sum(
            int(np.prod(leaf.shape)) for leaf in jax.tree_util.tree_leaves(self)
        )


def remat_wrap(fn, policy: str):
    """``jax.checkpoint`` under the model's remat policy (shared by the
    layer loop and the pipeline-parallel stage chain)."""
    if policy == "full":
        return jax.checkpoint(fn)
    if policy == "dots":
        return jax.checkpoint(
            fn,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    raise ValueError(f"remat_policy={policy!r}; expected full|dots")


def has_quantized_leaves(model) -> bool:
    """True if any leaf is an int8 :class:`QTensor` (a serving model —
    training must reject it: gradients through rounding are silently 0)."""
    return any(
        isinstance(l, QTensor)
        for l in jax.tree_util.tree_leaves(
            model, is_leaf=lambda x: isinstance(x, QTensor)
        )
    )


def train_step_flops(model: TransformerLM, batch: int, seq: int) -> float:
    """Analytic FLOPs of one train step: ~6·P_active·tokens for the matmul
    work plus the attention score/value terms (12·L·d·S²·B fwd+bwd). MoE
    expert gemms execute over ALL E·C static capacity slots (drops included
    — that's the static-shape trade), so expert params count at C/G weight,
    not the idealized 2/E."""
    p = model.num_params()
    tokens = batch * seq
    for m in model.moe_layers:
        if m is not None:
            expert_p = int(np.prod(m.w1.shape)) + int(np.prod(m.w2.shape))
            slots = m.num_experts * m._capacity(tokens)
            p -= expert_p * (1.0 - min(slots / (tokens * m.num_experts), 1.0))
    d = model.embed.shape[-1]
    attn = 12 * len(model.blocks) * d * seq * seq * batch
    return 6.0 * p * tokens + attn
