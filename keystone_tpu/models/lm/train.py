"""Training for the transformer LM: the jitted dp/tp step, the GPipe
pipeline-parallel step, the checkpointed loop, and corpora.

One buffer-donated XLA program per step is the design rule (the idiom the
framework's solvers use: one launch per step, no host round-trips), with
preemption-safe orbax checkpointing whose resumed trajectory is exactly
the uninterrupted one — batches derive from ``(seed, step)``, never from
sequential RNG state.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
import optax

from keystone_tpu.core.logging import get_logger
from keystone_tpu.models.lm.losses import (
    next_token_loss,
    token_cross_entropy,
)
from keystone_tpu.models.lm.model import (
    TransformerLM,
    _block_apply,
    _embed,
    _tied_logits,
    has_quantized_leaves,
)

logger = get_logger("keystone_tpu.models.lm_transformer")


def pp_forward(model: TransformerLM, tokens, mesh, *, n_micro: int,
               axis: str = "model", data_axis: str | None = None):
    """Pipeline-parallel forward: the block chain runs as GPipe stages
    over the mesh ``axis`` (one group of ``depth/n_stages`` blocks per
    device, microbatches streamed via ppermute —
    :func:`keystone_tpu.parallel.pipeline_parallel.gpipe`), embedding and
    tied logits replicated outside the pipe. Completes the LM's
    parallelism matrix (dp × tp × sp × ep × pp). Dense blocks only (MoE
    routing wants the expert axis, not the stage axis); parameters stay
    replicated in HBM — pp here parallelizes compute, the memory story
    is remat + the other axes.
    """
    import jax.numpy as jnp

    if any(m is not None for m in model.moe_layers):
        raise ValueError(
            "pipeline-parallel path supports dense blocks only (route "
            "experts over the model axis with moe_every instead)"
        )
    if model.seq_mode != "local":
        raise ValueError(
            "pipeline-parallel path requires seq_mode='local': the "
            f"{model.seq_mode!r} attention opens its own shard_map, which "
            "cannot nest inside the pipeline's"
        )
    n_stages = mesh.shape[axis]
    depth = len(model.blocks)
    if depth % n_stages:
        raise ValueError(
            f"depth {depth} not divisible by {n_stages} pipeline stages"
        )
    b = tokens.shape[0]
    if b % n_micro:
        raise ValueError(
            f"batch {b} not divisible by n_micro={n_micro}"
        )
    per = depth // n_stages
    cdt = jnp.dtype(model.compute_dtype)
    x = _embed(model, tokens, cdt)
    # pre-split microbatches HERE: gpipe's n_micro reshape heuristic is
    # ambiguous when B == n_micro (it would mistake (B, S, d) for an
    # already-microbatched (n_micro, S, d))
    x = x.reshape(n_micro, b // n_micro, *x.shape[1:])

    # stack the per-block pytrees: leading axis depth → (stages, per)
    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *model.blocks
    )
    stacked = jax.tree_util.tree_map(
        lambda l: l.reshape(n_stages, per, *l.shape[1:]), stacked
    )

    def stage_fn(stage_params, act):
        for j in range(per):
            blk = jax.tree_util.tree_map(lambda l: l[j], stage_params)
            act = _block_apply(
                act, blk, cdt,
                lambda y, bb: (model._attention(y, bb), None),
            )[0]
        return act

    if model.remat:
        from keystone_tpu.models.lm.model import remat_wrap

        stage_fn = remat_wrap(stage_fn, model.remat_policy)
    from keystone_tpu.parallel.pipeline_parallel import gpipe

    out = gpipe(stage_fn, stacked, x, mesh, axis=axis, data_axis=data_axis)
    out = out.reshape(b, *out.shape[2:])
    return _tied_logits(out, model.embed, cdt)


def next_token_loss_pp(model: TransformerLM, tokens, mesh, *,
                       n_micro: int, axis: str = "model",
                       data_axis: str | None = None):
    """Next-token CE through the GPipe forward (differentiable: scan,
    ppermute, and psum all have transposes — the backward is the reverse
    pipeline schedule, derived by AD rather than hand-scheduled)."""
    logits = pp_forward(
        model, tokens[:, :-1], mesh, n_micro=n_micro, axis=axis,
        data_axis=data_axis,
    )
    return token_cross_entropy(logits, tokens[:, 1:])


def make_pp_train_step(optimizer, mesh, *, n_micro: int,
                       axis: str = "model",
                       data_axis: str | None = None):
    """Buffer-donated jitted pipeline-parallel train step. ``data_axis``
    composes dp × pp: each data-row of devices pipelines its own batch
    slice (grad psums across rows come from XLA's sharding propagation —
    params are replicated over the data axis)."""

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(model, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda m, t: next_token_loss_pp(
                m, t, mesh, n_micro=n_micro, axis=axis,
                data_axis=data_axis,
            )
        )(model, tokens)
        updates, opt_state = optimizer.update(
            grads, opt_state, params=model
        )
        model = optax.apply_updates(model, updates)
        return model, opt_state, loss

    return step


def make_train_step(optimizer, *, logit_chunk: int = 0):
    """One buffer-donated jitted program: grads + AdamW update + loss.
    ``logit_chunk`` chunks the CE so the (B, S, V) f32 logits never
    materialize (the long-context memory/bandwidth lever — see
    :func:`keystone_tpu.models.lm.losses.chunked_token_cross_entropy`)."""

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(model, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            functools.partial(next_token_loss, logit_chunk=logit_chunk)
        )(model, tokens)
        updates, opt_state = optimizer.update(
            grads, opt_state, params=model
        )
        model = optax.apply_updates(model, updates)
        return model, opt_state, loss

    return step


def _step_batch(corpus, seed: int, i: int, batch: int, seq: int):
    """Step ``i``'s token windows, derived from ``(seed, i)`` alone — no
    sequential RNG state, so a resumed run regenerates the exact batch
    sequence an uninterrupted run would have seen."""
    rng = np.random.default_rng(np.random.SeedSequence((seed, i)))
    starts = rng.integers(0, len(corpus) - seq - 1, size=batch)
    return np.stack([corpus[s : s + seq + 1] for s in starts])


def make_optimizer(
    lr: float,
    *,
    steps: int = 0,
    schedule: str = "constant",
    warmup_frac: float = 0.05,
    grad_clip: float = 0.0,
    weight_decay: float = 0.01,
):
    """The LM training optimizer: AdamW, optionally behind global-norm
    gradient clipping, with a constant or warmup-cosine learning rate.
    ``schedule="cosine"`` warms up over ``warmup_frac`` of ``steps`` and
    decays to lr/10 — the standard LM recipe."""
    if schedule not in ("constant", "cosine"):
        raise ValueError(
            f"schedule={schedule!r}; expected constant|cosine"
        )
    if schedule == "cosine":
        if steps <= 0:
            raise ValueError("schedule='cosine' needs the total steps")
        lr = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=lr,
            warmup_steps=max(1, int(steps * warmup_frac)),
            decay_steps=steps,
            end_value=lr / 10.0,
        )
    opt = optax.adamw(lr, weight_decay=weight_decay)
    if grad_clip > 0.0:
        opt = optax.chain(optax.clip_by_global_norm(grad_clip), opt)
    return opt


def train(
    model: TransformerLM,
    corpus: np.ndarray,
    *,
    steps: int,
    batch: int,
    seq: int,
    lr: float = 3e-4,
    mesh=None,
    seed: int = 0,
    log_every: int = 0,
    checkpoint_dir: str = "",
    checkpoint_every: int = 0,
    schedule: str = "constant",
    grad_clip: float = 0.0,
    logit_chunk: int = 0,
):
    """Train on random windows of ``corpus`` (1-D int array). Returns
    (model, losses). Batches are dp-sharded over the mesh ``data`` axis
    unless the model is sequence-parallel (then S is the sharded axis and
    the batch is replicated).

    ``checkpoint_dir`` makes the run preemption-safe: model + optimizer
    state are orbax-checkpointed every ``checkpoint_every`` steps (default
    0 = ``steps // 10``, ~10 checkpoints per run), and a rerun with the
    same arguments resumes from the last completed step on the *identical*
    trajectory — batches are derived per-step from ``(seed, i)``, not from
    sequential RNG state (the LM analog of the solvers' ``resumable_fit``).
    ``losses`` covers only the steps this invocation ran. Note:
    ``schedule="cosine"`` derives its decay horizon from THIS invocation's
    ``steps`` — resuming with a longer schedule is allowed (steps are not
    run identity) but stretches the cosine rather than replaying the
    original horizon. ``logit_chunk`` chunks the CE — equivalent to the
    dense loss up to FP reduction order, which is exactly why it IS part
    of the run identity (a resume must not silently change the low bits
    of the trajectory).
    """
    import hashlib

    import jax.numpy as jnp

    from keystone_tpu.parallel.mesh import data_sharding

    if len(corpus) < seq + 2:
        raise ValueError(
            f"corpus of {len(corpus)} tokens is too short for seq={seq} "
            f"(needs at least seq+2 = {seq + 2}); shorten --seq or grow "
            "the corpus"
        )
    if has_quantized_leaves(model):
        raise ValueError(
            "model holds int8 QTensor weights (quantize_for_decode is "
            "inference-only) — gradients through the rounding would be "
            "silently zero; train the float model and re-quantize"
        )
    optimizer = make_optimizer(
        lr, steps=steps, schedule=schedule, grad_clip=grad_clip
    )
    opt_state = optimizer.init(model)
    step = make_train_step(optimizer, logit_chunk=logit_chunk)
    losses = []
    sharding = None
    if (
        mesh is not None
        and model.seq_mode == "local"
        and batch % mesh.shape.get("data", 1) == 0
    ):
        sharding = data_sharding(mesh, ndim=2)

    ckpt = None
    start = 0
    if checkpoint_dir:
        from keystone_tpu.core.checkpoint import TrainCheckpointer

        # default cadence: ~10 checkpoints per run, not one per step — a
        # jitted LM step is milliseconds while a synchronous full-state
        # orbax save is not (resumable_fit's every=1 default amortizes
        # over whole BCD passes, a much coarser unit)
        every = checkpoint_every or max(steps // 10, 1)
        corpus_head = np.asarray(corpus[:64], np.int64)
        ckpt = TrainCheckpointer(
            checkpoint_dir,
            # `steps` is deliberately absent (resuming with a longer
            # schedule is the point — the over-trained guard below covers
            # the short case), mirroring resumable_fit's num_iter rule.
            # Everything else that shapes the trajectory is here: a
            # param-shape match alone would silently accept a different
            # model function (num_heads, dtype policy, seq_mode...)
            {
                "kind": "lm_transformer",
                "batch": batch,
                "seq": seq,
                "lr": lr,
                "seed": seed,
                "schedule": schedule,
                "grad_clip": grad_clip,
                "logit_chunk": logit_chunk,
                "num_heads": model.num_heads,
                # normalized (kv_heads, never the 0 alias) so MHA spelled
                # either way compares equal
                "num_kv_heads": model.kv_heads,
                "seq_mode": model.seq_mode,
                "compute_dtype": model.compute_dtype,
                "pos_encoding": model.pos_encoding,
                "remat": model.remat,
                "remat_policy": model.remat_policy,
                "moe_aux_weight": model.moe_aux_weight,
                "moe_experts": [
                    None if m is None else m.num_experts
                    for m in model.moe_layers
                ],
                "moe_capacity": [
                    None if m is None else m.capacity_factor
                    for m in model.moe_layers
                ],
                "corpus_len": int(len(corpus)),
                "corpus_head_sha": hashlib.sha256(
                    corpus_head.tobytes()
                ).hexdigest()[:16],
                "param_shapes": [
                    list(map(int, leaf.shape))
                    for leaf in jax.tree_util.tree_leaves(model)
                ],
            },
            # keys added after checkpoints already existed in the wild:
            # an older sidecar without them must compare as the value the
            # code used at the time, not brick the resume
            legacy_defaults={
                "pos_encoding": "learned",
                "schedule": "constant",
                "grad_clip": 0.0,
                # pre-chunked-CE checkpoints were all dense
                "logit_chunk": 0,
                # pre-policy checkpoints always full-rematerialized
                "remat_policy": "full",
                # pre-GQA checkpoints were all MHA
                "num_kv_heads": model.num_heads,
            },
        )
    try:
        if ckpt is not None:
            (model, opt_state), start = ckpt.restore((model, opt_state))
            if start > steps:
                raise ValueError(
                    f"{checkpoint_dir} holds a step-{start} checkpoint but "
                    f"this run is only {steps} steps — refusing to return "
                    "an over-trained model; point at a fresh directory"
                )
        for i in range(start, steps):
            toks = jnp.asarray(_step_batch(corpus, seed, i, batch, seq))
            if sharding is not None:
                toks = jax.device_put(toks, sharding)
            model, opt_state, loss = step(model, opt_state, toks)
            # keep the loss on device: a float() here would block a host
            # round-trip into every step and serialize the dispatch queue
            losses.append(loss)
            if log_every and (i + 1) % log_every == 0:
                logger.info("step %d loss %.4f", i + 1, float(loss))
            if ckpt is not None and (
                (i + 1) % every == 0 or (i + 1) == steps
            ):
                ckpt.save((model, opt_state), i + 1)
    finally:
        if ckpt is not None:
            ckpt.close()
    return model, [float(l) for l in losses]


def synthetic_corpus(n: int, vocab: int, seed: int = 0) -> np.ndarray:
    """A learnable-but-not-trivial token stream: an order-1 Markov chain
    with a sparse, deterministic-ish transition structure."""
    rng = np.random.default_rng(seed)
    succ = rng.integers(0, vocab, size=(vocab, 4))
    probs = np.array([0.7, 0.15, 0.1, 0.05])
    out = np.empty(n, np.int32)
    out[0] = 0
    choices = rng.choice(4, size=n, p=probs)
    for i in range(1, n):
        out[i] = succ[out[i - 1], choices[i]]
    return out
