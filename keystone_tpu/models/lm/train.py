"""Training for the transformer LM: the jitted dp/tp step, the GPipe
pipeline-parallel step, the checkpointed loop, and corpora.

One buffer-donated XLA program per step is the design rule (the idiom the
framework's solvers use: one launch per step, no host round-trips), with
preemption-safe orbax checkpointing whose resumed trajectory is exactly
the uninterrupted one — batches derive from ``(seed, step)``, never from
sequential RNG state.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
import optax

from keystone_tpu.core.logging import get_logger
from keystone_tpu.models.lm.losses import (
    next_token_loss,
    token_cross_entropy,
)
from keystone_tpu.models.lm.model import (
    TransformerLM,
    _block_apply,
    _embed,
    _tied_logits,
    has_quantized_leaves,
    train_step_flops,
)

logger = get_logger("keystone_tpu.models.lm_transformer")


def pp_forward(model: TransformerLM, tokens, mesh, *, n_micro: int,
               axis: str = "model", data_axis: str | None = None):
    """Pipeline-parallel forward: the block chain runs as GPipe stages
    over the mesh ``axis`` (one group of ``depth/n_stages`` blocks per
    device, microbatches streamed via ppermute —
    :func:`keystone_tpu.parallel.pipeline_parallel.gpipe`), embedding and
    tied logits replicated outside the pipe. Completes the LM's
    parallelism matrix (dp × tp × sp × ep × pp). Dense blocks only (MoE
    routing wants the expert axis, not the stage axis); parameters stay
    replicated in HBM — pp here parallelizes compute, the memory story
    is remat + the other axes.
    """
    import jax.numpy as jnp

    if any(m is not None for m in model.moe_layers):
        raise ValueError(
            "pipeline-parallel path supports dense blocks only (route "
            "experts over the model axis with moe_every instead)"
        )
    if model.seq_mode != "local":
        raise ValueError(
            "pipeline-parallel path requires seq_mode='local': the "
            f"{model.seq_mode!r} attention opens its own shard_map, which "
            "cannot nest inside the pipeline's"
        )
    n_stages = mesh.shape[axis]
    depth = len(model.blocks)
    if depth % n_stages:
        raise ValueError(
            f"depth {depth} not divisible by {n_stages} pipeline stages"
        )
    b = tokens.shape[0]
    if b % n_micro:
        raise ValueError(
            f"batch {b} not divisible by n_micro={n_micro}"
        )
    per = depth // n_stages
    cdt = jnp.dtype(model.compute_dtype)
    x = _embed(model, tokens, cdt)
    # pre-split microbatches HERE: gpipe's n_micro reshape heuristic is
    # ambiguous when B == n_micro (it would mistake (B, S, d) for an
    # already-microbatched (n_micro, S, d))
    x = x.reshape(n_micro, b // n_micro, *x.shape[1:])

    # stack the per-block pytrees: leading axis depth → (stages, per)
    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *model.blocks
    )
    stacked = jax.tree_util.tree_map(
        lambda l: l.reshape(n_stages, per, *l.shape[1:]), stacked
    )

    def stage_fn(stage_params, act):
        for j in range(per):
            blk = jax.tree_util.tree_map(lambda l: l[j], stage_params)
            act = _block_apply(
                act, blk, cdt,
                lambda y, bb: (model._attention(y, bb), None),
            )[0]
        return act

    if model.remat:
        from keystone_tpu.models.lm.model import remat_wrap

        stage_fn = remat_wrap(stage_fn, model.remat_policy)
    from keystone_tpu.parallel.pipeline_parallel import gpipe

    out = gpipe(stage_fn, stacked, x, mesh, axis=axis, data_axis=data_axis)
    out = out.reshape(b, *out.shape[2:])
    return _tied_logits(out, model.embed, cdt)


def next_token_loss_pp(model: TransformerLM, tokens, mesh, *,
                       n_micro: int, axis: str = "model",
                       data_axis: str | None = None):
    """Next-token CE through the GPipe forward (differentiable: scan,
    ppermute, and psum all have transposes — the backward is the reverse
    pipeline schedule, derived by AD rather than hand-scheduled)."""
    logits = pp_forward(
        model, tokens[:, :-1], mesh, n_micro=n_micro, axis=axis,
        data_axis=data_axis,
    )
    return token_cross_entropy(logits, tokens[:, 1:])


def make_pp_train_step(optimizer, mesh, *, n_micro: int,
                       axis: str = "model",
                       data_axis: str | None = None):
    """Buffer-donated jitted pipeline-parallel train step. ``data_axis``
    composes dp × pp: each data-row of devices pipelines its own batch
    slice (grad psums across rows come from XLA's sharding propagation —
    params are replicated over the data axis)."""

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(model, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda m, t: next_token_loss_pp(
                m, t, mesh, n_micro=n_micro, axis=axis,
                data_axis=data_axis,
            )
        )(model, tokens)
        updates, opt_state = optimizer.update(
            grads, opt_state, params=model
        )
        model = optax.apply_updates(model, updates)
        return model, opt_state, loss

    return step


def make_train_step(
    optimizer, *, logit_chunk: int = 0, guarded: bool = False,
    skip_nonfinite: bool = True,
):
    """One buffer-donated jitted program: grads + AdamW update + loss.
    ``logit_chunk`` chunks the CE so the (B, S, V) f32 logits never
    materialize (the long-context memory/bandwidth lever — see
    :func:`keystone_tpu.models.lm.losses.chunked_token_cross_entropy`).

    ``guarded=True`` returns the poison-aware variant
    ``step(model, opt_state, tokens, poison)``: ``poison`` (scalar
    bool) NaNs the loss *and* grads for deterministic fault injection —
    multiplicative, so the unpoisoned path is bit-identical to itself
    across runs. With ``skip_nonfinite=True`` (a guard mode is on) the
    update is additionally applied only where the loss is finite (a
    leafwise ``where`` select — with buffer donation the pre-update
    state is unrecoverable on the host, so skip-batch MUST be decided
    in-program); with it False an injected NaN corrupts exactly what a
    real bad batch would. Still one XLA launch per step."""
    if not guarded:

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(model, opt_state, tokens):
            loss, grads = jax.value_and_grad(
                functools.partial(next_token_loss, logit_chunk=logit_chunk)
            )(model, tokens)
            updates, opt_state = optimizer.update(
                grads, opt_state, params=model
            )
            model = optax.apply_updates(model, updates)
            return model, opt_state, loss

        return step

    import jax.numpy as jnp

    from keystone_tpu.resilience.guards import guarded_update

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def guarded_step(model, opt_state, tokens, poison):
        def lossfn(m, t):
            loss = next_token_loss(m, t, logit_chunk=logit_chunk)
            # poison scales rather than adds so the backward pass NaNs
            # too — an injected bad batch corrupts exactly what a real
            # one would
            return loss * jnp.where(
                poison, jnp.float32(np.nan), jnp.float32(1.0)
            )

        loss, grads = jax.value_and_grad(lossfn)(model, tokens)
        updates, new_opt = optimizer.update(
            grads, opt_state, params=model
        )
        new_model = optax.apply_updates(model, updates)
        if skip_nonfinite:
            ok = jnp.isfinite(loss)
            new_model = guarded_update(ok, new_model, model)
            new_opt = guarded_update(ok, new_opt, opt_state)
        return new_model, new_opt, loss

    return guarded_step


def _step_batch(corpus, seed: int, i: int, batch: int, seq: int):
    """Step ``i``'s token windows, derived from ``(seed, i)`` alone — no
    sequential RNG state, so a resumed run regenerates the exact batch
    sequence an uninterrupted run would have seen."""
    rng = np.random.default_rng(np.random.SeedSequence((seed, i)))
    starts = rng.integers(0, len(corpus) - seq - 1, size=batch)
    return np.stack([corpus[s : s + seq + 1] for s in starts])


def make_optimizer(
    lr: float,
    *,
    steps: int = 0,
    schedule: str = "constant",
    warmup_frac: float = 0.05,
    grad_clip: float = 0.0,
    weight_decay: float = 0.01,
):
    """The LM training optimizer: AdamW, optionally behind global-norm
    gradient clipping, with a constant or warmup-cosine learning rate.
    ``schedule="cosine"`` warms up over ``warmup_frac`` of ``steps`` and
    decays to lr/10 — the standard LM recipe."""
    if schedule not in ("constant", "cosine"):
        raise ValueError(
            f"schedule={schedule!r}; expected constant|cosine"
        )
    if schedule == "cosine":
        if steps <= 0:
            raise ValueError("schedule='cosine' needs the total steps")
        lr = optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=lr,
            warmup_steps=max(1, int(steps * warmup_frac)),
            decay_steps=steps,
            end_value=lr / 10.0,
        )
    opt = optax.adamw(lr, weight_decay=weight_decay)
    if grad_clip > 0.0:
        opt = optax.chain(optax.clip_by_global_norm(grad_clip), opt)
    return opt


def train(
    model: TransformerLM,
    corpus: np.ndarray,
    *,
    steps: int,
    batch: int,
    seq: int,
    lr: float = 3e-4,
    mesh=None,
    seed: int = 0,
    log_every: int = 0,
    checkpoint_dir: str = "",
    checkpoint_every: int = 0,
    schedule: str = "constant",
    grad_clip: float = 0.0,
    logit_chunk: int = 0,
    guard=None,
    step_timeout_s: float = 0.0,
):
    """Train on random windows of ``corpus`` (1-D int array). Returns
    (model, losses). Batches are dp-sharded over the mesh ``data`` axis
    unless the model is sequence-parallel (then S is the sharded axis and
    the batch is replicated).

    ``checkpoint_dir`` makes the run preemption-safe: model + optimizer
    state are orbax-checkpointed every ``checkpoint_every`` steps (default
    0 = ``steps // 10``, ~10 checkpoints per run), and a rerun with the
    same arguments resumes from the last completed step on the *identical*
    trajectory — batches are derived per-step from ``(seed, i)``, not from
    sequential RNG state (the LM analog of the solvers' ``resumable_fit``).
    ``losses`` covers only the steps this invocation ran. Note:
    ``schedule="cosine"`` derives its decay horizon from THIS invocation's
    ``steps`` — resuming with a longer schedule is allowed (steps are not
    run identity) but stretches the cosine rather than replaying the
    original horizon. ``logit_chunk`` chunks the CE — equivalent to the
    dense loss up to FP reduction order, which is exactly why it IS part
    of the run identity (a resume must not silently change the low bits
    of the trajectory).

    Resilience (see :mod:`keystone_tpu.resilience`):

    - ``guard`` — a ``GuardConfig``, a mode string (``"skip"``/
      ``"halt"``), or None (→ the ``KEYSTONE_GUARD`` env default).
      ``skip`` leaves model+optimizer untouched on a non-finite-loss
      step (decided in-program — donation-safe); ``halt`` additionally
      stops at the next interval check and returns the last
      checkpointed state. Guard state syncs the loss window once per
      ``check_every`` steps, never per step.
    - with ``checkpoint_dir`` set, SIGTERM/SIGINT checkpoint the last
      completed step and return early, and every exit path attempts a
      final checkpoint in ``finally`` — a clean break (signal,
      preemption, a host-side exception between steps) loses at most
      the in-flight step. A hard device failure can poison the live
      buffers mid-step; the rescue save then fails (logged, never
      masking the original error) and the run falls back to the last
      periodic checkpoint.
    - ``step_timeout_s`` (or ``KEYSTONE_STEP_TIMEOUT_S``) arms a
      watchdog that logs thread stacks when a step stops completing;
      ``KEYSTONE_STEP_ESCALATE=N`` additionally hard-aborts the process
      after N consecutive stalls so a supervisor can replace it.
    - on a multihost run with an active cluster monitor
      (:mod:`keystone_tpu.resilience.cluster`), every completed step is
      reported to the heartbeat thread, checkpoint saves are
      coordinated behind a membership barrier, and a declared host loss
      exits the loop with :class:`HostLostError` on the last periodic
      checkpoint (the coordinated rescue save is impossible with a dead
      peer) — the run supervisor relaunches on the survivor set.
    - fault sites ``train.nan`` / ``train.preempt`` / ``train.sigterm``
      / ``cluster.host_kill`` (``KEYSTONE_FAULTS``, keyed by step index
      so schedules survive resume) inject each failure
      deterministically.
    """
    import hashlib
    import os as _os
    import signal as _signal
    import threading as _threading
    import time as _time

    import jax.numpy as jnp

    from keystone_tpu.observe import devices as _observe_devices
    from keystone_tpu.observe import spans as _spans
    from keystone_tpu.observe import telemetry as _telemetry
    from keystone_tpu.observe import tracing as _tracing
    from keystone_tpu.parallel.mesh import data_sharding
    from keystone_tpu.resilience import cluster as _cluster
    from keystone_tpu.resilience import faults as _faults
    from keystone_tpu.resilience.retry import RetryExhausted
    from keystone_tpu.resilience.guards import (
        LossGuard,
        NumericalHealthError,
        resolve_guard,
    )

    if len(corpus) < seq + 2:
        raise ValueError(
            f"corpus of {len(corpus)} tokens is too short for seq={seq} "
            f"(needs at least seq+2 = {seq + 2}); shorten --seq or grow "
            "the corpus"
        )
    if has_quantized_leaves(model):
        raise ValueError(
            "model holds int8 QTensor weights (quantize_for_decode is "
            "inference-only) — gradients through the rounding would be "
            "silently zero; train the float model and re-quantize"
        )
    guard_cfg = resolve_guard(guard)
    plan = _faults.active()
    # the guarded step is a DIFFERENT compiled program (poison arg, and
    # the update select only under an actual guard mode — an injected
    # NaN with no guard must corrupt like the real thing); build it
    # only when asked, so the default hot loop is untouched
    skip_nonfinite = guard_cfg.mode != "off"
    guarded = skip_nonfinite or (
        plan is not None and plan.has_site("train.nan")
    )
    optimizer = make_optimizer(
        lr, steps=steps, schedule=schedule, grad_clip=grad_clip
    )
    opt_state = optimizer.init(model)
    step = make_train_step(
        optimizer, logit_chunk=logit_chunk, guarded=guarded,
        skip_nonfinite=skip_nonfinite,
    )
    losses = []
    sharding = None
    if (
        mesh is not None
        and model.seq_mode == "local"
        and batch % mesh.shape.get("data", 1) == 0
    ):
        sharding = data_sharding(mesh, ndim=2)

    ckpt = None
    start = 0
    try:
        _nprocs = jax.process_count()
    except Exception:  # noqa: BLE001 — backend init failure
        _nprocs = 1
    if checkpoint_dir:
        from keystone_tpu.core.checkpoint import TrainCheckpointer

        # default cadence: ~10 checkpoints per run, not one per step — a
        # jitted LM step is milliseconds while a synchronous full-state
        # orbax save is not (resumable_fit's every=1 default amortizes
        # over whole BCD passes, a much coarser unit)
        every = checkpoint_every or max(steps // 10, 1)
        corpus_head = np.asarray(corpus[:64], np.int64)
        ckpt = TrainCheckpointer(
            checkpoint_dir,
            # `steps` is deliberately absent (resuming with a longer
            # schedule is the point — the over-trained guard below covers
            # the short case), mirroring resumable_fit's num_iter rule.
            # Everything else that shapes the trajectory is here: a
            # param-shape match alone would silently accept a different
            # model function (num_heads, dtype policy, seq_mode...)
            {
                "kind": "lm_transformer",
                "batch": batch,
                "seq": seq,
                "lr": lr,
                "seed": seed,
                "schedule": schedule,
                "grad_clip": grad_clip,
                "logit_chunk": logit_chunk,
                # the guarded step is a different program; like
                # logit_chunk it may move low bits, so it IS run
                # identity. False = plain step, "inject" = poison arg
                # only, "skip" = poison + non-finite update select
                "guarded": (
                    False if not guarded
                    else ("skip" if skip_nonfinite else "inject")
                ),
                "num_heads": model.num_heads,
                # normalized (kv_heads, never the 0 alias) so MHA spelled
                # either way compares equal
                "num_kv_heads": model.kv_heads,
                "seq_mode": model.seq_mode,
                "compute_dtype": model.compute_dtype,
                "pos_encoding": model.pos_encoding,
                "remat": model.remat,
                "remat_policy": model.remat_policy,
                "moe_aux_weight": model.moe_aux_weight,
                "moe_experts": [
                    None if m is None else m.num_experts
                    for m in model.moe_layers
                ],
                "moe_capacity": [
                    None if m is None else m.capacity_factor
                    for m in model.moe_layers
                ],
                "corpus_len": int(len(corpus)),
                "corpus_head_sha": hashlib.sha256(
                    corpus_head.tobytes()
                ).hexdigest()[:16],
                "param_shapes": [
                    list(map(int, leaf.shape))
                    for leaf in jax.tree_util.tree_leaves(model)
                ],
            },
            # informational, EXCLUDED from the identity check: the
            # host set at save time, so the supervisor / a re-meshed
            # resume can see what the checkpoint was written by
            cluster_info={
                "num_processes": _nprocs,
                "mesh": (
                    {k: int(v) for k, v in mesh.shape.items()}
                    if mesh is not None
                    else None
                ),
            },
            # keys added after checkpoints already existed in the wild:
            # an older sidecar without them must compare as the value the
            # code used at the time, not brick the resume
            legacy_defaults={
                "pos_encoding": "learned",
                "schedule": "constant",
                "grad_clip": 0.0,
                # pre-chunked-CE checkpoints were all dense
                "logit_chunk": 0,
                # pre-resilience checkpoints all ran the plain step
                "guarded": False,
                # pre-policy checkpoints always full-rematerialized
                "remat_policy": "full",
                # pre-GQA checkpoints were all MHA
                "num_kv_heads": model.num_heads,
            },
        )
    if step_timeout_s <= 0:
        step_timeout_s = float(
            _os.environ.get("KEYSTONE_STEP_TIMEOUT_S", "0") or 0
        )
    loss_guard = LossGuard(guard_cfg)
    # first signal → flag only; the loop checks it each step and the
    # finally path checkpoints, so SIGTERM/SIGINT lose at most the
    # in-flight step. A SECOND signal means the loop isn't getting back
    # to its check (a wedged step): restore the previous dispositions
    # and re-deliver so repeat Ctrl-C / SIGTERM actually escalates.
    stop_signal: dict = {"sig": None}
    prev_handlers: dict = {}
    if ckpt is not None and _threading.current_thread() is _threading.main_thread():
        def _on_signal(signum, frame):
            if stop_signal["sig"] is not None:
                for s, h in prev_handlers.items():
                    _signal.signal(s, h)
                prev = prev_handlers.get(signum)
                if callable(prev):
                    prev(signum, frame)
                else:
                    _signal.raise_signal(signum)
                return
            stop_signal["sig"] = signum

        for s in (_signal.SIGTERM, _signal.SIGINT):
            prev_handlers[s] = _signal.signal(s, _on_signal)

    dog = None
    if step_timeout_s > 0:
        from keystone_tpu.resilience.watchdog import Watchdog

        # created here, STARTED after the first step completes: the
        # first iteration includes jit compilation, which would
        # otherwise guarantee a spurious stall report on every run.
        # KEYSTONE_STEP_ESCALATE=N hard-aborts after N consecutive
        # stalls — a wedged main thread would otherwise heartbeat
        # forever from the cluster monitor's daemon thread
        escalate = int(
            _os.environ.get("KEYSTONE_STEP_ESCALATE", "0") or 0
        )
        dog = Watchdog(
            step_timeout_s,
            label="lm_train",
            escalate_after=escalate if escalate > 0 else None,
        )

    # live telemetry (observe/telemetry.py): per-step loss / tokens-per-s
    # / MFU into steps.jsonl whenever an observe sink is active, HBM
    # watermark sampling, and programmatic profiler windows
    # (KEYSTONE_PROFILE_STEPS / SIGUSR2). With no sink and no windows the
    # per-step cost is one global read (active_step_log) plus one no-op
    # tracer check.
    step_flops = train_step_flops(model, batch, seq)
    devmon = _observe_devices.DeviceMemoryMonitor()
    # the self-tuning controller (KEYSTONE_TUNE=1): per-step host-vs-
    # compute walls + token goodput feed its rolling attribution window.
    # tune_active is the cheap gate — no plan import on untuned runs.
    from keystone_tpu.core.staging import tune_active as _tune_active

    tuner = _tune_active()
    tracer = _tracing.StepTracer.from_env(
        install_signal=(
            _threading.current_thread() is _threading.main_thread()
        ),
        label="lm_train",
    )

    completed = last_saved = 0
    halted = False
    cluster_lost = False
    # one trace for the whole training run: every step/checkpoint span
    # shares it, so `observe trace` renders the loop as one causal unit
    import uuid as _uuid

    _train_trace = "train-" + _uuid.uuid4().hex[:8]
    try:
        if ckpt is not None:
            with _spans.span(
                "train.restore", bucket="checkpoint", trace=_train_trace
            ):
                (model, opt_state), start = ckpt.restore((model, opt_state))
            if start > steps:
                raise ValueError(
                    f"{checkpoint_dir} holds a step-{start} checkpoint but "
                    f"this run is only {steps} steps — refusing to return "
                    "an over-trained model; point at a fresh directory"
                )
        completed = last_saved = start
        for i in range(start, steps):
            if tracer is not None:
                tracer.step(i)
            t_step0 = _time.perf_counter()
            toks = jnp.asarray(_step_batch(corpus, seed, i, batch, seq))
            if sharding is not None:
                toks = jax.device_put(toks, sharding)
            t_host = _time.perf_counter() - t_step0
            if guarded:
                poison = _faults.fire("train.nan", key=i)
                model, opt_state, loss = step(
                    model, opt_state, toks, poison
                )
            else:
                model, opt_state, loss = step(model, opt_state, toks)
            # keep the loss on device: a float() here would block a host
            # round-trip into every step and serialize the dispatch queue
            # (exception: an active telemetry sink reads the scalar below
            # — that host read IS the live stream's cost, and it makes
            # the recorded per-step wall honest under async dispatch)
            losses.append(loss)
            completed = i + 1
            _cluster.note_step(completed)
            steplog = _telemetry.active_step_log()
            if steplog is not None or tuner is not None:
                # the float() below is the one per-step host sync the
                # live stream (and honest tuner walls) pays — measure
                # the wall AFTER it so the recorded step time is honest
                # under async dispatch
                loss_f = float(loss)
                wall = _time.perf_counter() - t_step0
                if tuner is not None:
                    # host-batch vs dispatched-compute attribution +
                    # token goodput for the self-tuning window
                    tuner.observe(
                        rows=batch * seq,
                        buckets={
                            "wait_host": t_host,
                            "compute": max(wall - t_host, 0.0),
                        },
                    )
            if steplog is not None:
                steplog.step(
                    step=i + 1,
                    loss=loss_f,
                    tokens=batch * seq,
                    wall_s=wall,
                    flops=step_flops,
                    hbm_peak_bytes=devmon.maybe_sample(),
                )
                # the step's causal record: host-side batch production
                # vs dispatched device work, classified for the goodput
                # report (structural root; children carry the buckets)
                span_log = _spans.active_span_log()
                if span_log is not None:
                    s_ctx = span_log.record_span(
                        "train.step",
                        wall_s=wall,
                        trace=_train_trace,
                        step=i + 1,
                    )
                    span_log.record_span(
                        "train.host_batch",
                        wall_s=t_host,
                        bucket="wait_host",
                        parent=s_ctx,
                    )
                    span_log.record_span(
                        "train.compute",
                        wall_s=max(wall - t_host, 0.0),
                        bucket="compute",
                        parent=s_ctx,
                    )
            # one host sync per check interval, not per step
            loss_guard.note(i, loss)
            if dog is not None:
                dog.pet() if dog.running else dog.start()
            if log_every and (i + 1) % log_every == 0:
                logger.info("step %d loss %.4f", i + 1, float(loss))
            if _faults.fire("cluster.host_kill", key=i):
                # a dying machine checkpoints nothing, flushes nothing,
                # cleans up nothing — SIGKILL models exactly that; the
                # survivors' failure detector and the run supervisor
                # take it from here (fires BEFORE the periodic save so
                # the drill actually loses in-interval steps)
                logger.warning(
                    "cluster.host_kill fault at step %d: killing this "
                    "process", i
                )
                _os.kill(_os.getpid(), _signal.SIGKILL)
            lost = _cluster.check_lost()
            if lost is not None:
                # exit BEFORE the periodic save: a coordinated save
                # with a known-dead peer can only time out at the
                # barrier
                raise _cluster.HostLostError(lost)
            if ckpt is not None and (
                (i + 1) % every == 0 or (i + 1) == steps
            ):
                try:
                    with _spans.span(
                        "train.checkpoint",
                        bucket="checkpoint",
                        trace=_train_trace,
                        step=i + 1,
                    ):
                        ckpt.save((model, opt_state), i + 1)
                    last_saved = i + 1
                except (OSError, RetryExhausted) as e:
                    # a full disk / exhausted IO retries at a PERIODIC
                    # save must not kill hours of training: the previous
                    # checkpoint is intact (atomic save), so degrade
                    # loudly and try again next interval — the risk
                    # window widens by one interval, the run survives.
                    # (A coordinated-barrier failure is a membership
                    # problem, not an IO one — ClusterBarrierError still
                    # propagates above.)
                    logger.warning(
                        "periodic checkpoint save at step %d failed "
                        "(%r); continuing on the step-%d checkpoint",
                        i + 1,
                        e,
                        last_saved,
                    )
                    _emit_resilience(
                        "ckpt_save_failed",
                        counter="ckpt_save_failures",
                        step=i + 1,
                        last_saved=last_saved,
                        error=repr(e),
                    )
            if _faults.fire("train.sigterm", key=i):
                if prev_handlers:
                    # a REAL signal to this process: exercises the
                    # handler path end to end, not a shortcut around it
                    _signal.raise_signal(_signal.SIGTERM)
                else:
                    # no handler installed (no checkpoint_dir, or not
                    # the main thread): a real SIGTERM would just kill
                    # the process — that tests nothing about us
                    logger.warning(
                        "train.sigterm fault fired at step %d but no "
                        "handler is installed; ignoring", i
                    )
            if stop_signal["sig"] is not None:
                logger.warning(
                    "signal %d at step %d: writing final checkpoint and "
                    "stopping early",
                    stop_signal["sig"],
                    i + 1,
                )
                _emit_resilience(
                    "signal_stop", signum=stop_signal["sig"], step=i + 1
                )
                break
            _faults.maybe_preempt(key=i)
        loss_guard.flush()
    except _cluster.ClusterError as e:
        # a lost peer makes the coordinated rescue save impossible (its
        # barrier would wait on the dead host) — exit cleanly on the
        # last periodic checkpoint, at most one checkpoint interval
        # behind; the supervisor re-meshes and resumes from there
        cluster_lost = True
        logger.warning(
            "training stopped by cluster membership change at step %d: "
            "%s", completed, e,
        )
        _emit_resilience("host_lost_exit", step=completed, error=repr(e))
        raise
    except NumericalHealthError as e:
        # halt-with-last-good-checkpoint: training is unhealthy; return
        # the last checkpointed state rather than the post-spike one
        halted = True
        logger.warning("training halted by health guard: %s", e)
        _emit_resilience("guard_halt", step=completed, error=repr(e))
        if ckpt is None:
            raise
        (model, opt_state), restored = ckpt.restore((model, opt_state))
        if restored == 0:
            # nothing was ever checkpointed (saves start at step >= 1):
            # there is no "last good" state to return — restore() just
            # handed back the live post-spike template, so propagate
            raise
        losses = losses[: max(restored - start, 0)]
    finally:
        try:
            if (
                ckpt is not None
                and completed > last_saved
                and not halted
                and not cluster_lost
            ):
                # preemption / signal / crash path: the loop's periodic
                # save didn't cover the last completed step — write it
                # now so at most the in-flight step is lost
                with _spans.span(
                    "train.checkpoint",
                    bucket="checkpoint",
                    trace=_train_trace,
                    step=completed,
                    rescue=True,
                ):
                    ckpt.save((model, opt_state), completed)
                _emit_resilience("final_checkpoint", step=completed)
        except Exception:  # noqa: BLE001 — a failed rescue save must
            # not mask the original exception (the preemption itself)
            logger.exception(
                "final checkpoint save at step %d failed", completed
            )
        finally:
            if ckpt is not None:
                ckpt.close()
            if dog is not None:
                dog.stop()
            if tracer is not None:
                tracer.close()
            for s, h in prev_handlers.items():
                _signal.signal(s, h)
    if loss_guard.skipped:
        logger.warning(
            "guard skipped %d non-finite step(s): %s",
            len(loss_guard.skipped),
            loss_guard.skipped,
        )
    return model, [float(l) for l in losses]


def _emit_resilience(action: str, **fields) -> None:
    from keystone_tpu.resilience.emit import decision

    decision(action, **fields)


def synthetic_corpus(n: int, vocab: int, seed: int = 0) -> np.ndarray:
    """A learnable-but-not-trivial token stream: an order-1 Markov chain
    with a sparse, deterministic-ish transition structure."""
    rng = np.random.default_rng(seed)
    succ = rng.integers(0, vocab, size=(vocab, 4))
    probs = np.array([0.7, 0.15, 0.1, 0.05])
    out = np.empty(n, np.int32)
    out[0] = 0
    choices = rng.choice(4, size=n, p=probs)
    for i in range(1, n):
        out[i] = succ[out[i - 1], choices[i]]
    return out
