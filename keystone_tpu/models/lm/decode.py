"""KV-cache serving for the transformer LM: prefill → decode → sample.

Static shapes are the design rule throughout — the whole generate loop
compiles to ONE program (prefill + a lax.scan of decode steps) with
in-place `dynamic_update_slice` cache writes, no retracing as the
sequence grows. Weight-only int8 (:func:`quantize_for_decode`) and the
int8 KV cache attack the two HBM streams that bound decode rate on TPU:
the parameters and, at long context, the cache itself.

The reference serves f64 BLAS models and has no autoregressive path;
this module is beyond-reference serving capability (SURVEY §5).
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp

from keystone_tpu.core.treenode import treenode
from keystone_tpu.models.lm.model import (
    LMBlock,
    TransformerLM,
    _block_apply,
    _embed,
    _gather_embed,
    _tied_logits,
    model_mm,
)
from keystone_tpu.ops.quantization import quantize_int8


@treenode
class KVCache:
    """Preallocated decode cache: static (L, B, KV_heads, S_max, hd)
    buffers (KV_heads < num_heads under GQA — that ratio IS the cache
    saving) plus the number of valid positions. Static shapes are the point — the whole
    generate loop compiles to ONE program (prefill + a lax.scan of decode
    steps) with in-place `dynamic_update_slice` writes, no retracing as
    the sequence grows (the XLA analog of the reference's nothing: it has
    no autoregressive models).

    With ``kv_dtype="int8"`` the buffers hold per-position symmetric int8
    with (L, B, KV_heads, S_max, 1) scales: at long context the cache, not the
    weights, dominates each decode step's HBM reads, and the scales pull
    OUT of both dots exactly (scores = (q·k_q^T)·scale_k; out =
    (p·scale_v)·v_q), so nothing dequantized ever materializes."""

    k: jnp.ndarray
    v: jnp.ndarray
    pos: jnp.ndarray  # scalar int32
    k_scale: jnp.ndarray | None = None
    v_scale: jnp.ndarray | None = None


def _kv_quant(t):
    """(..., hd) → (int8 codes, f32 scale (..., 1)) per-position — the
    shared symmetric recipe pooling over the head dim."""
    from keystone_tpu.ops.quantization import symmetric_int8

    return symmetric_int8(t, (-1,))


def prefill(model: TransformerLM, tokens, s_max: int,
            kv_dtype: str | None = None, lengths=None):
    """Run the prompt through the model once, capturing per-layer K/V into
    an ``s_max``-long cache (optionally int8 — see :class:`KVCache`).
    Returns (last-position logits (B, V), cache). Local attention only
    (sequence-parallel decode shards the cache — use ring/Ulysses for
    training, gather to local for decode).

    ``lengths`` ((B,) int32) admits a batch of unequal-length prompts
    right-padded to a common width: logits are gathered at each
    sequence's own last real token (``lengths - 1``) and the cache comes
    back with a *per-sequence* ``pos`` vector, so decode resumes each
    row at its own position. Causal attention already keeps right-pad
    K/V out of every real token's view, and decode overwrites the pad
    region before its positions ever become valid — no mask plumbing
    needed (the positions past ``pos`` are excluded by
    :func:`decode_step`'s validity mask)."""
    if model.seq_mode != "local":
        raise ValueError("prefill/decode require seq_mode='local'")
    if kv_dtype not in (None, "int8"):
        raise ValueError(f"kv_dtype={kv_dtype!r}; expected None|'int8'")
    cdt = jnp.dtype(model.compute_dtype)
    n, s = tokens.shape
    x = _embed(model, tokens, cdt)

    ks, vs = [], []
    for i, blk in enumerate(model.blocks):
        x, (k, v), _ = _block_apply(
            x, blk, cdt,
            lambda y, b: model._attention(y, b, return_kv=True),
            moe=model._moe(i),
            mm_fn=model_mm(model),
        )
        ks.append(k)
        vs.append(v)
    if lengths is None:
        logits = _tied_logits(x[:, -1:], model.embed, cdt)[:, 0]
        pos = jnp.asarray(s, jnp.int32)
    else:
        lengths = jnp.asarray(lengths, jnp.int32)
        last = jnp.take_along_axis(
            x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1
        )  # (B, 1, d) — each row's own final real token
        logits = _tied_logits(last, model.embed, cdt)[:, 0]
        pos = lengths
    pad = [(0, 0), (0, 0), (0, s_max - s), (0, 0)]
    k_stack = jnp.stack([jnp.pad(k, pad) for k in ks])
    v_stack = jnp.stack([jnp.pad(v, pad) for v in vs])
    if kv_dtype == "int8":
        kq, ksc = _kv_quant(k_stack)
        vq, vsc = _kv_quant(v_stack)
        cache = KVCache(k=kq, v=vq, pos=pos, k_scale=ksc, v_scale=vsc)
    else:
        cache = KVCache(k=k_stack, v=v_stack, pos=pos)
    return logits, cache


def decode_step(model: TransformerLM, token, cache: KVCache):
    """One autoregressive step: (B,) token at position ``cache.pos`` →
    ((B, V) logits, updated cache). Attention reads the full static-shape
    cache with positions ≥ pos masked — compiler-friendly in exchange for
    O(S_max) work per step.

    ``cache.pos`` may be the classic scalar (every row at the same
    position — one in-place 5-D slice write per buffer, the cheapest
    path, kept bit-identical) or a **(B,) vector**: each row decodes at
    its own position, which is what continuous batching needs — slots
    join and retire independently, so the pool's rows are never aligned.
    The vector path writes via a one-hot select over the position axis
    (O(S_max) per layer — the same order as the attention read that
    follows, so nothing asymptotically new)."""
    cdt = jnp.dtype(model.compute_dtype)
    d = model.embed.shape[-1]
    h = model.num_heads
    hd = d // h
    n = token.shape[0]
    pos = cache.pos
    s_cap = cache.k.shape[3]
    vec = getattr(pos, "ndim", 0) >= 1  # per-row positions
    x = _gather_embed(model.embed, token)[:, None] * math.sqrt(d)
    if model.pos_encoding == "learned":
        if vec:
            x = x + jnp.take(model.pos_embed, pos, axis=0)[:, None]
        else:
            x = x + jax.lax.dynamic_slice_in_dim(model.pos_embed, pos, 1)
    x = x.astype(cdt)

    if vec:
        valid = (jnp.arange(s_cap)[None, :] <= pos[:, None])[:, None, None, :]
        hit = (jnp.arange(s_cap)[None, :] == pos[:, None])[:, None, :, None]
    else:
        valid = (jnp.arange(s_cap) <= pos)[None, None, None, :]
    quantized = cache.k_scale is not None
    new_k, new_v = cache.k, cache.v
    new_ks, new_vs = cache.k_scale, cache.v_scale

    kvh = model.kv_heads
    g = h // kvh  # query heads per K/V head (1 = plain MHA)

    def write(buf, i, val):
        """Write the (B, KV_heads, 1, *) new-position slab into layer
        ``i`` of a (L, B, KV_heads, S_max, *) buffer at ``pos``."""
        if not vec:
            return jax.lax.dynamic_update_slice(
                buf, val[None].astype(buf.dtype), (i, 0, 0, pos, 0)
            )
        layer = jnp.where(hit, val.astype(buf.dtype), buf[i])
        return jax.lax.dynamic_update_slice(buf, layer[None], (i, 0, 0, 0, 0))

    def cached_attn(i):
        def attn(y, blk):
            nonlocal new_k, new_v, new_ks, new_vs
            # the shared split+rope helper, at the new token's global
            # position; cached keys were stored rotated by prefill /
            # earlier steps
            q, k1, v1 = model._qkv_heads(
                y, blk, positions=pos[:, None] if vec else pos[None]
            )
            if quantized:
                k1, k1s = _kv_quant(k1)
                v1, v1s = _kv_quant(v1)
                new_ks = write(new_ks, i, k1s)
                new_vs = write(new_vs, i, v1s)
            # one 5-D in-place update per buffer — not gather + rewrite,
            # which XLA may lower to an O(L·S_max) cache copy per layer
            new_k = write(new_k, i, k1)
            new_v = write(new_v, i, v1)
            layer_k, layer_v = new_k[i], new_v[i]
            # grouped attention (MHA is the g=1 special case): q heads
            # regroup as (KV, G) against the KV-head cache — no repeated
            # K/V ever materializes, which is GQA's decode point
            qg = q.reshape(n, kvh, g, 1, hd).astype(cdt)
            scores = jnp.einsum(
                "bkgqd,bksd->bkgqs", qg, layer_k.astype(cdt),
                preferred_element_type=jnp.float32,
            ) / math.sqrt(hd)
            if quantized:
                # per-position scales pull out of the contraction exactly
                scores = scores * new_ks[i][..., 0][:, :, None, None, :]
            scores = jnp.where(valid[:, :, None], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            if quantized:
                probs = probs * new_vs[i][..., 0][:, :, None, None, :]
            out = jnp.einsum(
                "bkgqs,bksd->bkgqd", probs.astype(cdt),
                layer_v.astype(cdt),
                preferred_element_type=jnp.float32,
            )
            proj = mm_fn(
                out.reshape(n, h, 1, hd).transpose(0, 2, 1, 3).reshape(
                    n, 1, d
                ).astype(cdt),
                blk.wo,
                cdt,
            )
            return proj, None

        return attn

    mm_fn = model_mm(model)
    for i, blk in enumerate(model.blocks):
        x, _, _ = _block_apply(
            x, blk, cdt, cached_attn(i), moe=model._moe(i), mm_fn=mm_fn
        )
    logits = _tied_logits(x, model.embed, cdt)[:, 0]
    # past-capacity poison: at pos >= S_max the cache write would clamp
    # onto S_max-1 and return plausible-but-wrong logits; pos is traced,
    # so the honest device-side failure is loud NaNs, not an exception
    in_cap = (pos < s_cap)[:, None] if vec else pos < s_cap
    logits = jnp.where(in_cap, logits, jnp.nan)
    return logits, KVCache(
        k=new_k, v=new_v, pos=pos + 1, k_scale=new_ks, v_scale=new_vs
    )


def _filter_logits(logits, top_k: int, top_p: float):
    """Top-k then nucleus filtering on (B, V) logits (already temperature
    -scaled — the nucleus mass is meaningful only on the distribution
    actually sampled): everything outside the keep-set drops to -inf.
    Static-shape throughout, one descending sort shared by both filters.
    """
    v = logits.shape[-1]
    if top_k < 0 or top_k > v:
        raise ValueError(f"top_k={top_k} outside [0, vocab={v}]")
    sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
    if top_k:
        kth = sorted_l[:, top_k - 1][:, None]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
        # the nucleus below must see the top-k-filtered distribution
        sorted_l = jnp.where(
            jnp.arange(v)[None, :] < top_k, sorted_l, -jnp.inf
        )
    if top_p:
        probs = jax.nn.softmax(sorted_l, axis=-1)
        # exclusive cumulative mass BEFORE each token: a token stays while
        # the mass above it is < top_p (the first token always stays)
        csum = jnp.cumsum(probs, axis=-1) - probs
        keep = csum < top_p
        # smallest kept logit per row = the threshold
        thresh = jnp.min(
            jnp.where(keep, sorted_l, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits >= thresh, logits, -jnp.inf)
    return logits


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_new", "temperature", "top_k", "top_p", "kv_dtype", "eos_id"
    ),
)
def generate(
    model: TransformerLM,
    prompt,
    *,
    max_new: int,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    kv_dtype: str | None = None,
    key=None,
    prompt_lens=None,
    eos_id: int | None = None,
):
    """Greedy (temperature=0) or sampled decode of ``max_new`` tokens after
    ``prompt`` (B, P). One jitted program: prefill + lax.scan over steps.
    ``top_k``/``top_p`` (nucleus) restrict sampling to the head of the
    distribution (0 = off; both compose); ``kv_dtype="int8"`` halves the
    cache stream at long context (see :class:`KVCache`). Returns
    (B, max_new) int32.

    ``prompt_lens`` ((B,) int32) admits unequal-length prompts
    right-padded to ``P``: each row's first pick comes from its own last
    real token and decode continues at its own position (per-row cache
    positions — see :func:`prefill` / :func:`decode_step`). ``eos_id``
    arms per-sequence early exit: a row that emits EOS is frozen (its
    remaining output is EOS-filled) and the whole loop stops — still one
    compiled program, as a ``lax.while_loop`` with a dynamic trip count
    — as soon as every row has finished, so a batch of short answers
    never pays ``max_new`` steps. With both arguments left at their
    defaults the program is the original scan, bit-identical."""
    if key is None:
        key = jax.random.key(0)
    s_max = prompt.shape[1] + max_new
    if model.pos_encoding == "learned" and s_max > model.pos_embed.shape[0]:
        raise ValueError(
            f"prompt+max_new={s_max} exceeds max_seq={model.pos_embed.shape[0]}"
        )
    if prompt_lens is not None:
        prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
    logits0, cache = prefill(
        model, prompt, s_max, kv_dtype=kv_dtype, lengths=prompt_lens
    )

    def pick(logits, k):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # temperature FIRST: the nucleus cut must measure mass on the
        # distribution being sampled, not the unscaled one
        logits = _filter_logits(logits / temperature, top_k, top_p)
        return jax.random.categorical(k, logits).astype(jnp.int32)

    keys = jax.random.split(key, max_new)
    tok0 = pick(logits0, keys[0])
    if max_new == 1:
        return tok0[:, None]

    if eos_id is not None:
        # early-exit decode: a while_loop whose trip count is data-
        # dependent — per-step keys via fold_in (a scan's pre-split keys
        # can't be indexed ahead of a dynamic counter as cheaply)
        out0 = jnp.full(
            (prompt.shape[0], max_new), eos_id, jnp.int32
        ).at[:, 0].set(tok0)

        def cond(c):
            i, _, _, done, _ = c
            return (i < max_new) & ~jnp.all(done)

        def body(c):
            i, tok, cache, done, out = c
            logits, cache2 = decode_step(model, tok, cache)
            tok2 = pick(logits, jax.random.fold_in(key, i))
            tok2 = jnp.where(done, eos_id, tok2)
            out = jax.lax.dynamic_update_slice(out, tok2[:, None], (0, i))
            return (i + 1, tok2, cache2, done | (tok2 == eos_id), out)

        carry = (
            jnp.asarray(1, jnp.int32), tok0, cache, tok0 == eos_id, out0
        )
        return jax.lax.while_loop(cond, body, carry)[4]

    # scan max_new-1 steps: the token for step i is picked from step i-1's
    # logits, so the final logits need no decode step of their own
    def step(carry, k):
        tok, cache = carry
        logits, cache2 = decode_step(model, tok, cache)
        tok2 = pick(logits, k)
        return (tok2, cache2), tok2

    (_, _), rest = jax.lax.scan(step, (tok0, cache), keys[1:])
    return jnp.concatenate([tok0[:, None], rest.T], axis=1)  # (B, max_new)


def quantize_for_decode(model: TransformerLM) -> TransformerLM:
    """Weight-only int8 quantization for serving: every block matrix gets
    symmetric per-output-channel int8 (``ops/quantization.py``), the tied
    embedding per-row scales (serving both the gather and the logit
    transpose). Decode is HBM-bound — every step re-reads all params — so
    halving the weight stream is the decode-rate lever on TPU. Inference
    only: ``train`` rejects quantized models (gradients through rounding
    are silently zero). MoE experts and pos_embed stay full precision
    (experts want per-(expert, channel) scales; the table is tiny)."""

    def qmat(w):
        return quantize_int8(w) if w.size else w

    blocks = tuple(
        LMBlock(
            wq=qmat(b.wq), wk=qmat(b.wk), wv=qmat(b.wv), wo=qmat(b.wo),
            w1=qmat(b.w1), w2=qmat(b.w2),
        )
        for b in model.blocks
    )
    return dataclasses.replace(
        model,
        embed=quantize_int8(model.embed, channel_axis=0),
        blocks=blocks,
    )
