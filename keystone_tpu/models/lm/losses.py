"""Next-token losses for the transformer LM.

The numerically sensitive ``logsumexp − gold`` form lives ONCE here
(:func:`token_cross_entropy`); the chunked variant reduces the CE in
S-chunks so the (B, S, V) f32 logits never materialize — at long
context that tensor is the step's single largest HBM object
(S=16k × V=32k f32 = 2.1 GB, twice more with its gradient).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from keystone_tpu.models.lm.model import TransformerLM, _tied_logits


def token_cross_entropy(logits, targets) -> jnp.ndarray:
    """Mean next-token cross-entropy. logits: (B, S, V) f32; targets:
    (B, S) int. The single source of the numerically sensitive
    ``logsumexp - gold`` form, shared by training loss and evaluation."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)


def chunked_token_cross_entropy(x, embed, targets, cdt, chunk: int):
    """Mean next-token CE from final hidden states without ever holding
    the (B, S, V) f32 logits: positions are processed in S-chunks — each
    chunk's logits are built, reduced to ``logsumexp − gold``, and
    dropped (``jax.checkpoint`` recomputes them in the backward),
    turning the full logits tensor into a ``chunk`` × V working set."""
    b, s, d = x.shape
    if chunk <= 0 or s % chunk:
        raise ValueError(
            f"logit_chunk={chunk} must be a positive divisor of the "
            f"sequence length {s}"
        )
    n_c = s // chunk
    xc = x.reshape(b, n_c, chunk, d).transpose(1, 0, 2, 3)
    tc = targets.reshape(b, n_c, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk_sum(xx, tt):
        logits = _tied_logits(xx, embed, cdt)  # (B, chunk, V) f32
        # token_cross_entropy stays the single source of the CE form;
        # mean × count turns it back into this chunk's sum exactly
        return token_cross_entropy(logits, tt) * tt.size

    total, _ = jax.lax.scan(
        lambda c, args: (c + chunk_sum(*args), None),
        jnp.float32(0),
        (xc, tc),
    )
    return total / (b * s)


def next_token_loss(
    model: TransformerLM, tokens, logit_chunk: int = 0
) -> jnp.ndarray:
    """Mean cross-entropy of predicting ``tokens[:, 1:]`` from the prefix
    (the model runs on the first S tokens of an S+1 window), plus the
    weighted MoE load-balance auxiliary when the model routes.
    ``logit_chunk > 0`` computes the CE in S-chunks so the full (B, S, V)
    f32 logits never materialize (see chunked_token_cross_entropy)."""
    if logit_chunk:
        cdt = jnp.dtype(model.compute_dtype)
        x, aux = model.backbone(tokens[:, :-1])
        ce = chunked_token_cross_entropy(
            x, model.embed, tokens[:, 1:], cdt, logit_chunk
        )
        return ce + model.moe_aux_weight * aux
    logits, aux = model.forward_with_aux(tokens[:, :-1])
    ce = token_cross_entropy(logits, tokens[:, 1:])
    return ce + model.moe_aux_weight * aux
