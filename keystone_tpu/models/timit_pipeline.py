"""TIMIT phoneme classification
(reference ``pipelines/speech/TimitPipeline.scala``):
440-dim pre-featurized frames → ``num_cosines`` batches of 4096 cosine
random features (gaussian or cauchy W), each standard-scaled → block least
squares over the feature batches with ``num_epochs`` BCD passes → argmax →
multiclass eval (147 classes)."""

from __future__ import annotations

import dataclasses
import time

import jax
import numpy as np

from keystone_tpu.core.config import arg, parse_config
from keystone_tpu.core.logging import get_logger
from keystone_tpu.core.pipeline import Pipeline, Transformer
from keystone_tpu.core.treenode import treenode
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.loaders.labeled import LabeledData
from keystone_tpu.loaders.timit import NUM_CLASSES, TIMIT_DIMENSION, load_timit_split
from keystone_tpu.ops.linear import BlockLeastSquaresEstimator
from keystone_tpu.ops.stats import CosineRandomFeatures, StandardScaler
from keystone_tpu.ops.util import ClassLabelIndicators, MaxClassifier
from keystone_tpu.parallel.mesh import create_mesh, shard_batch

logger = get_logger("keystone_tpu.models.timit")


@dataclasses.dataclass
class TimitConfig:
    """TIMIT workload (reference TimitConfig: 50 batches x 4096 cosine
    features, gamma 0.0555, 5 epochs)."""

    train_data_location: str = arg(default="")
    train_labels_location: str = arg(default="")
    test_data_location: str = arg(default="")
    test_labels_location: str = arg(default="")
    num_cosines: int = arg(default=50, help="number of 4096-wide batches")
    cosine_features: int = arg(default=4096)
    gamma: float = arg(default=0.05555)
    rf_type: str = arg(default="gaussian", choices=("gaussian", "cauchy"))
    lam: float = arg(default=0.0)
    lam_sweep: str = arg(
        default="",
        help="comma-separated λ list: ridge path at shared-Gram cost, "
        "selected on a held-out 10%% of train, refit at the winner "
        "(overrides --lam)",
    )
    num_epochs: int = arg(default=5)
    checkpoint_dir: str = arg(
        default="",
        help="if set, checkpoint the solver between BCD epochs and "
        "resume from this directory (reference setCheckpointDir, "
        "TimitPipeline.scala:34,38)",
    )
    checkpoint_every: int = arg(
        default=1,
        help="BCD epochs per checkpoint chunk (higher amortizes the "
        "per-chunk Gram recomputation)",
    )
    seed: int = arg(default=123)
    synthetic: int = arg(default=0, help="if > 0, N synthetic frames")


@treenode
class ScaledCosineBank(Transformer):
    """The full TIMIT featurizer as one row-wise Transformer: every
    (cosine features → standard scaler) chain applied to the batch,
    returning the list of (N, cosine_features) blocks — the shape the
    block solver consumes. Being a treenode lets the planner's
    fused-fit rule absorb the whole bank into the streaming
    normal-equations sink (one jitted chunk step, blocks never
    corpus-resident)."""

    chains: tuple  # of Pipeline(featurizer >> fitted scaler)

    def __call__(self, batch):
        return [chain(batch) for chain in self.chains]


def _load(conf: TimitConfig, which: str) -> LabeledData:
    if conf.synthetic:
        n = conf.synthetic if which == "train" else max(conf.synthetic // 5, 1)
        rng = np.random.default_rng(0 if which == "train" else 1)
        k = min(NUM_CLASSES, 12)
        labels = rng.integers(0, k, size=n).astype(np.int32)
        centers = np.random.default_rng(42).normal(
            size=(k, TIMIT_DIMENSION)
        )
        data = (centers[labels] * 2 + rng.normal(size=(n, TIMIT_DIMENSION))).astype(
            np.float32
        )
        return LabeledData(labels=labels, data=data)
    if which == "train":
        return load_timit_split(
            conf.train_data_location, conf.train_labels_location
        )
    return load_timit_split(conf.test_data_location, conf.test_labels_location)


def run(conf: TimitConfig, mesh=None) -> dict:
    if mesh is None and len(jax.devices()) > 1:
        mesh = create_mesh()
    t0 = time.perf_counter()
    train, test = _load(conf, "train"), _load(conf, "test")
    n_train, n_test = len(train), len(test)

    keys = jax.random.split(jax.random.key(conf.seed), conf.num_cosines)
    featurizers = [
        CosineRandomFeatures.create(
            TIMIT_DIMENSION,
            conf.cosine_features,
            keys[i],
            gamma=conf.gamma,
            distribution=conf.rf_type,
        )
        for i in range(conf.num_cosines)
    ]

    x_train = shard_batch(train.data, mesh)
    x_test = shard_batch(test.data, mesh)

    from keystone_tpu import plan as plan_mod

    # KEYSTONE_PLAN: the fit streams chunks through featurize+scale+
    # accumulate fused (plan/fused_fit.py) — the corpus-wide block list
    # (num_cosines × 4096 × N, the big resident object of the classic
    # path) is never materialized. Scalers still need their one pass
    # over each block's raw features, but each block is dropped as soon
    # as its scaler is fitted. The λ-sweep and the between-epoch
    # checkpoint protocol both consume resident blocks — those runs
    # keep the classic path.
    streamed_fit = plan_mod.enabled() and not (
        conf.lam_sweep or conf.checkpoint_dir
    )
    apply_node = jax.jit(lambda node, b: node(b))
    # per-batch cosine features, standard-scaled (fit on train)
    train_blocks, scalers = [], []
    for f in featurizers:
        raw = apply_node(f, x_train)
        scaler = StandardScaler().fit(raw, n_valid=n_train)
        scalers.append(scaler)
        if not streamed_fit:
            train_blocks.append(apply_node(scaler, raw))
        del raw

    y = np.zeros(x_train.shape[0], np.int32)
    y[:n_train] = train.labels
    indicators = ClassLabelIndicators(num_classes=NUM_CLASSES)(y)
    t_feat = time.perf_counter()

    lam = conf.lam
    if conf.lam_sweep:
        from keystone_tpu.evaluation.model_selection import (
            holdout_lambda_sweep,
        )

        # selection at one BCD pass (like MNIST): cheap relative to the
        # final multi-epoch fit, and the final fit stays under the
        # --checkpoint-dir preemption protection
        report = holdout_lambda_sweep(
            BlockLeastSquaresEstimator(
                block_size=conf.cosine_features, num_iter=1
            ),
            train_blocks,
            indicators,
            y,
            conf.lam_sweep,
            n_train=n_train,
            num_classes=NUM_CLASSES,
        )
        lam = report["best_lam"]
        logger.info(
            "lambda sweep %s -> val errors %s; refitting at best lam=%g",
            report["lams"],
            [round(e, 4) for e in report["val_errors"]],
            lam,
        )
    est = BlockLeastSquaresEstimator(
        block_size=conf.cosine_features, num_iter=conf.num_epochs, lam=lam
    )
    bank = ScaledCosineBank(
        chains=tuple(
            Pipeline.of(f, s) for f, s in zip(featurizers, scalers)
        )
    )
    if streamed_fit:
        from keystone_tpu.core.pipeline import ChainedLabelEstimator

        fitted = plan_mod.fit_streaming(
            ChainedLabelEstimator(prefix=bank, est=est),
            x_train,
            indicators,
            n_valid=n_train,
            mesh=mesh,
        )
        model = jax.block_until_ready(fitted[-1])
    else:
        from keystone_tpu.core.checkpoint import checkpointed_fit

        model = jax.block_until_ready(
            checkpointed_fit(
                est,
                train_blocks,
                indicators,
                checkpoint_dir=conf.checkpoint_dir,
                every=conf.checkpoint_every,
                n_valid=n_train,
            )
        )
    t_fit = time.perf_counter()

    classify = MaxClassifier()
    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    score = jax.jit(lambda b: model(bank(b)))
    # classic path: the blocks are already resident — don't re-featurize
    train_scores = (
        score(x_train) if streamed_fit else model(train_blocks)
    )
    train_eval = evaluator(classify(train_scores), y, n_valid=n_train)

    y_test = np.zeros(x_test.shape[0], np.int32)
    y_test[:n_test] = test.labels
    test_eval = evaluator(
        classify(score(x_test)), y_test, n_valid=n_test
    )

    result = {
        "train_error": train_eval.error,
        "test_error": test_eval.error,
        "n_train": n_train,
        "n_test": n_test,
        "featurize_s": t_feat - t0,
        "fit_s": t_fit - t_feat,
        "total_s": time.perf_counter() - t0,
    }
    logger.info(
        "Timit: train err %.4f, test err %.4f", train_eval.error, test_eval.error
    )
    return result


def main(argv=None) -> dict:
    conf = parse_config(TimitConfig, argv)
    if not conf.synthetic and not conf.train_data_location:
        raise SystemExit("need the four TIMIT locations, or --synthetic N")
    return run(conf)


if __name__ == "__main__":
    main()
