"""Shared descriptor→Fisher-vector branch used by the VOC and ImageNet
pipelines (reference VOCSIFTFisher / ImageNetSiftLcsFV both build
SIFT/LCS → PCA → GMM → FV → vectorize → normalize → hellinger → normalize
chains with per-branch fitted PCA/GMM)."""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from keystone_tpu.core.batching import apply_in_chunks
from keystone_tpu.core.logging import get_logger
from keystone_tpu.core.pipeline import jit_apply as _apply_node
from keystone_tpu.ops.gmm import (
    FisherVector,
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
)
from keystone_tpu.ops.linalg import BatchPCATransformer, compute_pca
from keystone_tpu.ops.stats import (
    NormalizeRows,
    SignedHellingerMapper,
    sample_columns,
)
from keystone_tpu.ops.util import MatrixVectorizer

logger = get_logger("keystone_tpu.models.fisher_common")

# _apply_node is core.pipeline.jit_apply: ONE process-wide jitted apply —
# the node travels as a pytree argument, so new PCA/GMM fits reuse the
# compiled programs, and every other jit_apply user shares the cache


class FisherBranch:
    """Fit (or load) PCA + GMM on a descriptor family and featurize batches
    of (N, d, m) descriptors into normalized Fisher vectors."""

    def __init__(
        self,
        desc_dim: int,
        vocab_size: int,
        num_pca_samples: int,
        num_gmm_samples: int,
        seed: int,
        pca_file: str = "",
        gmm_files: tuple[str, str, str] = ("", "", ""),
    ):
        self.desc_dim = desc_dim
        self.vocab_size = vocab_size
        self.num_pca_samples = num_pca_samples
        self.num_gmm_samples = num_gmm_samples
        self.seed = seed
        self.pca_file = pca_file
        self.gmm_files = gmm_files
        self.pca: BatchPCATransformer | None = None
        self.post = None

    def _fit_pca(self, sample_fn) -> None:
        """Artifact-aware PCA fit; ``sample_fn()`` supplies (n, d) rows."""
        if self.pca_file and os.path.exists(self.pca_file):
            pca_mat = jnp.asarray(
                np.loadtxt(self.pca_file, delimiter=",", ndmin=2), jnp.float32
            )
            logger.info("loaded PCA from %s", self.pca_file)
        else:
            pca_mat = compute_pca(sample_fn(), self.desc_dim)
            if self.pca_file:
                np.savetxt(self.pca_file, np.asarray(pca_mat), delimiter=",")
        self.pca = BatchPCATransformer(pca_mat=pca_mat)

    def _fit_gmm_and_post(self, proj_sample_fn) -> None:
        """Artifact-aware GMM fit + the 5-stage fisher post chain;
        ``proj_sample_fn()`` supplies (n, desc_dim) projected rows."""
        if all(self.gmm_files) and all(
            os.path.exists(f) for f in self.gmm_files
        ):
            gmm = GaussianMixtureModel.load_csv(*self.gmm_files)
            logger.info("loaded GMM from %s", self.gmm_files[0])
        else:
            gmm = GaussianMixtureModelEstimator(k=self.vocab_size).fit(
                proj_sample_fn()
            )
            if all(self.gmm_files):
                gmm.save_csv(*self.gmm_files)
        self.post = (
            FisherVector(gmm=gmm)
            >> MatrixVectorizer()
            >> NormalizeRows()
            >> SignedHellingerMapper()
            >> NormalizeRows()
        )

    def fit(self, train_desc, chunk_size: int, n_valid: int | None = None):
        """Fit PCA/GMM (artifact-aware) and return the projected train
        descriptors (reused by featurize of the training set).

        ``n_valid``: count of real rows when the batch was zero-padded for
        sharding — pad images' all-zero descriptors are excluded from the
        PCA/GMM sample (they would otherwise seed a spurious zero cluster).
        """
        fit_desc = train_desc if n_valid is None else train_desc[:n_valid]
        self._fit_pca(
            lambda: sample_columns(fit_desc, self.num_pca_samples, self.seed)
        )
        projected = apply_in_chunks(
            lambda d: _apply_node(self.pca, d), train_desc, chunk_size
        )
        proj_fit = projected if n_valid is None else projected[:n_valid]
        self._fit_gmm_and_post(
            lambda: sample_columns(
                proj_fit, self.num_gmm_samples, self.seed + 1
            )
        )
        return projected

    def fit_from_samples(self, sample_cols) -> None:
        """Fit PCA + GMM from a bounded descriptor-column sample (n, d) —
        the streaming path: the sample comes from a
        :class:`keystone_tpu.loaders.streaming.ColumnReservoir` filled
        across the corpus instead of from materialized descriptors."""
        sample_cols = jnp.asarray(sample_cols, jnp.float32)
        self._fit_pca(lambda: sample_cols[: self.num_pca_samples])
        self._fit_gmm_and_post(
            lambda: (sample_cols @ self.pca.pca_mat)[
                : self.num_gmm_samples
            ]
        )

    def featurize_projected(self, projected, chunk_size: int):
        return apply_in_chunks(
            lambda d: _apply_node(self.post, d), projected, chunk_size
        )

    def featurize(self, desc, chunk_size: int):
        projected = apply_in_chunks(
            lambda d: _apply_node(self.pca, d), desc, chunk_size
        )
        return self.featurize_projected(projected, chunk_size)
