"""ViT-ridge: frozen random ViT featurizer + ridge solver (the BASELINE
stretch config — the reference's random-features philosophy on a modern
encoder; CIFAR-shaped by default, ImageNet-shaped by flags)."""

from __future__ import annotations

import dataclasses
import time

import jax
import numpy as np

from keystone_tpu.core.batching import apply_in_chunks
from keystone_tpu.core.config import arg, parse_config
from keystone_tpu.core.logging import get_logger
from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.models.cifar_linear_pixels import LinearPixelsConfig, _load
from keystone_tpu.ops.linear import LinearMapEstimator
from keystone_tpu.ops.util import ClassLabelIndicators, MaxClassifier
from keystone_tpu.ops.vit import ViTFeaturizer
from keystone_tpu.parallel.mesh import create_mesh, shard_batch

logger = get_logger("keystone_tpu.models.vit_ridge")

NUM_CLASSES = 10


@dataclasses.dataclass
class ViTRidgeConfig:
    train_location: str = arg(default="", help="CIFAR-10 binary file/dir")
    test_location: str = arg(default="")
    patch_size: int = arg(default=8)
    dim: int = arg(default=128)
    depth: int = arg(default=4)
    num_heads: int = arg(default=4)
    lam: float = arg(default=1.0)
    chunk_size: int = arg(default=512)
    seed: int = arg(default=0)
    synthetic: int = arg(default=0, help="if > 0, N synthetic samples")


def run(conf: ViTRidgeConfig, mesh=None) -> dict:
    if mesh is None and len(jax.devices()) > 1:
        mesh = create_mesh()
    t0 = time.perf_counter()
    lp_conf = LinearPixelsConfig(
        train_location=conf.train_location,
        test_location=conf.test_location,
        synthetic=conf.synthetic,
    )
    train, test = _load(lp_conf, "train"), _load(lp_conf, "test")
    n_train, n_test = len(train), len(test)

    vit = ViTFeaturizer.create(
        jax.random.key(conf.seed),
        image_size=train.images.shape[1],
        patch_size=conf.patch_size,
        dim=conf.dim,
        depth=conf.depth,
        num_heads=conf.num_heads,
    )
    feat_fn = jax.jit(lambda b, v=vit: v(b / 255.0))

    def featurize(images):
        return apply_in_chunks(feat_fn, shard_batch(images, mesh), conf.chunk_size)

    f_train = featurize(train.images)
    t_feat = time.perf_counter()

    y = np.zeros(f_train.shape[0], np.int32)
    y[:n_train] = train.labels
    indicators = ClassLabelIndicators(num_classes=NUM_CLASSES)(y)
    model = LinearMapEstimator(lam=conf.lam).fit(
        f_train, indicators, n_valid=n_train
    )
    t_fit = time.perf_counter()

    classify = MaxClassifier()
    evaluator = MulticlassClassifierEvaluator(NUM_CLASSES)
    train_eval = evaluator(classify(model(f_train)), y, n_valid=n_train)
    f_test = featurize(test.images)
    y_test = np.zeros(f_test.shape[0], np.int32)
    y_test[:n_test] = test.labels
    test_eval = evaluator(classify(model(f_test)), y_test, n_valid=n_test)

    result = {
        "train_error": train_eval.error,
        "test_error": test_eval.error,
        "n_train": n_train,
        "n_test": n_test,
        "featurize_s": t_feat - t0,
        "fit_s": t_fit - t_feat,
        "total_s": time.perf_counter() - t0,
        "featurize_fit_samples_per_s": n_train / (t_fit - t0),
    }
    logger.info(
        "ViTRidge: train err %.4f, test err %.4f, %.0f samples/s",
        train_eval.error,
        test_eval.error,
        result["featurize_fit_samples_per_s"],
    )
    return result


def main(argv=None) -> dict:
    conf = parse_config(ViTRidgeConfig, argv)
    if not conf.synthetic and not (conf.train_location and conf.test_location):
        raise SystemExit("need --train-location AND --test-location, or --synthetic N")
    return run(conf)


if __name__ == "__main__":
    main()
