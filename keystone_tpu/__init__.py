"""keystone_tpu — a TPU-native large-scale ML pipeline framework.

A from-scratch JAX/XLA/Pallas rebuild of the capabilities of KeystoneML
(nkhuyu/keystone): typed dataflow pipelines of Transformer / Estimator /
LabelEstimator nodes, a distributed block-coordinate least-squares solver
layer, image / speech / text featurization ops, loaders, and evaluators —
re-designed TPU-first:

- data parallelism = arrays sharded over a ``jax.sharding.Mesh`` "data" axis
  (the moral successor of Spark RDD partitions),
- model/feature-block parallelism = sharding over a "model" axis with XLA
  collectives over ICI (the successor of mlmatrix block coordinate descent),
- all reductions are ``psum``-shaped (XLA inserts them from sharding specs),
- heavy ops are jitted matmuls/convs on the MXU in bf16/f32.

Package layout (mirrors SURVEY.md §2 of the reference analysis):

- ``core``       pipeline DSL, pytree node helper, config, logging
- ``parallel``   mesh construction, sharding helpers, distributed reductions
- ``ops``        the node library (stats, linear solvers, images, nlp, ...)
- ``loaders``    host-side data ingestion feeding sharded device arrays
- ``evaluation`` multiclass / binary / mean-AP evaluators
- ``models``     end-to-end applications (MNIST, CIFAR, VOC, ImageNet, TIMIT,
                 Newsgroups, n-gram LM, transformer LM with the full
                 dp × tp × sp × ep × pp matrix)
"""

from keystone_tpu.core.pipeline import (
    Estimator,
    bind,
    FunctionNode,
    LabelEstimator,
    Pipeline,
    Transformer,
    transformer,
    estimator,
    label_estimator,
)
from keystone_tpu.core.treenode import treenode, static_field
from keystone_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    create_mesh,
    data_sharding,
    replicated_sharding,
    shard_batch,
)

__version__ = "0.3.0"

__all__ = [
    "Estimator",
    "bind",
    "FunctionNode",
    "LabelEstimator",
    "Pipeline",
    "Transformer",
    "transformer",
    "estimator",
    "label_estimator",
    "treenode",
    "static_field",
    "DATA_AXIS",
    "MODEL_AXIS",
    "create_mesh",
    "data_sharding",
    "replicated_sharding",
    "shard_batch",
]
