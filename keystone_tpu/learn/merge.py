"""The fit protocol's missing third verb: ``merge``.

``fit_stats_init/update/finalize`` (PR 10) stream one corpus through
one process. Online learning needs two more degrees of freedom:

- **time** — fold today's chunks into the state accumulated yesterday,
  which requires the state itself to persist
  (:func:`save_fit_state` / :func:`load_fit_state`, digest-checked so
  a torn or tampered state file refuses loudly), and
- **space** — combine states accumulated by different hosts, which
  requires a commutative/associative pairwise :func:`fit_stats_merge`
  (the multihost reduction IS the merge;
  :func:`allmerge_fit_state` runs it over the coordination-service KV
  channel the same way ``parallel/multihost.py`` gathers metrics).

Merge semantics per state type:

- :class:`~keystone_tpu.ops.linear.NormalEqState` keeps its Gram
  CENTERED about a running mean, so the merge is Chan's pairwise
  update: sums add, and a rank-1 mean-difference correction
  ``(n·m/(n+m)) · δδᵀ`` re-centers the combined Gram. Commutative by
  symmetry, associative in exact arithmetic (f32 drift stays inside
  the fused-fit tolerance — the property tests pin 1e-6 on the
  finalized mapper).
- :class:`~keystone_tpu.ops.weighted_linear.WeightedEqState` holds raw
  (uncentered) per-class sums — the merge is plain leaf-wise addition.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import pickle
from typing import Any

import jax
import jax.numpy as jnp

from keystone_tpu.core.logging import get_logger
from keystone_tpu.core.serialization import _to_host, atomic_write

logger = get_logger("keystone_tpu.learn.merge")

_MAGIC_STATE = b"KSTS1\n"


class FitStateError(ValueError):
    """A fit-state file is torn, tampered, or structurally wrong —
    digest mismatch, bad magic, or a state whose shapes disagree with
    the estimator that is supposed to finalize it. Loud by design:
    statistics silently merged onto a corrupt base would poison every
    model refit from then on. Subclasses ValueError like
    ``PipelineSpecError`` so generic callers keep working."""


# ------------------------------------------------------------------- merge


@jax.jit
def _merge_normal_eq(a, b):
    """Chan pairwise merge of two centered normal-equation states. An
    empty side (n = 0) contributes nothing: the rank-1 weight n·m/(n+m)
    and the mean step m/(n+m) both vanish, exactly as in the per-chunk
    update."""
    from keystone_tpu.ops.linear import NormalEqState

    n_new = jnp.maximum(a.n + b.n, 1.0)
    w = a.n * b.n / n_new
    da = b.mean_a - a.mean_a
    db = b.mean_b - a.mean_b
    return NormalEqState(
        ata=a.ata + b.ata + w * jnp.outer(da, da),
        atb=a.atb + b.atb + w * jnp.outer(da, db),
        mean_a=a.mean_a + (b.n / n_new) * da,
        mean_b=a.mean_b + (b.n / n_new) * db,
        n=a.n + b.n,
    )


@jax.jit
def _merge_additive(a, b):
    return jax.tree_util.tree_map(jnp.add, a, b)


def fit_stats_merge(a: Any, b: Any) -> Any:
    """Merge two accumulated fit states of the same type and shape —
    commutative and associative, so a corpus split k ways folds to the
    same statistics in any order (the property the multihost reduction
    and the refit daemon's resume path both lean on).

    Raises :class:`FitStateError` on type or shape disagreement: a
    cross-host merge of states from different pipelines must fail at
    the merge, not at a finalize three steps later.
    """
    from keystone_tpu.ops.linear import NormalEqState
    from keystone_tpu.ops.weighted_linear import WeightedEqState

    if type(a) is not type(b):
        raise FitStateError(
            f"cannot merge fit states of different types: "
            f"{type(a).__name__} vs {type(b).__name__}"
        )
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    shapes_a = [tuple(getattr(x, "shape", ())) for x in la]
    shapes_b = [tuple(getattr(x, "shape", ())) for x in lb]
    if shapes_a != shapes_b:
        raise FitStateError(
            f"cannot merge fit states of different shapes: "
            f"{shapes_a} vs {shapes_b} (different pipelines?)"
        )
    if isinstance(a, NormalEqState):
        return _merge_normal_eq(a, b)
    if isinstance(a, WeightedEqState):
        # every field is a raw masked sum — addition IS the merge
        return _merge_additive(a, b)
    raise FitStateError(
        f"no merge rule for fit-state type {type(a).__name__}"
    )


# ------------------------------------------------------------ persistence


@dataclasses.dataclass
class FitState:
    """One loaded fit-state artifact: the accumulated statistics plus
    everything a refit needs to keep folding and re-finalizing —
    the estimator (its ``fit_stats_*`` protocol and hyperparameters),
    the featurize prefix the fused segment re-applies to NEW chunks
    only, the feature-block ``widths`` finalize pins block edges with,
    and free-form ``meta`` (the refit daemon keeps its persisted
    offsets and version counter here)."""

    state: Any
    est: Any = None
    prefix: tuple = ()
    widths: tuple | None = None
    meta: dict = dataclasses.field(default_factory=dict)


def save_fit_state(
    state: Any,
    path: str,
    *,
    est: Any = None,
    prefix: Any = (),
    widths: Any = None,
    **meta: Any,
) -> str:
    """Persist accumulated fit statistics (plus the estimator/prefix
    needed to resume folding) to ``path`` — atomically, with a sha256
    digest over the payload so :func:`load_fit_state` can refuse a torn
    or corrupted artifact loudly. Returns the hex digest."""
    if prefix is None:
        prefix = ()
    payload = {
        "version": 1,
        "state": _to_host(state),
        "est": _to_host(est),
        "prefix": tuple(_to_host(p) for p in prefix),
        "widths": tuple(widths) if widths else None,
        "meta": dict(meta),
    }
    blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    digest = hashlib.sha256(blob).hexdigest()
    with atomic_write(path) as f:
        f.write(_MAGIC_STATE)
        f.write(digest.encode() + b"\n")
        f.write(blob)
    return digest


def load_fit_state(path: str) -> FitState:
    """Load a :func:`save_fit_state` artifact, verifying the stored
    digest over the payload bytes. Raises :class:`FitStateError` on bad
    magic or digest mismatch (the ``refit.state_digest`` fault site
    drills the mismatch path deterministically)."""
    from keystone_tpu.resilience import faults as _faults

    with open(path, "rb") as f:
        magic = f.read(len(_MAGIC_STATE))
        if magic != _MAGIC_STATE:
            raise FitStateError(
                f"{path} is not a keystone_tpu fit-state file"
            )
        digest = f.readline().strip().decode()
        blob = f.read()
    actual = hashlib.sha256(blob).hexdigest()
    if actual != digest or _faults.fire("refit.state_digest", path):
        raise FitStateError(
            f"{path}: fit-state digest mismatch (stored {digest[:12]}…, "
            f"computed {actual[:12]}…) — torn write or corruption; "
            "refusing to fold new data onto a corrupt base"
        )
    payload = pickle.loads(blob)
    return FitState(
        state=payload["state"],
        est=payload.get("est"),
        prefix=tuple(payload.get("prefix") or ()),
        widths=payload.get("widths"),
        meta=dict(payload.get("meta") or {}),
    )


# ------------------------------------------------------- cross-host merge

# per-process merge sequence: every host calls allmerge in the same
# SPMD program order, so the counter yields matching KV keys/barrier
# ids without extra coordination (the rollup_metrics idiom)
_merge_seq = itertools.count()


def allmerge_fit_state(state: Any, timeout_s: float = 60.0) -> Any:
    """Merge this host's accumulated fit state with every peer's over
    the coordination-service KV channel; ALL hosts must call it (it
    synchronizes at a barrier) and every host returns the identical
    merged state (states merged in process-id order — deterministic,
    and associativity makes the order immaterial to 1e-6).

    Single-process runs and transport failures degrade to the local
    state with a warning — a lost merge loses freshness, never the run.
    """
    try:
        nprocs = jax.process_count()
        pid = jax.process_index()
    except Exception:  # noqa: BLE001 — backend init failure
        nprocs, pid = 1, 0
    if nprocs == 1:
        return state
    from keystone_tpu.parallel.multihost import _coordination_client

    client = _coordination_client()
    if client is None:
        logger.warning(
            "fit-state merge: no coordination-service client; keeping "
            "this host's state only"
        )
        return state
    seq = next(_merge_seq)
    try:
        import base64

        blob = pickle.dumps(
            _to_host(state), protocol=pickle.HIGHEST_PROTOCOL
        )
        client.key_value_set(
            f"keystone/fitstate/{seq}/{pid}",
            base64.b64encode(blob).decode(),
        )
        client.wait_at_barrier(
            f"keystone_fitstate_merge_{seq}", int(timeout_s * 1000)
        )
        merged = None
        for i in range(nprocs):
            peer_blob = base64.b64decode(
                client.blocking_key_value_get(
                    f"keystone/fitstate/{seq}/{i}", int(timeout_s * 1000)
                )
            )
            peer = pickle.loads(peer_blob)
            merged = peer if merged is None else fit_stats_merge(merged, peer)
        # second barrier BEFORE the delete: every host must finish its
        # reads first, or a fast host 0 would reclaim keys a slow peer
        # is still fetching and that peer would silently degrade to a
        # different (local-only) state than everyone else
        client.wait_at_barrier(
            f"keystone_fitstate_merge_done_{seq}", int(timeout_s * 1000)
        )
        if pid == 0:
            try:
                client.key_value_delete(f"keystone/fitstate/{seq}/")
            except Exception:  # noqa: BLE001 — older jaxlib, best-effort
                pass
        return merged
    except Exception as e:  # noqa: BLE001 — degraded, never fatal
        logger.warning(
            "fit-state merge over the coordination service failed (%r); "
            "keeping this host's state only",
            e,
        )
        return state
