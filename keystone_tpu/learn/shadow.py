"""Shadow A/B promotion: score the candidate on live traffic first.

A refreshed model should not take over the request path on the
strength of its training metrics alone. The shadow runner keeps the
incumbent answering every request while the candidate re-scores a
sample of the SAME rows in the background:

- per-request **divergence** between primary and candidate outputs is
  recorded as a ``shadow.compare`` span (when a span sink is active)
  and in ``serve_shadow_*`` metrics — argmax-mismatch rate for
  class-score outputs, exact mismatch rate for integer predictions,
  normalized mean-abs difference otherwise;
- **feature drift** of the incoming rows against the fit state's
  accumulated means/variances feeds
  :meth:`keystone_tpu.observe.health.HealthMonitor.note_feature_drift`
  — a ``serve.feature_drift`` alert marks traffic the accumulated
  statistics never saw (retrain territory, not promote territory);
- **promotion is gated**, not automatic: :meth:`ShadowRunner.verdict`
  promotes only after ``min_samples`` scored requests with mean
  divergence under the threshold and zero drift alerts since the
  shadow started. A failed gate keeps the incumbent — the last-good
  version — serving (auto-rollback by never committing).

Scoring rides a bounded background queue: shadow work never adds
latency to the primary path, and overload drops shadow samples
(counted) instead of backing up requests.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Any

import numpy as np

from keystone_tpu.core.logging import get_logger
from keystone_tpu.observe import health as _health
from keystone_tpu.observe import metrics as _metrics
from keystone_tpu.observe import spans as _spans

logger = get_logger("keystone_tpu.learn.shadow")


def divergence(primary: Any, candidate: Any) -> float:
    """One scalar disagreement score in [0, 1]-ish between two model
    outputs on the same rows: mismatch rate for integer predictions,
    argmax-mismatch rate for (n, k) score matrices, normalized mean-abs
    difference for everything else (shape disagreement scores 1.0 —
    maximally divergent by definition)."""
    p = np.asarray(primary)
    c = np.asarray(candidate)
    if p.shape != c.shape:
        return 1.0
    if p.size == 0:
        return 0.0
    if np.issubdtype(p.dtype, np.integer):
        return float(np.mean(p != c))
    if p.ndim >= 2 and p.shape[-1] > 1:
        return float(np.mean(np.argmax(p, -1) != np.argmax(c, -1)))
    denom = float(np.mean(np.abs(p))) + 1e-12
    return float(np.mean(np.abs(p - c)) / denom)


def input_feature_stats(fit_state: Any) -> tuple | None:
    """(mean, variance) of the corpus the state accumulated, in the
    space the drift check can actually compare incoming request rows
    against — which is only the INPUT space, so this returns None when
    the saved state folds a non-trivial featurize prefix (the state's
    means then live post-featurize where raw rows can't reach)."""
    from keystone_tpu.core.pipeline import Identity
    from keystone_tpu.ops.linear import NormalEqState
    from keystone_tpu.ops.weighted_linear import WeightedEqState

    prefix = tuple(getattr(fit_state, "prefix", ()) or ())
    if any(not isinstance(p, Identity) for p in prefix):
        return None
    s = fit_state.state
    if isinstance(s, NormalEqState):
        n = max(float(s.n), 1.0)
        return np.asarray(s.mean_a), np.diag(np.asarray(s.ata)) / n
    if isinstance(s, WeightedEqState):
        n = max(float(s.n), 1.0)
        mean = np.asarray(s.sum_a) / n
        var = np.diag(np.asarray(s.ata)) / n - mean**2
        return mean, var
    return None


class ShadowRunner:
    """Scores an AOT-exported candidate on sampled primary requests.

    ``sample_every=k`` scores every k-th request (deterministic — the
    same burst samples the same requests every run); ``feature_stats``
    is an optional ``(mean, variance)`` pair in input space for the
    drift gate. ``observe`` is called by the serve path AFTER the
    primary result resolved; it only copies references into a bounded
    queue, so the primary path pays microseconds.
    """

    def __init__(
        self,
        exported: Any,
        version: str,
        *,
        sample_every: int = 1,
        divergence_threshold: float = 0.02,
        min_samples: int = 20,
        feature_stats: tuple | None = None,
        max_queue: int = 64,
    ):
        self.exported = exported
        self.version = version
        self.sample_every = max(int(sample_every), 1)
        self.divergence_threshold = float(divergence_threshold)
        self.min_samples = int(min_samples)
        self.feature_stats = feature_stats
        self._seen = itertools.count()
        self._queue: collections.deque = collections.deque()
        self.max_queue = int(max_queue)
        self._cond = threading.Condition()
        self._stop = False
        self._busy = False  # worker mid-score (guarded by _cond)
        self._lock = threading.Lock()
        self._samples = 0
        self._div_sum = 0.0
        self._div_max = 0.0
        # the gate counts its OWN drift hits (per-candidate, no
        # cross-candidate state): the health monitor's alert is the
        # operator surface and rate-limits with a cooldown, which must
        # not be able to hide live drift from a later candidate's gate
        self._drift_hits = 0
        self._worker = threading.Thread(
            target=self._run, name="serve-shadow", daemon=True
        )
        self._worker.start()

    # ---------------------------------------------------------- feeding

    def observe(self, rows: Any, primary_out: Any, rid: Any = None) -> None:
        """Maybe enqueue one (rows, primary result) pair for shadow
        scoring — the serve path's hook. Never blocks: a full queue
        drops the sample (counted)."""
        if next(self._seen) % self.sample_every:
            return
        reg = _metrics.get_registry()
        with self._cond:
            if self._stop:
                return
            if len(self._queue) >= self.max_queue:
                reg.counter("serve_shadow_dropped").inc()
                return
            self._queue.append((rows, primary_out, rid))
            self._cond.notify()

    # ---------------------------------------------------------- scoring

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(timeout=0.5)
                if self._stop and not self._queue:
                    return
                rows, primary, rid = self._queue.popleft()
                self._busy = True
            try:
                self._score(rows, primary, rid)
            except Exception as e:  # noqa: BLE001 — shadow must not die
                _metrics.get_registry().counter(
                    "serve_shadow_errors"
                ).inc()
                logger.warning("shadow scoring failed: %r", e)
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def _score(self, rows: Any, primary: Any, rid: Any) -> None:
        reg = _metrics.get_registry()
        span_log = _spans.active_span_log()
        t0 = time.perf_counter()
        candidate = np.asarray(self.exported(rows))
        wall = time.perf_counter() - t0
        div = divergence(primary, candidate)
        with self._lock:
            self._samples += 1
            self._div_sum += div
            self._div_max = max(self._div_max, div)
        reg.counter("serve_shadow_requests").inc()
        reg.timer("serve_shadow_seconds").observe(wall)
        reg.gauge("serve_shadow_divergence").set(div)
        if span_log is not None:
            span_log.record_span(
                "shadow.compare",
                wall_s=wall,
                rid=rid,
                divergence=round(div, 6),
                candidate_version=self.version,
                rows=int(np.asarray(rows).shape[0]),
            )
        if self.feature_stats is not None:
            mon = _health.get_monitor()
            mean, var = self.feature_stats
            sigma = np.sqrt(np.maximum(np.asarray(var), 1e-12))
            x = np.asarray(rows, np.float32)
            z = float(
                np.mean(np.abs(x.mean(axis=0) - np.asarray(mean)) / sigma)
            )
            # the gate's own tally first: the monitor's alert below is
            # cooldown-rate-limited and must not be the gate's memory
            if z > mon.config.feature_drift_z:
                with self._lock:
                    self._drift_hits += 1
            mon.note_feature_drift(z, rid=rid)

    # ---------------------------------------------------------- verdict

    def drain(self, timeout: float = 10.0) -> None:
        """Block until every queued AND in-flight sample is scored
        (tests and the promotion endpoint call this so verdicts cover
        the burst that was just sent)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._queue or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._cond.wait(timeout=min(remaining, 0.1))

    def verdict(self) -> dict:
        """The promotion gate's inputs and its decision: promote only
        when enough requests scored, mean divergence clears the
        threshold, and no feature-drift alert fired since the shadow
        started."""
        with self._lock:
            samples = self._samples
            mean_div = self._div_sum / samples if samples else 0.0
            max_div = self._div_max
            drift = self._drift_hits
        promote = (
            samples >= self.min_samples
            and mean_div <= self.divergence_threshold
            and drift == 0
        )
        return {
            "candidate_version": self.version,
            "samples": samples,
            "min_samples": self.min_samples,
            "mean_divergence": round(mean_div, 6),
            "max_divergence": round(max_div, 6),
            "divergence_threshold": self.divergence_threshold,
            "drift_alerts": drift,
            "promote": promote,
        }

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._worker.join(timeout=10.0)
