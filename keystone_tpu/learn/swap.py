"""Atomic hot-swap of a running server's model, under traffic.

The refit daemon publishes a new fitted pipeline; the server must start
answering with it without dropping a single in-flight request and
without trusting it blindly. The swap protocol:

1. **Load through the spec check** —
   :func:`keystone_tpu.core.serialization.load_fitted` refuses a
   checkpoint whose structure drifted from the code
   (:class:`~keystone_tpu.core.serialization.PipelineSpecError`).
2. **Compile before commit** — the candidate is AOT-exported over the
   SAME batch buckets as the incumbent
   (:class:`~keystone_tpu.serve.export.ExportedApply`), warm-started
   from the persistent compile cache, and probed with one real row.
   Until this succeeds the incumbent serves every request.
3. **Commit atomically** — :meth:`ServeApp.swap_exported
   <keystone_tpu.serve.server.ServeApp>` replaces the micro-batcher
   under the server's model lock (submits and swaps serialize on it,
   so no request can reach a closing batcher), then drains the old
   batcher: queued requests finish on the model they were admitted
   under. Zero dropped requests, zero 5xx — the threaded-burst test
   pins it.
4. **Fail loudly, keep the last good version** — any failure (spec
   drift, compile error, the ``serve.swap_fail`` drill) leaves the
   incumbent serving, bumps ``serve_model_swap_failed``, and emits a
   ``model_swap`` event with ``action="rollback"`` naming both
   versions.

Every committed swap emits ``model_swap`` (``action="swap"``) with the
old/new version ids — ``/healthz``, ``observe top``, and the run
report all show the served version and swap count.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any

import numpy as np

from keystone_tpu.core.logging import get_logger
from keystone_tpu.observe import events as _events
from keystone_tpu.observe import metrics as _metrics
from keystone_tpu.resilience import faults as _faults

logger = get_logger("keystone_tpu.learn.swap")


class SwapError(RuntimeError):
    """A model hot-swap failed before commit; the prior version is
    still serving (the rollback already happened by construction —
    nothing was committed)."""


def version_of(path: str, meta: dict | None = None) -> str:
    """The version id a checkpoint serves under: its ``save_fitted``
    ``version`` meta when present, else the file's basename."""
    if meta and meta.get("version") is not None:
        return str(meta["version"])
    return os.path.basename(path)


class ModelSwapper:
    """Orchestrates hot-swaps for one :class:`~keystone_tpu.serve.
    server.ServeApp`: load → spec-check → AOT-export → probe → commit,
    with rollback-by-not-committing on any failure.

    ``source_path`` is the default reload source (the checkpoint the
    server originally loaded, or the refit daemon's ``current.kst``
    pointer) — ``POST /admin/reload`` with no body path and SIGHUP
    both reload from it.
    """

    def __init__(self, app: Any, *, source_path: str | None = None):
        self.app = app
        self.source_path = source_path
        self._swap_idx = itertools.count()
        self._lock = threading.Lock()  # one swap at a time

    # ------------------------------------------------------------ swaps

    def swap_to_path(self, path: str | None = None) -> dict:
        """Load the fitted pipeline at ``path`` (default: the source
        path) and hot-swap it in. Returns
        ``{old_version, new_version, swaps, wall_s}`` on success;
        raises :class:`SwapError` (prior version still serving) on any
        failure."""
        path = path or self.source_path
        if not path:
            raise SwapError(
                "no model path to reload from (server was not started "
                "from a checkpoint; pass an explicit path)"
            )
        with self._lock:
            idx = next(self._swap_idx)
            old_version = getattr(self.app, "model_version", None)
            t0 = time.perf_counter()
            try:
                from keystone_tpu.core.serialization import load_fitted

                pipe, meta = load_fitted(path, with_meta=True)
                new_version = version_of(path, meta)
                exported = self._export(pipe, meta)
                if _faults.fire("serve.swap_fail", idx):
                    raise SwapError(
                        f"injected swap failure (serve.swap_fail, swap "
                        f"{idx})"
                    )
                # one real row through the candidate before commit: a
                # pipeline that compiles but can't answer must roll back
                probe = np.zeros(
                    (1, *exported.row_shape), exported.dtype
                )
                np.asarray(exported(probe))
            except Exception as e:  # noqa: BLE001 — every failure rolls back
                self._observe(
                    "rollback",
                    old_version=old_version,
                    new_version=version_of(path),
                    path=path,
                    error=f"{type(e).__name__}: {str(e)[:200]}",
                )
                logger.warning(
                    "model swap to %s failed (%r); still serving %r",
                    path,
                    e,
                    old_version,
                )
                if isinstance(e, SwapError):
                    raise
                raise SwapError(
                    f"swap to {path} failed; still serving "
                    f"{old_version!r} ({type(e).__name__}: {e})"
                ) from e
            return self._commit(
                exported, new_version, path=path, t0=t0
            )

    def promote(self, exported: Any, version: str) -> dict:
        """Commit an already-built-and-probed candidate (the shadow
        runner's promotion path — the candidate has been scoring live
        traffic, so load/compile/probe are already paid)."""
        with self._lock:
            return self._commit(exported, version, path=None,
                                t0=time.perf_counter())

    # ---------------------------------------------------------- helpers

    def _export(self, pipe: Any, meta: dict) -> Any:
        """AOT-export the candidate over the incumbent's buckets and
        row shape (warm compile cache makes this seconds); the row
        shape is the serving contract — a candidate that needs
        different rows is spec drift at the traffic level and fails
        here, before commit."""
        from keystone_tpu.serve.export import ExportedApply

        incumbent = self.app.exported
        if incumbent is None:
            raise SwapError("server has no exported pipeline to swap")
        sample = meta.get("sample")
        if sample is None:
            sample = np.zeros(
                (1, *incumbent.row_shape), incumbent.dtype
            )
        sample = np.asarray(sample)
        if tuple(sample.shape[1:]) != tuple(incumbent.row_shape):
            raise SwapError(
                f"candidate row shape {tuple(sample.shape[1:])} != "
                f"served {tuple(incumbent.row_shape)}"
            )
        return ExportedApply(pipe, sample, buckets=incumbent.buckets)

    def _commit(
        self, exported: Any, version: str, *, path: str | None, t0: float
    ) -> dict:
        old_version = getattr(self.app, "model_version", None)
        self.app.swap_exported(exported, version=version)
        wall = time.perf_counter() - t0
        _metrics.get_registry().counter("serve_model_swaps").inc()
        self._observe(
            "swap",
            old_version=old_version,
            new_version=version,
            path=path,
            swaps=self.app.swap_count,
            wall_s=round(wall, 3),
        )
        logger.info(
            "model swapped: %r -> %r in %.2fs (swap #%d)",
            old_version,
            version,
            wall,
            self.app.swap_count,
        )
        return {
            "old_version": old_version,
            "new_version": version,
            "swaps": self.app.swap_count,
            "wall_s": round(wall, 3),
        }

    def _observe(self, action: str, **fields: Any) -> None:
        if action == "rollback":
            _metrics.get_registry().counter(
                "serve_model_swap_failed"
            ).inc()
        log = _events.active()
        if log is not None:
            log.emit("model_swap", action=action, **fields)
