"""Online learning: the streaming-fit state becomes a live model.

KeystoneML fits once and stops; the fit protocol
(``fit_stats_init/update/finalize`` in :mod:`keystone_tpu.ops.linear` /
:mod:`keystone_tpu.ops.weighted_linear`) is already an *incremental
learner* — running (AᵀA, AᵀB, μ, n) statistics that fold new labeled
chunks in O(chunk·D²) and re-finalize in O(D³) without ever revisiting
old rows. This package is the production loop KeystoneML never closed:
train → serve → observe → retrain, on mergeable sufficient statistics
instead of a parameter server.

- :mod:`.merge` — the missing third verb of the fit protocol:
  ``fit_stats_merge`` (commutative/associative Chan pairwise merge for
  :class:`~keystone_tpu.ops.linear.NormalEqState`, additive for the
  per-class :class:`~keystone_tpu.ops.weighted_linear.WeightedEqState`),
  plus digest-checked ``save_fit_state`` / ``load_fit_state`` so
  accumulated statistics persist across runs and merge across hosts
  over the coordination-service KV channel.
- :mod:`.refit` — the refit daemon (``python -m keystone_tpu refit
  <state> --watch <dir>``): tails a labeled-chunk stream with persisted
  offsets (at-least-once), folds each chunk through the fused
  featurize+accumulate segment
  (:func:`keystone_tpu.plan.executor.fit_stream`), re-finalizes, and
  publishes a versioned fitted pipeline via
  :func:`keystone_tpu.core.serialization.save_fitted`.
- :mod:`.swap` — atomic hot-swap of the running server's model under
  traffic: ``POST /admin/reload`` (and SIGHUP) loads the candidate
  through the serialization spec check, rebuilds the AOT bucket
  executables off the warm compile cache, and swaps the handler with
  zero dropped requests; a failed candidate rolls back loudly
  (``serve.swap_fail`` drills it).
- :mod:`.shadow` — shadow A/B promotion: primary serves, the candidate
  scores sampled requests in shadow, per-request divergence lands in
  spans/metrics, and promotion is gated on the divergence threshold
  plus ``observe/health.py`` feature-drift alerts (incoming feature
  distribution vs the state's accumulated means).
"""

from __future__ import annotations

from keystone_tpu.learn.merge import (
    FitStateError,
    fit_stats_merge,
    load_fit_state,
    save_fit_state,
)
from keystone_tpu.learn.swap import ModelSwapper, SwapError

__all__ = [
    "FitStateError",
    "ModelSwapper",
    "SwapError",
    "fit_stats_merge",
    "load_fit_state",
    "save_fit_state",
]
