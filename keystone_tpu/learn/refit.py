"""The refit daemon: fold live labeled chunks, re-finalize, republish.

``python -m keystone_tpu refit <state.ksts> --watch <dir>`` tails a
directory of labeled chunks (``*.npz`` files with ``data`` and
``labels`` arrays — the producer drops them in ATOMICALLY, writing a
temp name then renaming to ``*.npz``; the daemon never deletes them)
and closes the online-learning loop:

- each NEW chunk is folded through the SAME fused featurize+accumulate
  segment the original fit used
  (:func:`keystone_tpu.plan.executor.fit_stream` with the persisted
  state as ``init_state``) — old rows are never re-featurized, the
  per-chunk cost is O(chunk·D²) however much history the state holds;
- re-finalize is the estimator's ``fit_stats_finalize`` — O(D³),
  N-independent — and the result is published as a **versioned**
  fitted pipeline (``model_v000042.kst`` plus an atomically-replaced
  ``current.kst`` pointer) via
  :func:`keystone_tpu.core.serialization.save_fitted`, ready for the
  server's ``/admin/reload`` hot-swap (``--notify URL`` posts the
  reload automatically);
- offsets persist **in the state file's own meta** (the ``processed``
  chunk list rides the digest-checked artifact), so delivery is
  at-least-once with no double counting: a crash after folding but
  before the state save lands resumes from the last durable state and
  re-folds exactly the unacked chunks;
- a chunk that won't read — truncated producer write, or the
  ``refit.corrupt_chunk`` drill — is skipped loudly (counter + a
  ``refit`` event) and the stream continues; a state file that fails
  its digest (``refit.state_digest`` drill) refuses to start at all.

The daemon is single-process; across hosts, accumulate per-host states
and combine with :func:`keystone_tpu.learn.merge.allmerge_fit_state`
(the merge IS the multihost reduction).
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import sys
import threading
import time
from typing import Any

import numpy as np

from keystone_tpu.core.logging import get_logger
from keystone_tpu.learn.merge import (
    FitStateError,
    load_fit_state,
    save_fit_state,
)
from keystone_tpu.observe import events as _events
from keystone_tpu.observe import metrics as _metrics
from keystone_tpu.resilience import faults as _faults

logger = get_logger("keystone_tpu.learn.refit")

#: the atomically-replaced pointer to the newest published model —
#: what a server's reload endpoint and SIGHUP re-read
CURRENT_MODEL = "current.kst"


def bootstrap_state(
    chain: Any, data: Any, labels: Any, state_path: str, **meta: Any
):
    """Fit the initial corpus through the fused streaming path and
    persist the accumulated state — the artifact the refit daemon
    resumes from. Returns ``(fitted_pipeline, state)``; the state file
    carries the estimator, prefix, block widths, a 1-row input sample
    (so ``serve`` can export reloads without ``--input-dim``), and the
    row count."""
    from keystone_tpu.core.pipeline import Pipeline
    from keystone_tpu.plan import executor as _executor
    from keystone_tpu.plan.fused_fit import plan_fit

    plan = plan_fit(chain, data, labels)
    if not plan.fit or not plan.fit.fused:
        raise FitStateError(
            "bootstrap needs a fully fusable streaming-fit chain "
            f"(fallback reason recorded in the plan decisions: "
            f"{[d for d in plan.decisions if d.get('rule') == 'fit_fallback']})"
        )
    state = _executor.fit_stream(plan, data, labels)
    model = chain.est.fit_stats_finalize(state, widths=plan.fit.widths)
    from keystone_tpu.plan.executor import _prefix_nodes

    prefix = tuple(_prefix_nodes(chain))
    save_fit_state(
        state,
        state_path,
        est=chain.est,
        prefix=prefix,
        widths=plan.fit.widths,
        sample=np.asarray(data[:1]),
        rows=int(np.asarray(data).shape[0]),
        version=0,
        processed=[],
        **meta,
    )
    return Pipeline.of(chain.prefix, model), state


class RefitDaemon:
    """One watch loop over a labeled-chunk directory. Construction
    loads (and digest-verifies) the state; :meth:`run_once` folds every
    new chunk and republishes when anything changed; :meth:`run` loops
    with a poll interval until SIGTERM/SIGINT."""

    def __init__(
        self,
        state_path: str,
        watch_dir: str,
        *,
        out_dir: str | None = None,
        notify_url: str | None = None,
    ):
        self.state_path = state_path
        self.watch_dir = watch_dir
        self.out_dir = out_dir or os.path.dirname(
            os.path.abspath(state_path)
        )
        self.notify_url = notify_url
        self.fs = load_fit_state(state_path)  # loud on digest mismatch
        if self.fs.est is None:
            raise FitStateError(
                f"{state_path} carries no estimator — it was saved "
                "without est=; the refit daemon cannot re-finalize it"
            )
        self.state = self.fs.state
        self.processed: set[str] = set(
            self.fs.meta.get("processed") or ()
        )
        self.version = int(self.fs.meta.get("version") or 0)
        self.rows_total = int(self.fs.meta.get("rows") or 0)
        self._plan = None
        self._stop = threading.Event()
        os.makedirs(self.out_dir, exist_ok=True)

    # ------------------------------------------------------------- scan

    def pending(self) -> list[str]:
        """New chunk files in arrival (name) order — producers name
        chunks monotonically (timestamps, sequence numbers) so sorted
        order is stream order."""
        try:
            names = os.listdir(self.watch_dir)
        except OSError:
            return []
        return sorted(
            f
            for f in names
            if f.endswith(".npz") and f not in self.processed
        )

    # ------------------------------------------------------------- fold

    def _label_width(self) -> int:
        s = self.state
        if hasattr(s, "atb"):
            return int(np.asarray(s.atb).shape[-1])
        return int(np.asarray(s.at_labels).shape[-1])

    def _ensure_plan(self, first_chunk: Any = None):
        """The fused fit plan, built ONCE from the state's own saved
        input sample (falling back to the first chunk when an old state
        carries none) and reused for every fold. Probing off the STATE
        — not off whatever chunk happens to arrive — is what separates
        failure classes: a plan that won't fuse here is a
        daemon/config-level problem and raises :class:`FitStateError`
        (the daemon halts loudly, chunks stay pending), while a
        malformed individual chunk fails later inside the fold step and
        is skipped without touching its neighbors."""
        if self._plan is not None:
            return self._plan
        from keystone_tpu.core.pipeline import (
            ChainedLabelEstimator,
            Identity,
            Pipeline,
        )
        from keystone_tpu.plan.fused_fit import _DEFAULT_FIT_CHUNK, plan_fit

        probe = self.fs.meta.get("sample")
        if probe is None:
            probe = first_chunk
        if probe is None:
            raise FitStateError(
                f"{self.state_path} carries no input sample and no "
                "chunk is available to probe the plan with"
            )
        probe = np.asarray(probe)[:1]
        labels_probe = np.zeros((1, self._label_width()), np.float32)
        prefix = (
            Pipeline(nodes=tuple(self.fs.prefix))
            if self.fs.prefix
            else Identity()
        )
        chain = ChainedLabelEstimator(prefix=prefix, est=self.fs.est)
        # probe is 1 row, so the planner has no row count to bound the
        # chunk size with — pin the default so an oversized chunk file
        # still streams instead of staging whole
        plan = plan_fit(
            chain, probe, labels_probe, chunk_size=_DEFAULT_FIT_CHUNK
        )
        if not plan.fit or not plan.fit.fused:
            raise FitStateError(
                "refit does not plan as a fused streaming fit "
                "(non-row-wise prefix or state over budget) — refusing "
                "to fold through a different code path than the state "
                "was accumulated on"
            )
        d_state = int(np.asarray(self.state.ata).shape[0])
        if plan.fit.d != d_state:
            raise FitStateError(
                f"the state's sample featurizes to d={plan.fit.d} but "
                f"its statistics accumulate d={d_state} — stale or "
                "mismatched state file"
            )
        self._plan = plan
        return plan

    def fold(self, fname: str) -> int:
        """Fold one chunk file into the state; returns rows folded (0
        when the chunk was skipped — unreadable, corrupt, or
        malformed: wrong feature width, mismatched rows. Skipped
        chunks are marked processed so one bad file can't wedge the
        stream; the skip is loud (counter + event) either way. The
        state assignment is last and atomic, so a failed fold leaves
        the accumulated statistics untouched."""
        reg = _metrics.get_registry()
        path = os.path.join(self.watch_dir, fname)
        try:
            if _faults.fire("refit.corrupt_chunk", fname):
                raise OSError(
                    f"injected corrupt chunk (refit.corrupt_chunk, "
                    f"{fname})"
                )
            with np.load(path) as z:
                data = np.asarray(z["data"])
                labels = np.asarray(z["labels"])
        except Exception as e:  # noqa: BLE001 — skip loudly, continue
            return self._skip(fname, e, reg)
        # plan construction is OUTSIDE the skip bracket: a plan that
        # won't build is a config-level fault (FitStateError) that must
        # HALT the daemon with every chunk still pending, not quietly
        # consume the stream one durable skip at a time
        plan = self._ensure_plan(first_chunk=data)
        try:
            from keystone_tpu.plan import executor as _executor

            state = _executor.fit_stream(
                plan, data, labels, init_state=self.state
            )
        except Exception as e:  # noqa: BLE001 — malformed chunk: skip
            return self._skip(fname, e, reg)
        self.state = state
        self.processed.add(fname)
        rows = int(data.shape[0])
        self.rows_total += rows
        reg.counter("refit_chunks_folded").inc()
        reg.counter("refit_rows_folded").inc(rows)
        return rows

    def _skip(self, fname: str, err: Exception, reg) -> int:
        """Durably skip one bad chunk, loudly (counter + event). Only
        chunk-specific failures land here — producers must publish
        atomically (write a temp name, then rename to ``*.npz``) or a
        file caught mid-write is skipped as torn."""
        reg.counter("refit_chunks_skipped").inc()
        self._emit(
            "chunk_skipped",
            chunk=fname,
            error=f"{type(err).__name__}: {str(err)[:200]}",
        )
        logger.warning("refit: skipping bad chunk %s (%r)", fname, err)
        self.processed.add(fname)
        return 0

    # ---------------------------------------------------------- publish

    def _widths(self):
        return self.fs.widths or (
            self._plan.fit.widths if self._plan else None
        )

    def _save_state(self) -> None:
        """Persist the state + offsets durably (the at-least-once ack —
        also called alone when a cycle only SKIPPED chunks: the skip
        must stick without minting a pointless new model version)."""
        meta = dict(self.fs.meta)
        meta.update(
            processed=sorted(self.processed),
            version=self.version,
            rows=self.rows_total,
        )
        save_fit_state(
            self.state,
            self.state_path,
            est=self.fs.est,
            prefix=self.fs.prefix,
            widths=self._widths(),
            **meta,
        )
        self.fs.meta = meta

    def publish(self) -> str:
        """Re-finalize off the accumulated state (O(D³), N-independent)
        and publish: versioned model file, atomically-replaced
        ``current.kst`` pointer, durable state save carrying the new
        offsets — in that order, so a crash between steps re-publishes
        rather than losing data."""
        from keystone_tpu.core.pipeline import Pipeline
        from keystone_tpu.core.serialization import (
            atomic_write,
            save_fitted,
        )

        t0 = time.perf_counter()
        model = self.fs.est.fit_stats_finalize(
            self.state, widths=self._widths()
        )
        pipe = Pipeline.of(*self.fs.prefix, model)
        self.version += 1
        vname = f"model_v{self.version:06d}.kst"
        vpath = os.path.join(self.out_dir, vname)
        save_fitted(
            pipe,
            vpath,
            version=self.version,
            rows=self.rows_total,
            refit=True,
            sample=self.fs.meta.get("sample"),
        )
        # the pointer: byte-copy then os.replace, so a reader holding
        # current.kst open mid-swap still reads one complete artifact
        current = os.path.join(self.out_dir, CURRENT_MODEL)
        with open(vpath, "rb") as src, atomic_write(current) as dst:
            shutil.copyfileobj(src, dst)
        self._save_state()
        wall = time.perf_counter() - t0
        _metrics.get_registry().counter("refit_publishes").inc()
        self._emit(
            "publish",
            version=self.version,
            model=vname,
            rows_total=self.rows_total,
            wall_s=round(wall, 3),
        )
        logger.info(
            "refit: published %s (v%d, %d rows total) in %.2fs",
            vpath, self.version, self.rows_total, wall,
        )
        self._notify(current)
        return vpath

    def _notify(self, model_path: str) -> None:
        """Best-effort POST /admin/reload at the configured server —
        the push half of the loop; a server that is down simply picks
        the new ``current.kst`` up on its next reload."""
        if not self.notify_url:
            return
        import urllib.request

        url = self.notify_url.rstrip("/") + "/admin/reload"
        body = json.dumps({"path": os.path.abspath(model_path)}).encode()
        try:
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30.0) as r:
                payload = json.loads(r.read())
            logger.info("refit: server reloaded: %s", payload)
        except Exception as e:  # noqa: BLE001 — push is best-effort
            _metrics.get_registry().counter("refit_notify_failed").inc()
            self._emit(
                "notify_failed",
                url=url,
                error=f"{type(e).__name__}: {str(e)[:200]}",
            )
            logger.warning("refit: reload notify failed: %r", e)

    def _emit(self, action: str, **fields: Any) -> None:
        log = _events.active()
        if log is not None:
            log.emit("refit", action=action, **fields)

    # -------------------------------------------------------------- run

    def run_once(self) -> dict:
        """One scan-fold-publish cycle; returns a summary (no publish
        when nothing new arrived)."""
        folded = skipped = rows = 0
        for fname in self.pending():
            if self._stop.is_set():
                break
            n = self.fold(fname)
            if n:
                folded += 1
                rows += n
            else:
                skipped += 1
        out = {
            "chunks_folded": folded,
            "chunks_skipped": skipped,
            "rows": rows,
            "version": self.version,
        }
        if folded:
            out["model"] = self.publish()
            out["version"] = self.version
        elif skipped:
            # nothing new folded: persist the skip offsets only — no
            # new model version, no pointless reload of the server
            self._save_state()
        return out

    def run(self, interval_s: float = 2.0) -> None:
        """Poll until stopped (SIGTERM/SIGINT set the stop event; the
        in-flight cycle completes — the last durable state always
        covers every acked chunk)."""
        while not self._stop.is_set():
            summary = self.run_once()
            if summary.get("model"):
                print(
                    f"refit: v{summary['version']} "
                    f"({summary['chunks_folded']} chunk(s), "
                    f"{summary['rows']} row(s)) -> {summary['model']}",
                    flush=True,
                )
            self._stop.wait(interval_s)

    def stop(self) -> None:
        self._stop.set()


# --------------------------------------------------------------------- CLI


USAGE = """usage: python -m keystone_tpu refit <state.ksts> --watch DIR [options]
<state.ksts>: a save_fit_state artifact (digest-checked on load)
options:
  --watch DIR     labeled-chunk directory to tail (*.npz with data/labels)
  --out DIR       published-model directory (default: the state file's dir)
  --once          one scan-fold-publish cycle, then exit
  --interval S    poll interval in seconds (default 2)
  --notify URL    POST /admin/reload at this server after each publish
"""


def _parse(argv: list[str]) -> tuple[str, dict]:
    if not argv or argv[0] in ("-h", "--help"):
        raise SystemExit(USAGE)
    state, args, i = argv[0], {}, 1
    valued = {
        "--watch": "watch", "--out": "out",
        "--interval": "interval", "--notify": "notify",
    }
    while i < len(argv):
        a = argv[i]
        if a == "--once":
            args["once"] = True
            i += 1
        elif a in valued:
            if i + 1 >= len(argv):
                raise SystemExit(f"{a} needs a value")
            args[valued[a]] = argv[i + 1]
            i += 2
        else:
            raise SystemExit(f"unknown option {a!r}\n{USAGE}")
    if "watch" not in args:
        raise SystemExit(f"--watch DIR is required\n{USAGE}")
    return state, args


def main(argv: list[str] | None = None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    state_path, args = _parse(argv)
    from keystone_tpu.core.runtime import enable_compilation_cache

    enable_compilation_cache()
    try:
        daemon = RefitDaemon(
            state_path,
            args["watch"],
            out_dir=args.get("out"),
            notify_url=args.get("notify"),
        )
    except FitStateError as e:
        raise SystemExit(f"refit: {e}")
    if args.get("once"):
        summary = daemon.run_once()
        print(json.dumps(summary), flush=True)
        return
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: daemon.stop())
    daemon.run(interval_s=float(args.get("interval", 2.0)))


if __name__ == "__main__":
    main()
